"""Host data layer tests: tokenizer, vocabulary, COCO index, DataSet."""

import os
import time

import numpy as np
import pytest

from sat_tpu.data import (
    CocoCaptions,
    DataSet,
    Vocabulary,
    tokenize,
    tokenize_no_punct,
)
from sat_tpu.data.dataset import prepare_eval_data, prepare_train_data


class TestTokenizer:
    def test_basic_caption(self):
        assert tokenize("A man riding a horse.") == [
            "a", "man", "riding", "a", "horse", ".",
        ]

    def test_commas_and_contractions(self):
        assert tokenize("It's a dog, isn't it?") == [
            "it", "'s", "a", "dog", ",", "is", "n't", "it", "?",
        ]

    def test_no_punct_variant(self):
        assert tokenize_no_punct("A man, riding; a horse.") == [
            "a", "man", "riding", "a", "horse",
        ]

    def test_numbers_keep_commas(self):
        # Treebank keeps commas inside numbers
        assert "1,000" in tokenize("there are 1,000 birds.")

    def test_ellipsis_and_quotes(self):
        toks = tokenize('he said "stop" ... now.')
        assert "``" in toks and "''" in toks and "..." in toks


class TestVocabulary:
    def test_build_order_and_start_token(self):
        v = Vocabulary(size=50)
        v.build(["a dog and a cat.", "a dog runs."])
        assert v.words[0] == "<start>"
        # 'a' (3) and '.' (2) are the most frequent
        assert v.words[1] == "a"
        assert v.word2idx["a"] == 1

    def test_shrinks_to_corpus(self):
        v = Vocabulary(size=5000)
        v.build(["a dog.", "a cat."])
        assert v.size == len(set("a dog . cat".split())) + 1

    def test_roundtrip_and_sentence(self, tmp_path):
        v = Vocabulary(size=100)
        v.build(["a man riding a horse on the beach."])
        p = str(tmp_path / "vocab.csv")
        v.save(p)
        v2 = Vocabulary(size=100, save_file=p)
        assert list(v2.words) == list(v.words)
        idxs = v2.process_sentence("a man riding a horse.")
        assert v2.get_sentence(idxs) == "a man riding a horse."

    def test_get_sentence_truncates_at_period(self):
        v = Vocabulary(size=100)
        v.build(["a dog runs fast."])
        idxs = v.process_sentence("a dog. runs fast.")
        assert v.get_sentence(idxs) == "a dog."

    def test_get_sentence_appends_period(self):
        v = Vocabulary(size=100)
        v.build(["a dog runs."])
        idxs = v.process_sentence("a dog runs")
        assert v.get_sentence(idxs) == "a dog runs."

    @pytest.mark.skipif(
        not os.path.exists("/root/reference/data/vocabulary.csv"),
        reason="reference fixture not mounted",
    )
    def test_loads_reference_csv_format(self):
        v = Vocabulary(size=5000, save_file="/root/reference/data/vocabulary.csv")
        assert v.words[0] == "<start>"
        assert "." in v.word2idx


class TestCoco:
    def test_index_and_normalization(self, coco_fixture):
        coco = CocoCaptions(coco_fixture["train_json"])
        assert len(coco.imgs) == 12
        assert len(coco.anns) == 24
        for ann in coco.anns.values():
            assert ann["caption"].endswith(".")
            assert ann["caption"] == ann["caption"].lower()

    def test_max_ann_cap(self, coco_fixture):
        coco = CocoCaptions(coco_fixture["train_json"], max_ann_num=5)
        assert len(coco.anns) == 5

    def test_filter_by_cap_len(self, coco_fixture):
        coco = CocoCaptions(coco_fixture["train_json"])
        coco.filter_by_cap_len(6)
        for ann in coco.anns.values():
            assert len(tokenize(ann["caption"])) <= 6

    def test_filter_by_words(self, coco_fixture):
        coco = CocoCaptions(coco_fixture["train_json"])
        vocab = {"a", "man", "riding", "horse", "on", "the", "beach", "."}
        coco.filter_by_words(vocab)
        assert all(
            set(tokenize(a["caption"])) <= vocab for a in coco.anns.values()
        )
        # images with no surviving annotations are dropped
        for img_id in coco.imgs:
            assert coco.img_to_anns.get(img_id)

    def test_load_results_validates(self, coco_fixture):
        coco = CocoCaptions(coco_fixture["val_json"])
        res = coco.load_results(
            [{"image_id": 1, "caption": "a dog."}, {"image_id": 2, "caption": "a cat."}]
        )
        assert len(res.imgs) == 2
        with pytest.raises(ValueError):
            coco.load_results([{"image_id": 99999, "caption": "x."}])


class TestDataSet:
    def test_fake_count_padding(self):
        n, bs = 10, 4
        ds = DataSet(
            list(range(n)), [f"f{i}" for i in range(n)], bs,
            np.zeros((n, 20), np.int32), np.ones((n, 20), np.float32),
            is_train=True, shuffle=False, seed=0,
        )
        assert ds.num_batches == 3
        assert ds.fake_count == 2
        batches = list(ds)
        assert len(batches) == 3
        for files, words, masks in batches:
            assert len(files) == bs and words.shape == (bs, 20)

    def test_shuffle_on_reset(self):
        n = 32
        ds = DataSet(list(range(n)), [str(i) for i in range(n)], 4,
                     np.zeros((n, 20)), np.ones((n, 20)),
                     is_train=True, shuffle=True, seed=1)
        order1 = list(ds.idxs)
        ds.reset()
        assert list(ds.idxs) != order1


class TestPrepare:
    def test_prepare_train_data(self, coco_fixture):
        cfg = coco_fixture["config"]
        ds = prepare_train_data(cfg)
        assert ds.count == 24
        files, words, masks = ds.next_batch()
        assert words.shape == (cfg.batch_size, cfg.max_caption_length)
        assert masks.max() == 1.0
        # caches were written and reload cleanly
        assert os.path.exists(cfg.temp_annotation_file)
        assert os.path.exists(cfg.temp_data_file)
        ds2 = prepare_train_data(cfg)
        assert ds2.count == ds.count

    def test_prepare_eval_data(self, coco_fixture):
        cfg = coco_fixture["config"]
        coco, ds, vocab = prepare_eval_data(cfg)
        assert ds.count == cfg.max_eval_ann_num
        assert not ds.is_train
        assert vocab.words[0] == "<start>"

    def test_image_loader(self, coco_fixture):
        from sat_tpu.data import ImageLoader

        loader = ImageLoader()
        files = [
            os.path.join(coco_fixture["train_img_dir"], f)
            for f in sorted(os.listdir(coco_fixture["train_img_dir"]))[:3]
        ]
        batch = loader.load_images(files)
        assert batch.shape == (3, 224, 224, 3)
        assert batch.dtype == np.float32

    def test_prefetch_loader(self, coco_fixture):
        from sat_tpu.data import PrefetchLoader

        cfg = coco_fixture["config"]
        ds = prepare_train_data(cfg)
        seen = 0
        for batch in PrefetchLoader(ds, num_workers=2, prefetch_depth=2):
            assert batch["images"].shape == (cfg.batch_size, 224, 224, 3)
            assert batch["word_idxs"].shape == (cfg.batch_size, 20)
            seen += 1
        assert seen == ds.num_batches


hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402


class TestDataSetProperties:
    """Property-based invariants of the batch iterator (hypothesis)."""

    @given(
        n=st.integers(1, 64),
        batch_size=st.integers(1, 16),
        shuffle=st.booleans(),
        seed=st.integers(0, 5),
    )
    @settings(max_examples=40, deadline=None)
    def test_every_epoch_covers_every_item_exactly_once(
        self, n, batch_size, shuffle, seed
    ):
        ds = DataSet(
            list(range(n)), [f"f{i}" for i in range(n)], batch_size,
            shuffle=shuffle, seed=seed,
        )
        for _ in range(2):
            files = []
            batches = 0
            for batch in ds:
                assert len(batch) == batch_size          # static shapes
                files.extend(batch)
                batches += 1
            assert batches == ds.num_batches
            # the non-pad portion (everything but the final batch's
            # fake_count tail) is exactly a permutation of the dataset
            core = [f for b in range(ds.num_batches - 1)
                    for f in files[b * batch_size:(b + 1) * batch_size]]
            tail_real = files[(ds.num_batches - 1) * batch_size:][
                : n - (ds.num_batches - 1) * batch_size
            ]
            assert sorted(core + tail_real) == sorted(f"f{i}" for i in range(n))

    @given(
        n=st.integers(2, 48),
        batch_size=st.integers(1, 8),
        epoch=st.integers(0, 3),
        seed=st.integers(0, 3),
        offset_raw=st.integers(0, 63),
    )
    @settings(max_examples=40, deadline=None)
    def test_seek_replays_any_epoch_tail(
        self, n, batch_size, epoch, seed, offset_raw
    ):
        mk = lambda: DataSet(  # noqa: E731
            list(range(n)), [f"f{i}" for i in range(n)], batch_size,
            shuffle=True, seed=seed,
        )
        ds = mk()
        epochs = []
        for _ in range(epoch + 1):
            epochs.append([tuple(b) for b in ds])
        offset = offset_raw % ds.num_batches   # any valid batch offset
        ds2 = mk()
        ds2.seek(epoch, offset)
        assert [tuple(b) for b in ds2] == epochs[epoch][offset:]


def test_prefetch_loader_surfaces_worker_errors(coco_fixture, tmp_path):
    """A missing/corrupt image mid-epoch must raise on the consumer side
    (not hang the queue or silently skip the batch)."""
    import shutil

    from sat_tpu.data import PrefetchLoader

    cfg = coco_fixture["config"]
    # private image dir so deleting a file can't break sibling tests
    img_dir = tmp_path / "images"
    shutil.copytree(cfg.train_image_dir, img_dir)
    cfg = cfg.replace(
        train_image_dir=str(img_dir),
        temp_annotation_file=str(tmp_path / "anns.csv"),
        temp_data_file=str(tmp_path / "data.npy"),
    )
    ds = prepare_train_data(cfg)
    victim = sorted(img_dir.iterdir())[2]
    victim.unlink()
    with pytest.raises(FileNotFoundError):
        for _ in PrefetchLoader(ds, num_workers=2, prefetch_depth=2):
            pass


def test_prefetch_loader_abandoned_iterator_releases_producer(coco_fixture):
    """Breaking out of the loader mid-epoch must stop the producer thread
    (the bounded put aborts on the consumer-gone signal) — an abandoned
    iterator may not pin a thread or deadlock interpreter exit."""
    import threading

    from sat_tpu.data import PrefetchLoader

    ds = prepare_train_data(coco_fixture["config"])
    before = threading.active_count()
    it = iter(PrefetchLoader(ds, num_workers=2, prefetch_depth=1))
    next(it)
    it.close()  # generator finalizer sets the stop event
    deadline = time.time() + 10
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


class TestDevicePreprocess:
    """uint8 raw feed + on-device mean-sub (config.device_preprocess) must
    be bitwise-equal to the host path: the resize already runs on the
    uint8 image in both modes (reference utils/misc.py:22-27 order), so
    deferring astype(float32)−mean to the accelerator changes nothing
    numerically while shrinking the feed 4x."""

    def _jpg(self, tmp_path):
        import cv2

        rng = np.random.default_rng(0)
        f = str(tmp_path / "img.jpg")
        cv2.imwrite(f, rng.integers(0, 255, (48, 64, 3), dtype=np.uint8))
        return f

    def test_raw_loader_matches_host_preprocess(self, tmp_path):
        from sat_tpu.data.images import ILSVRC_2012_MEAN, ImageLoader

        f = self._jpg(tmp_path)
        host = ImageLoader(size=32).load_image(f)
        raw = ImageLoader(size=32, raw=True).load_image(f)
        assert raw.dtype == np.uint8
        np.testing.assert_array_equal(
            host, raw.astype(np.float32) - ILSVRC_2012_MEAN
        )

    def test_encode_uint8_feed_bitwise_equals_float_feed(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from sat_tpu.config import Config
        from sat_tpu.data.images import ILSVRC_2012_MEAN
        from sat_tpu.models.captioner import encode, init_variables

        cfg = Config(
            image_size=32, vocabulary_size=30, dim_embedding=8,
            num_lstm_units=8, dim_initialize_layer=8, dim_attend_layer=8,
            dim_decode_layer=8, compute_dtype="float32",
        )
        variables = init_variables(jax.random.PRNGKey(0), cfg)
        rng = np.random.default_rng(1)
        raw = rng.integers(0, 255, (2, 32, 32, 3), dtype=np.uint8)
        host = raw.astype(np.float32) - ILSVRC_2012_MEAN

        ctx_raw, _ = encode(variables, cfg, jnp.asarray(raw), train=False)
        ctx_host, _ = encode(variables, cfg, jnp.asarray(host), train=False)
        np.testing.assert_array_equal(np.asarray(ctx_raw), np.asarray(ctx_host))
