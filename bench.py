"""Benchmark: training throughput of the flagship caption model.

Measures steady-state captions/sec of the jitted train step — VGG16
encoder forward (frozen CNN, the reference's published configuration,
/root/reference/config.py:8-43 + README.md:85-89), 20-step scan decoder,
backward, global-norm clip 5.0, Adam — on whatever single device JAX
provides (the driver runs this on one real TPU chip).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no throughput numbers (SURVEY.md §6), so
``vs_baseline`` is computed against ``published.train_captions_per_sec``
in BASELINE.json when present (recorded from a prior round), else 1.0.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from sat_tpu.config import Config
    from sat_tpu.train.step import create_train_state, make_jit_train_step

    config = Config(batch_size=64)
    B, T = config.batch_size, config.max_caption_length

    rng = np.random.default_rng(0)
    batch = {
        "images": jnp.asarray(rng.normal(size=(B, 224, 224, 3)).astype(np.float32)),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
        ),
        "masks": jnp.asarray(
            (np.arange(T)[None, :] < rng.integers(8, T + 1, size=(B, 1))).astype(
                np.float32
            )
        ),
    }

    state = create_train_state(jax.random.PRNGKey(0), config)
    train_step = make_jit_train_step(config)
    step_rng = jax.random.PRNGKey(1)

    # Sync barrier: fetch a scalar to host.  (block_until_ready alone does
    # not actually block on tunneled device platforms.)
    def sync(metrics):
        return float(metrics["total_loss"])

    # compile + settle
    for _ in range(2):
        state, metrics = train_step(state, batch, step_rng)
    sync(metrics)

    n_steps = int(os.environ.get("BENCH_STEPS", "20"))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = train_step(state, batch, step_rng)
    sync(metrics)
    elapsed = time.perf_counter() - t0

    captions_per_sec = n_steps * B / elapsed

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("train_captions_per_sec")
    except (OSError, json.JSONDecodeError):
        pass
    vs_baseline = captions_per_sec / baseline if baseline else 1.0

    print(
        json.dumps(
            {
                "metric": "train_captions_per_sec",
                "value": round(captions_per_sec, 2),
                "unit": "captions/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
