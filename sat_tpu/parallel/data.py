"""Per-host input sharding for multi-process training.

The reference's distributed mode has every worker read the whole dataset
and rely on asynchrony to decorrelate (/root/reference/main_distributed.py:
67-79).  The SPMD design instead gives each host a disjoint slice of the
global batch: the per-host DataSet below yields ``global_batch /
process_count`` items per step, and ``make_global_batch`` (collectives.py)
stitches the host shards into one data-sharded global array.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from ..data.dataset import DataSet


def mesh_data_shard(mesh) -> Tuple[int, int]:
    """Map THIS process to its slot along the mesh's 'data' axis.

    Returns ``(shard_index, num_shards)`` for the per-host input feed.
    The feed must be keyed on the DATA-axis layout, not the process
    count: when the 'model' axis spans processes (context parallelism or
    cross-host TP), several processes hold the same data row and must
    feed identical replicas of it — `jax.make_array_from_process_local_data`
    maps each process's local rows onto the rows its devices own.

    * every process's devices in one data row (model axis across hosts):
      that row's index, out of dp rows — pure-CP meshes give (0, 1),
      every host feeding the full batch;
    * one-or-more rows per process and dp == global layout (the plain DP
      case, incl. several rows per process): falls back to
      ``(process_index, process_count)`` — the contiguous-block ownership
      of the data-major device order.
    """
    axes = list(mesh.axis_names)
    devs = np.moveaxis(np.asarray(mesh.devices), axes.index("data"), 0)
    dp = devs.shape[0]
    rows = {
        r
        for r in range(dp)
        for d in devs[r].flat
        if d.process_index == jax.process_index()
    }
    if len(rows) == 1:
        return rows.pop(), dp
    # multi-row fallback: only valid when this process owns EXACTLY the
    # contiguous row block implied by (process_index, process_count) — a
    # straddling layout (devices-per-process not a multiple of the model
    # axis) would silently map the wrong dataset rows onto the owned
    # shards, so fail loudly instead
    pi, pc = jax.process_index(), jax.process_count()
    if dp % pc == 0 and rows == set(range(pi * (dp // pc), (pi + 1) * (dp // pc))):
        return pi, pc
    raise ValueError(
        f"process {pi}'s devices straddle data rows {sorted(rows)} of {dp} "
        f"(mesh {dict(mesh.shape)} over {pc} processes) — the per-host feed "
        "cannot map dataset rows onto this layout; use a mesh where each "
        "process's devices sit in one data row or an exact row block"
    )


class _ProcessShardView(DataSet):
    """Per-process view of a global DataSet whose batch stream is
    INVARIANT to the process layout.

    Every epoch this view draws the GLOBAL keyed order — the permutation
    and fake_count padding of DataSet._set_epoch, same key, same call
    order — and takes the contiguous block of each global batch that this
    process's data row owns.  make_global_batch places block ``r`` at the
    global array's rows ``[r*Bl, (r+1)*Bl)``, so the assembled global
    batch is element-for-element the batch a single-process run feeds at
    the same (seed, epoch, step).  Two properties follow:

    * loss parity: an N-process run computes each step's loss over the
      exact example set (and row order) of the single-process run — the
      multihost demo asserts it end to end;
    * elastic resume: a run checkpointed under one process count and
      resumed under another replays the same global batch stream
      (the cursor is f(seed, epoch) exactly as on one process).

    The global fake_count padding is part of the order, so every shard
    always holds whole local batches and the synchronous step count
    agrees across hosts with no truncation or process padding.
    """

    def __init__(self, global_ds: DataSet, shard_index: int, shard_count: int):
        self._global_batch = global_ds.batch_size
        self._shard_index = shard_index
        self._shard_count = shard_count
        super().__init__(
            global_ds.image_ids,
            global_ds.image_files,
            global_ds.batch_size // shard_count,
            global_ds.word_idxs,
            global_ds.masks,
            is_train=global_ds.is_train,
            shuffle=global_ds.shuffle,
            seed=global_ds.seed,
        )

    def setup(self) -> None:
        # count / num_batches / fake_count describe the GLOBAL set (the
        # step count every host must agree on); batch_size is local
        self.count = len(self.image_ids)
        self.num_batches = int(np.ceil(self.count / self._global_batch))
        self.fake_count = self.num_batches * self._global_batch - self.count
        self.epoch = -1
        self._pending_seek = False
        self.seek(0, 0)

    def _set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        rng = np.random.default_rng((self.seed, epoch))
        order = (
            list(rng.permutation(self.count))
            if self.shuffle
            else list(range(self.count))
        )
        if self.fake_count:
            order += list(rng.choice(self.count, self.fake_count))
        B, Bl, r = self._global_batch, self.batch_size, self._shard_index
        self.idxs = [
            order[b * B + r * Bl + k]
            for b in range(self.num_batches)
            for k in range(Bl)
        ]
        self._pad_idxs = []  # padding is part of the global order above

    # the local sequence is always whole batches (len(idxs) =
    # num_batches * local batch) — iterate it, not the global count
    def has_next_batch(self) -> bool:
        return self.current_idx < len(self.idxs)

    def has_full_next_batch(self) -> bool:
        return self.current_idx + self.batch_size <= len(self.idxs)


def process_local_dataset(
    dataset: DataSet,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> DataSet:
    """This process's view of a *global* DataSet: ``global_batch /
    process_count`` items per step, each step's items being the contiguous
    block of the global batch the process's data row owns
    (:class:`_ProcessShardView` — the global batch stream is invariant to
    the process layout).  Single-process runs return the dataset
    unchanged."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc == 1:
        return dataset
    if dataset.batch_size % pc:
        raise ValueError(
            f"global batch {dataset.batch_size} not divisible by "
            f"{pc} processes"
        )
    return _ProcessShardView(dataset, pi, pc)
