"""Dynamic micro-batcher: bounded admission queue → pad-to-bucket batches.

PERF.md's decode measurements show the single-program beam search is
dispatch-latency-bound at production batch sizes — one more image in the
batch is nearly free, one more dispatch is not.  The batcher converts
that headroom into request throughput: requests accumulate in a bounded
queue, the dispatch thread gathers up to ``max_batch`` of them (holding
an underfull batch open at most ``max_wait_ms``), pads the batch to the
engine's bucket ladder, and dispatches.

Admission control and flow:

* a full queue sheds immediately — ``Rejected(429)`` — so overload turns
  into fast client-visible backpressure instead of unbounded latency;
* a request whose deadline passed while it queued fails fast with 504 at
  the dispatch boundary, never spending device time on it;
* ``drain()`` flips the batcher into reject-new mode (503), completes
  everything already admitted — queued *and* in flight — then stops.

The dispatch chain is double-buffered exactly like
``runtime.device_prefetch``: batch n+1 is dispatched to the device before
batch n's results are drained, so host-side detokenization (and the HTTP
threads' JPEG decoding) overlaps device beam search.  The only
host↔device sync is the engine's ``decode_output`` drain.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..resilience import faultinject


class _WedgeTimeout(Exception):
    """An in-flight batch's result drain exceeded serve_wedge_timeout_ms."""


class Rejected(Exception):
    """Admission refused; ``status`` is the HTTP code the frontend maps."""

    def __init__(self, status: int, reason: str):
        super().__init__(reason)
        self.status = status
        self.reason = reason


@dataclass
class Request:
    """One admitted caption request; ``done`` fires with either ``result``
    (the engine's per-image dict) or ``error`` (http status, message)."""

    image: np.ndarray
    t_submit_ns: int
    deadline_unix: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, Any]] = None
    error: Optional[Tuple[int, str]] = None
    bucket: Optional[int] = None
    # request-scoped tracing (telemetry.tracectx): stamped when the
    # gather loop pops this request; the trace rides along so the batcher
    # can attribute each phase to the originating X-Request-Id
    t_gather_ns: Optional[int] = None
    trace: Optional[Any] = None

    def mark(self, phase: str, t0_ns: int, dur_ns: int) -> None:
        if self.trace is not None:
            self.trace.mark(phase, t0_ns, dur_ns)

    def fail(self, status: int, reason: str) -> None:
        self.error = (status, reason)
        self.done.set()


class MicroBatcher:
    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        tel=None,
        pipeline_depth: int = 1,
        on_wedge: Optional[Callable[[], None]] = None,
        wedge_timeout_ms: Optional[float] = None,
    ) -> None:
        config = engine.config
        self.engine = engine
        self.max_batch = int(
            max_batch if max_batch is not None else config.serve_max_batch
        )
        wait_ms = (
            max_wait_ms if max_wait_ms is not None else config.serve_max_wait_ms
        )
        self.max_wait_s = wait_ms / 1e3
        depth = int(
            queue_depth if queue_depth is not None else config.serve_queue_depth
        )
        self._q: "queue.Queue[Request]" = queue.Queue(maxsize=depth)
        self._tel = tel if tel is not None else telemetry.get()
        # in-flight dispatches held before draining (device_prefetch's
        # ``ahead``); 0 degrades to fully synchronous dispatch→drain
        self.pipeline_depth = max(0, int(pipeline_depth))
        # wedge containment (docs/SERVING.md degraded health): when > 0,
        # the result drain of each in-flight batch is bounded — a batch
        # the device never returns fails its requests with 500 instead of
        # stranding them, and ``on_wedge`` (the server's degrade+re-warm
        # hook) fires.  0 keeps the drain unbounded (the default).
        wedge_ms = (
            wedge_timeout_ms
            if wedge_timeout_ms is not None
            else config.serve_wedge_timeout_ms
        )
        self.wedge_timeout_s = float(wedge_ms) / 1e3  # sync-ok: host config scalar
        self.on_wedge = on_wedge
        # armed only via SAT_FI_WEDGE_SERVE_BATCH (inert in production);
        # captured once so the fire-once bookkeeping persists across
        # batches
        self._plan = faultinject.FaultPlan.from_env()
        self._batch_index = 0  # 1-based, counted at dispatch
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- admission (called from HTTP worker threads) -----------------------

    def submit(
        self,
        image: np.ndarray,
        deadline_unix: Optional[float] = None,
        trace: Optional[Any] = None,
    ) -> Request:
        """Admit one preprocessed image; raises Rejected(503) while
        draining and Rejected(429) when the queue is full."""
        if self._draining.is_set():
            self._tel.count("serve/rejected_draining")
            raise Rejected(503, "server is draining; not accepting work")
        req = Request(
            image=image,
            t_submit_ns=time.perf_counter_ns(),
            deadline_unix=deadline_unix,
            trace=trace,
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._tel.count("serve/shed")
            raise Rejected(
                429, f"queue full ({self._q.maxsize} waiting); shed"
            ) from None
        self._tel.count("serve/submitted")
        self._tel.gauge("serve/queue_depth", self._q.qsize())
        return req

    def queue_depth(self) -> int:
        return self._q.qsize()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sat-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = 60.0) -> None:
        """Graceful stop: new submits reject (503), everything already
        admitted is dispatched, completed and signalled, then the
        dispatch thread exits."""
        self._draining.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- dispatch loop -----------------------------------------------------

    def _gather(self) -> Optional[List[Request]]:
        """Block for the first request (polling the drain flag), then hold
        the batch open up to ``max_wait_s`` or until ``max_batch``.
        Returns None when draining and the queue is empty."""
        while True:
            try:
                first = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                if self._draining.is_set():
                    return None
        first.t_gather_ns = time.perf_counter_ns()
        batch = [first]
        flush_at = time.monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            wait = flush_at - time.monotonic()
            if wait <= 0:
                break
            try:
                rider = self._q.get(timeout=wait)
            except queue.Empty:
                break
            rider.t_gather_ns = time.perf_counter_ns()
            batch.append(rider)
        return batch

    def _admit(self, batch: List[Request]) -> List[Request]:
        """Queue-wait accounting + deadline triage at the dispatch
        boundary: expired requests fail fast (504) without device time."""
        now_ns = time.perf_counter_ns()
        now_unix = time.time()
        live = []
        for r in batch:
            self._tel.record(
                "serve/queue_wait", r.t_submit_ns, now_ns - r.t_submit_ns
            )
            # per-request phase attribution: queue_wait ends when the
            # gather loop popped the request; batch_form is the hold-open
            # window between that pop and this dispatch boundary
            t_gather = r.t_gather_ns if r.t_gather_ns is not None else now_ns
            r.mark("queue_wait", r.t_submit_ns, t_gather - r.t_submit_ns)
            r.mark("batch_form", t_gather, now_ns - t_gather)
            if r.deadline_unix is not None and now_unix > r.deadline_unix:
                self._tel.count("serve/expired")
                r.fail(504, "deadline expired while queued")
            else:
                live.append(r)
        return live

    def _dispatch(self, live: List[Request]):
        t0 = time.perf_counter_ns()
        batch, bucket = self.engine.pad_batch([r.image for r in live])
        out = self.engine.dispatch(batch)
        t1 = time.perf_counter_ns()
        self._tel.record("serve/dispatch", t0, t1 - t0)
        self._tel.count("serve/batches")
        self._tel.count(f"serve/bucket_{bucket}")
        self._tel.count("serve/padded_rows", bucket - len(live))
        for r in live:
            r.bucket = bucket
            r.mark("dispatch", t0, t1 - t0)
        return out

    def _bounded_decode(self, decode: Callable[[], Any]):
        """Run ``decode`` in a helper thread bounded by
        ``wedge_timeout_s``; raises :class:`_WedgeTimeout` when the device
        never returns.  The helper is a daemon — a truly wedged drain
        parks it forever, which is exactly the state the timeout reports
        instead of sharing."""
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _run():
            try:
                box["results"] = decode()
            except BaseException as e:
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_run, name="sat-serve-drain", daemon=True)
        t.start()
        if not done.wait(timeout=self.wedge_timeout_s):
            raise _WedgeTimeout()
        if "error" in box:
            raise box["error"]
        return box["results"]

    def _finish(self, entry) -> None:
        out, live, index = entry

        def _drain():
            if self._plan.maybe_wedge_serve(index):
                # injected stuck batch: park exactly like a drain whose
                # device never answers (interruptible only by process exit)
                time.sleep(3600.0)
            self._plan.maybe_slow_serve()
            return self.engine.drain_output(out, len(live))

        try:
            t0 = time.perf_counter_ns()
            # only the device drain is wedge-bounded — detok is pure host
            # work that cannot hang on the device
            if self.wedge_timeout_s > 0:
                arrays = self._bounded_decode(_drain)
            else:
                arrays = _drain()
            t1 = time.perf_counter_ns()
            results = self.engine.detok_rows(arrays, len(live))
            t2 = time.perf_counter_ns()
            # the aggregate span keeps its pre-split meaning (drain+detok)
            # so /stats latency percentiles stay comparable across runs
            self._tel.record("serve/detok", t0, t2 - t0)
            for r in live:
                r.mark("drain", t0, t1 - t0)
                r.mark("detok", t1, t2 - t1)
        except _WedgeTimeout:
            # the batch is gone; its requesters get a fast 500 and the
            # server's hook degrades health + re-warms the engine
            self._tel.count("serve/wedged_batches")
            for r in live:
                if not r.done.is_set():
                    r.fail(
                        500,
                        "in-flight batch wedged past "
                        f"{self.wedge_timeout_s * 1e3:g}ms; results discarded",
                    )
            if self.on_wedge is not None:
                try:
                    self.on_wedge()
                except Exception:
                    pass  # degrading health must never kill the batcher
            return
        except Exception as e:  # keep serving; fail only this batch
            self._tel.count("serve/detok_errors")
            for r in live:
                if not r.done.is_set():
                    r.fail(500, f"decode failed: {e}")
            return
        for r, result in zip(live, results):
            r.result = result
            r.done.set()
            self._tel.count("serve/completed")

    def _loop(self) -> None:
        inflight: "deque" = deque()
        while True:
            if inflight and self._q.qsize() == 0:
                # Nothing to gather right now: flush the oldest in-flight
                # batch instead of parking in _gather while its requesters
                # wait on a device that may already be done.  Overlap
                # still happens under load — the queue is non-empty then,
                # so dispatch n+1 precedes this drain of n.
                self._finish(inflight.popleft())
                continue
            batch = self._gather()
            self._tel.gauge("serve/queue_depth", self._q.qsize())
            if batch is None:
                break
            live = self._admit(batch)
            if not live:
                continue
            try:
                out = self._dispatch(live)
            except Exception as e:  # device/shape failure: fail the batch
                self._tel.count("serve/dispatch_errors")
                for r in live:
                    r.fail(500, f"dispatch failed: {e}")
                continue
            self._batch_index += 1
            inflight.append((out, live, self._batch_index))
            while len(inflight) > self.pipeline_depth:
                self._finish(inflight.popleft())
        while inflight:  # drain: complete what the device still owes
            self._finish(inflight.popleft())
