"""Execution supervisor: hang/wedge watchdog, crash-only auto-restart,
and topology-elastic checkpoint resume (docs/RESILIENCE.md).

Unit layer pins the contracts in isolation — the watchdog escalation
ladder (gauges → stack dump → abort 86) with an injected abort, the
supervisor restart policy through its ``runner`` hook, fault-plan knob
parsing, the force-kill defer window, the topology sidecar, and the
regression gate's infra-skip exit.

The chaos layer drives the whole stack end-to-end through real
subprocesses on the 8-virtual-device CPU backend:
``--supervise`` + ``SAT_FI_WEDGE_AT_STEP`` → watchdog abort (exit 86,
stack-dump artifact) → auto-restart from LAST_GOOD → a final state
bitwise-identical to an uninterrupted control run.  Elastic resume is
pinned in-process: an 8-chip checkpoint re-placed onto 4- and 1-chip
meshes bitwise-exactly, then trained further on the smaller mesh.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from sat_tpu import runtime, telemetry
from sat_tpu.parallel.mesh import mesh_from_devices
from sat_tpu.parallel.sharding import reshard_train_state
from sat_tpu.resilience import lineage
from sat_tpu.resilience.faultinject import FaultPlan
from sat_tpu.resilience.preempt import GracefulShutdown
from sat_tpu.resilience.supervisor import (
    RESTARTS_ENV,
    _strip_supervise,
    supervise,
)
from sat_tpu.resilience.watchdog import (
    ABORTING,
    DUMPED,
    OK,
    STALLED,
    WATCHDOG_EXIT_CODE,
    Watchdog,
    deadlines_from_config,
)
from sat_tpu.train import checkpoint as ckpt_mod
from sat_tpu.train.checkpoint import latest_checkpoint, state_to_flat

from tests.test_resilience import _cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# watchdog: escalation ladder with an injected abort
# ---------------------------------------------------------------------------


def _make_wd(tmp_path, deadlines, **kw):
    aborts = []
    kw.setdefault("poll_s", 0.05)
    kw.setdefault("grace_s", 0.0)
    kw.setdefault("dump_path", str(tmp_path / "watchdog_stacks.txt"))
    wd = Watchdog(deadlines, abort=aborts.append, **kw)
    return wd, aborts


def test_watchdog_ladder_escalates_to_abort(tmp_path):
    flushed = []
    wd, aborts = _make_wd(
        tmp_path, {"step": 0.01}, pre_abort=lambda: flushed.append(1)
    )
    with wd.phase("step"):  # first completion arms enforcement
        pass
    assert wd.state == OK
    wd._enter("step")
    time.sleep(0.03)

    wd.check()  # rung 1: gauges
    assert wd.state == STALLED and wd.stalled_phase == "step"
    wd.check()  # rung 2: stack dump
    assert wd.state == DUMPED
    dump = open(str(tmp_path / "watchdog_stacks.txt")).read()
    assert dump.startswith("sat_tpu watchdog stack dump: phase=step")
    assert f"pid={os.getpid()}" in dump
    wd.check()  # rung 3 (grace 0): pre-abort hook, then abort
    assert wd.state == ABORTING
    assert flushed == [1]
    assert aborts == [WATCHDOG_EXIT_CODE] == [86]


def test_watchdog_cold_start_never_false_trips(tmp_path):
    """A phase that has NEVER completed (first step compiling for minutes)
    is tracked but not enforced."""
    wd, aborts = _make_wd(tmp_path, {"step": 0.01})
    wd._enter("step")
    time.sleep(0.03)
    wd.check()
    assert wd.state == OK and aborts == []
    wd._exit("step")
    # ...but the second entry IS enforced
    wd._enter("step")
    time.sleep(0.03)
    wd.check()
    assert wd.state == STALLED


def test_watchdog_stands_down_when_phase_completes(tmp_path):
    wd, aborts = _make_wd(tmp_path, {"dispatch": 0.01})
    with wd.phase("dispatch"):
        pass
    wd._enter("dispatch")
    time.sleep(0.03)
    wd.check()
    assert wd.state == STALLED
    wd._exit("dispatch")  # the stall resolved after all
    assert wd.state == OK and wd.stalled_phase is None
    wd.check()
    assert wd.state == OK and aborts == []


def test_watchdog_untracked_phase_never_enforced(tmp_path):
    wd, aborts = _make_wd(tmp_path, {"step": 0.01})
    with wd.phase("warmup"):  # no deadline entry
        pass
    wd._enter("warmup")
    time.sleep(0.03)
    for _ in range(4):
        wd.check()
    assert wd.state == OK and aborts == []


def test_slow_but_alive_steps_keep_watchdog_quiet(tmp_path):
    """SAT_FI_SLOW_STEP_MS semantics: a degraded-but-progressing loop
    completes its phases and must never climb the ladder.  Driven on a
    fake clock (``use_clock``) so "slow but under deadline" is exact —
    the wall-clock version raced suite CPU contention and flaked when a
    5 ms stall ran past the 50 ms deadline on a loaded host."""
    plan = FaultPlan(slow_step_ms=5)
    now = [0.0]
    wd, aborts = _make_wd(tmp_path, {"step": 0.05})
    wd.use_clock(lambda: now[0])
    for step in range(5):
        with wd.phase("step"):
            plan.maybe_slow(step)  # real stall; watchdog time is frozen
            now[0] += 0.04  # each step runs 40 ms on the fake clock
        wd.check()
    assert wd.state == OK and aborts == []
    # same cadence past the deadline DOES climb: proves the fake-clock
    # harness still exercises enforcement, not a disconnected timer
    wd._enter("step")
    now[0] += 0.06
    wd.check()
    assert wd.state == STALLED


def test_watchdog_threaded_smoke(tmp_path):
    """The real observer thread drives the same ladder: a parked phase
    reaches the injected abort without any manual check() calls."""
    wd, aborts = _make_wd(tmp_path, {"step": 0.05}, poll_s=0.05)
    wd.start()
    try:
        with wd.phase("step"):
            pass
        wd._enter("step")
        deadline = time.time() + 10.0
        while not aborts and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd._exit("step")
        wd.stop()
    assert aborts == [WATCHDOG_EXIT_CODE]


def test_deadlines_from_config_drops_disabled_phases():
    from sat_tpu.config import Config

    config = Config(
        watchdog_step_s=10.0,
        watchdog_data_wait_s=0.0,  # 0 disables this phase
        watchdog_dispatch_s=5.0,
        watchdog_checkpoint_s=7.0,
    )
    d = deadlines_from_config(config)
    assert d["step"] == 10.0 and d["dispatch"] == 5.0 and d["checkpoint"] == 7.0
    wd = Watchdog(d, abort=lambda rc: None)
    assert "data_wait" not in wd.deadlines


# ---------------------------------------------------------------------------
# fault-plan knobs added for the supervisor PR
# ---------------------------------------------------------------------------


def test_fault_plan_parses_wedge_and_slow_knobs():
    assert FaultPlan.from_env({}).inert
    plan = FaultPlan.from_env(
        {
            "SAT_FI_WEDGE_AT_STEP": "5",
            "SAT_FI_SLOW_STEP_MS": "20",
            "SAT_FI_WEDGE_SERVE_BATCH": "2",
        }
    )
    assert not plan.inert
    assert plan.wedge_at_step == 5
    assert plan.slow_step_ms == 20
    assert plan.wedge_serve_batch == 2
    with pytest.raises(ValueError, match="expected an integer"):
        FaultPlan.from_env({"SAT_FI_WEDGE_AT_STEP": "later"})


def test_fault_plan_serve_wedge_fires_exactly_once():
    plan = FaultPlan(wedge_serve_batch=2)
    assert not plan.maybe_wedge_serve(1)
    assert plan.maybe_wedge_serve(2)
    assert not plan.maybe_wedge_serve(2)  # fired already
    assert not plan.maybe_wedge_serve(3)


def test_fault_plan_slow_step_stalls_host_time():
    plan = FaultPlan(slow_step_ms=30)
    t0 = time.monotonic()
    plan.maybe_slow(1)
    plan.maybe_slow(2)  # slow is per-step, not fire-once
    assert time.monotonic() - t0 >= 0.05


# ---------------------------------------------------------------------------
# supervisor: restart policy through the runner hook
# ---------------------------------------------------------------------------


def test_strip_supervise_variants():
    argv = [
        "--config", "c.json", "--supervise", "--max_restarts", "4",
        "--watchdog", "1.0",
    ]
    assert _strip_supervise(argv) == ["--config", "c.json", "--watchdog", "1.0"]
    assert _strip_supervise(["--supervise", "--max_restarts=4"]) == []
    assert _strip_supervise(["--load"]) == ["--load"]


def test_supervisor_restarts_with_load_and_disarmed_faults(monkeypatch):
    """Child failures burn the budget; every restarted child resumes with
    --load, a bumped SAT_SUPERVISOR_RESTARTS, and NO SAT_FI_* vars (an
    injected deterministic fault must not live-lock the restart loop)."""
    monkeypatch.setenv("SAT_FI_WEDGE_AT_STEP", "5")
    calls = []
    rcs = iter([WATCHDOG_EXIT_CODE, 1, 0])

    def runner(cmd, env):
        calls.append((list(cmd), dict(env)))
        return next(rcs)

    sleeps = []
    rc = supervise(
        ["--config", "c.json", "--supervise", "--max_restarts", "5"],
        max_restarts=5,
        backoff_base_s=0.01,
        runner=runner,
        sleep=sleeps.append,
    )
    assert rc == 0
    assert len(calls) == 3
    assert len(sleeps) == 2 and all(s > 0 for s in sleeps)

    cmd0, env0 = calls[0]
    assert cmd0[:3] == [sys.executable, "-m", "sat_tpu.cli"]
    assert "--supervise" not in cmd0 and "--max_restarts" not in cmd0
    assert "--load" not in cmd0  # first launch: fresh run as asked
    assert env0[RESTARTS_ENV] == "0"
    assert env0.get("SAT_FI_WEDGE_AT_STEP") == "5"  # first child keeps it

    for n, (cmd, env) in enumerate(calls[1:], start=1):
        assert cmd.count("--load") == 1  # appended once, never duplicated
        assert env[RESTARTS_ENV] == str(n)
        assert not any(k.startswith("SAT_FI_") for k in env)


def test_supervisor_budget_spent_returns_last_rc():
    calls = []

    def runner(cmd, env):
        calls.append(cmd)
        return WATCHDOG_EXIT_CODE

    rc = supervise(
        ["--config", "c.json"],
        max_restarts=2,
        backoff_base_s=0.0,
        runner=runner,
        sleep=lambda s: None,
    )
    assert rc == WATCHDOG_EXIT_CODE
    assert len(calls) == 3  # 1 launch + 2 restarts


def test_supervisor_clean_child_never_restarts():
    calls = []
    rc = supervise(
        ["--config", "c.json"],
        max_restarts=3,
        runner=lambda cmd, env: (calls.append(cmd), 0)[1],
        sleep=lambda s: None,
    )
    assert rc == 0 and len(calls) == 1


def test_supervisor_signal_stops_restart_loop():
    """A SIGTERM delivered to the supervisor while a child is failing
    stops the restart loop (the pair is being preempted, not wedged)."""
    calls = []

    def runner(cmd, env):
        calls.append(cmd)
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)  # let the handler observe the signal
        return WATCHDOG_EXIT_CODE

    before = signal.getsignal(signal.SIGTERM)
    rc = supervise(
        ["--config", "c.json"],
        max_restarts=5,
        runner=runner,
        sleep=lambda s: None,
    )
    assert rc == WATCHDOG_EXIT_CODE
    assert len(calls) == 1  # no restart after the signal
    assert signal.getsignal(signal.SIGTERM) is before  # handler restored


# ---------------------------------------------------------------------------
# graceful-shutdown defer window (force-kill held mid-checkpoint-flush)
# ---------------------------------------------------------------------------


def test_defer_holds_force_kill_until_window_closes(capsys):
    fired = []
    with GracefulShutdown() as s:
        s._handler(signal.SIGTERM, None)  # first signal: graceful stop
        assert s.stop_requested
        # observable stand-in for the original disposition
        s._previous[signal.SIGTERM] = lambda signum, frame: fired.append(signum)
        with s.defer():
            s._handler(signal.SIGTERM, None)  # force-kill mid-flush
            assert fired == []  # held, not dropped
            err = capsys.readouterr().err
            assert "held until the in-flight checkpoint" in err
        assert fired == [signal.SIGTERM]  # released when the window closed


def test_defer_is_reentrant_releases_at_outermost_exit():
    fired = []
    with GracefulShutdown() as s:
        s._handler(signal.SIGTERM, None)
        s._previous[signal.SIGTERM] = lambda signum, frame: fired.append(signum)
        with s.defer():
            with s.defer():
                s._handler(signal.SIGTERM, None)
            assert fired == []  # inner exit: still one window deep
        assert fired == [signal.SIGTERM]


def test_defer_without_pending_force_is_inert():
    with GracefulShutdown() as s:
        with s.defer():
            pass
        assert not s.stop_requested


# ---------------------------------------------------------------------------
# topology sidecar + elastic-restore note
# ---------------------------------------------------------------------------


def _write_npz(path, **arrays):
    if not arrays:
        arrays = {"w": np.arange(8, dtype=np.float32)}
    with open(path, "wb") as f:
        np.savez(f, **arrays)
    return path


def test_topology_sidecar_round_trip_and_verify_compat(tmp_path):
    path = _write_npz(str(tmp_path / "3.npz"))
    topo = {
        "device_count": 8,
        "platform": "cpu",
        "process_count": 1,
        "mesh_shape": [8, 1],
        "mesh_axes": ["data", "model"],
    }
    lineage.write_sidecar(path, topology=topo)
    # the digest contract is untouched by the extension
    assert lineage.verify_checkpoint(path) == (True, "sha256 ok")
    assert lineage.read_sidecar_topology(path) == topo
    # sidecars without the extension read as None, not an error
    legacy = _write_npz(str(tmp_path / "6.npz"))
    lineage.write_sidecar(legacy)
    assert lineage.read_sidecar_topology(legacy) is None


def test_elastic_restore_note_fires_only_on_topology_change(tmp_path, capsys):
    path = _write_npz(str(tmp_path / "3.npz"))
    lineage.write_sidecar(
        path, topology={"device_count": 2, "mesh_shape": [2, 1]}
    )
    ckpt_mod._note_elastic_restore(path)
    err = capsys.readouterr().err
    assert "elastic resume" in err and "2 device(s)" in err
    # matching topology: silent
    same = _write_npz(str(tmp_path / "6.npz"))
    lineage.write_sidecar(
        same,
        topology={"device_count": len(jax.devices()), "mesh_shape": [8, 1]},
    )
    ckpt_mod._note_elastic_restore(same)
    assert "elastic resume" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# regression gate: infra-skip exit (satellite)
# ---------------------------------------------------------------------------

GATE = os.path.join(REPO, "scripts", "check_regression.py")


def _gate(*argv, timeout=60):
    return subprocess.run(
        [sys.executable, GATE, *argv], capture_output=True, text=True,
        cwd=REPO, timeout=timeout,
    )


def _row(**kw):
    row = {
        "metric": "train_captions_per_sec",
        "value": 1000.0,
        "unit": "captions/s",
        "vs_baseline": 1.0,
        "schema_version": telemetry.SCHEMA_VERSION,
    }
    row.update(kw)
    return row


def test_gate_infra_skips_device_unreachable_candidate(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_row()))
    cur.write_text(json.dumps(_row(value=None, error="device_unreachable")))
    proc = _gate(str(base), str(cur))
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "infra-skip" in proc.stderr and "device_unreachable" in proc.stderr


def test_gate_regression_outranks_infra_skip(tmp_path):
    """A measured regression in the same artifact must fail the gate even
    when a later attempt hit the outage."""
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_row()))
    cur.write_text(
        json.dumps(_row(value=500.0))  # -50%: a real regression
        + "\n"
        + json.dumps(_row(value=None, error="device_unreachable"))
    )
    proc = _gate(str(base), str(cur))
    assert proc.returncode == 2, proc.stdout + proc.stderr


def test_gate_unrecognized_error_warns_but_passes(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps(_row()))
    cur.write_text(json.dumps(_row(value=None, error="cosmic_rays")))
    proc = _gate(str(base), str(cur))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "not a recognized infra-skip" in proc.stderr


def test_bench_error_line_carries_provenance_stamp():
    sys.path.insert(0, REPO)
    try:
        from bench import _error_line
    finally:
        sys.path.remove(REPO)
    row = json.loads(_error_line("device_unreachable", attempts=3))
    assert row["error"] == "device_unreachable"
    assert row["value"] is None and row["attempts"] == 3
    # the stamp check_regression's infra-skip decision hangs off
    assert row["schema_version"] == telemetry.SCHEMA_VERSION
    assert row["run_id"] and row["git_sha"]


def test_bench_watchdog_overhead_gate():
    """scripts/bench_watchdog.py: the armed watchdog's hot-path cost must
    clear its own < 0.5%-of-step acceptance bar."""
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_watchdog.py"),
            "--iters", "20000",
        ],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "watchdog_hot_path_overhead"
    assert row["unit"] == "%_of_step"
    assert row["value"] <= 0.5
    assert row["schema_version"] == telemetry.SCHEMA_VERSION


# ---------------------------------------------------------------------------
# elastic resume: 8-chip checkpoint onto 4- and 1-chip meshes (in-process)
# ---------------------------------------------------------------------------


def test_elastic_resume_8_to_4_to_1_bitwise(coco_fixture, tmp_path, capsys):
    """Train on an (8,1) mesh, then restore+re-place the checkpoint onto
    4- and 1-device meshes: state must be bitwise identical, and training
    must continue on the smaller mesh."""
    cfg8 = _cfg(
        coco_fixture, tmp_path, "elastic", mesh_shape=(8, 1), batch_size=8
    )
    state = runtime.train(cfg8)
    ref = state_to_flat(state)
    path = latest_checkpoint(cfg8.save_dir)
    topo = lineage.read_sidecar_topology(path)
    assert topo is not None
    assert topo["device_count"] == 8
    assert topo["mesh_shape"] == [8, 1]
    assert topo["platform"] == "cpu"

    for n in (4, 1):
        cfg_n = cfg8.replace(mesh_shape=(n, 1))
        restored = runtime.setup_state(cfg_n, load=True)
        mesh = mesh_from_devices(jax.devices()[:n], (n, 1), ("data", "model"))
        placed = reshard_train_state(restored, cfg_n, mesh)
        got = state_to_flat(placed)
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=f"n={n}: {k}")

    # the resumed run actually trains on the smaller mesh
    cfg4 = cfg8.replace(mesh_shape=(4, 1), num_epochs=2)
    resumed = runtime.setup_state(cfg4, load=True)
    start = int(resumed.step)
    cont = runtime.train(cfg4, state=resumed)
    assert int(cont.step) > start


# ---------------------------------------------------------------------------
# chaos e2e: wedge → watchdog abort 86 → supervised restart → bitwise resume
# ---------------------------------------------------------------------------


def _subprocess_env(extra=None):
    """Child env: the test env minus any SAT_FI_* leakage, with the
    suite's per-machine XLA compile cache so children skip recompiles."""
    from sat_tpu.utils.compile_cache import cache_dir

    env = {
        k: v for k, v in os.environ.items() if not k.startswith("SAT_FI_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir(".jax_cache")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    env["SAT_DEVICE_WATCHDOG_S"] = "0"
    env.update(extra or {})
    return env


def _run_cli(args, env_extra=None, timeout=420):
    return subprocess.run(
        [sys.executable, "-m", "sat_tpu.cli", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=_subprocess_env(env_extra),
        timeout=timeout,
    )


def _flat_npz(path):
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def test_chaos_wedge_abort_restart_bitwise(coco_fixture, tmp_path):
    """The acceptance run: under --supervise with SAT_FI_WEDGE_AT_STEP=5,
    the wedged child is aborted by the watchdog with exit code 86 (stack
    dump artifact on disk), the supervisor restarts it from LAST_GOOD with
    faults disarmed, and the relaunched run finishes with a final
    checkpoint bitwise-identical to an uninterrupted control run."""
    chaos = dict(
        watchdog_interval=0.2,
        watchdog_step_s=5.0,
        watchdog_data_wait_s=120.0,
        watchdog_dispatch_s=120.0,
        watchdog_checkpoint_s=120.0,
        watchdog_grace_s=0.3,
        supervise_backoff_s=0.1,
    )
    control_cfg = _cfg(coco_fixture, tmp_path, "chaos_control", **chaos)
    control_cfg.save(str(tmp_path / "control.json"))
    chaos_cfg = _cfg(coco_fixture, tmp_path, "chaos_wedged", **chaos)
    chaos_cfg.save(str(tmp_path / "chaos.json"))

    control = _run_cli(["--config", str(tmp_path / "control.json")])
    assert control.returncode == 0, control.stdout + control.stderr
    control_final = latest_checkpoint(control_cfg.save_dir)
    assert control_final.endswith("6.npz")

    proc = _run_cli(
        ["--config", str(tmp_path / "chaos.json"), "--supervise"],
        env_extra={"SAT_FI_WEDGE_AT_STEP": "5"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # the first child wedged at step 5 and the watchdog climbed the ladder
    assert "sat_tpu watchdog: phase 'step' exceeded" in proc.stderr
    assert "aborting with exit code 86" in proc.stderr
    # the supervisor recognized 86 and restarted from LAST_GOOD
    assert "watchdog abort (wedged run; LAST_GOOD landed)" in proc.stderr
    assert "restarting from LAST_GOOD" in proc.stderr
    assert "run completed after 1 restart(s)" in proc.stderr
    # stack-dump artifact landed next to the telemetry outputs
    dump_path = os.path.join(
        chaos_cfg.summary_dir, "telemetry", "watchdog_stacks.txt"
    )
    assert os.path.isfile(dump_path)
    assert "phase=step" in open(dump_path).read()

    # LAST_GOOD advanced to the final step on the restarted incarnation
    assert lineage.last_good_step(chaos_cfg.save_dir) == 6
    chaos_final = latest_checkpoint(chaos_cfg.save_dir)
    assert chaos_final.endswith("6.npz")

    # bitwise-identical continuation: wedge + abort + resume changed nothing
    want = _flat_npz(control_final)
    got = _flat_npz(chaos_final)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
