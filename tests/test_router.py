"""Fleet router tests (sat_tpu/serve/router.py).

Three layers, cheapest first:

* pure routing math — weight/effective-load/pick/merge_fleet driven
  directly, no sockets;
* scripted stub replicas — real HTTP upstreams whose /healthz, /stats
  and /caption replies are mutable dicts, so retry/shed/drain paths run
  against real sockets without a jax engine;
* end-to-end — two real CaptionServers behind a real Router HTTP
  process: request-id stitching across the hop (router access.jsonl +
  exactly one replica access.jsonl) and zero steady-state recompiles.
"""

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from sat_tpu import telemetry
from sat_tpu.config import Config
from sat_tpu.serve.replica import Endpoint, parse_endpoints
from sat_tpu.serve.router import (
    Router,
    effective_load,
    merge_fleet,
    pick_replica,
    replica_weight,
)
from sat_tpu.telemetry import tracectx

# ---------------------------------------------------------------------------
# Pure routing math
# ---------------------------------------------------------------------------


def test_replica_weight_multiplies_per_signal():
    assert replica_weight(False, False, 0.25) == 1.0
    assert replica_weight(True, False, 0.25) == 0.25
    assert replica_weight(False, True, 0.25) == 0.25
    # degraded straggler: doubly discounted but never zero
    assert replica_weight(True, True, 0.25) == pytest.approx(0.0625)


def test_effective_load_placement_and_weighting():
    # the +1 is the request being placed: an idle down-weighted replica
    # ranks below an idle healthy one instead of tying at 0
    assert effective_load(0, 0, 1.0) == 1.0
    assert effective_load(0, 0, 0.25) == 4.0
    assert effective_load(3, 2, 1.0) == 6.0
    # negative signals from a confused replica clamp instead of helping
    assert effective_load(-5, -5, 1.0) == 1.0
    assert effective_load(0, 0, 0.0) == float("inf")  # sync-ok: host sentinel


def test_pick_replica_least_load_with_hysteresis():
    loads = {"r0": 2.0, "r1": 1.0}
    assert pick_replica(loads, None, 0.25) == "r1"
    # sticky: last stays while within (1 + hysteresis) of the best
    assert pick_replica({"r0": 1.2, "r1": 1.0}, "r0", 0.25) == "r0"
    # beyond the band the pick flips
    assert pick_replica({"r0": 1.3, "r1": 1.0}, "r0", 0.25) == "r1"
    # a vanished last falls through to the best
    assert pick_replica(loads, "gone", 0.25) == "r1"
    assert pick_replica({}, None, 0.25) is None


def _snap(**kw):
    base = {
        "reachable": True,
        "ready": True,
        "status": "ok",
        "degraded": False,
        "queue_depth": 0,
        "in_flight": 0,
        "p50_ms": None,
        "p99_ms": None,
    }
    base.update(kw)
    return base


def test_merge_fleet_degraded_down_weighted_not_blackholed():
    view = merge_fleet(
        {
            "r0": _snap(status="degraded", degraded=True),
            "r1": _snap(queue_depth=5),
        },
        {"r0": "in", "r1": "in"},
        straggler_factor=2.0,
        down_weight=0.25,
    )
    assert view["routable"] == ["r0", "r1"]
    # idle degraded: 1/0.25 = 4; healthy with 5 queued: 6 — the degraded
    # replica still absorbs load when the healthy one is deeper
    assert view["replicas"]["r0"]["effective_load"] == pytest.approx(4.0)
    assert view["replicas"]["r1"]["effective_load"] == pytest.approx(6.0)
    assert view["queue_depth"] == 5


def test_merge_fleet_straggler_ruling_uses_routable_p99s():
    view = merge_fleet(
        {
            "r0": _snap(p50_ms=100.0, p99_ms=100.0),
            "r1": _snap(p50_ms=110.0, p99_ms=120.0),
            "r2": _snap(p50_ms=150.0, p99_ms=900.0),
        },
        {"r0": "in", "r1": "in", "r2": "in"},
        straggler_factor=2.0,
        down_weight=0.5,
    )
    assert view["straggler"]["verdict"] is True
    assert view["straggler"]["name"] == "r2"
    assert view["replicas"]["r2"]["straggler"] is True
    assert view["replicas"]["r2"]["weight"] == pytest.approx(0.5)
    assert view["replicas"]["r0"]["weight"] == 1.0
    # fleet p50 is the median over routable replicas' request p50s
    assert view["fleet_p50_ms"] == pytest.approx(110.0)


def test_merge_fleet_drain_and_unreachable_leave_rotation():
    view = merge_fleet(
        {
            "r0": _snap(),
            "r1": _snap(reachable=False, ready=False, status="unreachable"),
            "r2": _snap(),
        },
        {"r0": "in", "r1": "in", "r2": "draining"},
        straggler_factor=2.0,
        down_weight=0.25,
    )
    assert view["routable"] == ["r0"]
    assert view["replicas"]["r1"]["routable"] is False
    assert view["replicas"]["r2"]["drain_state"] == "draining"
    assert view["replicas"]["r2"]["effective_load"] is None


def test_config_validates_route_knobs():
    Config(phase="route")  # route is a legal phase
    with pytest.raises(ValueError):
        Config(route_num_replicas=0)
    with pytest.raises(ValueError):
        Config(route_hysteresis=-0.1)
    with pytest.raises(ValueError):
        Config(route_down_weight=0.0)  # zero would blackhole
    with pytest.raises(ValueError):
        Config(route_down_weight=1.5)
    with pytest.raises(ValueError):
        Config(route_poll_interval_s=0.0)
    with pytest.raises(ValueError):
        Config(route_upstream_timeout_s=0.0)


def test_parse_endpoints_names_and_failfast():
    eps = parse_endpoints("127.0.0.1:9000, 127.0.0.1:9001")
    assert [(e.name, e.port) for e in eps] == [("r0", 9000), ("r1", 9001)]
    with pytest.raises(ValueError):
        parse_endpoints("127.0.0.1")  # no port
    with pytest.raises(ValueError):
        parse_endpoints("host:notaport")
    with pytest.raises(ValueError):
        parse_endpoints(",")


def test_cli_route_flags():
    from sat_tpu.cli import build_config

    config, _ = build_config(
        ["--phase=route", "--num_replicas=3", "--port=0"]
    )
    assert config.phase == "route"
    assert config.route_num_replicas == 3
    assert config.route_port == 0  # --port binds the router in route phase

    # naming endpoints implies the route phase
    config, _ = build_config(
        ["--replicas=127.0.0.1:9000,127.0.0.1:9001", "--port=8801"]
    )
    assert config.phase == "route"
    assert config.route_replicas == "127.0.0.1:9000,127.0.0.1:9001"
    assert config.route_port == 8801


# ---------------------------------------------------------------------------
# Scripted stub replicas: retry / shed / drain against real sockets
# ---------------------------------------------------------------------------


class StubReplica:
    """A scripted CaptionServer stand-in: /healthz and /stats serve
    mutable dicts, /caption replies with a scripted status, and every
    X-Request-Id seen is recorded — enough surface for the router's
    poller, proxy and drain machinery without a jax engine."""

    def __init__(self, name):
        self.name = name
        self.health = {
            "ready": True,
            "status": "ok",
            "queue_depth": 0,
            "in_flight": 0,
            "serve_mode": "batch",
        }
        self.stats = {
            "latency_ms": {"serve/request": {"p50": 100.0, "p99": 150.0}},
            "compiles_since_ready": 0,
        }
        self.caption_status = 200
        self.retry_after = "7"  # the per-replica hint the router ignores
        self.seen_rids = []
        self.seen_paths = []
        self.seen_ctypes = []
        stub = self

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    code = 200 if stub.health.get("ready") else 503
                    self._reply(code, dict(stub.health))
                elif self.path == "/stats":
                    self._reply(200, dict(stub.stats))
                else:
                    self._reply(404, {"error": "nope"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                self.rfile.read(length)
                rid = self.headers.get(tracectx.TRACE_HEADER)
                stub.seen_rids.append(rid)
                stub.seen_paths.append(self.path)
                stub.seen_ctypes.append(self.headers.get("Content-Type"))
                status = stub.caption_status
                if status == 429:
                    self._reply(
                        status,
                        {"error": "shed", "retry_after_ms": 7000},
                        headers={"Retry-After": stub.retry_after},
                    )
                elif status == 200:
                    self._reply(
                        status,
                        {"caption": f"stub from {stub.name}",
                         "request_id": rid},
                    )
                else:
                    self._reply(status, {"error": f"scripted {status}"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        self.port = self._httpd.server_address[1]

    @property
    def endpoint(self):
        return Endpoint(self.name, "127.0.0.1", self.port)

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()
        self._httpd = None


def _router_config(tmp_path, **kw):
    return Config(
        phase="route",
        summary_dir=str(tmp_path / "summary"),
        route_poll_interval_s=60.0,  # the tests drive poll_once() by hand
        route_stats_every=1,  # every hand-driven tick folds /stats in
        route_hysteresis=0.25,
        route_down_weight=0.25,
        **kw,
    )


@pytest.fixture()
def stub_pair(tmp_path):
    tel = telemetry.get()
    was_enabled = tel.enabled
    if not was_enabled:
        tel = telemetry.enable(capacity=8192)
    a, b = StubReplica("r0"), StubReplica("r1")
    router = Router(
        _router_config(tmp_path), [a.endpoint, b.endpoint]
    )
    router.poll_once()
    yield {"a": a, "b": b, "router": router, "tel": tel}
    a.stop()
    b.stop()
    router.shutdown()
    if not was_enabled:
        telemetry.disable()


def test_pick_follows_load_and_downweights_degraded(stub_pair):
    a, b, router = stub_pair["a"], stub_pair["b"], stub_pair["router"]
    # healthy idle pair: the pick sticks to one replica (hysteresis),
    # whichever it is
    first = router.pick()
    assert first in ("r0", "r1")
    assert router.pick() == first
    # load the picked one well beyond the band: the pick flips
    (a if first == "r0" else b).health["queue_depth"] = 9
    router.poll_once()
    flipped = router.pick()
    assert flipped != first
    # degrade the new pick with the other still deep: degraded-idle
    # (1/0.25 = 4) still beats healthy-deep (10) — down-weighted, not
    # blackholed
    (a if flipped == "r0" else b).health["status"] = "degraded"
    router.poll_once()
    assert router.pick() == flipped


def test_burst_picks_stay_balanced_despite_hysteresis(stub_pair):
    # a burst between poll ticks is balanced by the router's own
    # outstanding counts: the hysteresis band damps polled-view noise
    # but must never let the sticky replica run ahead on exact local
    # bookkeeping (it would otherwise take (1+hysteresis)x the work)
    router = stub_pair["router"]
    counts = {"r0": 0, "r1": 0}
    for _ in range(16):
        name = router.pick()
        router._note_outstanding(name, +1)
        counts[name] += 1
    assert abs(counts["r0"] - counts["r1"]) <= 1, counts


def test_single_retry_on_refused_lands_on_other_replica(stub_pair):
    a, b, router, tel = (
        stub_pair["a"], stub_pair["b"], stub_pair["router"], stub_pair["tel"]
    )
    # make r1 the clear pick, then kill it without telling the poller —
    # the forward hits a dead socket and must retry on r0 exactly once
    a.health["queue_depth"] = 9
    router.poll_once()
    assert router.pick() == "r1"
    b.stop()
    before = tel.counters().get("route/retries", 0)
    status, data, _, headers = router.proxy_caption(b"img", "rid-retry-1")
    assert status == 200
    assert json.loads(data)["caption"] == "stub from r0"
    assert headers.get("X-Routed-Retry") == "1"
    assert headers.get("X-Routed-Replica") == "r0"
    assert tel.counters().get("route/retries", 0) == before + 1
    assert a.seen_rids == ["rid-retry-1"]  # the SAME rid crossed the hop
    # the failed socket marked r1 unreachable immediately (no poll wait)
    assert router.view()["replicas"]["r1"]["reachable"] is False


def test_both_replicas_refused_is_502_with_hint(stub_pair):
    a, b, router = stub_pair["a"], stub_pair["b"], stub_pair["router"]
    a.stop()
    b.stop()
    status, data, _, headers = router.proxy_caption(b"img", "rid-down-1")
    assert status == 502
    assert int(headers["Retry-After"]) >= 1  # never 0s
    payload = json.loads(data)
    assert payload["request_id"] == "rid-down-1"
    # once the poller catches up, the edge sheds 503 before forwarding
    router.poll_once()
    status, _, _, headers = router.proxy_caption(b"img", "rid-down-2")
    assert status == 503
    assert int(headers["Retry-After"]) >= 1


def test_coherent_shed_uses_fleet_p50_not_replica_hint(stub_pair):
    a, b, router = stub_pair["a"], stub_pair["b"], stub_pair["router"]
    for stub in (a, b):
        stub.caption_status = 429
        stub.retry_after = "19"  # per-replica hint the edge must override
        stub.stats["latency_ms"]["serve/request"] = {
            "p50": 2400.0, "p99": 3000.0,
        }
    router.poll_once()
    status, data, _, headers = router.proxy_caption(b"img", "rid-shed-1")
    assert status == 429
    # ceil(fleet p50 2.4s) = 3s — coherent across whichever replica shed
    assert headers["Retry-After"] == "3"
    payload = json.loads(data)
    assert payload["retry_after_ms"] == 3000
    assert payload["request_id"] == "rid-shed-1"
    # both replicas were tried (the single retry applies to sheds too)
    assert len(a.seen_rids) + len(b.seen_rids) == 2


def test_drain_sequencing_one_at_a_time(stub_pair):
    a, b, router = stub_pair["a"], stub_pair["b"], stub_pair["router"]
    status, payload = router.start_drain("r1")
    assert status == 200
    assert payload["mechanism"] == "hold-out"  # endpoint-mode replica
    # one at a time: a second drain is refused while r1 is in flight
    status, payload = router.start_drain("r0")
    assert status == 409
    assert payload["draining"] == "r1"
    # draining replicas leave rotation immediately
    assert router.view()["routable"] == ["r0"]
    status, _ = router.start_drain("r1")
    assert status == 409  # already draining
    status, _ = router.start_drain("nope")
    assert status == 404
    # observed idle + not ready -> drained; then ready again -> rotation
    b.health.update(ready=False, queue_depth=0, in_flight=0)
    router.poll_once()
    assert router.view()["replicas"]["r1"]["drain_state"] == "drained"
    b.health["ready"] = True
    router.poll_once()
    assert router.view()["replicas"]["r1"]["drain_state"] == "in"
    assert router.view()["routable"] == ["r0", "r1"]
    # undrain is only for held-out replicas
    status, _ = router.undrain("r1")
    assert status == 409


def test_proactive_shed_at_configured_depth(stub_pair, tmp_path):
    a, b = stub_pair["a"], stub_pair["b"]
    router = Router(
        _router_config(tmp_path / "shed", route_shed_depth=4),
        [a.endpoint, b.endpoint],
    )
    a.health["queue_depth"] = 4
    b.health["queue_depth"] = 5
    router.poll_once()
    status, _, _, headers = router.proxy_caption(b"img", "rid-depth-1")
    assert status == 429
    assert int(headers["Retry-After"]) >= 1
    assert a.seen_rids == [] and b.seen_rids == []  # no forwarding
    # one replica with room is enough to route again
    a.health["queue_depth"] = 0
    router.poll_once()
    status, _, _, _ = router.proxy_caption(b"img", "rid-depth-2")
    assert status == 200
    router.shutdown()


def test_tiered_fleet_two_hops_passthrough_and_starved_shed(tmp_path):
    """Disaggregated routing against scripted stubs: an image request
    makes two hops (/encode on the encode tier, then the grid body to
    /caption on the decode tier); a client-supplied grid skips hop one;
    a starved tier sheds 429 (scope=tier), never a 5xx."""
    from sat_tpu.serve.handoff import GRID_CONTENT_TYPE

    enc, dec = StubReplica("r0"), StubReplica("r1")
    enc.health["tier"] = "encode"
    dec.health["tier"] = "decode"
    router = Router(
        _router_config(tmp_path), [enc.endpoint, dec.endpoint]
    )
    try:
        router.poll_once()
        view = router.view()
        assert view["routable_encode"] == ["r0"]
        assert view["routable_decode"] == ["r1"]
        # image in: encode hop mints the grid, decode hop captions it
        status, _body, _ct, headers = router.proxy_caption(
            b"img", "rid-tier-1", content_type="image/jpeg"
        )
        assert status == 200
        assert enc.seen_paths == ["/encode"]
        assert dec.seen_paths == ["/caption"]
        assert dec.seen_ctypes == [GRID_CONTENT_TYPE]
        assert headers.get("X-Routed-Encode-Replica") == "r0"
        assert headers.get("X-Routed-Replica") == "r1"
        # rid propagates across BOTH hops (trace stitching)
        assert enc.seen_rids == ["rid-tier-1"]
        assert dec.seen_rids == ["rid-tier-1"]
        # a client-supplied grid goes straight to the decode tier
        status, _b, _c, _h = router.proxy_caption(
            b"frame", "rid-tier-2", content_type=GRID_CONTENT_TYPE
        )
        assert status == 200
        assert enc.seen_paths == ["/encode"]  # untouched
        assert dec.seen_paths == ["/caption", "/caption"]
        # encode tier gone: image traffic sheds coherently (429, scope
        # tier — capacity returns on respawn), grids still flow
        enc.health["ready"] = False
        router.poll_once()
        status, _b, _c, headers = router.proxy_caption(
            b"img", "rid-tier-3", content_type="image/jpeg"
        )
        assert status == 429
        assert headers["X-Shed-Scope"] == "tier"
        status, _b, _c, _h = router.proxy_caption(
            b"frame", "rid-tier-4", content_type=GRID_CONTENT_TYPE
        )
        assert status == 200
        # healthz/stats carry the tier split for operators
        payload, _code = router.healthz()
        assert payload["replicas_encode"] == 0
        assert payload["replicas_decode"] == 1
        assert router.stats()["routable_decode"] == ["r1"]
    finally:
        router.shutdown()
        enc.stop()
        dec.stop()


# ---------------------------------------------------------------------------
# End-to-end: two real CaptionServers behind a real router
# ---------------------------------------------------------------------------


_SENTENCES = [
    "a man rides a horse .",
    "a dog runs on the grass .",
    "two people walk along the beach .",
    "a plate of food sits on the table .",
]


def _jpeg(size):
    import cv2

    rng = np.random.default_rng(7)
    img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
    ok, buf = cv2.imencode(".jpg", img)
    assert ok
    return bytes(buf)


@pytest.fixture(scope="module")
def router_fleet(tmp_path_factory):
    """Fresh tiny params saved through checkpoint+lineage, loaded by TWO
    in-process CaptionServers (separate summary dirs -> separate
    access.jsonl), fronted by a real Router HTTP server."""
    import jax

    from sat_tpu import runtime
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    root = tmp_path_factory.mktemp("router_e2e")
    vocab_file = str(root / "vocabulary.csv")
    vocabulary = Vocabulary(size=50)
    vocabulary.build(_SENTENCES)
    vocabulary.save(vocab_file)
    config = Config(
        phase="serve",
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        compute_dtype="float32",
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        beam_size=2,
        save_dir=str(root / "models"),
        summary_dir=str(root / "summary"),
        serve_buckets=(1, 4),
        serve_max_batch=4,
        serve_max_wait_ms=10.0,
        serve_queue_depth=16,
        heartbeat_interval=0.0,
    )
    os.makedirs(config.save_dir, exist_ok=True)
    tel = telemetry.enable(capacity=1 << 16)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))

    servers = []
    for i in range(2):
        rcfg = config.replace(
            summary_dir=str(root / f"r{i}" / "summary")
        )
        rstate, _ = load_serving_state(rcfg)
        engine = ServeEngine(rcfg, rstate, vocabulary, tel=tel)
        engine.warmup()
        servers.append(CaptionServer(rcfg, engine, port=0).start())
    endpoints = [
        Endpoint(f"r{i}", "127.0.0.1", s.port)
        for i, s in enumerate(servers)
    ]
    route_cfg = config.replace(
        phase="route",
        summary_dir=str(root / "router" / "summary"),
        route_poll_interval_s=0.1,
        route_stats_every=2,
    )
    router = Router(route_cfg, endpoints, port=0).start()
    yield {
        "router": router,
        "servers": servers,
        "tel": tel,
        "root": root,
        "config": config,
    }
    router.shutdown()
    for s in servers:
        s.shutdown()
    telemetry.disable()


def _http(port, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body,
        headers=headers or {},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _hop_records(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_e2e_routes_with_rid_stitching_and_zero_recompiles(router_fleet):
    router = router_fleet["router"]
    tel = router_fleet["tel"]
    root = router_fleet["root"]
    jpeg = _jpeg(router_fleet["config"].image_size)
    port = router.port

    status, headers, health = _http(port, "GET", "/healthz")
    assert status == 200
    assert health["role"] == "router"
    assert health["replicas_routable"] == 2
    assert health["serve_mode"] == "batch"
    assert "queue_depth" in health and "in_flight" in health

    # first post pays the host-side first-touch costs
    status, _, _ = _http(
        port, "POST", "/caption", jpeg,
        {"Content-Type": "image/jpeg"},
    )
    assert status == 200

    compiles0 = tel.counters().get("jax/compiles", 0)
    rids = [f"rid-e2e-{i}" for i in range(4)]
    for rid in rids:
        status, headers, payload = _http(
            port, "POST", "/caption", jpeg,
            {"Content-Type": "image/jpeg", tracectx.TRACE_HEADER: rid},
        )
        assert status == 200
        assert headers[tracectx.TRACE_HEADER] == rid
        assert headers["X-Routed-Replica"] in ("r0", "r1")
        assert payload["request_id"] == rid  # replica echoed OUR id
        assert payload["captions"][0]["caption"]
    # steady state: the warmed buckets absorb every shape
    assert tel.counters().get("jax/compiles", 0) == compiles0

    # the hop stitches: each rid appears in the router's own access log
    # AND in exactly one replica's access log
    router_log = _hop_records(
        str(root / "router" / "summary" / "telemetry" / "access.jsonl")
    )
    replica_logs = {
        f"r{i}": _hop_records(
            str(root / f"r{i}" / "summary" / "telemetry" / "access.jsonl")
        )
        for i in range(2)
    }
    for rid in rids:
        hops = [r for r in router_log if r["trace_id"] == rid]
        assert len(hops) == 1 and hops[0]["hop"] == "route"
        assert hops[0]["status"] == 200
        served_by = [
            name
            for name, records in replica_logs.items()
            if any(r.get("trace_id") == rid for r in records)
        ]
        assert len(served_by) == 1
        # the router recorded the same replica the trace landed on
        assert hops[0]["replica"] == served_by[0]


def test_e2e_stats_and_metrics_surfaces(router_fleet):
    router = router_fleet["router"]
    port = router.port
    status, _, stats = _http(port, "GET", "/stats")
    assert status == 200
    assert stats["role"] == "router"
    assert set(stats["replicas"]) == {"r0", "r1"}
    assert stats["counters"].get("route/requests", 0) > 0
    assert "route/request" in stats["latency_ms"]
    assert "route/overhead" in stats["latency_ms"]
    req = urllib.request.Request(f"http://127.0.0.1:{port}/metrics")
    with urllib.request.urlopen(req, timeout=30) as r:
        text = r.read().decode()
    assert 'sat_gauge{name="route/replicas_routable"} 2' in text
    assert 'name="route/requests"' in text
