"""Per-request device-cost attribution and per-tenant metering.

PR 17 gave every tenant a quota and an SLO lane; this module answers the
question none of that can: *what does a tenant actually cost*.  Shared
device work makes the naive answer wrong in both serving modes — encode
lanes batch several requests into one power-of-two dispatch, and a fused
``decode_multi_step`` window advances every live slot under ONE dispatch
— so device time must be *attributed*, not measured per request.

The attribution rules (docs/OBSERVABILITY.md has the full table):

* **encode** — each request in an encode-lane chunk is charged an equal
  share of that chunk's measured device window (``dur / chunk_len``; the
  padded lane slots are burned capacity, tracked separately for the lane
  -fill gauge, not billed to anyone).
* **decode** — each *live* slot riding a fused decode window is charged
  an equal share of the window (``dur / n_live``), per dispatch.  A
  request that rides 10 windows at different pool fills accumulates 10
  different shares — exactly the marginal cost of keeping its slot hot.
* **occupancy** — admission→retire wall time: the HBM-seconds the
  request's slot (KV pages, beam state) was held.  Not device compute;
  the sizing signal for the paged slot heap (ROADMAP item 3).
* **queue / detok** — host-side phases, lifted from the request's
  existing trace phases (no new timing).

Every charge happens on a host-side boundary that is *already* synced
and telemetry-gated (the same ``# sync-ok`` windows the serve spans use),
so metering adds zero device syncs and no steady-state recompiles.

The per-tenant roll-up lands in three places: a torn-tolerant
``metering.jsonl`` ledger (cumulative rows through ``rotating_append`` —
a torn tail costs one snapshot, never the ledger; readers keep the last
parseable row per tenant), the server's ``/stats`` ``tenants_cost``
block, and float telemetry counters that ``promtext`` exports to
``/metrics`` for free (and the router fans in fleet-wide).

Deliberately jax-free, like the rest of ``sat_tpu/telemetry``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from sat_tpu.telemetry.exporters import rotating_append, run_id

# Ledger rows are cumulative snapshots, not deltas: replaying a ledger
# needs only the LAST full row per tenant, so a torn tail (kill -9 mid
# append) costs one snapshot of staleness, never a double-count.
LEDGER_SCHEMA = 1

# Cost fields accumulated per tenant, in the order /stats reports them.
_FIELDS = (
    "requests",
    "errors",
    "encode_ms",
    "decode_ms",
    "device_ms",
    "occupancy_ms",
    "queue_ms",
    "detok_ms",
    "decode_steps",
    "dispatches",
)


class RequestCost(object):
    """Mutable per-request cost accumulator, attached to a request at
    submit and charged to its tenant at the terminal funnel.

    Attribution sites mutate it via plain adds on already-synced,
    telemetry-gated boundaries (slot_pool encode chunks, batcher decode
    windows) — no locks: a request's cost is only ever touched by the
    single thread driving its current phase."""

    __slots__ = (
        "encode_ns",
        "decode_ns",
        "occupancy_ns",
        "decode_steps",
        "dispatches",
    )

    def __init__(self) -> None:
        self.encode_ns = 0
        self.decode_ns = 0
        self.occupancy_ns = 0
        self.decode_steps = 0
        self.dispatches = 0

    def add_encode(self, dur_ns: int) -> None:
        self.encode_ns += int(dur_ns)

    def add_decode(self, dur_ns: int, steps: int = 0) -> None:
        self.decode_ns += int(dur_ns)
        self.decode_steps += int(steps)
        self.dispatches += 1

    def set_occupancy(self, dur_ns: int) -> None:
        self.occupancy_ns = int(dur_ns)

    @property
    def device_ns(self) -> int:
        return self.encode_ns + self.decode_ns

    def as_dict(self) -> Dict[str, float]:
        """ms-denominated view for access.jsonl / API responses."""
        return {
            "encode_ms": round(self.encode_ns / 1e6, 4),
            "decode_ms": round(self.decode_ns / 1e6, 4),
            "device_ms": round(self.device_ns / 1e6, 4),
            "occupancy_ms": round(self.occupancy_ns / 1e6, 3),
            "decode_steps": int(self.decode_steps),
            "dispatches": int(self.dispatches),
        }


class MeteringLedger(object):
    """Per-tenant cost roll-up + torn-tolerant JSONL sink.

    ``charge()`` is called once per request from the server's terminal
    funnel — a dict update under one small lock (the same cost profile
    as a telemetry counter tick), then a rate-limited flush: at most one
    ledger append burst per ``flush_interval_s``, so the sink costs
    nothing measurable per request."""

    def __init__(
        self,
        path: str = "",
        cap_bytes: int = 0,
        tel=None,
        flush_interval_s: float = 5.0,
        clock=time.monotonic,
    ) -> None:
        self._path = path
        self._cap_bytes = int(cap_bytes)
        self._tel = tel
        self._interval = float(flush_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: Dict[str, Dict[str, float]] = {}
        self._t_flush = clock()
        self._dirty = False

    # -- write side --------------------------------------------------------

    def charge(
        self,
        tenant: str,
        cost: Optional[RequestCost] = None,
        queue_ms: float = 0.0,
        detok_ms: float = 0.0,
        error: bool = False,
    ) -> None:
        """Fold one finished request into its tenant's totals."""
        t = tenant or "default"
        enc = cost.encode_ns / 1e6 if cost is not None else 0.0
        dec = cost.decode_ns / 1e6 if cost is not None else 0.0
        occ = cost.occupancy_ns / 1e6 if cost is not None else 0.0
        with self._lock:
            row = self._tenants.get(t)
            if row is None:
                row = self._tenants[t] = dict.fromkeys(_FIELDS, 0.0)
            row["requests"] += 1
            row["errors"] += 1 if error else 0
            row["encode_ms"] += enc
            row["decode_ms"] += dec
            row["device_ms"] += enc + dec
            row["occupancy_ms"] += occ
            row["queue_ms"] += float(queue_ms)
            row["detok_ms"] += float(detok_ms)
            if cost is not None:
                row["decode_steps"] += cost.decode_steps
                row["dispatches"] += cost.dispatches
            self._dirty = True
        if self._tel is not None and self._tel.enabled:
            # Float counters ride the existing promtext export, so every
            # tenant's cumulative cost appears on /metrics with no new
            # exposition machinery (dimension-on-the-name, house style).
            self._tel.count("metering/%s/requests" % t)
            self._tel.count("metering/%s/device_ms" % t, enc + dec)
            self._tel.count("metering/%s/occupancy_ms" % t, occ)
        self.maybe_flush()

    def maybe_flush(self, force: bool = False) -> None:
        """Append one cumulative row per tenant, at most once per
        interval.  Failures degrade inside ``rotating_append``."""
        if not self._path:
            return
        now = self._clock()
        with self._lock:
            if not self._dirty:
                return
            if not force and now - self._t_flush < self._interval:
                return
            self._t_flush = now
            self._dirty = False
            rows = [
                dict(v, tenant=k, schema=LEDGER_SCHEMA, run_id=run_id(),
                     wall_time=round(time.time(), 3))
                for k, v in sorted(self._tenants.items())
            ]
        for row in rows:
            rotating_append(
                self._path, json.dumps(row), self._cap_bytes, tel=self._tel
            )

    # -- read side ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """{tenant: totals} with ms fields rounded — the /stats block."""
        with self._lock:
            out = {}
            for t, row in sorted(self._tenants.items()):
                out[t] = {
                    k: (round(v, 3) if k.endswith("_ms") else int(v))
                    for k, v in row.items()
                }
            return out

    def attributed_device_ms(self) -> float:
        """Sum of encode+decode ms attributed across all tenants — the
        left side of the accounting identity."""
        with self._lock:
            return sum(r["device_ms"] for r in self._tenants.values())


def read_ledger(path: str) -> List[Dict]:
    """Parse a metering ledger, torn-tail tolerant: unparsable lines
    (a half-written tail after kill -9, a corrupted block) are skipped,
    never fatal.  Reads the single ``.1`` rollover first so rows come
    back oldest-first across the rotation boundary."""
    rows: List[Dict] = []
    for p in (path + ".1", path):
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(row, dict) and "tenant" in row:
                        rows.append(row)
        except OSError:
            continue
    return rows


def latest_totals(rows: List[Dict]) -> Dict[str, Dict]:
    """Last full cumulative row per tenant — how a billing job replays
    the ledger (later rows supersede earlier ones, so a dropped tail
    only loses recency, never correctness)."""
    out: Dict[str, Dict] = {}
    for row in rows:
        out[str(row["tenant"])] = row
    return out


# Span names whose aggregate totals ARE the measured device-busy windows
# the attributor splits: continuous mode records serve/encode per lane
# chunk and serve/step per fused window; batch mode records serve/encode
# per dispatch and serve/decode_window per drained batch.  Only one mode
# runs per server, so summing all three never double-counts.
BUSY_SPANS = ("serve/encode", "serve/step", "serve/decode_window")


def measured_busy_ms(tel) -> float:
    """Engine busy time (ms) from span aggregates — the right side of
    the accounting identity (attributed ≈ measured within ±5%)."""
    agg = tel.aggregates()
    return sum(agg[n][1] / 1e6 for n in BUSY_SPANS if n in agg)
