"""Compile-time XLA cost/memory accounting → ``compile_report.json``.

Step time tells you a PR got slower; it cannot tell you *why*.  XLA
already knows: every compiled executable carries a cost analysis (FLOPs,
bytes accessed, transcendentals) and a memory analysis (temp / argument
/ output / alias HBM bytes).  This module snapshots those per jitted
function — ``train_step``, the eval encoder, the beam program — into one
JSON artifact per run, so the regression gate
(``scripts/check_regression.py``) can catch a silent FLOP or HBM
regression even when wall-clock noise hides it, and a post-mortem can
answer "did the working set grow" without a profiler window.

``analyze()`` uses the AOT path (``fn.lower(*args).compile()``) *before*
the loop's first dispatch: lowering against live arguments does not
consume donated buffers, and the lower/compile caches (plus the
persistent compile cache ``__graft_entry__`` enables) are shared with
the normal call path, so the real first step reuses the executable
instead of compiling twice.

Like ``device.py`` this module imports jax and is therefore NOT imported
eagerly by the package ``__init__`` (the core telemetry package stays
jax-free); runtime imports it directly and only when telemetry is on.
Every probe degrades: a backend without ``memory_analysis`` (CPU) just
leaves those fields null, and no failure here may take the run down.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional

from ..utils.fileio import atomic_write
from . import SCHEMA_VERSION, run_id

# per-run accumulator: reset by runtime._telemetry_begin, written by
# runtime._telemetry_finish — one entry per analyzed jitted function
_entries: Dict[str, Dict[str, Any]] = {}

_COST_KEYS = ("flops", "transcendentals", "bytes accessed")
_MEMORY_ATTRS = (
    "temp_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def reset() -> None:
    _entries.clear()


def entries() -> Dict[str, Dict[str, Any]]:
    return dict(_entries)


def _arg_bytes(args, kwargs) -> Optional[int]:
    """Host-side argument footprint from shape/dtype metadata only (valid
    even for donated buffers — metadata survives donation)."""
    import jax

    total = 0
    try:
        for leaf in jax.tree_util.tree_leaves((args, kwargs)):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            total += n * getattr(dtype, "itemsize", 0)
        return int(total)
    except Exception:
        return None


def analyze(name: str, jitted, *args, tel=None, **kwargs) -> Optional[Dict]:
    """AOT lower+compile ``jitted`` on ``args``' shapes and record its
    cost/memory/donation facts under ``name``.  Never raises; returns the
    entry dict (None when the probe failed).  Safe to call with live
    donated arguments — lowering reads only avals."""
    t0 = time.perf_counter()
    try:
        lowered = jitted.lower(*args, **kwargs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    except Exception as e:
        print(
            f"sat_tpu: compile accounting skipped for {name}: {e}",
            file=sys.stderr,
            flush=True,
        )
        return None

    entry: Dict[str, Any] = {
        "lower_seconds": round(t1 - t0, 3),
        "compile_seconds": round(t2 - t1, 3),
        "argument_bytes_host_estimate": _arg_bytes(args, kwargs),
        "cost": None,
        "memory": None,
        "donation": None,
    }

    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):       # per-device list on older jax
            ca = ca[0] if ca else None
        if ca:
            entry["cost"] = {
                k.replace(" ", "_"): float(ca[k]) for k in _COST_KEYS if k in ca
            }
    except Exception:
        pass

    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            mem = {}
            for attr in _MEMORY_ATTRS:
                v = getattr(ma, attr, None)
                if v is not None:
                    mem[attr.replace("_size_in_bytes", "_bytes")] = int(v)
            entry["memory"] = mem or None
    except Exception:
        pass  # CPU backends may not implement memory analysis

    try:
        import jax

        infos = jax.tree_util.tree_leaves(lowered.args_info)
        donated = sum(1 for i in infos if getattr(i, "donated", False))
        entry["donation"] = {"donated_args": donated, "total_args": len(infos)}
    except Exception:
        pass

    _entries[name] = entry
    if tel is not None and getattr(tel, "enabled", False):
        cost = entry.get("cost") or {}
        mem = entry.get("memory") or {}
        if "flops" in cost:
            tel.gauge(f"xla/{name}/gflops", round(cost["flops"] / 1e9, 3))
        if "temp_bytes" in mem:
            tel.gauge(f"xla/{name}/temp_mb", round(mem["temp_bytes"] / 2**20, 2))
        tel.gauge(f"xla/{name}/compile_s", entry["compile_seconds"])
    return entry


def report() -> Optional[Dict[str, Any]]:
    """The compile_report.json document (None when nothing was analyzed)."""
    if not _entries:
        return None
    doc: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "run_id": run_id(),
        "time_unix": round(time.time(), 3),
        "functions": dict(_entries),
    }
    try:
        if "jax" in sys.modules:  # never trigger backend init from here
            jax = sys.modules["jax"]
            doc["backend"] = jax.default_backend()
            doc["device_kind"] = jax.local_devices()[0].device_kind
    except Exception:
        pass
    return doc


def write_report(path: str) -> Optional[str]:
    """Atomically write the report; returns the path (None when empty or
    the write failed — warned, never raised)."""
    doc = report()
    if doc is None:
        return None
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        atomic_write(path, "w", lambda f: json.dump(doc, f, indent=1))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: compile report export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def format_summary() -> Optional[str]:
    """One human line per analyzed function for the end-of-run printout."""
    if not _entries:
        return None
    lines = ["compile report:"]
    for name, e in _entries.items():
        cost = e.get("cost") or {}
        mem = e.get("memory") or {}
        parts = [f"  {name:<18} compile {e['compile_seconds']:.2f}s"]
        if "flops" in cost:
            parts.append(f"{cost['flops'] / 1e9:.3f} GFLOP/call")
        if "temp_bytes" in mem:
            parts.append(f"temp {mem['temp_bytes'] / 2**20:.1f} MB")
        if "output_bytes" in mem:
            parts.append(f"out {mem['output_bytes'] / 2**20:.1f} MB")
        don = e.get("donation")
        if don and don.get("donated_args"):
            parts.append(f"donated {don['donated_args']}/{don['total_args']} args")
        lines.append("  ".join(parts))
    return "\n".join(lines)
