"""On-device batched beam search.

The reference decodes with a host-side Python loop: ~beam_size × 20
sess.run round-trips per batch, a heap rebuilt between each
(/root/reference/base_model.py:163-240).  Here the whole search is ONE
compiled XLA program: a ``lax.scan`` over time carrying ``[batch, beam]``
states, so a batch of images decodes in a single device dispatch.  This is
the single biggest performance win over the reference (SURVEY.md §3.2).

Semantics preserved (the reference is the correctness oracle):
* a hypothesis completes when it emits the terminator token ('.' in the
  vocabulary, base_model.py:229-232) — completed captions include it;
* completed hypotheses accumulate in a per-image top-K set while partial
  beams keep expanding (the TopN pair, base_model.py:172-181);
* scores multiply raw next-word probabilities with no length
  normalization (base_model.py:224) — we carry log-probabilities, whose
  ordering is identical; reported scores are the same products;
* if nothing completed after max_caption_length steps, the partial beams
  are returned (base_model.py:236-237).

Deliberate upgrade: each step takes the global top-K over all beam×vocab
continuations (the eos column excluded from continuation) instead of the
reference's per-beam top-(K+1) heap pushes — a strictly-at-least-as-good
candidate set, computed as one ``lax.top_k`` on device.

Greedy decoding is the beam_size=1 special case of the same program.

Two drivers run the SAME expansion math (``_expand_step``):

* the monolithic ``run_search`` while_loop — one dispatch per batch, the
  offline/eval path and the serving correctness oracle;
* the resumable stepped decode (``decode_step`` over a ``SlotCarry``) —
  the serve engine's continuous-batching path, where each slot of a
  fixed-capacity pool advances independently, new requests are seeded
  into free slots between steps (``init_slots``) and finished slots are
  harvested the step their early-exit condition fires
  (``harvest_slots``).  Per-slot results are bitwise-identical to the
  monolithic search because both paths share one step body: a slot
  freezes the step it seals (all K finished slots filled and
  min(fin) ≥ max(live)) — from that step on the monolithic search can no
  longer alter that image's merged result either (a later completion
  scores ≤ max(live) ≤ min(fin), and ``lax.top_k`` tie-breaks toward the
  lower index, where the finished entries sit).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..config import Config
from ..models.decoder import (
    DecoderState,
    decoder_step,
    init_state,
    precompute_attend,
)

NEG_INF = -1e30
# Added to completed-caption scores when ranking them against live partial
# beams at the end of the search, so every completed caption outranks every
# partial one (scores are log-probs of ≤20 tokens, far above -1e6).
_FINISHED_RANK_BONUS = 1e6


class BeamResult(NamedTuple):
    """Per-image captions ranked finished-first (reference semantics:
    completed captions beat live partials, base_model.py:236-237), then by
    descending score within each group — so log_scores is NOT globally
    monotonic when a weak completed caption outranks a strong partial."""

    words: jnp.ndarray      # [B, K, T] int32 token ids ('.'-terminated)
    log_scores: jnp.ndarray  # [B, K] sum of log p(word) — product ordering
    lengths: jnp.ndarray    # [B, K] int32 number of emitted tokens
    # [B, K, T, N] per-word attention maps of each returned caption
    # (soft-attention α over the context grid at the step that emitted
    # word t); None unless return_alphas was set
    alphas: Optional[jnp.ndarray] = None
    # decode-loop iterations actually executed — the deterministic
    # observability probe for the early exit (None unless return_steps
    # was set, so the default output pytree — and the shard_map out_specs
    # built from it — is unchanged).  Scalar int32 from run_search;
    # per-slot [S] int32 from harvest_slots.
    steps_run: Optional[jnp.ndarray] = None


class SearchState(NamedTuple):
    """The pure search bookkeeping of ``B`` independent images — everything
    the expansion step reads/writes besides the decoder's LSTM state."""

    live_logp: jnp.ndarray    # [B, K] cumulative log-prob of live beams
    live_words: jnp.ndarray   # [B, K, T]
    live_len: jnp.ndarray     # [B, K]
    last_word: jnp.ndarray    # [B, K] input word of the NEXT step
    fin_logp: jnp.ndarray     # [B, K] finished top-K (NEG_INF = empty slot)
    fin_words: jnp.ndarray    # [B, K, T]
    fin_len: jnp.ndarray      # [B, K]
    live_alphas: jnp.ndarray  # [B, K, T, An] (An=0 unless return_alphas)
    fin_alphas: jnp.ndarray   # [B, K, T, An]


def _init_search(B: int, K: int, T: int, An: int) -> SearchState:
    # beam 0 alive at logp 0; others dead so step 0 expands a single beam
    return SearchState(
        live_logp=jnp.full((B, K), NEG_INF, jnp.float32).at[:, 0].set(0.0),
        live_words=jnp.zeros((B, K, T), jnp.int32),
        live_len=jnp.zeros((B, K), jnp.int32),
        last_word=jnp.zeros((B, K), jnp.int32),  # <start> = 0 (model.py:253)
        fin_logp=jnp.full((B, K), NEG_INF, jnp.float32),
        fin_words=jnp.zeros((B, K, T), jnp.int32),
        fin_len=jnp.zeros((B, K), jnp.int32),
        live_alphas=jnp.zeros((B, K, T, An), jnp.float32),
        fin_alphas=jnp.zeros((B, K, T, An), jnp.float32),
    )


def _expand_step(
    eos_id: int,
    K: int,
    V: int,
    An: int,
    valid_size: Optional[int],
    new_state: DecoderState,
    logits: jnp.ndarray,
    alpha: jnp.ndarray,
    t_vec: jnp.ndarray,
    s: SearchState,
):
    """One beam-expansion step over ``B`` independent rows — the single
    implementation both the monolithic while_loop and the stepped slot
    pool run (bitwise parity between the two paths is BY CONSTRUCTION).

    new_state/logits/alpha: the decoder step's outputs over the flattened
    [B*K] beam batch.  t_vec [B] int32: each row's own time index —
    per-row because pool slots run staggered; the monolithic driver
    passes the loop counter broadcast to all rows.  Time-indexed writes
    use a one-hot select over the T axis (value-identical to an
    ``.at[:, :, t].set``, which needs a scalar t).
    """
    B = s.live_logp.shape[0]
    T = s.live_words.shape[2]
    H = new_state.output.shape[-1]
    batch_idx = jnp.arange(B)[:, None]  # [B,1] for beam gathers
    t_hot = jnp.arange(T)[None, :] == t_vec[:, None]            # [B,T]

    step_alpha = alpha.reshape(B, K, -1)[:, :, :An]             # [B,K,An]
    if valid_size is not None and valid_size < V:
        logits = logits.at[:, valid_size:].set(NEG_INF)
    step_logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    step_logp = step_logp.reshape(B, K, V)
    logp = step_logp + s.live_logp[..., None]          # [B,K,V] cumulative

    # --- completions: an eos hypothesis only becomes a candidate when
    # eos is within its beam's top-(K+1) next words — the reference only
    # ever pushes words from that set (base_model.py:219-230), so junk
    # completions can't crowd out the partial-beam fallback.
    kth = jax.lax.top_k(step_logp, min(K + 1, V))[0][..., -1]   # [B,K]
    eos_allowed = step_logp[:, :, eos_id] >= kth
    eos_scores = jnp.where(eos_allowed, logp[:, :, eos_id], NEG_INF)  # [B,K]
    eos_words = jnp.where(t_hot[:, None, :], jnp.int32(eos_id), s.live_words)
    eos_len = s.live_len + 1
    # the eos word was emitted from THIS step's attention
    eos_alphas = jnp.where(
        t_hot[:, None, :, None], step_alpha[:, :, None, :], s.live_alphas
    )
    cand_logp = jnp.concatenate([s.fin_logp, eos_scores], axis=1)   # [B,2K]
    cand_words = jnp.concatenate([s.fin_words, eos_words], axis=1)  # [B,2K,T]
    cand_len = jnp.concatenate([s.fin_len, eos_len], axis=1)
    cand_alphas = jnp.concatenate([s.fin_alphas, eos_alphas], axis=1)
    top_fin, fin_sel = jax.lax.top_k(cand_logp, K)
    fin_logp = top_fin
    fin_words = cand_words[batch_idx, fin_sel]
    fin_len = cand_len[batch_idx, fin_sel]
    fin_alphas = cand_alphas[batch_idx, fin_sel]

    # --- continuations: global top-K over beam×vocab, eos excluded
    cont = logp.at[:, :, eos_id].set(NEG_INF).reshape(B, K * V)
    top_live, flat_sel = jax.lax.top_k(cont, K)            # [B,K]
    parent = flat_sel // V                                 # source beam
    word = (flat_sel % V).astype(jnp.int32)                # chosen token

    gather_bk = lambda x: x.reshape(B, K, -1)[batch_idx, parent]  # noqa: E731
    state = DecoderState(
        memory=gather_bk(new_state.memory).reshape(B * K, H),
        output=gather_bk(new_state.output).reshape(B * K, H),
        recurrent=gather_bk(new_state.recurrent).reshape(B * K, H),
    )
    live_words = jnp.where(
        t_hot[:, None, :], word[:, :, None], s.live_words[batch_idx, parent]
    )
    live_len = s.live_len[batch_idx, parent] + 1
    live_alphas = jnp.where(
        t_hot[:, None, :, None],
        step_alpha[batch_idx, parent][:, :, None, :],
        s.live_alphas[batch_idx, parent],
    )
    return state, SearchState(
        live_logp=top_live,
        live_words=live_words,
        live_len=live_len,
        last_word=word,
        fin_logp=fin_logp,
        fin_words=fin_words,
        fin_len=fin_len,
        live_alphas=live_alphas,
        fin_alphas=fin_alphas,
    )


def _sealed(fin_logp: jnp.ndarray, live_logp: jnp.ndarray) -> jnp.ndarray:
    """[B] bool: which rows' results can no longer change.  Cumulative
    scores are sums of log-probs, so a live beam's score can only FALL.
    Once a row has all K finished slots filled and its worst finished
    caption outranks its best live beam, no later step can alter its
    merged result (a new completion scores below min(fin) and the merge
    ranks finished first)."""
    return jnp.all(fin_logp > NEG_INF / 2, axis=1) & (
        fin_logp.min(axis=1) >= live_logp.max(axis=1)
    )


def _merge_results(
    s: SearchState,
    K: int,
    return_alphas: bool,
    steps: Optional[jnp.ndarray] = None,
) -> BeamResult:
    """Final ranking: completed captions first (the reference only falls
    back to partials when NOTHING completed, base_model.py:236-237); any
    fin slots that never filled are backfilled per-slot from the live
    partial beams instead of surfacing -inf junk rows."""
    B = s.live_logp.shape[0]
    batch_idx = jnp.arange(B)[:, None]
    fin_valid = s.fin_logp > NEG_INF / 2
    rank_key = jnp.concatenate(
        [
            jnp.where(fin_valid, s.fin_logp + _FINISHED_RANK_BONUS, NEG_INF),
            s.live_logp,
        ],
        axis=1,
    )                                                       # [B,2K]
    cand_logp = jnp.concatenate([s.fin_logp, s.live_logp], axis=1)
    cand_words = jnp.concatenate([s.fin_words, s.live_words], axis=1)
    cand_len = jnp.concatenate([s.fin_len, s.live_len], axis=1)
    _, sel = jax.lax.top_k(rank_key, K)                     # [B,K]
    alphas = None
    if return_alphas:
        cand_alphas = jnp.concatenate([s.fin_alphas, s.live_alphas], axis=1)
        alphas = cand_alphas[batch_idx, sel]
    return BeamResult(
        words=cand_words[batch_idx, sel],
        log_scores=cand_logp[batch_idx, sel],
        lengths=cand_len[batch_idx, sel],
        alphas=alphas,
        steps_run=steps,
    )


def run_search(
    config: Config,
    step_fn,
    state0: DecoderState,
    B: int,
    eos_id: int,
    beam_size: Optional[int] = None,
    max_len: Optional[int] = None,
    valid_size: Optional[int] = None,
    return_alphas: bool = False,
    alpha_width: Optional[int] = None,
    early_exit: bool = True,
    return_steps: bool = False,
) -> BeamResult:
    """The search engine shared by the single-device and context-parallel
    decode paths.

    step_fn(state, last_word [B*K] int32) -> (new_state, logits [B*K, V],
    alpha [B*K, Na]) — one decoder step over the flattened beam batch.
    state0: the per-image initial DecoderState already tiled to [B*K, H].
    alpha_width: Na of step_fn's alpha (the LOCAL context-block width
    under context parallelism); required when return_alphas is set.
    early_exit: stop the while_loop as soon as no image's result can
    change (see cond below) — exact, result-identical; False forces the
    full T steps (the A/B + testing control).
    """
    K = beam_size or config.beam_size
    T = max_len or config.max_caption_length
    V = config.vocabulary_size

    # per-step attention maps of every hypothesis; zero-width unless
    # requested, so the carry copies cost nothing in the default path
    if return_alphas and alpha_width is None:
        raise ValueError("return_alphas requires alpha_width")
    An = (alpha_width or 0) if return_alphas else 0
    search0 = _init_search(B, K, T, An)

    def body(loop_carry):
        t, (state, s) = loop_carry
        new_state, logits, alpha = step_fn(state, s.last_word.reshape(B * K))
        t_vec = jnp.full((B,), t, jnp.int32)
        state, s = _expand_step(
            eos_id, K, V, An, valid_size, new_state, logits, alpha, t_vec, s
        )
        return t + 1, (state, s)

    def cond(loop_carry):
        t, (_, s) = loop_carry
        if not early_exit:
            return t < T
        # Exact early exit (see _sealed).  Mean COCO captions run well
        # short of T=20 (reference filter ≤20, coco.py:323-339), so this
        # saves real decode steps with bit-identical results (pinned by
        # tests).
        return (t < T) & ~jnp.all(_sealed(s.fin_logp, s.live_logp))

    t_final, (_, search) = jax.lax.while_loop(
        cond, body, (jnp.int32(0), (state0, search0))
    )
    return _merge_results(
        search, K, return_alphas, steps=t_final if return_steps else None
    )


def tile_beams(x: jnp.ndarray, K: int) -> jnp.ndarray:
    """[B, ...] -> [B*K, ...] with each image's row repeated K times — the
    shared per-image tensors (context grid, hoisted projection, initial
    state) flattened to the search's [B*K] step batch."""
    B = x.shape[0]
    return jnp.broadcast_to(x[:, None], (B, K) + x.shape[1:]).reshape(
        (B * K,) + x.shape[1:]
    )


def beam_search(
    params,
    config: Config,
    contexts: jnp.ndarray,
    eos_id: int,
    beam_size: Optional[int] = None,
    max_len: Optional[int] = None,
    valid_size: Optional[int] = None,
    hoist_attention: bool = True,
    return_alphas: bool = False,
    early_exit: bool = True,
    return_steps: bool = False,
) -> BeamResult:
    """Decode captions for a batch of context grids.

    contexts: [B, N, D] float32 (encoder output).
    eos_id: vocabulary index of the '.' terminator token.
    valid_size: number of real vocabulary entries; logit columns beyond it
      are masked out.  The model's logit width is config.vocabulary_size,
      but a vocabulary built from a small corpus shrinks below that
      (reference vocabulary.py:25-26), leaving trailing logit columns with
      no word — the reference would index past its word list there.
    hoist_attention: precompute the context half of the attention MLP
      outside the decode loop (inference-exact; False keeps the
      step-by-step oracle path for testing).
    return_alphas: also carry each hypothesis's per-step attention maps
      through the search (the paper's per-word attention figures; neither
      the reference nor its upstream exposes them at decode time).

    The context-parallel twin of this wrapper (context grid sharded over
    the mesh's 'model' axis, distributed-softmax attend) is
    :func:`sat_tpu.parallel.context.cp_beam_search`; both plug their step
    function into the same :func:`run_search` engine.
    """
    K = beam_size or config.beam_size
    B, N, D = contexts.shape

    # one shared context grid per image, flattened to a [B*K] step batch
    ctx_tiled = tile_beams(contexts, K)

    # hoist the context half of the attention MLP out of the T×K loop
    # (loop-invariant at inference; the reference recomputes it every step)
    proj_tiled = None
    if hoist_attention:
        proj_tiled = tile_beams(precompute_attend(params, config, contexts), K)

    state0 = init_state(params, config, contexts, train=False)  # [B, H]
    state0 = DecoderState(*(tile_beams(s, K) for s in state0))

    def step_fn(state, last_word):
        return decoder_step(
            params, config, ctx_tiled, state, last_word,
            train=False, ctx_proj=proj_tiled,
        )

    return run_search(
        config, step_fn, state0, B, eos_id,
        beam_size=K, max_len=max_len, valid_size=valid_size,
        return_alphas=return_alphas, alpha_width=N, early_exit=early_exit,
        return_steps=return_steps,
    )


@partial(
    jax.jit,
    static_argnames=(
        "config", "eos_id", "beam_size", "max_len", "valid_size",
        "return_alphas", "early_exit", "return_steps",
    ),
)
def beam_search_jit(
    params, config, contexts, eos_id, beam_size=None, max_len=None,
    valid_size=None, return_alphas=False, early_exit=True, return_steps=False,
):
    return beam_search(
        params, config, contexts, eos_id, beam_size, max_len, valid_size,
        return_alphas=return_alphas, early_exit=early_exit,
        return_steps=return_steps,
    )


def greedy_decode(
    params,
    config: Config,
    contexts: jnp.ndarray,
    eos_id: int,
    max_len: Optional[int] = None,
    valid_size: Optional[int] = None,
    return_steps: bool = False,
) -> BeamResult:
    """Argmax decoding — the degenerate beam=1 case."""
    return beam_search(
        params, config, contexts, eos_id,
        beam_size=1, max_len=max_len, valid_size=valid_size,
        return_steps=return_steps,
    )


# ---------------------------------------------------------------------------
# Resumable stepped decode — the serve engine's continuous-batching path
# ---------------------------------------------------------------------------


class SlotCarry(NamedTuple):
    """Full resumable state of an S-slot decode pool.

    Every leaf has a fixed shape for a given pool geometry, so one AOT
    compile of each pool program (``init_slots`` / ``decode_step`` /
    ``retire_slots`` / ``harvest_slots``) serves the pool's whole
    lifetime — the serving zero-recompile guarantee extends to the
    stepped path unchanged.  Slots advance independently: ``t`` is each
    slot's own time index and ``alive`` its in-flight flag; inactive
    rows pass through every program untouched (one-hot selects only —
    no scatter at traced offsets anywhere).
    """

    ctx: jnp.ndarray        # [S*K, N, D] per-slot context grid, K-tiled
    ctx_proj: jnp.ndarray   # [S*K, N] or [S*K, N, da] hoisted attention
    state: DecoderState     # [S*K, H] LSTM carry
    search: SearchState     # [S, ...] beam bookkeeping
    t: jnp.ndarray          # [S] int32 per-slot time index
    alive: jnp.ndarray      # [S] bool — seeded and not yet finished


def init_slot_pool(
    config: Config,
    slots: int,
    beam_size: Optional[int] = None,
    max_len: Optional[int] = None,
    return_alphas: bool = False,
    alpha_width: Optional[int] = None,
) -> SlotCarry:
    """An empty pool: all slots dead, all state zeroed."""
    K = beam_size or config.beam_size
    T = max_len or config.max_caption_length
    N, D, H = config.num_ctx, config.dim_ctx, config.num_lstm_units
    An = (alpha_width or N) if return_alphas else 0
    S = int(slots)
    if config.num_attend_layers == 1:
        ctx_proj = jnp.zeros((S * K, N), jnp.float32)
    else:
        ctx_proj = jnp.zeros((S * K, N, config.dim_attend_layer), jnp.float32)
    return SlotCarry(
        ctx=jnp.zeros((S * K, N, D), jnp.float32),
        ctx_proj=ctx_proj,
        state=DecoderState(
            memory=jnp.zeros((S * K, H), jnp.float32),
            output=jnp.zeros((S * K, H), jnp.float32),
            recurrent=jnp.zeros((S * K, H), jnp.float32),
        ),
        search=_init_search(S, K, T, An),
        t=jnp.zeros((S,), jnp.int32),
        alive=jnp.zeros((S,), jnp.bool_),
    )


def init_slots(
    params,
    config: Config,
    carry: SlotCarry,
    lane_ctx: jnp.ndarray,
    slot_src: jnp.ndarray,
    admit_mask: jnp.ndarray,
    beam_size: Optional[int] = None,
) -> SlotCarry:
    """Seed slots anywhere in the pool from an encoded admission lane.

    lane_ctx: [L, N, D] — one encoder output per freshly admitted image
    (L is the lane width the encoder was compiled at, ≤ page_width).
    slot_src: [S] int32 — which lane row feeds each slot (gathered, so
    scattered free slots seed from one contiguous encode; rows of
    non-admitted slots are ignored — point them at 0).  admit_mask: [S]
    bool — True slots are (re)initialized to a fresh t=0 search over
    their lane context; False slots keep whatever state they held.

    The gather + full-pool select keeps this ONE compiled program per
    lane width regardless of which slots the host hands out, and the
    expensive encode runs at lane width while the cheap per-slot init
    (fc layers, beam bookkeeping) runs pool-wide.
    """
    K = beam_size or config.beam_size
    S = carry.t.shape[0]
    T = carry.search.live_words.shape[2]
    An = carry.search.live_alphas.shape[3]

    contexts = lane_ctx[slot_src]                               # [S, N, D]
    ctx_new = tile_beams(contexts, K)
    proj_new = tile_beams(precompute_attend(params, config, contexts), K)
    st = init_state(params, config, contexts, train=False)      # [S, H]
    st = DecoderState(*(tile_beams(x, K) for x in st))          # [S*K, H]
    fresh = _init_search(S, K, T, An)

    row_mask = jnp.repeat(admit_mask, K)                        # [S*K]

    def sel(new, old, mask):
        return jnp.where(
            mask.reshape(mask.shape + (1,) * (old.ndim - 1)), new, old
        )

    return SlotCarry(
        ctx=sel(ctx_new, carry.ctx, row_mask),
        ctx_proj=sel(proj_new, carry.ctx_proj, row_mask),
        state=DecoderState(
            *(sel(n, o, row_mask) for n, o in zip(st, carry.state))
        ),
        search=SearchState(
            *(sel(n, o, admit_mask) for n, o in zip(fresh, carry.search))
        ),
        t=sel(jnp.zeros((S,), jnp.int32), carry.t, admit_mask),
        alive=sel(jnp.ones((S,), jnp.bool_), carry.alive, admit_mask),
    )


def decode_step(
    params,
    config: Config,
    carry: SlotCarry,
    slot_mask: jnp.ndarray,
    eos_id: int,
    beam_size: Optional[int] = None,
    valid_size: Optional[int] = None,
) -> tuple:
    """Advance every active slot by one decode step.

    slot_mask: [S] bool — the host's view of which slots hold in-flight
    requests; a slot only advances when both slot_mask and carry.alive
    are set, so harvested-but-not-yet-reseeded slots stay frozen.

    Returns ``(carry, done)`` where done [S] bool flags slots that
    finished THIS step — sealed by the exact early-exit condition (same
    :func:`_sealed` the monolithic path uses) or out of time (t == T).
    The decoder runs over all S*K rows every step (dead rows compute
    garbage that one-hot selects discard); with bucket-sized pools this
    is the same arithmetic the monolithic batch spends on padding.
    """
    K = beam_size or config.beam_size
    S = carry.t.shape[0]
    T = carry.search.live_words.shape[2]
    V = config.vocabulary_size
    An = carry.search.live_alphas.shape[3]
    active = slot_mask & carry.alive                             # [S]
    row_active = jnp.repeat(active, K)                           # [S*K]

    # dead rows' stale carry state is garbage to the decoder: row_mask
    # zeroes their attention inside the (Pallas or XLA) attend so nothing
    # non-finite can arise there; their outputs are then discarded by the
    # selects below exactly as before.  Live rows are bitwise unchanged.
    new_state, logits, alpha = decoder_step(
        params, config, carry.ctx, carry.state,
        carry.search.last_word.reshape(S * K),
        train=False, ctx_proj=carry.ctx_proj, row_mask=row_active,
    )
    g_state, stepped = _expand_step(
        eos_id, K, V, An, valid_size, new_state, logits, alpha,
        carry.t, carry.search,
    )

    # freeze everything in non-active slots — including sealed ones, whose
    # results must hold bitwise until the host harvests them

    def sel_rows(new, old):
        return jnp.where(
            row_active.reshape((S * K,) + (1,) * (old.ndim - 1)), new, old
        )

    def sel_slot(new, old):
        return jnp.where(
            active.reshape((S,) + (1,) * (old.ndim - 1)), new, old
        )

    state = DecoderState(
        *(sel_rows(n, o) for n, o in zip(g_state, carry.state))
    )
    search = SearchState(
        *(sel_slot(n, o) for n, o in zip(stepped, carry.search))
    )
    t = jnp.where(active, carry.t + 1, carry.t)
    sealed = _sealed(search.fin_logp, search.live_logp)
    alive = jnp.where(active, ~sealed & (t < T), carry.alive)
    done = active & ~alive
    return (
        SlotCarry(
            ctx=carry.ctx, ctx_proj=carry.ctx_proj, state=state,
            search=search, t=t, alive=alive,
        ),
        done,
    )


def decode_multi_step(
    params,
    config: Config,
    carry: SlotCarry,
    slot_mask: jnp.ndarray,
    eos_id: int,
    k=1,
    beam_size: Optional[int] = None,
    valid_size: Optional[int] = None,
) -> tuple:
    """Advance the pool by up to ``k`` decode steps in ONE dispatch.

    The inner loop is a ``lax.while_loop`` whose body is *exactly*
    :func:`decode_step` — a slot that seals on inner iteration i drops
    ``alive`` and is excluded from every later iteration by the same
    ``slot_mask & alive`` gate the host-driven loop applies between
    dispatches, so K fused steps are bitwise-identical to K sequential
    ``decode_step`` dispatches (words, scores, alphas, per-slot ``t``).
    Done detection moves on-device: the accumulated ``done`` mask names
    every slot that sealed anywhere inside the window, and the host only
    harvests.  The loop early-exits when nothing is left active, so a
    pool that drains mid-window never burns the full K.

    ``k`` is a dynamic operand — ``lax.while_loop`` takes a traced
    bound, so ONE executable serves every ladder depth and the
    zero-recompile guarantee across the ladder is structural, not a
    warmed-lane-per-K inventory.  Returns ``(carry, done, steps_run)``
    with ``steps_run`` the number of inner iterations actually executed
    (``< k`` on early exit).
    """
    S = carry.t.shape[0]

    def cond(loop):
        i, c, _ = loop
        return (i < k) & jnp.any(slot_mask & c.alive)

    def body(loop):
        i, c, done_acc = loop
        c, done = decode_step(
            params, config, c, slot_mask, eos_id,
            beam_size=beam_size, valid_size=valid_size,
        )
        return (i + 1, c, done_acc | done)

    steps_run, carry, done = jax.lax.while_loop(
        cond, body, (jnp.int32(0), carry, jnp.zeros((S,), jnp.bool_))
    )
    return carry, done, steps_run


def retire_slots(carry: SlotCarry, retire_mask: jnp.ndarray) -> SlotCarry:
    """Mark slots dead after harvest (idempotent — ``decode_step`` already
    cleared ``alive`` for sealed slots; this also covers cancelling a
    still-running slot, e.g. a request whose client gave up)."""
    return carry._replace(alive=carry.alive & ~retire_mask)


def harvest_slots(
    carry: SlotCarry, return_alphas: bool = False
) -> BeamResult:
    """Merge every slot's finished/live beams into ranked results [S, ...]
    (the host slices the done rows).  steps_run is the per-slot [S] step
    count — the continuous path's decode_steps observability probe."""
    K = carry.search.live_logp.shape[1]
    return _merge_results(carry.search, K, return_alphas, steps=carry.t)
