"""Continuous step-level batching tests (docs/SERVING.md).

Pins the stepped-decode contracts the continuous-batching ISSUE promises:

* BITWISE parity — the stepped slot-pool decode (staggered admission,
  early retirement, slot reuse) produces `BeamResult`s identical to the
  monolithic `beam_search` per request: words, log_scores, lengths and
  alphas, including the early-exit and valid_size paths.  Both drivers
  run the same `_expand_step` body, and these tests prove the carry
  freeze preserves equality end to end;
* `return_steps` plumbing through `beam_search_jit` / `greedy_decode`;
* `PagedSlotPool` bookkeeping: capacity, page-local seeding, harvest
  frees slots, reset empties the pool;
* `ContinuousBatcher` flow control: inter-step admission beyond pool
  capacity, 504 deadline triage, drain-to-completion then 503;
* `BucketOverflow` → 429 with a Retry-After hint (batch mode), and the
  429 surface carrying the header end-to-end;
* the HTTP surface in `--serve_mode continuous`: caption parity vs the
  monolithic oracle, ZERO XLA compiles during the request phase, /stats
  decode-step percentiles + slot-pool occupancy, /metrics gauges;
* wedge containment: an injected stuck decode step fails in-flight
  slots with fast 500s, the pool re-warms, health recovers.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

from sat_tpu.models.decoder import init_decoder_params

# the ops package re-exports the beam_search FUNCTION, which shadows the
# submodule on every attribute-style import — load the module directly
bs = importlib.import_module("sat_tpu.ops.beam_search")
from sat_tpu.serve.batcher import ContinuousBatcher, MicroBatcher, Rejected
from sat_tpu.serve.engine import BucketOverflow
from sat_tpu.serve.server import CaptionServer
from sat_tpu.serve.slot_pool import PagedSlotPool

from tests.test_beam_search import EOS, tiny_config
from tests.test_serve import (  # noqa: F401  (fixture re-export)
    _fixture_files,
    _get,
    _post,
    _zero_image,
    served,
)


# ---------------------------------------------------------------------------
# Stepped-decode parity at the ops layer (no engine, tiny params)
# ---------------------------------------------------------------------------


def _ops_setup(B=5, seed=0, **kw):
    cfg = tiny_config(**kw)
    params = init_decoder_params(jax.random.PRNGKey(seed), cfg)
    contexts = jnp.asarray(
        np.random.default_rng(seed).normal(
            size=(B, cfg.num_ctx, cfg.dim_ctx)
        ),
        jnp.float32,
    )
    return cfg, params, contexts


def _stepped_decode_all(
    cfg, params, contexts, pages, width, *,
    return_alphas=False, valid_size=None, admit_every=1, k=1,
):
    """Run every request through a pages×width slot pool with staggered
    admission (one new request every ``admit_every`` ticks while slots
    are free), harvesting/retiring the tick each slot finishes.  ``k=1``
    drives the pool with single ``decode_step`` dispatches (the fused
    path's correctness baseline); ``k>1`` runs one fused
    ``decode_multi_step`` window per tick, admissions landing only
    between windows.  Returns per-request host BeamResults in
    submission order."""
    B = contexts.shape[0]
    S = pages * width
    seed = jax.jit(bs.init_slots, static_argnames=("config", "beam_size"))
    step = jax.jit(
        bs.decode_step,
        static_argnames=("config", "eos_id", "beam_size", "valid_size"),
    )
    multi = jax.jit(
        bs.decode_multi_step,
        static_argnames=("config", "eos_id", "beam_size", "valid_size"),
    )
    harv = jax.jit(bs.harvest_slots, static_argnames=("return_alphas",))
    ret = jax.jit(bs.retire_slots)

    carry = bs.init_slot_pool(
        cfg, slots=S, return_alphas=return_alphas
    )
    free = list(range(S))
    binding = {}  # slot -> request index
    results = {}
    next_req = 0
    ticks = 0
    while len(results) < B:
        # staggered admission: at most one page seeding per loop, only
        # on admit_every ticks — requests land mid-decode of others
        if free and next_req < B and ticks % admit_every == 0:
            s = free.pop(0)
            lane_ctx = contexts[next_req][None]        # 1-wide lane
            slot_src = np.zeros((S,), np.int32)
            admit = np.zeros((S,), np.bool_)
            admit[s] = True
            carry = seed(
                params, cfg, carry, lane_ctx,
                jnp.asarray(slot_src), jnp.asarray(admit),
            )
            binding[s] = next_req
            next_req += 1
        ticks += 1
        mask = np.zeros((S,), np.bool_)
        for s in binding:
            mask[s] = True
        if k == 1:
            carry, done = step(
                params, cfg, carry, jnp.asarray(mask), EOS,
                valid_size=valid_size,
            )
        else:
            carry, done, steps_run = multi(
                params, cfg, carry, jnp.asarray(mask), EOS,
                jnp.int32(k), valid_size=valid_size,
            )
            assert int(np.asarray(steps_run)) <= k
        done = np.asarray(done)
        if done.any():
            out = harv(carry, return_alphas=return_alphas)
            retire = np.zeros((S,), np.bool_)
            for s in np.nonzero(done)[0]:
                s = int(s)
                if s not in binding:
                    continue
                r = binding.pop(s)
                results[r] = bs.BeamResult(
                    words=np.asarray(out.words)[s],
                    log_scores=np.asarray(out.log_scores)[s],
                    lengths=np.asarray(out.lengths)[s],
                    alphas=(
                        None if out.alphas is None
                        else np.asarray(out.alphas)[s]
                    ),
                    steps_run=np.asarray(out.steps_run)[s],
                )
                retire[s] = True
                free.append(s)
            carry = ret(carry, jnp.asarray(retire))
        assert ticks < 10 * B * cfg.max_caption_length, "pool livelock"
    return [results[r] for r in range(B)]


@pytest.mark.parametrize("valid_size", [None, 25])
def test_stepped_parity_staggered_admission(valid_size):
    """5 requests through a 2x2 pool, admitted one per step: words,
    scores, lengths AND alphas bitwise-equal to the monolithic search,
    with early finishers retiring (and their slots reseeding) mid-run."""
    cfg, params, contexts = _ops_setup(B=5)
    mono = bs.beam_search(
        params, cfg, contexts, EOS,
        return_alphas=True, valid_size=valid_size,
    )
    stepped = _stepped_decode_all(
        cfg, params, contexts, pages=2, width=2,
        return_alphas=True, valid_size=valid_size,
    )
    for i, got in enumerate(stepped):
        assert np.array_equal(np.asarray(mono.words)[i], got.words), i
        assert np.array_equal(
            np.asarray(mono.log_scores)[i], got.log_scores
        ), i
        assert np.array_equal(np.asarray(mono.lengths)[i], got.lengths), i
        assert np.array_equal(np.asarray(mono.alphas)[i], got.alphas), i


def test_stepped_parity_bursty_admission_and_single_slot():
    """Degenerate geometries: a 1-wide pool (fully serial reuse) and
    bursty admission every 3 steps still match the oracle bitwise."""
    cfg, params, contexts = _ops_setup(B=3, seed=7)
    mono = bs.beam_search(params, cfg, contexts, EOS)
    for pages, width, every in ((1, 1, 1), (1, 2, 3)):
        stepped = _stepped_decode_all(
            cfg, params, contexts, pages=pages, width=width,
            admit_every=every,
        )
        for i, got in enumerate(stepped):
            assert np.array_equal(
                np.asarray(mono.words)[i], got.words
            ), (pages, width, i)
            assert np.array_equal(
                np.asarray(mono.log_scores)[i], got.log_scores
            ), (pages, width, i)


def test_stepped_per_slot_steps_reflect_early_exit():
    """harvest_slots reports per-slot step counts: an early-sealing
    request runs fewer steps than max_caption_length."""
    cfg, params, contexts = _ops_setup(B=4)
    stepped = _stepped_decode_all(cfg, params, contexts, pages=2, width=2)
    steps = [int(r.steps_run) for r in stepped]
    assert all(1 <= s <= cfg.max_caption_length for s in steps)
    mono = bs.beam_search_jit(
        params, cfg, contexts, EOS,
        beam_size=cfg.beam_size, return_steps=True,
    )
    # the pool runs each slot exactly as long as the monolithic whole-
    # batch early exit would have run its slowest member
    assert max(steps) == int(np.asarray(mono.steps_run))


# ---------------------------------------------------------------------------
# Fused decode window (decode_multi_step) — ISSUE 16
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [2, 4, 8])
def test_fused_window_bitwise_parity_staggered(k):
    """K fused steps per dispatch vs K=1 stepped decode under staggered
    admission: words, scores, lengths, alphas AND per-slot step counts
    bitwise-equal — the fused while_loop body IS decode_step, and a slot
    frozen mid-window stays frozen exactly as it would between host
    dispatches."""
    cfg, params, contexts = _ops_setup(B=5)
    base = _stepped_decode_all(
        cfg, params, contexts, pages=2, width=2, return_alphas=True, k=1,
    )
    fused = _stepped_decode_all(
        cfg, params, contexts, pages=2, width=2, return_alphas=True, k=k,
    )
    for i, (want, got) in enumerate(zip(base, fused)):
        assert np.array_equal(want.words, got.words), (k, i)
        assert np.array_equal(want.log_scores, got.log_scores), (k, i)
        assert np.array_equal(want.lengths, got.lengths), (k, i)
        assert np.array_equal(want.alphas, got.alphas), (k, i)
        assert int(want.steps_run) == int(got.steps_run), (k, i)
    # and both match the monolithic oracle (transitivity made explicit)
    mono = bs.beam_search(params, cfg, contexts, EOS, return_alphas=True)
    for i, got in enumerate(fused):
        assert np.array_equal(np.asarray(mono.words)[i], got.words), (k, i)
        assert np.array_equal(
            np.asarray(mono.alphas)[i], got.alphas
        ), (k, i)


@pytest.mark.parametrize("valid_size", [None, 25])
def test_fused_window_bitwise_parity_bursty(valid_size):
    """Bursty admission (every 3 ticks) through degenerate geometries
    with a deep window: still bitwise vs the K=1 baseline, valid_size
    masking included."""
    cfg, params, contexts = _ops_setup(B=3, seed=7)
    for pages, width in ((1, 1), (1, 2)):
        base = _stepped_decode_all(
            cfg, params, contexts, pages=pages, width=width,
            admit_every=3, valid_size=valid_size, k=1,
        )
        fused = _stepped_decode_all(
            cfg, params, contexts, pages=pages, width=width,
            admit_every=3, valid_size=valid_size, k=4,
        )
        for i, (want, got) in enumerate(zip(base, fused)):
            assert np.array_equal(want.words, got.words), (pages, width, i)
            assert np.array_equal(
                want.log_scores, got.log_scores
            ), (pages, width, i)
            assert int(want.steps_run) == int(got.steps_run), (
                pages, width, i,
            )


def test_fused_window_on_device_early_exit():
    """A pool that seals mid-window stops iterating ON DEVICE: steps_run
    comes back < k, and a fully inactive pool runs zero steps."""
    cfg, params, contexts = _ops_setup(B=1)
    mono = bs.beam_search_jit(
        params, cfg, contexts, EOS,
        beam_size=cfg.beam_size, return_steps=True,
    )
    n = int(np.asarray(mono.steps_run))
    S = 2
    carry = bs.init_slot_pool(cfg, slots=S)
    slot_src = np.zeros((S,), np.int32)
    admit = np.zeros((S,), np.bool_)
    admit[0] = True
    carry = bs.init_slots(
        params, cfg, carry, contexts[0][None],
        jnp.asarray(slot_src), jnp.asarray(admit),
    )
    mask = np.zeros((S,), np.bool_)
    mask[0] = True
    carry, done, steps_run = bs.decode_multi_step(
        params, cfg, carry, jnp.asarray(mask), EOS, k=n + 4,
    )
    # the slot seals after exactly its monolithic step count and the
    # while_loop exits the moment nothing is active — never burning the
    # remaining window
    assert int(np.asarray(steps_run)) == n < n + 4
    done = np.asarray(done)
    assert done[0] and not done[1]
    # drained pool (the harvested slot's mask dropped): zero iterations
    carry, done2, steps2 = bs.decode_multi_step(
        params, cfg, carry, jnp.zeros((S,), jnp.bool_), EOS, k=4,
    )
    assert int(np.asarray(steps2)) == 0
    assert not np.asarray(done2).any()


def test_adaptive_k_policy_units():
    """Queue pressure forces the shallow lane; an idle queue runs deep."""
    from sat_tpu.serve.batcher import choose_decode_depth

    depths = (1, 2, 4, 8)
    assert choose_decode_depth(depths, 0, 0) == 8    # idle -> deepest
    assert choose_decode_depth(depths, 1, 0) == 1    # queued burst
    assert choose_decode_depth(depths, 7, 3) == 1    # both
    assert choose_decode_depth(depths, 0, 2) == 1    # held pending
    assert choose_decode_depth((1,), 0, 0) == 1      # ladder of one
    assert choose_decode_depth((1, 4), 0, 0) == 4


def test_serve_decode_depth_config_validation():
    cfg = tiny_config()
    assert cfg.serve_decode_depth == (1, 2, 4, 8)
    # list arrivals normalize to a hashable tuple (jit static arg rule)
    assert cfg.replace(
        serve_decode_depth=[1, 3]
    ).serve_decode_depth == (1, 3)
    for bad in ((), (2, 4), (1, 4, 2), (1, 1, 2), (1, 0)):
        with pytest.raises(ValueError):
            cfg.replace(serve_decode_depth=bad)
    # JSON round-trip restores the tuple
    from sat_tpu.config import Config

    assert Config.from_dict(
        {"serve_decode_depth": [1, 2]}
    ).serve_decode_depth == (1, 2)


@pytest.mark.parametrize(
    "pages,width,every", [(2, 2, 1), (1, 2, 3), (1, 1, 1)]
)
def test_stepped_pallas_vs_xla_slot_pool_parity(monkeypatch, pages, width, every):
    """Fused-kernel decode at slot-pool geometry vs the XLA combine.

    The pool batches dead slots alongside live ones (inactive-slot masks,
    staggered admission, mid-pool retirement with slot reuse) — exactly
    the geometry the row-masked kernel exists for.  The interpret-mode
    kernel must produce the SAME captions as the XLA attend across every
    geometry, and scores must agree to kernel-numerics tolerance."""
    from sat_tpu.ops import pallas_attention

    cfg, params, contexts = _ops_setup(
        B=5, use_pallas_attention=True, num_attend_layers=2
    )
    xla = _stepped_decode_all(
        cfg.replace(use_pallas_attention=False), params, contexts,
        pages=pages, width=width, admit_every=every,
    )
    monkeypatch.setattr(pallas_attention, "FORCE_INTERPRET", True)
    fused = _stepped_decode_all(
        cfg, params, contexts, pages=pages, width=width, admit_every=every,
    )
    for i, (want, got) in enumerate(zip(xla, fused)):
        assert np.array_equal(want.words, got.words), (pages, width, i)
        np.testing.assert_allclose(
            got.log_scores, want.log_scores, rtol=1e-4, atol=1e-5,
            err_msg=str((pages, width, i)),
        )


def test_stepped_pallas_matches_monolithic_pallas(monkeypatch):
    """With the kernel forced on BOTH paths, the stepped slot-pool decode
    still matches the monolithic search caption-for-caption — the row
    mask changes nothing for live rows."""
    from sat_tpu.ops import pallas_attention

    cfg, params, contexts = _ops_setup(
        B=4, seed=3, use_pallas_attention=True, num_attend_layers=2
    )
    monkeypatch.setattr(pallas_attention, "FORCE_INTERPRET", True)
    mono = bs.beam_search(params, cfg, contexts, EOS)
    stepped = _stepped_decode_all(cfg, params, contexts, pages=2, width=2)
    for i, got in enumerate(stepped):
        assert np.array_equal(np.asarray(mono.words)[i], got.words), i
        np.testing.assert_allclose(
            got.log_scores, np.asarray(mono.log_scores)[i],
            rtol=1e-5, atol=1e-6, err_msg=str(i),
        )


@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="compiled (non-interpret) Pallas kernel needs a real TPU",
)
def test_stepped_pallas_vs_xla_on_tpu():
    """Same parity assertion with the Mosaic-compiled kernel on a real
    chip (the serve-path configuration: --serve_mode continuous runs this
    kernel every decode step)."""
    cfg, params, contexts = _ops_setup(
        B=5, use_pallas_attention=True, num_attend_layers=2
    )
    xla = _stepped_decode_all(
        cfg.replace(use_pallas_attention=False), params, contexts,
        pages=2, width=2,
    )
    fused = _stepped_decode_all(cfg, params, contexts, pages=2, width=2)
    for i, (want, got) in enumerate(zip(xla, fused)):
        assert np.array_equal(want.words, got.words), i
        np.testing.assert_allclose(
            got.log_scores, want.log_scores, rtol=1e-4, atol=1e-5,
        )


def test_return_steps_plumbing():
    """return_steps rides beam_search_jit and greedy_decode without
    perturbing results; off by default (None)."""
    cfg, params, contexts = _ops_setup(B=3)
    base = bs.beam_search_jit(
        params, cfg, contexts, EOS, beam_size=cfg.beam_size
    )
    assert base.steps_run is None
    counted = bs.beam_search_jit(
        params, cfg, contexts, EOS,
        beam_size=cfg.beam_size, return_steps=True,
    )
    n = int(np.asarray(counted.steps_run))
    assert 1 <= n <= cfg.max_caption_length
    assert np.array_equal(
        np.asarray(base.words), np.asarray(counted.words)
    )
    assert np.array_equal(
        np.asarray(base.log_scores), np.asarray(counted.log_scores)
    )
    g0 = bs.greedy_decode(params, cfg, contexts, EOS)
    g1 = bs.greedy_decode(params, cfg, contexts, EOS, return_steps=True)
    assert g0.steps_run is None and g1.steps_run is not None
    assert np.array_equal(np.asarray(g0.words), np.asarray(g1.words))


def test_bucket_overflow_carries_hint_fields():
    class _E:  # minimal stand-in; pick_bucket only needs .buckets
        buckets = (1, 4)
    from sat_tpu.serve.engine import ServeEngine
    with pytest.raises(BucketOverflow) as exc:
        ServeEngine.pick_bucket(_E(), 9)
    assert exc.value.n == 9 and exc.value.largest == 4
    assert isinstance(exc.value, ValueError)  # old callers still catch


# ---------------------------------------------------------------------------
# Slot pool + continuous batcher over a real engine
# ---------------------------------------------------------------------------


def _make_pool(served, pages=1, page_width=2):
    pool = PagedSlotPool(
        served["engine"], pages=pages, page_width=page_width,
        tel=served["tel"],
    )
    pool.warmup()
    return pool


def test_slot_pool_bookkeeping_and_zero_recompile_reuse(served):
    engine, tel = served["engine"], served["tel"]
    pool = _make_pool(served, pages=2, page_width=2)
    assert pool.slots == 4 and pool.free_count() == 4
    img = _zero_image(engine)
    n = pool.admit([(img, f"r{i}") for i in range(6)])
    assert n == 4  # surplus stays with the caller
    assert pool.occupancy() == 4 and pool.free_count() == 0
    assert pool.inflight_payloads() == ["r0", "r1", "r2", "r3"]
    compiles0 = tel.counters().get("jax/compiles", 0)
    for _ in range(engine.config.max_caption_length):
        done = np.asarray(pool.step())  # sync-ok: test drain
        if done.any():
            payloads, words, lengths, scores, steps, _alphas = pool.harvest(done)
            assert words.shape[0] == len(payloads)
            assert steps.shape == (len(payloads),)
    assert pool.occupancy() == 0 and pool.free_count() == 4
    # identical zero images: every slot sealed the same step, one harvest
    # reseeding + stepping reuse the warmed executables — nothing compiled
    assert pool.admit([(img, "again")]) == 1
    np.asarray(pool.step())  # sync-ok: test drain
    assert tel.counters().get("jax/compiles", 0) == compiles0
    pool.reset()
    assert pool.occupancy() == 0 and pool.inflight_payloads() == []


def test_multi_step_all_lanes_zero_recompile(served):
    """Every ladder depth steps the pool without a single XLA compile
    (the depth is a runtime operand of ONE warmed executable), and an
    off-ladder depth raises instead of silently widening the policy."""
    engine, tel = served["engine"], served["tel"]
    pool = _make_pool(served, pages=1, page_width=2)
    assert pool.decode_depths == (1, 2, 4, 8)
    img = _zero_image(engine)
    compiles0 = tel.counters().get("jax/compiles", 0)
    for k in pool.decode_depths:
        assert pool.admit([(img, f"lane{k}")]) == 1
        guard = 0
        while pool.occupancy():
            done, steps_dev = pool.multi_step(k)
            done = np.asarray(done)  # sync-ok: test drain
            steps = int(np.asarray(steps_dev))  # sync-ok: test drain
            assert 1 <= steps <= k
            if done.any():
                pool.harvest(done)
            guard += 1
            assert guard <= 2 * engine.config.max_caption_length
    assert tel.counters().get("jax/compiles", 0) == compiles0
    with pytest.raises(KeyError):
        pool.multi_step(3)
    # the lifecycle clone shares the fused executable (zero compiles there)
    clone = pool.clone_warmed("canary")
    assert clone._multi_exec is pool._multi_exec
    assert tel.counters().get("jax/compiles", 0) == compiles0


def test_continuous_batcher_admits_beyond_capacity_and_drains(served):
    """5 requests into a 2-slot pool: inter-step admission cycles them
    all through; drain completes everything then rejects 503."""
    engine = served["engine"]
    b = ContinuousBatcher(
        engine, pool=_make_pool(served, pages=1, page_width=2),
        queue_depth=8, tel=served["tel"],
    )
    img = _zero_image(engine)
    reqs = [b.submit(img) for _ in range(5)]
    b.start()
    b.drain()
    for r in reqs:
        assert r.done.is_set()
        assert r.error is None and r.result is not None
        assert r.bucket == 2  # the page width is the dispatch "bucket"
        assert r.result["captions"]
    with pytest.raises(Rejected) as exc:
        b.submit(img)
    assert exc.value.status == 503
    assert served["tel"].counters().get("serve/admitted", 0) >= 5


def test_continuous_expired_deadline_fails_fast_504(served):
    engine = served["engine"]
    b = ContinuousBatcher(
        engine, pool=_make_pool(served, pages=1, page_width=2),
        queue_depth=8, tel=served["tel"],
    )
    img = _zero_image(engine)
    expired = b.submit(img, deadline_unix=time.time() - 1.0)
    live = b.submit(img)
    b.start()
    try:
        assert expired.done.wait(timeout=10.0)
        assert live.done.wait(timeout=60.0)
        assert expired.error is not None and expired.error[0] == 504
        assert live.error is None and live.result is not None
    finally:
        b.drain()


def test_micro_batcher_maps_bucket_overflow_to_429(served):
    """A batch the warmed ladder can't hold sheds 429 (backpressure),
    not 500 — constructed directly with max_batch past the ladder."""
    engine = served["engine"]
    b = MicroBatcher(
        engine, max_batch=8, max_wait_ms=5.0, queue_depth=16,
        tel=served["tel"],
    )
    img = _zero_image(engine)
    reqs = [b.submit(img) for _ in range(6)]  # > buckets[-1] == 4
    b.start()
    try:
        for r in reqs:
            assert r.done.wait(timeout=30.0)
        statuses = {r.error[0] for r in reqs if r.error is not None}
        assert statuses == {429}
        assert all("exceeds the largest warmed bucket" in r.error[1]
                   for r in reqs)
    finally:
        b.drain()


# ---------------------------------------------------------------------------
# HTTP end-to-end in --serve_mode continuous
# ---------------------------------------------------------------------------


def _continuous_config(served, **kw):
    base = dict(
        serve_mode="continuous", serve_slot_pages=2, serve_page_width=2,
    )
    base.update(kw)
    return served["config"].replace(**base)


def _post_raw(port, data, timeout=60):
    """Like _post but also returns response headers (Retry-After)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/caption",
        data=data,
        method="POST",
        headers={"Content-Type": "image/jpeg"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_e2e_continuous_parity_stats_zero_recompiles(served):
    config = _continuous_config(served)
    engine, tel = served["engine"], served["tel"]
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        image_file = _fixture_files(served, 1)[0]
        jpeg = open(image_file, "rb").read()

        # oracle: the monolithic warmed path on the same image
        img = engine.loader.load_image(image_file)
        oracle = engine.decode_output(
            engine.dispatch(engine.pad_batch([img])[0]), 1
        )[0]

        compiles0 = tel.counters().get("jax/compiles", 0)

        status, payload, _ = _post_raw(port, jpeg)
        assert status == 200
        assert payload["captions"] == oracle["captions"]  # bitwise detok
        assert payload["bucket"] == 2  # page width, not a batch bucket

        # a burst past pool capacity (4 slots): everything completes via
        # inter-step admission, all identical to the oracle
        results = [None] * 7
        barrier = threading.Barrier(7)

        def client(i):
            barrier.wait()
            results[i] = _post_raw(port, jpeg)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(7)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None for r in results)
        assert all(s == 200 for s, _, _ in results)
        assert all(
            p["captions"][0]["caption"]
            == oracle["captions"][0]["caption"]
            for _, p, _ in results
        )

        # THE guarantee, extended to the stepped path: zero XLA compiles
        # in the request phase (admission, stepping, harvest, reseed)
        assert tel.counters().get("jax/compiles", 0) == compiles0

        status, stats = _get(port, "/stats")
        assert status == 200
        assert stats["serve_mode"] == "continuous"
        assert stats["slot_pool"] == {
            "slots": 4, "pages": 2, "page_width": 2, "busy": 0,
        }
        assert stats["compiles_since_ready"] == 0
        steps = stats["decode_steps"]
        assert steps["count"] >= 8
        assert 1 <= steps["p50"] <= steps["p95"]
        assert steps["p95"] <= config.max_caption_length
        assert "serve/step" in stats["latency_ms"]
        assert stats["counters"].get("serve/admitted", 0) >= 8

        # fused decode window observability: device steps per dispatch
        # in the engine block, bounded by the warmed ladder
        spd = stats["engine"]["steps_per_dispatch"]
        assert 1 <= spd["p50"] <= spd["p95"]
        assert spd["p95"] <= max(config.serve_decode_depth)
        assert stats["counters"].get("serve/dispatches", 0) >= 1
        # dispatch amortization: total steps never exceed dispatches x
        # the deepest lane, and the fused window actually engaged
        assert stats["counters"]["serve/steps"] <= (
            stats["counters"]["serve/dispatches"]
            * max(config.serve_decode_depth)
        )

        # /metrics exports the step distribution + occupancy gauges
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read().decode()
        assert 'sat_gauge{name="serve/decode_steps_p50"}' in body
        assert 'sat_gauge{name="serve/slot_occupancy"}' in body
        assert 'sat_gauge{name="serve/steps_per_dispatch"}' in body
        assert 'sat_gauge{name="serve/steps_per_dispatch_p95"}' in body
    finally:
        server.shutdown()


def test_e2e_429_carries_retry_after(served, monkeypatch):
    """Any 429 shed answers with a Retry-After header + retry_after_ms
    payload hint (satellite: BucketOverflow / queue-full backpressure)."""
    server = CaptionServer(served["config"], served["engine"], port=0)

    def shed(*a, **kw):
        raise Rejected(429, "queue full (test); shed")

    monkeypatch.setattr(server.batcher, "submit", shed)
    server.start()
    try:
        jpeg = open(_fixture_files(served, 1)[0], "rb").read()
        status, payload, headers = _post_raw(server.port, jpeg)
        assert status == 429
        assert payload["retry_after_ms"] >= 50
        assert int(headers["Retry-After"]) >= 1
        # RFC 7231: the header rounds the ms hint UP to whole seconds
        assert (
            int(headers["Retry-After"]) * 1000 >= payload["retry_after_ms"]
        )
    finally:
        server.shutdown()


def test_e2e_continuous_wedge_fails_slots_and_rewarms(served, monkeypatch):
    """SAT_FI_WEDGE_SERVE_BATCH in continuous mode: the wedged decode
    step fails its in-flight slots with fast 500s, the pool re-warms in
    the background, health recovers, and the next request serves."""
    engine, tel = served["engine"], served["tel"]
    rewarms_before = tel.counters().get("serve/rewarms", 0)
    monkeypatch.setenv("SAT_FI_WEDGE_SERVE_BATCH", "1")
    # generous timeout: the injected wedge parks the drain forever so
    # detection is unaffected, but a REAL step on a contended CI host can
    # stall past a tight bound and false-positive the retry below
    config = _continuous_config(served, serve_wedge_timeout_ms=2500.0)
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpeg = open(_fixture_files(served, 1)[0], "rb").read()
        status, payload, _ = _post_raw(port, jpeg, timeout=30)
        assert status == 500
        assert "wedged" in payload["error"]
        assert tel.counters().get("serve/wedged_batches", 0) >= 1
        # recovery: pool re-warmed (cached compiles), health back to ok
        deadline = time.time() + 30.0
        while time.time() < deadline:
            code, health = _get(port, "/healthz")
            if code == 200 and health["status"] == "ok":
                break
            time.sleep(0.05)
        assert code == 200 and health["status"] == "ok"
        assert tel.counters().get("serve/rewarms", 0) == rewarms_before + 1
        status, payload, _ = _post_raw(port, jpeg, timeout=60)
        assert status == 200 and payload["captions"]
        assert server.pool.occupancy() == 0
    finally:
        server.shutdown()


def test_cli_serve_mode_flag():
    from sat_tpu.cli import build_config

    config, _ = build_config(["--phase=serve", "--serve_mode=continuous"])
    assert config.serve_mode == "continuous"
    with pytest.raises(SystemExit):
        build_config(["--phase=serve", "--serve_mode=nope"])


def test_cli_serve_decode_depth_flag():
    from sat_tpu.cli import build_config

    config, _ = build_config(
        ["--phase=serve", "--serve_decode_depth=1,2,4"]
    )
    assert config.serve_decode_depth == (1, 2, 4)
    # --set rides the tuple-coercion path of _parse_override
    config, _ = build_config(
        ["--phase=serve", "--set", "serve_decode_depth=1,6"]
    )
    assert config.serve_decode_depth == (1, 6)
    with pytest.raises(ValueError):
        build_config(["--phase=serve", "--serve_decode_depth=2,4"])
