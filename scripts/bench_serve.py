"""Serving load generator: closed-loop throughput + open-loop latency.

Boots the full serving stack (docs/SERVING.md) against a procedurally
initialized tiny model — fresh params saved through the checkpoint/lineage
path, so the bench exercises the same lineage load, AOT bucket warmup,
micro-batcher and HTTP frontend production traffic hits — then drives it
two ways:

* **closed loop**: ``--concurrency`` workers each issue ``--requests``
  back-to-back POSTs; measures sustained throughput (the batcher should
  ride the top bucket) and per-request latency percentiles.
* **open loop**: Poisson arrivals at ``--rate`` req/s (seeded, so runs
  compare like-for-like); measures the latency distribution under an
  arrival process that does not self-throttle, plus how much the
  admission queue shed (429s are counted, not errors — shedding under
  overload is the contract).

Prints BENCH-contract JSON lines on stdout ({"metric", "value", "unit",
...extras} + telemetry.bench_stamp()), accepted by
scripts/check_regression.py:

* ``serve_closed_loop_throughput`` (req_per_s, higher is better)
* ``serve_open_loop_p99_latency_ms`` (ms, lower is better)
* ``serve_continuous_goodput`` (req_per_s, higher is better) — open
  loop at ``--cont-rate`` (≈ the batch path's padded-bucket capacity)
  against ``--serve_mode continuous`` (paged slot pool, step-level
  admission); a batch-mode run at the SAME rate is measured first and
  reported as ``batch_ref_goodput`` / ``batch_ref_p99_ms`` extras, so
  the row demonstrates continuous beating batch on both captions/s and
  p99 at high offered load
* ``serve_admission_latency_ms`` (ms, lower is better) — p95 submit →
  slot-seeded time in continuous mode (what the whole-batch gather +
  hold-open window used to cost).  Sampled ONLY over the open-loop
  load phase (warm-pass and single-stream admissions are sliced off)
  and reported next to the detok-thread queueing p95
  (``detok_queue_p95_ms``) so decode-lane wins are not masked by
  post-harvest string work sitting in the detok queue
* ``serve_single_stream_latency_ms`` (ms, lower is better) — one
  closed-loop client against the continuous server: the empty-queue
  regime where the adaptive policy picks the DEEPEST fused-decode lane
  (docs/SERVING.md "Fused decode window"), so per-request latency is
  dominated by K-step device dispatches instead of per-step host
  round-trips.  A second continuous arm pinned to
  ``serve_decode_depth=1`` runs the same client and rides the row as
  ``k1_p50_ms`` / ``k1_goodput`` extras — the K-ladder A/B.  Every K
  lane asserts zero steady-state recompiles (exit 1 otherwise).
* ``--tenants`` switches to the multi-tenant isolation campaign
  (docs/SERVING.md "Multi-tenant serving"): one continuous-mode server
  with a victim/peer/flood registry — ``tenant_isolation_p99_ratio``
  (ratio, lower is better: victim p99 under a 5x-quota flood over its
  flood-free baseline) and ``tenant_fair_share_error`` (fraction, lower
  is better: |observed − weighted| completion share across two
  backlogged lanes).  Exit 1 on any recompile, victim-lane shed/error
  or flood 5xx.
* ``--fleet`` switches to the fleet campaign (docs/SERVING.md fleet
  section): max(--fleet-sizes) subprocess replicas spawned once, then a
  matched open-loop Poisson load through the health-weighted router at
  each fleet size — ``fleet_goodput_rps`` (req_per_s, higher is better,
  with per-size goodput/scaling extras), ``fleet_open_loop_p99_latency_ms``
  (ms, lower is better) and ``fleet_router_overhead_ms`` (the router's
  own p50 per-request cost).  A final disaggregated arm spawns an
  encode-tier + decode-tier pair and runs the same load two-hop through
  the router (``fleet_disagg_goodput_rps``, req_per_s, higher is
  better — the feature-grid handoff priced against the n=1 arm).
* ``--encode-cache`` switches to the content-addressed encode-cache
  campaign (docs/SERVING.md "Encode cache & tiered fleets"): a hit/cold
  bitwise caption-parity phase, then an all-unique control arm and a
  Zipf repeat-traffic arm on one cache-on server —
  ``encode_cache_hit_ratio`` (ratio, higher is better; acceptance
  floor 0.6 on the Zipf arm, ~0 on unique) and
  ``cache_serve_goodput_rps`` (req_per_s, higher is better).  Exit 1
  on any recompile, any parity mismatch, or a dead/false ratio.
* ``--metering`` switches to the cost-attribution campaign
  (docs/OBSERVABILITY.md "Cost attribution and tenant metering"):
  ``metering_overhead_pct`` (pct, lower is better: the full
  per-request metering path — sketch observe, encode/decode cost
  shares, occupancy stamp, the terminal ``charge()`` — microbenched
  and priced against the live arm's request p50; hard gate 0.5, exit
  1 over) and ``encode_cache_would_hit_ratio`` (ratio, higher is
  better: the would-be encode-cache probe under Zipf-weighted repeat
  traffic, with an all-unique control arm riding as the ~0 extra —
  ROADMAP item 2's evidence).  Both live arms also assert the
  accounting identity (attributed device-ms within ±5% of measured
  busy) and zero steady-state recompiles.

The load generator keeps one persistent HTTP/1.1 connection per worker
(keep-alive; reconnects are counted in the BENCH rows) so high-rate runs
measure the server, not TCP connect overhead.  Both single-server modes
run against one warmed engine; every mode asserts ZERO XLA compiles
during its load phase (exit 1 on any steady-state recompile — per
replica, in fleet mode).

Usage: python scripts/bench_serve.py [--concurrency 8] [--requests 25]
       [--rate 50] [--open-requests 200] [--buckets 1,4,16]
       [--max-batch 16] [--max-wait-ms 5] [--workdir DIR]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_serve +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


SENTENCES = [
    "a man riding a horse on the beach.",
    "a group of people standing around a kitchen.",
    "two dogs playing with a red ball in the grass.",
    "a plate of food with rice and vegetables.",
    "a bus driving down a city street.",
    "a cat sitting on top of a wooden table.",
]


def _make_jpegs(n: int, size: int) -> list:
    """Structurally DIVERSE images — each index gets its own rng, solid
    region and channel, so the encoded contexts differ enough for
    input-dependent seal steps (near-identical noise images collapse to
    one caption length through the encoder, hiding the straggler regime
    continuous batching exists for)."""
    import cv2

    out = []
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        c = i % 3
        extent = size // 4 + (3 * i) % (3 * size // 4)
        if i % 2 == 0:
            img[:extent, :, c] = 30 * (i + 1) % 255
        else:
            img[:, :extent, c] = max(0, 250 - 25 * i)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        out.append(bytes(buf))
    return out


def _make_ckpt(args, workdir):
    """Tiny fresh model saved through checkpoint+lineage; returns the
    serve Config pointing at it — shared by the in-process servers below
    and the subprocess replica fleet (--fleet), which both load the same
    LAST_GOOD step through the lineage path."""
    import jax

    from sat_tpu import runtime, telemetry
    from sat_tpu.config import Config
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    vocab_file = os.path.join(workdir, "vocabulary.csv")
    vocabulary = Vocabulary(size=50)
    vocabulary.build(SENTENCES)
    vocabulary.save(vocab_file)

    buckets = tuple(int(b) for b in args.buckets.split(","))
    config = Config(
        phase="serve",
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        compute_dtype="float32",
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        beam_size=2,
        save_dir=os.path.join(workdir, "models"),
        summary_dir=os.path.join(workdir, "summary"),
        serve_buckets=buckets,
        serve_max_batch=args.max_batch,
        serve_max_wait_ms=args.max_wait_ms,
        serve_queue_depth=args.queue_depth,
        heartbeat_interval=0.0,
    )
    os.makedirs(config.save_dir, exist_ok=True)

    tel = telemetry.enable(capacity=1 << 18)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    if args.eos_bias != 0.0:
        # shape the synthetic model toward realistic caption-length
        # variance: a mild EOS-logit bias makes different inputs seal at
        # different steps (short captions + stragglers — the regime
        # continuous batching exists for).  Raw random params run every
        # beam to max_caption_length, hiding early retirement entirely.
        eos = vocabulary.word2idx["."]
        params = jax.tree_util.tree_map(lambda x: x, state.params)
        b = params["decoder"]["decode"]["fc_2"]["bias"]
        params["decoder"]["decode"]["fc_2"]["bias"] = b.at[eos].add(
            args.eos_bias
        )
        state = state._replace(params=params)
    path = save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
    log(f"fresh params saved to {path}")
    return config, vocabulary, tel


def _boot(args, workdir):
    """_make_ckpt + the real in-process serving stack: engine warmup and
    a CaptionServer on an ephemeral port."""
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer

    config, vocabulary, tel = _make_ckpt(args, workdir)
    state, source = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    server = CaptionServer(config, engine, port=0).start()
    log(f"server up on port {server.port} "
        f"(buckets {engine.buckets}, warm_compiles {engine.warm_compiles})")
    return server, engine, tel


class _KeepAliveClient:
    """Persistent HTTP/1.1 connections per port, checked out per request
    so concurrent workers never share a socket.  The old client opened a
    fresh TCP connection per POST — at high open-loop rates that
    measured the client's connect overhead, not the server.  ``connects``
    counts every fresh TCP connect (steady state: one per concurrent
    worker; anything above that is a reconnect after a dropped/broken
    keep-alive and is reported in the BENCH rows)."""

    def __init__(self):
        self._idle = {}  # port -> stack of idle connections
        self._lock = threading.Lock()
        self.connects = 0

    def post(self, port, data, timeout=60.0, host="127.0.0.1",
             headers=None):
        """One POST /caption; returns (status, latency_s); status 0 on a
        connection-level failure (refused/reset — the chaos scenario
        distinguishes these from HTTP 5xx).  ``headers`` adds request
        headers (the tenant arm sets ``X-Tenant`` per lane)."""
        t0 = time.perf_counter()
        with self._lock:
            stack = self._idle.setdefault(port, [])
            conn = stack.pop() if stack else None
            if conn is None:
                self.connects += 1
        if conn is None:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request(
                "POST", "/caption", body=data,
                headers={"Content-Type": "image/jpeg", **(headers or {})},
            )
            resp = conn.getresponse()
            resp.read()
            status = resp.status
            with self._lock:
                self._idle.setdefault(port, []).append(conn)
        except (OSError, http.client.HTTPException):
            try:
                conn.close()
            except Exception:
                pass
            status = 0
        return status, time.perf_counter() - t0

    def close_all(self):
        with self._lock:
            pools, self._idle = self._idle, {}
        for stack in pools.values():
            for conn in stack:
                try:
                    conn.close()
                except Exception:
                    pass


_CLIENT = _KeepAliveClient()


def _post(port, data, timeout=60.0, headers=None):
    """One POST over the shared keep-alive pool; (status, latency_s)."""
    return _CLIENT.post(port, data, timeout=timeout, headers=headers)


def _get_json(port, path, timeout=10.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return json.loads(r.read())


def _pcts(lat_s):
    data = np.sort(np.asarray(lat_s, np.float64)) * 1e3
    def pct(p):
        return round(float(data[min(len(data) - 1,
                                    int(p / 100.0 * len(data)))]), 3)
    return {"p50": pct(50), "p95": pct(95), "p99": pct(99)}


def closed_loop(port, jpegs, concurrency, requests, headers=None):
    """concurrency workers x requests sequential POSTs each."""
    lats, codes = [], []
    lock = threading.Lock()
    connects0 = _CLIENT.connects

    def worker(wid):
        local_l, local_c = [], []
        for i in range(requests):
            status, lat = _post(port, jpegs[(wid + i) % len(jpegs)],
                                headers=headers)
            local_c.append(status)
            if status == 200:
                local_l.append(lat)
        with lock:
            lats.extend(local_l)
            codes.extend(local_c)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ok = sum(1 for c in codes if c == 200)
    return {
        "wall_s": wall,
        "ok": ok,
        "shed": sum(1 for c in codes if c == 429),
        "throughput": ok / wall if wall > 0 else 0.0,
        # fresh TCP connects this loop forced: steady state is one per
        # worker; the excess is keep-alive reconnects
        "tcp_connects": _CLIENT.connects - connects0,
        "reconnects": max(0, _CLIENT.connects - connects0 - concurrency),
        **_pcts(lats or [0.0]),
    }


def open_loop(port, jpegs, rate, total, timeout=60.0, headers=None):
    """Poisson arrivals at ``rate`` req/s; each request on its own
    thread so slow responses never throttle the arrival process."""
    rng = random.Random(0)
    lats, codes = [], []
    lock = threading.Lock()
    threads = []
    connects0 = _CLIENT.connects

    def fire(i):
        status, lat = _post(port, jpegs[i % len(jpegs)], timeout=timeout,
                            headers=headers)
        with lock:
            codes.append(status)
            if status == 200:
                lats.append(lat)

    t0 = time.perf_counter()
    for i in range(total):
        time.sleep(rng.expovariate(rate))
        t = threading.Thread(target=fire, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=max(180.0, 2 * timeout))
    wall = time.perf_counter() - t0
    ok = sum(1 for c in codes if c == 200)
    return {
        "wall_s": wall,
        "ok": ok,
        "shed": sum(1 for c in codes if c == 429),
        "errors": sum(1 for c in codes if c == 0 or c >= 500),
        "offered_rate": rate,
        # keep-alive reconnects aren't separable from pool growth in an
        # open loop (concurrency is unbounded), so report raw connects
        "tcp_connects": _CLIENT.connects - connects0,
        **_pcts(lats or [0.0]),
    }


def fleet_bench(args, workdir) -> int:
    """--fleet: goodput scaling across an N-replica fleet behind the
    router (sat_tpu/serve/router.py).

    Spawns max(--fleet-sizes) serve replicas ONCE (subprocesses over the
    persistent compile cache, so later boots are cheap), then for each
    fleet size n runs the SAME open-loop Poisson load against an
    in-process Router fronting the first n endpoints.  Offered load is
    matched across arms and sits well ABOVE the largest arm's capacity:
    every arm is backlogged from its first dispatch (the bounded
    admission queue absorbs the burst), so goodput tracks fleet
    capacity — the acceptance story is near-linear scaling (>=1.7x at
    2, >=3x at 4).  The fleet arms run unit-batch geometry (one
    dispatch, one floor, one request): each replica is then a serial
    fixed-service-time queue, so scaling isolates the router's
    spreading/queueing behaviour instead of micro-batch fill dynamics
    (under-filled ramp/tail batches at short arms, which the
    single-server modes already characterize).

    Replicas are armed with a per-dispatched-batch service-time floor
    (``SAT_FI_SLOW_SERVE_MS``, --fleet-service-floor-ms) so each one is
    occupancy-bound the way a device-backed replica is.  Without it, N
    CPU-decode replicas timeshare this host's cores and goodput measures
    XLA CPU contention instead of router/queueing behaviour — on a
    single-core host scaling would be flat no matter how good the
    router is.  The floor rides the existing inert-by-default fault
    plan (sat_tpu/resilience/faultinject.py) and is recorded in the
    BENCH rows.  Emits ``fleet_goodput_rps`` and
    ``fleet_open_loop_p99_latency_ms`` BENCH rows (gated by
    check_regression.py) plus ``fleet_router_overhead_ms`` (the router's
    own p50 cost per request), and asserts zero steady-state recompiles
    on EVERY replica across the whole campaign."""
    from sat_tpu import telemetry
    from sat_tpu.serve.replica import LocalFleet
    from sat_tpu.serve.router import Router

    config, vocabulary, tel = _make_ckpt(args, workdir)
    sizes = sorted({int(s) for s in args.fleet_sizes.split(",")})
    floor_ms = int(args.fleet_service_floor_ms)
    fleet_env = (
        {"SAT_FI_SLOW_SERVE_MS": str(floor_ms)} if floor_ms > 0 else None
    )
    # unit-batch geometry: one floor per request makes each replica a
    # serial fixed-service-time queue (no partial-batch fill dynamics),
    # and the floor keeps per-request XLA time a minor term so N
    # co-hosted replicas don't just measure this host's CPU contention
    config = config.replace(serve_buckets=(1,), serve_max_batch=1)
    fleet = LocalFleet(
        config, max(sizes), root=os.path.join(workdir, "fleet"),
        env=fleet_env,
    )
    results, recompiles = {}, {}
    overhead_ns = []
    try:
        log(f"spawned {max(sizes)} replicas on ports "
            f"{[e.port for e in fleet.endpoints]}; waiting for readiness")
        fleet.wait_ready(timeout_s=600)
        log("fleet ready")
        jpegs = _make_jpegs(8, config.image_size)
        base_compiles = {}
        for e in fleet.endpoints:
            _post(e.port, jpegs[0])  # first-touch host costs per replica
            base_compiles[e.name] = _get_json(e.port, "/stats")[
                "compiles_since_ready"
            ]
        route_cfg = config.replace(
            phase="route",
            route_poll_interval_s=0.2,  # fresh view between arms
            # the saturated n=1 arm's tail queues for most of that arm's
            # wall time before its replica even dispatches it; the
            # default per-attempt proxy timeout would clip it into 5xx
            route_upstream_timeout_s=240.0,
        )
        # largest arm first: replica-side latency percentiles carry each
        # arm's saturated queue waits, so ascending order would hand the
        # n=2/n=4 routers a merged view where the replica that just
        # served the n=1 arm alone looks like a straggler (p99 ~= that
        # arm's wall time) and gets down-weighted despite being idle.
        # Descending order keeps every arm's history symmetric across
        # the replicas it fronts (and the n=1 arm cannot skew).
        for n in sorted(sizes, reverse=True):
            router = Router(
                route_cfg, fleet.endpoints[:n], port=0
            ).start()
            try:
                _post(router.port, jpegs[0])  # warm the edge + pools
                mark = len(tel.durations_ns("route/overhead"))
                # generous client timeout: the saturated n=1 arm's tail
                # waits out most of the arm's wall time by design
                res = open_loop(
                    router.port, jpegs, args.fleet_rate,
                    args.fleet_requests, timeout=150.0,
                )
                over = np.asarray(
                    tel.durations_ns("route/overhead")[mark:], np.float64
                )
                res["router_overhead_p50_ms"] = (
                    round(float(np.median(over)) / 1e6, 3)
                    if over.size else 0.0
                )
                overhead_ns.extend(over.tolist())
                res["goodput"] = (
                    res["ok"] / res["wall_s"] if res["wall_s"] else 0.0
                )
                stats = _get_json(router.port, "/stats")
                res["router_reconnects"] = sum(
                    stats.get("reconnects", {}).values()
                )
                res["retries"] = stats.get("counters", {}).get(
                    "route/retries", 0
                )
                results[n] = res
                log(f"fleet n={n} @ {args.fleet_rate}/s: {res['ok']} ok, "
                    f"{res['shed']} shed, {res['errors']} errors in "
                    f"{res['wall_s']:.1f}s -> {res['goodput']:.1f} req/s "
                    f"(p50 {res['p50']}ms p99 {res['p99']}ms, router "
                    f"overhead p50 {res['router_overhead_p50_ms']}ms)")
            finally:
                router.shutdown()
        for e in fleet.endpoints:
            recompiles[e.name] = (
                _get_json(e.port, "/stats")["compiles_since_ready"]
                - base_compiles[e.name]
            )
        log(f"per-replica steady-state recompiles: {recompiles}")

        # --- disaggregated arm: encode tier + decode tier ----------------
        # the same open-loop load through a two-replica tiered fleet
        # (docs/SERVING.md "Encode cache & tiered fleets"): the router
        # two-hops every image request (/encode on the encode tier, the
        # framed grid to /caption on the decode tier).  The service
        # floor arms only the batcher drain, so the arm is decode-bound
        # — goodput should track the n=1 arm (one floored decode
        # replica) and the row prices the handoff overhead against it.
        disagg_res = None
        disagg = LocalFleet(
            config, 2, root=os.path.join(workdir, "fleet_disagg"),
            env=fleet_env, tiers=["encode", "decode"],
        )
        try:
            log(f"disagg fleet (encode+decode tiers) on ports "
                f"{[e.port for e in disagg.endpoints]}")
            disagg.wait_ready(timeout_s=600)
            d_base = {}
            for e in disagg.endpoints:
                d_base[e.name] = _get_json(e.port, "/stats")[
                    "compiles_since_ready"
                ]
            router = Router(route_cfg, disagg.endpoints, port=0).start()
            try:
                _post(router.port, jpegs[0])  # warm both hops
                disagg_res = open_loop(
                    router.port, jpegs, args.fleet_rate,
                    args.fleet_requests, timeout=150.0,
                )
                disagg_res["goodput"] = (
                    disagg_res["ok"] / disagg_res["wall_s"]
                    if disagg_res["wall_s"] else 0.0
                )
                stats = _get_json(router.port, "/stats")
                disagg_res["handoffs"] = stats.get("counters", {}).get(
                    "route/handoffs", 0
                )
            finally:
                router.shutdown()
            for e in disagg.endpoints:
                recompiles[f"disagg_{e.name}"] = (
                    _get_json(e.port, "/stats")["compiles_since_ready"]
                    - d_base[e.name]
                )
            log(f"disagg arm @ {args.fleet_rate}/s: {disagg_res['ok']} "
                f"ok, {disagg_res['shed']} shed, {disagg_res['errors']} "
                f"errors -> {disagg_res['goodput']:.1f} req/s "
                f"({disagg_res['handoffs']} handoffs, p99 "
                f"{disagg_res['p99']}ms)")
        finally:
            disagg.stop_all()
    finally:
        _CLIENT.close_all()
        fleet.stop_all()

    g1 = results[min(sizes)]["goodput"]
    n_top = max(sizes)
    scaling = {
        n: round(results[n]["goodput"] / g1, 3) if g1 else 0.0
        for n in sizes
    }
    log(f"goodput scaling vs n={min(sizes)}: {scaling}")
    common = {
        "fleet_sizes": sizes,
        "offered_rate_per_s": args.fleet_rate,
        "arrivals_per_arm": args.fleet_requests,
        "service_floor_ms": floor_ms,
        "per_replica_recompiles": recompiles,
        "buckets": ",".join(str(b) for b in config.serve_buckets),
        "max_batch": config.serve_max_batch,
        **telemetry.bench_stamp(),
    }
    top = results[n_top]
    print(json.dumps({
        "metric": "fleet_goodput_rps",
        "value": round(top["goodput"], 2),
        "unit": "req_per_s",
        "replicas": n_top,
        "goodput_by_n": {
            str(n): round(r["goodput"], 2) for n, r in results.items()
        },
        "scaling_by_n": {str(n): s for n, s in scaling.items()},
        "completed": top["ok"], "shed": top["shed"],
        "errors": top["errors"],
        "tcp_connects": top["tcp_connects"],
        "router_reconnects": top["router_reconnects"],
        **common,
    }), flush=True)
    print(json.dumps({
        "metric": "fleet_open_loop_p99_latency_ms",
        "value": top["p99"],
        "unit": "ms",
        "replicas": n_top,
        "p50_ms": top["p50"], "p95_ms": top["p95"],
        "p99_by_n": {str(n): r["p99"] for n, r in results.items()},
        **common,
    }), flush=True)
    if disagg_res is not None:
        print(json.dumps({
            "metric": "fleet_disagg_goodput_rps",
            "value": round(disagg_res["goodput"], 2),
            "unit": "req_per_s",
            "tiers": ["encode", "decode"],
            "completed": disagg_res["ok"], "shed": disagg_res["shed"],
            "errors": disagg_res["errors"],
            "p50_ms": disagg_res["p50"], "p99_ms": disagg_res["p99"],
            "handoffs": disagg_res["handoffs"],
            "single_replica_goodput": (
                round(results[min(sizes)]["goodput"], 2)
                if min(sizes) in results else None
            ),
            **common,
        }), flush=True)
    over_all = np.asarray(overhead_ns, np.float64)
    print(json.dumps({
        "metric": "fleet_router_overhead_ms",
        "value": (
            round(float(np.median(over_all)) / 1e6, 3)
            if over_all.size else 0.0
        ),
        "unit": "ms",
        "percentile": "p50",
        "samples": int(over_all.size),
        **common,
    }), flush=True)
    # recompiling under load is the one hard failure; shed/scaling are
    # reported for the regression gate to judge
    return 0 if all(v == 0 for v in recompiles.values()) else 1


def tenants_bench(args, workdir) -> int:
    """--tenants: SLO isolation + fair-share on the multi-tenant plane
    (docs/SERVING.md "Multi-tenant serving").

    One continuous-mode server with a three-tenant registry: ``victim``
    (weight 4, unlimited — the paying tenant whose p99 the plane
    protects), ``peer`` (weight 1, unlimited — the fair-share
    counterparty) and ``flood`` (weight 1, quota ``--tenant-flood-rps``
    — the abuser).  Three phases:

    * **alone**: victim open loop at ``--tenant-rate`` — the flood-free
      p99 baseline;
    * **under flood**: the SAME victim load while flood offers
      ``--tenant-flood-rate`` (several times its quota) from background
      threads.  ``tenant_isolation_p99_ratio`` = victim p99 under
      flood / alone (1.0 = perfect isolation; the DRR scheduler +
      token-bucket admission keep it near 1);
    * **fair share**: victim and peer drive matched closed loops
      (both lanes continuously backlogged), so completions split by
      DRR weight.  ``tenant_fair_share_error`` = |observed victim
      share - 4/5|, noise-floored at 0.05 for the percent-delta
      regression gate (exact weighted fairness reads as the floor).

    Exits nonzero on any steady-state recompile, any victim
    error/shed (its lane must stay clean while the flood sheds), or a
    flood 5xx (overload must shed 429, not error)."""
    from sat_tpu import telemetry
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer

    config, vocabulary, tel = _make_ckpt(args, workdir)
    registry = os.path.join(workdir, "tenants.json")
    weights = {"victim": 4.0, "peer": 1.0, "flood": 1.0}
    with open(registry, "w") as f:
        json.dump({
            "default": "victim",
            "tenants": [
                {"name": "victim", "weight": weights["victim"]},
                {"name": "peer", "weight": weights["peer"]},
                {"name": "flood", "weight": weights["flood"],
                 "rps": args.tenant_flood_rps,
                 "burst": 2.0 * args.tenant_flood_rps},
            ],
        }, f)
    config = config.replace(
        serve_mode="continuous",
        serve_slot_pages=args.slot_pages,
        serve_page_width=args.page_width,
        tenants=registry,
    )
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpegs = _make_jpegs(8, config.image_size)
        log(f"tenant server up on port {port} (slot pool "
            f"{args.slot_pages}x{args.page_width}, weights {weights}, "
            f"flood quota {args.tenant_flood_rps} rps)")
        _post(port, jpegs[0])  # warm pass (first-touch host costs)
        compiles0 = tel.counters().get("jax/compiles", 0)

        vic = {"X-Tenant": "victim"}
        alone = open_loop(port, jpegs, args.tenant_rate,
                          args.tenant_requests, headers=vic)
        log(f"victim alone @ {args.tenant_rate}/s: {alone['ok']} ok "
            f"(p50 {alone['p50']}ms p99 {alone['p99']}ms)")

        # flood offers several times its quota for the WHOLE victim arm:
        # an open-loop driver (fire-and-forget threads, like open_loop)
        # so slow admitted requests never self-throttle the offered rate
        stop = threading.Event()
        flood_codes, flock = [], threading.Lock()

        def flood_fire():
            status, _lat = _post(port, jpegs[0],
                                 headers={"X-Tenant": "flood"})
            with flock:
                flood_codes.append(status)

        def flood_driver():
            rng = random.Random(7)
            while not stop.is_set():
                time.sleep(rng.expovariate(args.tenant_flood_rate))
                threading.Thread(target=flood_fire, daemon=True).start()

        driver = threading.Thread(target=flood_driver, daemon=True)
        driver.start()
        under = open_loop(port, jpegs, args.tenant_rate,
                          args.tenant_requests, headers=vic)
        stop.set()
        driver.join(timeout=60)
        time.sleep(2.0)  # let in-flight flood requests land
        with flock:
            flood_shed = sum(1 for c in flood_codes if c == 429)
            flood_5xx = sum(1 for c in flood_codes if c == 0 or c >= 500)
            flood_total = len(flood_codes)
        raw_ratio = (
            under["p99"] / alone["p99"] if alone["p99"] else 0.0
        )
        # noise-floored like the fair-share row: tail-over-tail on a
        # shared CPU host swings 1.1-2.5x run to run, which a
        # percent-delta gate would misread as a regression.  Ratios
        # under the floor are healthy isolation; a broken plane (no
        # quota, no DRR weighting) reads 4-11x and clears it by far
        ratio = round(max(raw_ratio, 3.0), 3)
        log(f"victim under flood @ {args.tenant_rate}/s: {under['ok']} ok, "
            f"{under['shed']} shed (p99 {under['p99']}ms vs "
            f"{alone['p99']}ms alone -> raw ratio {raw_ratio:.3f}, "
            f"floored {ratio}); flood: "
            f"{flood_total} offered, {flood_shed} shed, "
            f"{flood_5xx} 5xx")

        # fair share: a time-boxed contended interval.  Fixed-size
        # closed loops can't measure fairness (every loop completes all
        # its requests eventually — the split is 50/50 by construction);
        # instead both lanes run enough blocking clients to stay
        # backlogged for the same wall-clock window, and DRR splits the
        # completions by weight
        share_stop = threading.Event()
        share_ok = {"victim": 0, "peer": 0}
        share_lock = threading.Lock()

        def share_worker(tenant, wid):
            while not share_stop.is_set():
                status, _lat = _post(port, jpegs[wid % len(jpegs)],
                                     headers={"X-Tenant": tenant})
                if status == 200 and not share_stop.is_set():
                    with share_lock:
                        share_ok[tenant] += 1

        workers = [
            threading.Thread(target=share_worker, args=(t, w), daemon=True)
            for t in ("victim", "peer")
            for w in range(args.tenant_concurrency)
        ]
        for t in workers:
            t.start()
        time.sleep(args.tenant_share_seconds)
        share_stop.set()
        for t in workers:
            t.join(timeout=120)
        expected = weights["victim"] / (weights["victim"] + weights["peer"])
        total_ok = share_ok["victim"] + share_ok["peer"]
        observed = share_ok["victim"] / total_ok if total_ok else 0.0
        raw_err = abs(observed - expected)
        # noise-floored for the regression gate: the gate compares
        # percent deltas, and a near-zero baseline would turn count
        # jitter (0.01 -> 0.03) into a fake 200% regression.  Errors
        # under the floor are indistinguishable from scheduling noise;
        # real unfairness (a broken DRR reads ~0.2+) clears it by far
        share_err = round(max(raw_err, 0.05), 4)
        log(f"fair share over {args.tenant_share_seconds}s contended: "
            f"victim {share_ok['victim']} ok vs peer {share_ok['peer']} "
            f"ok -> observed share {observed:.3f} (weighted target "
            f"{expected:.3f}, error {share_err})")

        recompiles = tel.counters().get("jax/compiles", 0) - compiles0
        victim_bad = (
            alone["errors"] + under["errors"] + under["shed"]
            + alone["shed"]
        )
        log(f"steady-state XLA compiles during tenant load: {recompiles}")

        common = {
            "weights": weights,
            "flood_quota_rps": args.tenant_flood_rps,
            "flood_offered_rate_per_s": args.tenant_flood_rate,
            "victim_rate_per_s": args.tenant_rate,
            "victim_arrivals_per_arm": args.tenant_requests,
            "slot_pages": args.slot_pages,
            "page_width": args.page_width,
            "steady_state_compiles": recompiles,
            **telemetry.bench_stamp(),
        }
        print(json.dumps({
            "metric": "tenant_isolation_p99_ratio",
            "value": ratio,
            "unit": "ratio",
            "victim_alone_p99_ms": alone["p99"],
            "victim_under_flood_p99_ms": under["p99"],
            "raw_p99_ratio": round(raw_ratio, 3),
            "noise_floor": 3.0,
            "victim_alone_p50_ms": alone["p50"],
            "victim_under_flood_p50_ms": under["p50"],
            "victim_errors": victim_bad,
            "flood_offered": flood_total,
            "flood_shed": flood_shed,
            "flood_5xx": flood_5xx,
            **common,
        }), flush=True)
        print(json.dumps({
            "metric": "tenant_fair_share_error",
            "value": share_err,
            "unit": "fraction",
            "observed_victim_share": round(observed, 4),
            "expected_victim_share": round(expected, 4),
            "raw_share_error": round(raw_err, 4),
            "noise_floor": 0.05,
            "victim_completed": share_ok["victim"],
            "peer_completed": share_ok["peer"],
            "contended_seconds": args.tenant_share_seconds,
            "clients_per_tenant": args.tenant_concurrency,
            **common,
        }), flush=True)
        ok = recompiles == 0 and victim_bad == 0 and flood_5xx == 0
        if not ok:
            log(f"FAIL: isolation invariant violated "
                f"(recompiles={recompiles}, victim_bad={victim_bad}, "
                f"flood_5xx={flood_5xx})")
        return 0 if ok else 1
    finally:
        _CLIENT.close_all()
        server.shutdown()


def metering_bench(args, workdir) -> int:
    """--metering: what the cost-attribution plane itself costs, and the
    would-be encode-cache probe (docs/OBSERVABILITY.md "Cost attribution
    and tenant metering").

    * **charge-path microbench** — times the FULL per-request metering
      path in isolation (a sketch observe, one encode share, four
      fused-window decode shares, the occupancy stamp, then the terminal
      ``charge()`` with its three counter ticks and rate-limited ledger
      flush) and prices it against the live arm's request p50:
      ``metering_overhead_pct``.  Hard gate: raw overhead <= 0.5%
      (exit 1 over) — attribution must be free relative to the work it
      meters.
    * **would-be encode-cache probe** — two open-loop arms on fresh
      servers (each boot gets a fresh sliding sketch): UNIQUE traffic
      first (every arrival a distinct image, warm pass included — a
      content-addressed encode cache would buy nothing, so the probe
      must read ~0), then ZIPF traffic (arrivals drawn rank-weighted
      from a small base, p ∝ 1/rank^--zipf-s — the repeat-heavy regime
      ROADMAP item 2 hypothesizes).  ``encode_cache_would_hit_ratio``
      reports the Zipf arm's /stats gauge with the unique arm's riding
      as the control extra.

    Every live arm also asserts the accounting identity — attributed
    device-ms within ±5% of measured busy over the arm's own window
    (deltas from after the warm pass, so boot costs stay out) — and
    zero steady-state recompiles."""
    from sat_tpu import telemetry
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer
    from sat_tpu.telemetry.capacity import EncodeCacheSketch
    from sat_tpu.telemetry.metering import (
        MeteringLedger,
        RequestCost,
        measured_busy_ms,
    )

    config, vocabulary, tel = _make_ckpt(args, workdir)
    config = config.replace(
        serve_mode="continuous",
        serve_slot_pages=args.slot_pages,
        serve_page_width=args.page_width,
        serve_metering=True,
    )
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()

    # --- charge-path microbench (pure host, no server) ---------------
    mb_ledger = MeteringLedger(
        path=os.path.join(workdir, "microbench_metering.jsonl"),
        cap_bytes=1 << 20,
        tel=tel,
    )
    mb_sketch = EncodeCacheSketch()
    n_mb = 20000
    t0 = time.perf_counter()
    for i in range(n_mb):
        mb_sketch.observe(i % 64)
        cost = RequestCost()
        cost.add_encode(3_000_000)
        for _ in range(4):  # a typical ride: four fused windows
            cost.add_decode(2_000_000, steps=8)
        cost.set_occupancy(40_000_000)
        mb_ledger.charge("mb%d" % (i % 4), cost, queue_ms=0.4,
                         detok_ms=0.2)
    charge_us = (time.perf_counter() - t0) / n_mb * 1e6
    log(f"charge-path microbench: {charge_us:.2f}us/request over "
        f"{n_mb} charges (4 tenants, 4 decode windows each)")

    total = args.metering_requests

    def serve_arm(name, jpegs, warm):
        """One open-loop arm on a FRESH server (fresh sketch + ledger);
        returns the loop dict plus identity/compile/probe readings over
        the arm's own window."""
        server = CaptionServer(config, engine, port=0).start()
        try:
            port = server.port
            _post(port, warm)  # warm pass (first-touch host costs)
            compiles0 = tel.counters().get("jax/compiles", 0)
            attr0 = server.metering.attributed_device_ms()
            busy0 = measured_busy_ms(tel)
            loop = open_loop(port, jpegs, args.metering_rate, total)
            time.sleep(1.1)  # let the rate-limited capacity tick land
            stats = _get_json(port, "/stats")
            cap = stats.get("capacity", {})
            attributed = server.metering.attributed_device_ms() - attr0
            measured = measured_busy_ms(tel) - busy0
            err_pct = (
                abs(attributed - measured) / measured * 100.0
                if measured else 0.0
            )
            recompiles = tel.counters().get("jax/compiles", 0) - compiles0
            log(f"{name} arm: {loop['ok']} ok, {loop['shed']} shed "
                f"(p50 {loop['p50']}ms p99 {loop['p99']}ms); attributed "
                f"{attributed:.1f}ms vs measured {measured:.1f}ms busy "
                f"-> identity error {err_pct:.2f}%; would-hit "
                f"{cap.get('encode_cache_would_hit_ratio')}; "
                f"steady-state compiles {recompiles}")
            return {
                "loop": loop,
                "would_hit": float(
                    cap.get("encode_cache_would_hit_ratio", 0.0)
                ),
                "headroom_pct": cap.get("headroom_pct"),
                "identity_error_pct": round(err_pct, 3),
                "attributed_device_ms": round(attributed, 3),
                "measured_busy_ms": round(measured, 3),
                "recompiles": recompiles,
            }
        finally:
            _CLIENT.close_all()
            server.shutdown()

    # unique control first: warm image + every arrival all DISTINCT,
    # so a content-addressed encode cache would buy nothing
    unique_imgs = _make_jpegs(total + 1, config.image_size)
    uniq = serve_arm("unique", unique_imgs[1:], warm=unique_imgs[0])

    # zipf arm: arrivals drawn rank-weighted from a small base — the
    # repeat-heavy regime where caching WOULD pay (warm pass reuses the
    # hottest rank, like real traffic would)
    base = _make_jpegs(16, config.image_size)
    rng = np.random.default_rng(11)
    p = 1.0 / (np.arange(len(base)) + 1.0) ** args.zipf_s
    p = p / p.sum()
    picks = rng.choice(len(base), size=total, p=p)
    zipf_seq = [base[int(r)] for r in picks]
    zipf = serve_arm("zipf", zipf_seq, warm=base[0])

    raw_overhead = (
        charge_us / 1e3 / zipf["loop"]["p50"] * 100.0
        if zipf["loop"]["p50"] else 0.0
    )
    # noise-floored like the tenant rows: the raw number is ~0.005% and
    # a percent-delta regression gate would turn scheduler jitter on a
    # shared box into fake regressions; anything under the floor is
    # free, and the HARD gate below judges the raw value
    overhead = round(max(raw_overhead, 0.05), 4)
    identity_ok = (
        uniq["identity_error_pct"] <= 5.0
        and zipf["identity_error_pct"] <= 5.0
    )
    recompiles = uniq["recompiles"] + zipf["recompiles"]

    common = {
        "requests_per_arm": total,
        "offered_rate_per_s": args.metering_rate,
        "slot_pages": args.slot_pages,
        "page_width": args.page_width,
        "identity_error_pct_unique": uniq["identity_error_pct"],
        "identity_error_pct_zipf": zipf["identity_error_pct"],
        "steady_state_compiles": recompiles,
        **telemetry.bench_stamp(),
    }
    print(json.dumps({
        "metric": "metering_overhead_pct",
        "value": overhead,
        "unit": "pct",
        "raw_overhead_pct": round(raw_overhead, 5),
        "noise_floor": 0.05,
        "gate_pct": 0.5,
        "charge_path_us": round(charge_us, 3),
        "microbench_charges": n_mb,
        "request_p50_ms": zipf["loop"]["p50"],
        "attributed_device_ms": zipf["attributed_device_ms"],
        "measured_busy_ms": zipf["measured_busy_ms"],
        **common,
    }), flush=True)
    print(json.dumps({
        "metric": "encode_cache_would_hit_ratio",
        "value": round(zipf["would_hit"], 4),
        "unit": "ratio",
        "unique_traffic_ratio": round(uniq["would_hit"], 4),
        "zipf_s": args.zipf_s,
        "zipf_base_images": len(base),
        "headroom_pct": zipf["headroom_pct"],
        **common,
    }), flush=True)

    ok = (
        raw_overhead <= 0.5
        and identity_ok
        and recompiles == 0
        and zipf["would_hit"] > 0.0
        and uniq["would_hit"] <= 0.05
    )
    if not ok:
        log(f"FAIL: metering invariant violated (overhead "
            f"{raw_overhead:.4f}%, identity unique "
            f"{uniq['identity_error_pct']}% / zipf "
            f"{zipf['identity_error_pct']}%, recompiles {recompiles}, "
            f"would-hit zipf {zipf['would_hit']} / unique "
            f"{uniq['would_hit']})")
    return 0 if ok else 1


def _post_caption(port, data, timeout=60.0):
    """One POST /caption via urllib, returning (status, parsed JSON) —
    the parity phases need the caption STRINGS, not just latencies."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/caption", data=data,
        headers={"Content-Type": "image/jpeg"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, {}


def encode_cache_bench(args, workdir) -> int:
    """--encode-cache: the content-addressed encode cache under repeat
    traffic (docs/SERVING.md "Encode cache & tiered fleets").

    One cache-on continuous-mode server, three phases:

    * **parity** — every base image captioned cold (a cache miss each),
      then again (a hit each): the hit captions must be BITWISE equal
      to the cold ones.  The cache stores the encoder's own output grid
      and the decode path is shared, so ANY drift is a correctness bug
      — exit 1 on the first mismatch.
    * **unique control** — open loop where every arrival is a distinct
      image: content addressing buys nothing, the hit ratio must read
      ~0 (the cache is flushed first so the arm is self-contained).
    * **zipf arm** — the same open loop with arrivals drawn
      rank-weighted (p ∝ 1/(rank+1)^--zipf-s) from a small base: the
      repeat-heavy regime the cache exists for.  The arm's
      ``encode_cache_hit_ratio`` must clear the 0.6 acceptance floor,
      and ``cache_serve_goodput_rps`` reports its goodput with the
      unique arm riding as the control extra.

    The ring is AOT-warmed at boot (insert + gather per lane width), so
    every phase also asserts ZERO steady-state recompiles — one XLA
    compile under load exits 1."""
    from sat_tpu import telemetry
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer

    config, vocabulary, tel = _make_ckpt(args, workdir)
    config = config.replace(
        serve_mode="continuous",
        serve_slot_pages=args.slot_pages,
        serve_page_width=args.page_width,
        encode_cache="on",
        encode_cache_mb=args.encode_cache_mb,
    )
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    cache = engine.encode_cache
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        base = _make_jpegs(16, config.image_size)
        log(f"cache server up on port {port} (ring {cache.rows} rows, "
            f"warm widths {cache.warm_widths})")
        _post(port, base[0])  # warm pass (first-touch host costs)
        compiles0 = tel.counters().get("jax/compiles", 0)

        # --- parity: cold (miss) captions vs hit captions, bitwise ------
        cache.flush()
        cold, hot = [], []
        for img in base:
            status, body = _post_caption(port, img)
            assert status == 200, f"cold caption -> {status}"
            cold.append(body["captions"][0]["caption"])
        s_after_cold = cache.stats()
        for img in base:
            status, body = _post_caption(port, img)
            assert status == 200, f"hit caption -> {status}"
            hot.append(body["captions"][0]["caption"])
        s_after_hot = cache.stats()
        mismatches = sum(1 for c, h in zip(cold, hot) if c != h)
        hits_taken = s_after_hot["hits"] - s_after_cold["hits"]
        log(f"parity: {len(base)} cold -> {len(base)} hot captions, "
            f"{mismatches} mismatches ({hits_taken} served from cache)")
        if mismatches or hits_taken < len(base):
            log(f"FAIL: hit-path parity broken (mismatches={mismatches}, "
                f"cache hits {hits_taken}/{len(base)})")
            return 1

        total = args.cache_requests

        def arm(name, jpegs):
            """Flush, run one open loop, return (loop, arm hit ratio,
            arm stats deltas) — the ratio is computed over the arm's OWN
            lookups so phases never cross-contaminate."""
            cache.flush()
            s0 = cache.stats()
            loop = open_loop(port, jpegs, args.cache_rate, total)
            s1 = cache.stats()
            served = {
                k: s1[k] - s0[k]
                for k in ("hits", "misses", "coalesced", "evictions")
            }
            looked = (
                served["hits"] + served["misses"] + served["coalesced"]
            )
            ratio = (
                (served["hits"] + served["coalesced"]) / looked
                if looked else 0.0
            )
            loop["goodput"] = (
                loop["ok"] / loop["wall_s"] if loop["wall_s"] else 0.0
            )
            log(f"{name} arm @ {args.cache_rate}/s: {loop['ok']} ok, "
                f"{loop['shed']} shed -> {loop['goodput']:.1f} req/s "
                f"(p50 {loop['p50']}ms p99 {loop['p99']}ms); cache "
                f"{served['hits']} hit / {served['misses']} miss / "
                f"{served['coalesced']} coalesced -> ratio {ratio:.3f}")
            return loop, round(ratio, 4), served

        # unique control first: every arrival distinct
        uniq_loop, uniq_ratio, _ = arm(
            "unique", _make_jpegs(total, config.image_size)
        )

        # zipf arm: rank-weighted repeats over the small base
        rng = np.random.default_rng(11)
        p = 1.0 / (np.arange(len(base)) + 1.0) ** args.zipf_s
        p = p / p.sum()
        zipf_seq = [base[int(r)] for r in rng.choice(
            len(base), size=total, p=p)]
        zipf_loop, zipf_ratio, zipf_served = arm("zipf", zipf_seq)

        recompiles = tel.counters().get("jax/compiles", 0) - compiles0
        gather_ns = np.sort(np.asarray(
            tel.durations_ns("serve/cache_gather"), np.float64))
        gather_p95 = (
            round(float(gather_ns[min(gather_ns.size - 1,
                                      int(0.95 * gather_ns.size))]) / 1e6, 3)
            if gather_ns.size else None
        )
        stats_block = _get_json(port, "/stats").get("encode_cache", {})
        log(f"steady-state XLA compiles across all arms: {recompiles}; "
            f"cache gather p95 {gather_p95}ms")

        common = {
            "requests_per_arm": total,
            "offered_rate_per_s": args.cache_rate,
            "encode_cache_mb": args.encode_cache_mb,
            "cache_rows": cache.rows,
            "zipf_s": args.zipf_s,
            "zipf_base_images": len(base),
            "steady_state_compiles": recompiles,
            "parity_mismatches": mismatches,
            **telemetry.bench_stamp(),
        }
        print(json.dumps({
            "metric": "encode_cache_hit_ratio",
            "value": zipf_ratio,
            "unit": "ratio",
            "unique_traffic_ratio": uniq_ratio,
            "zipf_hits": zipf_served["hits"],
            "zipf_misses": zipf_served["misses"],
            "zipf_coalesced": zipf_served["coalesced"],
            "zipf_evictions": zipf_served["evictions"],
            "cache_entries": stats_block.get("entries"),
            "cache_bytes": stats_block.get("bytes"),
            "gather_p95_ms": gather_p95,
            **common,
        }), flush=True)
        print(json.dumps({
            "metric": "cache_serve_goodput_rps",
            "value": round(zipf_loop["goodput"], 2),
            "unit": "req_per_s",
            "completed": zipf_loop["ok"], "shed": zipf_loop["shed"],
            "p50_ms": zipf_loop["p50"], "p95_ms": zipf_loop["p95"],
            "p99_ms": zipf_loop["p99"],
            "unique_goodput_rps": round(uniq_loop["goodput"], 2),
            "unique_p50_ms": uniq_loop["p50"],
            "unique_p99_ms": uniq_loop["p99"],
            **common,
        }), flush=True)

        ok = (
            recompiles == 0
            and mismatches == 0
            and zipf_ratio >= 0.6
            and uniq_ratio <= 0.05
        )
        if not ok:
            log(f"FAIL: cache invariant violated (recompiles="
                f"{recompiles}, parity mismatches {mismatches}, zipf "
                f"ratio {zipf_ratio} < 0.6 or unique ratio {uniq_ratio} "
                f"> 0.05)")
        return 0 if ok else 1
    finally:
        _CLIENT.close_all()
        server.shutdown()


def _post_admin(port, action, timeout=240.0):
    """POST a lifecycle admin verb; (status, payload).  Long timeout:
    /promote blocks on the replica until the swap lands."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{action}", data=b"", method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def lifecycle_bench(args, workdir) -> int:
    """--lifecycle: cost of a full reload -> canary -> promote cycle on
    a live continuous-mode server.

    Arm A is a steady open loop against the incumbent alone; a retrained
    checkpoint then lands (sidecar + LAST_GOOD) and arm B runs the SAME
    open loop mid-canary, so ``canary_overhead_pct`` is the p50 price of
    dual-slot serving (hash routing + a second live slot pool).  The
    operator promote that follows measures ``swap_blackout_ms`` — the
    admission gap while in-flight pools drain before the param-slot
    flip.  Exits nonzero on any steady-state recompile or any dropped
    (5xx / connection-failed) request across the whole cycle: the
    zero-downtime invariant IS the bench contract."""
    from sat_tpu import telemetry
    from sat_tpu.data.vocabulary import vocab_fingerprint
    from sat_tpu.resilience import lineage
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer

    base_config, vocabulary, tel = _make_ckpt(args, workdir)
    config = base_config.replace(
        serve_mode="continuous",
        serve_slot_pages=args.slot_pages,
        serve_page_width=args.page_width,
        model_reload=0.0,          # the bench drives /reload itself
        canary_fraction=args.canary_fraction,
        canary_window_s=600.0,     # never auto-expires under the bench
        promote_policy="manual",   # the bench decides when to promote
        canary_shadow_rate=0.0,
    )
    state, _ = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    server = CaptionServer(config, engine, port=0).start()
    try:
        port = server.port
        jpegs = _make_jpegs(8, config.image_size)
        log(f"lifecycle server up on port {port} (slot pool "
            f"{args.slot_pages}x{args.page_width}, canary fraction "
            f"{args.canary_fraction})")
        _post(port, jpegs[0])  # warm pass (first-touch host costs)
        base_step = engine.step
        compiles0 = tel.counters().get("jax/compiles", 0)

        arm_a = open_loop(
            port, jpegs, args.lifecycle_rate, args.lifecycle_requests
        )
        log(f"arm A (incumbent only) @ {args.lifecycle_rate}/s: "
            f"{arm_a['ok']} ok, {arm_a['shed']} shed "
            f"(p50 {arm_a['p50']}ms p99 {arm_a['p99']}ms)")

        # a "retrain" lands: same geometry, nudged decoder params
        new_step = base_step + 100
        flat = dict(np.load(os.path.join(
            config.save_dir, f"{base_step}.npz")))
        for k in list(flat):
            if k.startswith("params/decoder/") and flat[k].dtype.kind == "f":
                flat[k] = flat[k] + np.asarray(1e-3, flat[k].dtype)
        flat["global_step"] = np.asarray(new_step, np.int64)
        cand_path = os.path.join(config.save_dir, f"{new_step}.npz")
        with open(cand_path, "wb") as f:
            np.savez(f, **flat)
        lineage.write_sidecar(cand_path, vocab=vocab_fingerprint(
            config.vocabulary_file, config.vocabulary_size))
        lineage.mark_last_good(config.save_dir, new_step)

        status, body = _post_admin(port, "reload")
        if status != 200:
            log(f"FAIL: /reload -> {status}: {body}")
            return 1
        deadline = time.time() + 120.0
        while time.time() < deadline:
            if _get_json(port, "/stats")["lifecycle"]["state"] == "CANARY":
                break
            time.sleep(0.05)
        else:
            log("FAIL: canary never armed")
            return 1
        log(f"canary armed for step {new_step}")

        arm_b = open_loop(
            port, jpegs, args.lifecycle_rate, args.lifecycle_requests
        )
        log(f"arm B (mid-canary) @ {args.lifecycle_rate}/s: "
            f"{arm_b['ok']} ok, {arm_b['shed']} shed "
            f"(p50 {arm_b['p50']}ms p99 {arm_b['p99']}ms)")

        status, body = _post_admin(port, "promote")
        if status != 200 or body.get("model_step") != new_step:
            log(f"FAIL: /promote -> {status}: {body}")
            return 1
        stats = _get_json(port, "/stats")
        last = stats["lifecycle"].get("last_cycle") or {}
        blackout_ms = last.get("blackout_ms")
        # post-promote sanity: the new incumbent answers
        post_status, _ = _post(port, jpegs[0])

        recompiles = tel.counters().get("jax/compiles", 0) - compiles0
        http_5xx = tel.counters().get("serve/http_5xx", 0)
        errors = arm_a["errors"] + arm_b["errors"]
        overhead_pct = (
            round((arm_b["p50"] / arm_a["p50"] - 1.0) * 100.0, 2)
            if arm_a["p50"] else None
        )
        log(f"promoted step {new_step}: swap blackout {blackout_ms}ms, "
            f"canary p50 overhead {overhead_pct}%, steady-state "
            f"recompiles {recompiles}, 5xx {http_5xx}")

        common = {
            "slot_pages": args.slot_pages,
            "page_width": args.page_width,
            "canary_fraction": args.canary_fraction,
            "offered_rate_per_s": args.lifecycle_rate,
            "requests_per_arm": args.lifecycle_requests,
            "steady_state_compiles": recompiles,
            "http_5xx": http_5xx,
            **telemetry.bench_stamp(),
        }
        print(json.dumps({
            "metric": "swap_blackout_ms",
            "value": blackout_ms,
            "unit": "ms",
            "promoted_step": new_step,
            "drain_mode": "continuous",
            **common,
        }), flush=True)
        print(json.dumps({
            "metric": "canary_overhead_pct",
            "value": overhead_pct,
            "unit": "pct",
            "incumbent_p50_ms": arm_a["p50"],
            "canary_p50_ms": arm_b["p50"],
            "incumbent_p99_ms": arm_a["p99"],
            "canary_p99_ms": arm_b["p99"],
            **common,
        }), flush=True)
        ok = (
            recompiles == 0 and http_5xx == 0 and errors == 0
            and blackout_ms is not None and post_status == 200
        )
        if not ok:
            log("FAIL: zero-downtime invariant violated "
                f"(recompiles={recompiles}, 5xx={http_5xx}, "
                f"errors={errors}, blackout={blackout_ms}, "
                f"post_promote={post_status})")
        return 0 if ok else 1
    finally:
        server.shutdown()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--requests", type=int, default=25,
                    help="closed loop: requests per worker")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="open loop: Poisson arrival rate, req/s")
    ap.add_argument("--cont-rate", type=float, default=8.5,
                    help="batch-vs-continuous comparison: Poisson rate "
                         "near the batch path's padded-bucket capacity")
    ap.add_argument("--open-requests", type=int, default=200,
                    help="open loop: total arrivals")
    ap.add_argument("--buckets", default="1,4,16")
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--queue-depth", type=int, default=128)
    ap.add_argument("--slot-pages", type=int, default=4,
                    help="continuous mode: pages in the slot pool")
    ap.add_argument("--page-width", type=int, default=4,
                    help="continuous mode: slots per page")
    ap.add_argument("--quant-ab", choices=("none", "bf16", "int8"),
                    default="none",
                    help="A/B the PTQ encoder (sat_tpu/nn/quant.py): after "
                         "the fp32 loops, reload the SAME checkpoint with "
                         "--encoder_quant and re-run the closed loop, "
                         "emitting serve_encode_ms / *_<mode> row pairs")
    ap.add_argument("--eos-bias", type=float, default=0.006,
                    help="EOS-logit bias on the fresh params: sits on the "
                         "seal-step cliff so the diverse bench images give "
                         "mixed caption lengths — most seal in 2-3 steps, "
                         "a few run to max_caption_length (0 disables)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: goodput scaling across N router-"
                         "fronted replicas instead of the single-server "
                         "arms (fleet_goodput_rps / "
                         "fleet_open_loop_p99_latency_ms rows)")
    ap.add_argument("--fleet-sizes", default="1,2,4",
                    help="fleet mode: replica counts per arm (max is "
                         "spawned once; arms front prefixes)")
    ap.add_argument("--fleet-rate", type=float, default=10.0,
                    help="fleet mode: matched open-loop Poisson rate per "
                         "arm; well above the LARGEST arm's capacity so "
                         "every arm is backlogged from its first dispatch "
                         "(full micro-batches throughout) and goodput "
                         "tracks fleet capacity at every size")
    ap.add_argument("--fleet-requests", type=int, default=24,
                    help="fleet mode: total arrivals per arm (bounded by "
                         "the saturated n=1 arm's wall time against the "
                         "client/proxy timeouts)")
    ap.add_argument("--fleet-service-floor-ms", type=int, default=4000,
                    help="fleet mode: per-dispatched-batch service-time "
                         "floor armed on every replica via "
                         "SAT_FI_SLOW_SERVE_MS.  Makes each replica "
                         "occupancy-bound (like a device-backed one) so "
                         "goodput scales with fleet size even when all "
                         "replicas share this host's CPUs; 0 disables "
                         "and measures raw CPU-decode contention")
    ap.add_argument("--tenants", action="store_true",
                    help="tenant mode: per-tenant SLO isolation + DRR "
                         "fair-share on one continuous-mode server "
                         "(tenant_isolation_p99_ratio / "
                         "tenant_fair_share_error rows; exit 1 on any "
                         "recompile, victim-lane shed/error or flood 5xx)")
    ap.add_argument("--tenant-rate", type=float, default=6.0,
                    help="tenant mode: victim open-loop Poisson rate for "
                         "the alone and under-flood arms")
    ap.add_argument("--tenant-requests", type=int, default=80,
                    help="tenant mode: victim arrivals per arm")
    ap.add_argument("--tenant-flood-rate", type=float, default=30.0,
                    help="tenant mode: offered flood rate, several times "
                         "the flood tenant's admission quota")
    ap.add_argument("--tenant-flood-rps", type=float, default=1.0,
                    help="tenant mode: the flood tenant's token-bucket "
                         "quota (rps; burst = 2x).  Small relative to "
                         "the box's capacity: the admitted remainder is "
                         "the flood's LEGAL share, and the isolation "
                         "ratio should price only that")
    ap.add_argument("--tenant-concurrency", type=int, default=18,
                    help="tenant mode: blocking clients PER TENANT in "
                         "the fair-share phase — must exceed the "
                         "victim's weighted share of the slot pool, or "
                         "its lane drains and work-conservation hands "
                         "the peer extra seats")
    ap.add_argument("--tenant-share-seconds", type=float, default=12.0,
                    help="tenant mode: wall-clock length of the "
                         "fair-share contended window")
    ap.add_argument("--metering", action="store_true",
                    help="metering mode: cost-attribution overhead + "
                         "would-be encode-cache probe "
                         "(metering_overhead_pct / "
                         "encode_cache_would_hit_ratio rows; exit 1 on "
                         "raw overhead > 0.5%%, identity error > 5%%, "
                         "any recompile, or a dead/false probe)")
    ap.add_argument("--metering-rate", type=float, default=6.0,
                    help="metering mode: open-loop Poisson rate per arm")
    ap.add_argument("--metering-requests", type=int, default=60,
                    help="metering mode: arrivals per arm")
    ap.add_argument("--zipf-s", type=float, default=1.1,
                    help="metering mode: Zipf exponent for the repeat-"
                         "heavy arm (rank r drawn with p proportional "
                         "to 1/(r+1)^s over the 16 base images)")
    ap.add_argument("--encode-cache", action="store_true",
                    help="cache mode: content-addressed encode cache "
                         "under Zipf vs unique traffic "
                         "(encode_cache_hit_ratio / "
                         "cache_serve_goodput_rps rows; exit 1 on any "
                         "recompile, hit/cold caption mismatch, Zipf "
                         "ratio < 0.6 or unique ratio > 0.05)")
    ap.add_argument("--cache-rate", type=float, default=6.0,
                    help="cache mode: open-loop Poisson rate per arm")
    ap.add_argument("--cache-requests", type=int, default=80,
                    help="cache mode: arrivals per arm")
    ap.add_argument("--encode-cache-mb", type=int, default=8,
                    help="cache mode: HBM ring budget (MB); the tiny "
                         "bench grids need well under 1MB, so the "
                         "default never evicts mid-arm")
    ap.add_argument("--lifecycle", action="store_true",
                    help="lifecycle mode: a full reload -> canary -> "
                         "promote cycle on a live continuous-mode server "
                         "(swap_blackout_ms / canary_overhead_pct rows; "
                         "exit 1 on any recompile or dropped request)")
    ap.add_argument("--lifecycle-rate", type=float, default=8.0,
                    help="lifecycle mode: open-loop Poisson rate for the "
                         "incumbent-only and mid-canary arms")
    ap.add_argument("--lifecycle-requests", type=int, default=120,
                    help="lifecycle mode: arrivals per arm")
    ap.add_argument("--canary-fraction", type=float, default=0.25,
                    help="lifecycle mode: request fraction hash-routed "
                         "to the candidate during arm B")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_serve_")
    made_workdir = args.workdir is None
    if (args.fleet or args.lifecycle or args.tenants or args.metering
            or args.encode_cache):
        try:
            if args.fleet:
                return fleet_bench(args, workdir)
            if args.tenants:
                return tenants_bench(args, workdir)
            if args.metering:
                return metering_bench(args, workdir)
            if args.encode_cache:
                return encode_cache_bench(args, workdir)
            return lifecycle_bench(args, workdir)
        finally:
            if made_workdir:
                shutil.rmtree(workdir, ignore_errors=True)
    server = None
    try:
        from sat_tpu import telemetry

        server, engine, tel = _boot(args, workdir)
        jpegs = _make_jpegs(8, engine.config.image_size)
        port = server.port

        # one warm pass so steady-state numbers exclude first-touch costs
        _post(port, jpegs[0])
        compiles0 = tel.counters().get("jax/compiles", 0)
        enc_mark = len(tel.durations_ns("serve/encode"))

        closed = closed_loop(port, jpegs, args.concurrency, args.requests)
        log(f"closed loop: {closed['ok']} ok in {closed['wall_s']:.1f}s -> "
            f"{closed['throughput']:.1f} req/s "
            f"(p50 {closed['p50']}ms p99 {closed['p99']}ms)")

        opened = open_loop(port, jpegs, args.rate, args.open_requests)
        log(f"open loop @ {args.rate}/s: {opened['ok']} ok, "
            f"{opened['shed']} shed in {opened['wall_s']:.1f}s "
            f"(p50 {opened['p50']}ms p99 {opened['p99']}ms)")

        recompiles = tel.counters().get("jax/compiles", 0) - compiles0
        log(f"steady-state XLA compiles during load: {recompiles}")

        counters = tel.counters()
        hist = {k[len("serve/bucket_"):]: v for k, v in counters.items()
                if k.startswith("serve/bucket_")}
        common = {
            "buckets": args.buckets,
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "bucket_histogram": hist,
            "warm_compiles": engine.warm_compiles,
            "steady_state_compiles": recompiles,
            **telemetry.bench_stamp(),
        }
        print(json.dumps({
            "metric": "serve_closed_loop_throughput",
            "value": round(closed["throughput"], 2),
            "unit": "req_per_s",
            "concurrency": args.concurrency,
            "requests_per_worker": args.requests,
            "p50_ms": closed["p50"], "p95_ms": closed["p95"],
            "p99_ms": closed["p99"],
            "tcp_connects": closed["tcp_connects"],
            "reconnects": closed["reconnects"],
            **common,
        }), flush=True)
        print(json.dumps({
            "metric": "serve_open_loop_p99_latency_ms",
            "value": opened["p99"],
            "unit": "ms",
            "offered_rate_per_s": args.rate,
            "completed": opened["ok"], "shed": opened["shed"],
            "p50_ms": opened["p50"], "p95_ms": opened["p95"],
            "tcp_connects": opened["tcp_connects"],
            **common,
        }), flush=True)

        def _enc_ms(start):
            """Encode-lane percentiles from the serve/encode spans the
            engine records (telemetry is on for the whole bench)."""
            ns = np.asarray(tel.durations_ns("serve/encode")[start:],
                            np.float64)
            if not ns.size:
                return None
            s = np.sort(ns) / 1e6
            def pct(p):
                return round(float(s[min(s.size - 1,
                                         int(p / 100.0 * s.size))]), 3)
            return {"count": int(s.size), "p50": pct(50), "p95": pct(95)}

        enc = _enc_ms(enc_mark)
        if enc:
            print(json.dumps({
                "metric": "serve_encode_ms",
                "value": enc["p50"],
                "unit": "ms",
                "percentile": "p50",
                "p95_ms": enc["p95"],
                "encodes": enc["count"],
                "encoder_quant": "off",
                **common,
            }), flush=True)

        # --- batch vs continuous at the SAME near-capacity rate ----------
        # deep saturation is the batch path's best case (every bucket
        # rides full, encode fully amortized); the regime continuous
        # batching exists for is offered load near the batch path's
        # padded-bucket capacity, where whole-batch windows hold every
        # request while lanes admit exactly what arrived
        ref = open_loop(port, jpegs, args.cont_rate, args.open_requests)
        ref_goodput = ref["ok"] / ref["wall_s"] if ref["wall_s"] else 0.0
        log(f"batch reference @ {args.cont_rate}/s: {ref['ok']} ok in "
            f"{ref['wall_s']:.1f}s -> {ref_goodput:.1f} req/s goodput "
            f"(p50 {ref['p50']}ms p99 {ref['p99']}ms)")

        server.shutdown()
        server = None
        from sat_tpu.serve.server import CaptionServer

        cont_config = engine.config.replace(
            serve_mode="continuous",
            serve_slot_pages=args.slot_pages,
            serve_page_width=args.page_width,
        )
        server = CaptionServer(cont_config, engine, port=0).start()
        port = server.port
        log(f"continuous server up on port {port} (slot pool "
            f"{args.slot_pages}x{args.page_width}, pool warm_compiles "
            f"{server.pool.warm_compiles})")
        _post(port, jpegs[0])  # warm pass (first-touch host costs)
        cont_compiles0 = tel.counters().get("jax/compiles", 0)
        steps_before = len(tel.durations_ns("serve/decode_steps"))

        def _span_pcts(name, start, scale=1e6):
            """p50/p95 over tel spans recorded after mark `start` (ms by
            default; scale=1 for raw-count spans like
            serve/steps_per_dispatch, whose duration field carries the
            fused steps-run count, not a time)."""
            vals = np.asarray(tel.durations_ns(name)[start:], np.float64)
            if not vals.size:
                return None
            s = np.sort(vals) / scale

            def pct(p):
                return round(float(s[min(s.size - 1,
                                         int(p / 100.0 * s.size))]), 3)
            return {"count": int(s.size), "p50": pct(50), "p95": pct(95)}

        # --- single-stream latency: the fused window's best case ---------
        # one closed-loop client keeps the admission queue empty, so the
        # adaptive policy runs every dispatch at the ladder's deepest K
        # and the per-step host round-trip leaves the critical path.
        spd_before = len(tel.durations_ns("serve/steps_per_dispatch"))
        single = closed_loop(port, jpegs, 1, args.requests)
        single_spd = _span_pcts("serve/steps_per_dispatch", spd_before,
                                scale=1.0)
        log(f"single stream (ladder "
            f"{list(cont_config.serve_decode_depth)}): {single['ok']} ok, "
            f"p50 {single['p50']}ms p99 {single['p99']}ms, steps/dispatch "
            f"p50 {single_spd['p50'] if single_spd else '?'}")

        # admission + detok-queue spans are sliced from HERE so the rows
        # below sample only the near-capacity open-loop phase (warm-pass
        # and single-stream admissions would dilute the burst regime)
        admit_before = len(tel.durations_ns("serve/admission_wait"))
        detokq_before = len(tel.durations_ns("serve/detok_queue"))
        cont = open_loop(port, jpegs, args.cont_rate, args.open_requests)
        cont_goodput = cont["ok"] / cont["wall_s"] if cont["wall_s"] else 0.0
        log(f"continuous open loop @ {args.cont_rate}/s: {cont['ok']} ok, "
            f"{cont['shed']} shed in {cont['wall_s']:.1f}s -> "
            f"{cont_goodput:.1f} req/s goodput "
            f"(p50 {cont['p50']}ms p99 {cont['p99']}ms; batch @ same rate: "
            f"{ref_goodput:.1f} req/s, p99 {ref['p99']}ms)")

        cont_recompiles = (
            tel.counters().get("jax/compiles", 0) - cont_compiles0
        )
        log(f"continuous steady-state XLA compiles during load: "
            f"{cont_recompiles}")
        admit = _span_pcts("serve/admission_wait", admit_before)
        admit_p95 = admit["p95"] if admit else 0.0
        detok_queue = _span_pcts("serve/detok_queue", detokq_before)
        load_spd = _span_pcts("serve/steps_per_dispatch", spd_before,
                              scale=1.0)
        steps = np.asarray(
            tel.durations_ns("serve/decode_steps")[steps_before:], np.float64
        )
        cont_common = dict(common)
        cont_common.update(
            slot_pages=args.slot_pages,
            page_width=args.page_width,
            pool_warm_compiles=server.pool.warm_compiles,
            steady_state_compiles=cont_recompiles,
            decode_depths=list(cont_config.serve_decode_depth),
            decode_steps_p50=(
                float(np.percentile(steps, 50)) if steps.size else None
            ),
        )
        print(json.dumps({
            "metric": "serve_continuous_goodput",
            "value": round(cont_goodput, 2),
            "unit": "req_per_s",
            "offered_rate_per_s": args.cont_rate,
            "completed": cont["ok"], "shed": cont["shed"],
            "p50_ms": cont["p50"], "p95_ms": cont["p95"],
            "p99_ms": cont["p99"],
            "batch_ref_goodput": round(ref_goodput, 2),
            "batch_ref_p50_ms": ref["p50"],
            "batch_ref_p99_ms": ref["p99"],
            **cont_common,
        }), flush=True)
        print(json.dumps({
            "metric": "serve_admission_latency_ms",
            "value": admit_p95,
            "unit": "ms",
            "percentile": "p95",
            "admitted": admit["count"] if admit else 0,
            "admission_p50_ms": admit["p50"] if admit else None,
            "detok_queue_p50_ms": detok_queue["p50"] if detok_queue else None,
            "detok_queue_p95_ms": detok_queue["p95"] if detok_queue else None,
            "load_steps_per_dispatch_p50": (
                load_spd["p50"] if load_spd else None
            ),
            **cont_common,
        }), flush=True)

        # --- K-ladder A/B: same geometry, fused window pinned off --------
        # serve_decode_depth=(1,) is exactly the pre-fused engine (one
        # decode step per host dispatch); the delta against the ladder
        # arm above is the fused window's contribution, with admission
        # p95 under the SAME near-capacity load as the no-worse check.
        server.shutdown()
        server = None
        k1_config = cont_config.replace(serve_decode_depth=(1,))
        server = CaptionServer(k1_config, engine, port=0).start()
        log(f"K=1 arm up on port {server.port} (pool warm_compiles "
            f"{server.pool.warm_compiles})")
        _post(server.port, jpegs[0])  # warm pass
        k1_compiles0 = tel.counters().get("jax/compiles", 0)
        k1_single = closed_loop(server.port, jpegs, 1, args.requests)
        k1_admit_before = len(tel.durations_ns("serve/admission_wait"))
        k1_open = open_loop(server.port, jpegs, args.cont_rate,
                            args.open_requests)
        k1_recompiles = tel.counters().get("jax/compiles", 0) - k1_compiles0
        k1_goodput = (
            k1_open["ok"] / k1_open["wall_s"] if k1_open["wall_s"] else 0.0
        )
        k1_admit = _span_pcts("serve/admission_wait", k1_admit_before)
        log(f"K=1 single stream: p50 {k1_single['p50']}ms p99 "
            f"{k1_single['p99']}ms; open loop goodput "
            f"{k1_goodput:.1f} req/s, admission p95 "
            f"{k1_admit['p95'] if k1_admit else 0.0}ms; steady-state "
            f"compiles {k1_recompiles}")

        print(json.dumps({
            "metric": "serve_single_stream_latency_ms",
            "value": single["p50"],
            "unit": "ms",
            "percentile": "p50",
            "p95_ms": single["p95"], "p99_ms": single["p99"],
            "requests": single["ok"],
            "steps_per_dispatch_p50": (
                single_spd["p50"] if single_spd else None
            ),
            "steps_per_dispatch_p95": (
                single_spd["p95"] if single_spd else None
            ),
            "k1_p50_ms": k1_single["p50"],
            "k1_p95_ms": k1_single["p95"],
            "k1_p99_ms": k1_single["p99"],
            "k1_goodput": round(k1_goodput, 2),
            "k1_admission_p95_ms": k1_admit["p95"] if k1_admit else None,
            "k1_steady_state_compiles": k1_recompiles,
            **cont_common,
        }), flush=True)

        # --- quantized-encoder A/B over the SAME checkpoint --------------
        q_recompiles = 0
        if args.quant_ab != "none":
            server.shutdown()
            server = None
            from sat_tpu.serve.engine import ServeEngine, load_serving_state

            qconfig = engine.config.replace(encoder_quant=args.quant_ab)
            qstate, _ = load_serving_state(qconfig)
            qengine = ServeEngine(
                qconfig, qstate, engine.vocabulary, tel=tel
            )
            qengine.warmup()
            server = CaptionServer(qconfig, qengine, port=0).start()
            log(f"quant arm ({args.quant_ab}) up on port {server.port} "
                f"(quantize {qengine.quantize_seconds:.2f}s, "
                f"warm_compiles {qengine.warm_compiles})")
            _post(server.port, jpegs[0])  # warm pass
            q_compiles0 = tel.counters().get("jax/compiles", 0)
            q_enc_mark = len(tel.durations_ns("serve/encode"))
            qclosed = closed_loop(
                server.port, jpegs, args.concurrency, args.requests
            )
            q_recompiles = (
                tel.counters().get("jax/compiles", 0) - q_compiles0
            )
            log(f"quant closed loop: {qclosed['ok']} ok -> "
                f"{qclosed['throughput']:.1f} req/s "
                f"(p99 {qclosed['p99']}ms); steady-state compiles "
                f"{q_recompiles}")
            q_enc = _enc_ms(q_enc_mark)
            q_common = dict(common)
            q_common.update(
                encoder_quant=args.quant_ab,
                quantize_seconds=round(qengine.quantize_seconds, 3),
                steady_state_compiles=q_recompiles,
            )
            if q_enc:
                print(json.dumps({
                    "metric": f"serve_encode_ms_{args.quant_ab}",
                    "value": q_enc["p50"],
                    "unit": "ms",
                    "percentile": "p50",
                    "p95_ms": q_enc["p95"],
                    "encodes": q_enc["count"],
                    "fp32_encode_p50_ms": enc["p50"] if enc else None,
                    **q_common,
                }), flush=True)
            print(json.dumps({
                "metric": f"serve_closed_loop_throughput_{args.quant_ab}",
                "value": round(qclosed["throughput"], 2),
                "unit": "req_per_s",
                "p50_ms": qclosed["p50"], "p95_ms": qclosed["p95"],
                "p99_ms": qclosed["p99"],
                "fp32_throughput": round(closed["throughput"], 2),
                **q_common,
            }), flush=True)

        # shedding under overload is fine; recompiling under load is not
        # — in ANY lane, including every fused-decode K lane
        return 0 if (
            recompiles == 0 and cont_recompiles == 0
            and k1_recompiles == 0 and q_recompiles == 0
        ) else 1
    finally:
        if server is not None:
            server.shutdown()
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
