"""Per-batch progress reporting for the train/eval/test loops.

The reference tqdm-bars every loop (/root/reference/base_model.py:49-50,
82,131); this is the dependency-free equivalent.  On a tty it redraws one
``\\r`` status line (rate-limited so the hot loop never stalls on
stderr); on a non-tty (driver logs, CI) it prints a full line every
``every`` items plus a final one, so long runs stay observable without
megabytes of log spam.

Deliberately metric-free: fetching a loss for the bar would device_get
every step and serialize the async dispatch chain the train loop is
built around (see runtime.train's host-side step counter note).
"""

from __future__ import annotations

import sys
import time
from typing import Iterable, Iterator, Optional, TextIO


class Progress:
    def __init__(
        self,
        total: int,
        desc: str = "",
        stream: Optional[TextIO] = None,
        every: Optional[int] = None,
        initial: int = 0,
        min_interval_s: float = 0.1,
    ):
        self.total = total
        self.desc = desc
        self.stream = stream if stream is not None else sys.stderr
        self.every = every if every else max(1, total // 20)
        self.count = initial
        self._initial = initial  # resume cursor: not work done this session
        self._t0 = time.perf_counter()
        self._last_draw = 0.0
        self._min_interval = min_interval_s
        self._tty = bool(getattr(self.stream, "isatty", lambda: False)())
        self._drew = False

    def _line(self) -> str:
        elapsed = time.perf_counter() - self._t0
        done = self.count - self._initial
        rate = done / elapsed if elapsed > 0 else 0.0
        return (
            f"{self.desc}: {self.count}/{self.total} "
            f"[{elapsed:.0f}s, {rate:.2f} it/s]"
        )

    def update(self, n: int = 1) -> None:
        self.count += n
        now = time.perf_counter()
        if self._tty:
            if now - self._last_draw >= self._min_interval or self.count >= self.total:
                self.stream.write("\r" + self._line())
                self.stream.flush()
                self._last_draw = now
                self._drew = True
        elif self.count % self.every == 0:
            self.stream.write(self._line() + "\n")
            self.stream.flush()

    def close(self) -> None:
        if self._tty:
            if self._drew:
                self.stream.write("\r" + self._line() + "\n")
                self.stream.flush()
        elif self.count % self.every != 0:
            # final line unless update() just printed this exact count
            self.stream.write(self._line() + "\n")
            self.stream.flush()

    def __enter__(self) -> "Progress":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def track(
    iterable: Iterable,
    total: int,
    desc: str = "",
    stream: Optional[TextIO] = None,
    every: Optional[int] = None,
) -> Iterator:
    """Wrap an iterable with a Progress bar (the tqdm call-shape)."""
    with Progress(total, desc, stream=stream, every=every) as bar:
        for item in iterable:
            yield item
            bar.update()
