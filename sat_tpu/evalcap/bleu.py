"""Corpus BLEU-1..4.

Own implementation of the BLEU metric (Papineni et al. 2002) with the
numeric conventions of the reference's vendored scorer
(/root/reference/utils/coco/pycocoevalcap/bleu/bleu_scorer.py:199-264) so
scores are comparable digit-for-digit:

* clipped n-gram matches against the per-ngram max reference count;
* 'closest' effective reference length per sentence (bleu_scorer.py:188-189);
* tiny/small epsilons (1e-15 / 1e-9) inside the precision ratios;
* brevity penalty exp(1 - 1/ratio) applied when ratio < 1, at both the
  corpus and the per-sentence level.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, List, Sequence, Tuple

_TINY = 1e-15
_SMALL = 1e-9


def _ngrams(words: Sequence[str], n: int) -> Counter:
    return Counter(tuple(words[i : i + n]) for i in range(len(words) - n + 1))


class Bleu:
    def __init__(self, n: int = 4):
        self.n = n

    def compute_score(
        self, gts: Dict, res: Dict
    ) -> Tuple[List[float], List[List[float]]]:
        """gts/res: {image_id: [caption strings]}; res has exactly one
        caption per image.  Returns ([bleu1..4], per-sentence lists)."""
        assert sorted(gts.keys()) == sorted(res.keys())
        n = self.n
        total_guess = [0] * n
        total_correct = [0] * n
        total_testlen = 0
        total_reflen = 0.0
        per_sentence: List[List[float]] = [[] for _ in range(n)]

        for img_id in sorted(gts.keys()):
            hyp = res[img_id]
            assert isinstance(hyp, list) and len(hyp) == 1
            hyp_words = hyp[0].split()
            ref_words = [r.split() for r in gts[img_id]]
            assert ref_words

            testlen = len(hyp_words)
            reflen = min((abs(len(r) - testlen), len(r)) for r in ref_words)[1]
            total_testlen += testlen
            total_reflen += reflen

            guess = [max(0, testlen - k) for k in range(n)]
            correct = []
            for k in range(1, n + 1):
                hyp_counts = _ngrams(hyp_words, k)
                max_ref: Counter = Counter()
                for r in ref_words:
                    for g, c in _ngrams(r, k).items():
                        if c > max_ref[g]:
                            max_ref[g] = c
                correct.append(
                    sum(min(c, max_ref[g]) for g, c in hyp_counts.items())
                )
            for k in range(n):
                total_guess[k] += guess[k]
                total_correct[k] += correct[k]

            # per-sentence score with its own brevity penalty
            bleu = 1.0
            ratio = (testlen + _TINY) / (reflen + _SMALL)
            for k in range(n):
                bleu *= (correct[k] + _TINY) / (guess[k] + _SMALL)
                s = bleu ** (1.0 / (k + 1))
                if ratio < 1:
                    s *= math.exp(1 - 1 / ratio)
                per_sentence[k].append(s)

        scores = []
        bleu = 1.0
        ratio = (total_testlen + _TINY) / (total_reflen + _SMALL)
        for k in range(n):
            bleu *= (total_correct[k] + _TINY) / (total_guess[k] + _SMALL)
            s = bleu ** (1.0 / (k + 1))
            if ratio < 1:
                s *= math.exp(1 - 1 / ratio)
            scores.append(s)
        return scores, per_sentence

    def method(self) -> str:
        return "Bleu"
