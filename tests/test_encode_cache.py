"""Content-addressed encode cache + tier disaggregation (ISSUE 20;
docs/SERVING.md "Encode cache & tiered fleets").

Pins the contracts:

* **hit-path bitwise parity** — a cache hit gathers the exact bits its
  original encode produced, so the hit caption is byte-identical to the
  cold caption (and the off-knob server agrees);
* **LRU discipline** — evictions go oldest-first, hits refresh recency,
  a plan never evicts a row it just pinned;
* **single-flight coalescing** — N concurrent requests for one image
  trigger exactly one encode, within a chunk (coalesced) or across
  chunks (the plan-time map update);
* **off-knob bit-identity** — ``--encode_cache off`` never constructs
  the cache: same captions, no /stats cache block, zero compile delta;
* **zero steady-state recompiles** with the cache on (gather/insert are
  AOT-warmed per dispatch width like every other serve program);
* **tier handoff** — /encode frames a grid a decode replica accepts on
  /caption; corrupt bytes (crc), wrong aval, and cross-generation steps
  are rejected before any device work;
* **router tier units** — endpoint tier parsing, the merged view's
  encode/decode routable sets, tier-restricted picks;
* **lifecycle coherence** — promote/rollback flushes; keys carry the
  param fingerprint so a stale entry could never hit anyway.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from sat_tpu.serve import handoff
from sat_tpu.serve.encode_cache import EncodeCache
from sat_tpu.serve.replica import Endpoint, LocalFleet, parse_endpoints
from sat_tpu.serve.router import merge_fleet, tier_capable

# ---------------------------------------------------------------------------
# EncodeCache planning: hit/miss/coalesce, LRU order, flush/drop (CPU jax)
# ---------------------------------------------------------------------------

ROW_SHAPE = (4, 8)


def _cache(min_rows=3, widths=(1, 2, 4), tel=None):
    """A tiny ring: capacity_mb=0 floors rows at min_rows+1, so the LRU
    edge cases are reachable with a handful of keys."""
    c = EncodeCache(0, tel=tel)
    c.ensure_store(ROW_SHAPE, np.float32, min_rows=min_rows)
    c.warm(widths)
    return c


def test_plan_hit_miss_and_counters():
    c = _cache()
    assert c.rows == 4 and c.warm_widths == (1, 2, 4)
    p1 = c.plan(["a", "b"])
    assert p1.n_miss == 2 and p1.hits == 0 and p1.coalesced == 0
    assert p1.miss_keys == ["a", "b"] and p1.miss_pos == [0, 1]
    assert p1.rows == p1.miss_rows
    p2 = c.plan(["b", "a"])
    assert p2.n_miss == 0 and p2.hits == 2
    assert p2.rows == [p1.miss_rows[1], p1.miss_rows[0]]
    assert c.hits == 2 and c.misses == 2 and c.lookups == 4
    assert c.hit_ratio() == pytest.approx(0.5)
    stats = c.stats()
    assert stats["entries"] == 2
    assert stats["bytes"] == 2 * c.row_bytes
    assert stats["capacity_bytes"] == 4 * c.row_bytes


def test_plan_coalesces_repeats_within_chunk():
    c = _cache()
    p = c.plan(["x", "x", "x", "y"])
    assert p.n_miss == 2 and p.coalesced == 2 and p.hits == 0
    # repeats ride the first occurrence's row: one encode, three seeds
    assert p.rows[0] == p.rows[1] == p.rows[2] != p.rows[3]
    assert p.miss_pos == [0, 3]
    # coalesced requests skipped the encode lane: they count as hits
    assert c.hit_ratio() == pytest.approx(0.5)


def test_lru_eviction_oldest_first_and_hit_refreshes():
    c = _cache(min_rows=3)  # 4 rows
    c.plan(["k1", "k2", "k3", "k4"])  # fills the ring
    c.plan(["k5"])  # evicts k1 (oldest)
    assert c.evictions == 1
    assert c.plan(["k4"]).hits == 1   # still resident
    assert c.plan(["k1"]).n_miss == 1  # evicted k2 to readmit k1
    # a hit refreshes recency: k3 would be next out, but touching it
    # pushes the eviction onto k5
    c.plan(["k3"])
    c.plan(["k6"])
    assert c.plan(["k3"]).hits == 1
    assert c.plan(["k5"]).n_miss == 1


def test_plan_never_evicts_a_row_it_just_pinned():
    c = _cache(min_rows=3)  # 4 rows: a full-width miss chunk pins all 4
    c.plan(["a", "b", "c", "d"])
    p = c.plan(["e", "f", "g", "h"])  # every alloc must evict, none pinned
    assert p.n_miss == 4
    assert len(set(p.rows)) == 4  # four distinct rows, no clobbering
    assert c.evictions == 4


def test_drop_unplans_failed_misses():
    c = _cache()
    p = c.plan(["a", "b"])
    c.drop(p.miss_keys)  # dispatch failed: rows hold garbage
    p2 = c.plan(["a", "b"])  # must re-encode, not serve garbage hits
    assert p2.n_miss == 2


def test_flush_forgets_everything():
    c = _cache()
    c.plan(["a", "b", "c"])
    c.flush()
    assert c.stats()["entries"] == 0 and c.flushes == 1
    assert c.plan(["a"]).n_miss == 1


def test_insert_gather_roundtrip_bitwise_and_scratch_isolation():
    c = _cache()
    rng = np.random.default_rng(3)
    p = c.plan(["a", "b"])
    lane = rng.standard_normal((2,) + ROW_SHAPE).astype(np.float32)
    c.insert(2, lane, p.miss_rows)
    # gather at a WIDER width: pad positions read the scratch row and
    # real rows come back bitwise
    out = np.asarray(c.gather(4, p.rows))
    assert np.array_equal(out[0], lane[0])
    assert np.array_equal(out[1], lane[1])
    # insert padded to a wider lane: pad rows land in scratch, the ring
    # rows of 'a'/'b' are untouched
    lane4 = rng.standard_normal((4,) + ROW_SHAPE).astype(np.float32)
    p2 = c.plan(["c"])
    c.insert(4, lane4, p2.miss_rows)
    again = np.asarray(c.gather(2, p.rows))
    assert np.array_equal(again[0], lane[0])
    assert np.array_equal(again[1], lane[1])
    assert np.array_equal(np.asarray(c.gather(1, p2.rows))[0], lane4[0])


def test_ensure_store_idempotent_and_aval_mismatch_raises():
    c = _cache()
    c.ensure_store(ROW_SHAPE, np.float32, min_rows=3)  # re-warm: no-op
    assert c.rows == 4
    with pytest.raises(ValueError, match="warmup now wants"):
        c.ensure_store((5, 8), np.float32, min_rows=3)
    with pytest.raises(ValueError, match="warmup now wants"):
        c.ensure_store(ROW_SHAPE, np.float16, min_rows=3)


def test_capacity_mb_sizes_the_ring():
    c = EncodeCache(1)  # 1 MB over 128-byte rows
    c.ensure_store(ROW_SHAPE, np.float32, min_rows=3)
    assert c.rows == int(1e6) // (4 * 8 * 4)


# ---------------------------------------------------------------------------
# Handoff frame: roundtrip + rejection (jax-free)
# ---------------------------------------------------------------------------


def test_handoff_roundtrip_bitwise():
    grid = np.arange(24, dtype=np.float32).reshape(4, 6)
    frame = handoff.encode_grid(grid, step=17)
    out, header = handoff.decode_grid(frame)
    assert np.array_equal(out, grid) and out.dtype == grid.dtype
    assert header["step"] == 17 and header["shape"] == [4, 6]


def test_handoff_crc_rejects_flipped_bit():
    frame = bytearray(handoff.encode_grid(np.ones((2, 3), np.float32)))
    frame[-1] ^= 0x40  # flip one payload bit
    with pytest.raises(handoff.HandoffError, match="crc32c mismatch"):
        handoff.decode_grid(bytes(frame))


def test_handoff_rejects_malformed_frames():
    good = handoff.encode_grid(np.ones((2, 3), np.float32))
    with pytest.raises(handoff.HandoffError, match="payload is"):
        handoff.decode_grid(good[:-4])  # truncated
    with pytest.raises(handoff.HandoffError, match="no header line"):
        handoff.decode_grid(b"\xff" * 64)
    with pytest.raises(handoff.HandoffError, match="bad magic"):
        handoff.decode_grid(b'{"magic": "nope"}\n')
    with pytest.raises(handoff.HandoffError, match="bad header field"):
        handoff.decode_grid(b'{"magic": "sat-grid1", "dtype": "float32"}\n')
    with pytest.raises(handoff.HandoffError, match="non-positive"):
        handoff.decode_grid(
            b'{"magic": "sat-grid1", "dtype": "float32", '
            b'"shape": [0, 3], "crc32c": 1}\n'
        )


def test_handoff_check_aval():
    grid = np.ones((4, 6), np.float32)
    handoff.check_aval(grid, (4, 6), np.float32)  # matching: no raise
    with pytest.raises(handoff.HandoffError, match="aval mismatch"):
        handoff.check_aval(grid, (4, 7), np.float32)
    with pytest.raises(handoff.HandoffError, match="aval mismatch"):
        handoff.check_aval(grid, (4, 6), np.float16)


# ---------------------------------------------------------------------------
# Router tier units (pure; jax-free)
# ---------------------------------------------------------------------------


def test_parse_endpoints_with_tiers():
    eps = parse_endpoints("h1:8000,h2:8001=encode,h3:8002=decode")
    assert [e.tier for e in eps] == ["both", "encode", "decode"]
    assert eps[1].address == "h2:8001"
    assert "=encode" in repr(eps[1]) and "=" not in repr(eps[0]).split("h1")[1]
    with pytest.raises(ValueError, match="tier must be"):
        parse_endpoints("h1:8000=gpu")
    with pytest.raises(ValueError, match="host:port"):
        parse_endpoints("8000=encode")


def test_tier_capable_matrix():
    assert tier_capable("both", "encode") and tier_capable("both", "decode")
    assert tier_capable("encode", "encode")
    assert not tier_capable("encode", "decode")
    assert tier_capable("decode", "decode")
    assert not tier_capable("decode", "encode")
    # unknown tier (pre-tier replica): treated as both
    assert tier_capable(None, "encode") and tier_capable(None, "decode")


def _snap(tier=None, ready=True, **kw):
    base = {
        "reachable": ready,
        "ready": ready,
        "status": "ok" if ready else "unreachable",
        "degraded": False,
        "tier": tier,
        "queue_depth": 0,
        "in_flight": 0,
        "serve_mode": "batch",
        "p50_ms": 5.0,
        "p99_ms": 9.0,
        "failures": 0,
    }
    base.update(kw)
    return base


def test_merge_fleet_tier_sets():
    view = merge_fleet(
        {
            "r0": _snap("encode"),
            "r1": _snap("decode"),
            "r2": _snap("both"),
            "r3": _snap(None),          # pre-tier replica: both
            "r4": _snap("decode", ready=False),  # down: in neither set
        },
        {},
        straggler_factor=2.0,
        down_weight=0.25,
    )
    assert view["routable"] == ["r0", "r1", "r2", "r3"]
    assert view["routable_encode"] == ["r0", "r2", "r3"]
    assert view["routable_decode"] == ["r1", "r2", "r3"]
    # a drained encode replica leaves the encode set too
    view2 = merge_fleet(
        {"r0": _snap("encode"), "r1": _snap("decode")},
        {"r0": "draining"},
        straggler_factor=2.0,
        down_weight=0.25,
    )
    assert view2["routable_encode"] == []
    assert view2["routable_decode"] == ["r1"]


def test_local_fleet_tier_validation(tmp_path):
    from sat_tpu.config import Config

    with pytest.raises(ValueError, match="names 3 replicas"):
        LocalFleet(
            Config(), 2, root=str(tmp_path),
            tiers=["encode", "decode", "both"],
        )
    with pytest.raises(ValueError, match="must be one of"):
        LocalFleet(Config(), 1, root=str(tmp_path), tiers=["gpu"])


def test_endpoint_tier_defaults_both():
    e = Endpoint("r0", "h", 1)
    assert e.tier == "both"


# ---------------------------------------------------------------------------
# End-to-end on a booted CPU server (tiny model, batch mode)
# ---------------------------------------------------------------------------

TINY_MODEL = dict(
    phase="serve",
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    beam_size=2,
    serve_buckets=(1, 4),
    serve_max_batch=4,
    serve_max_wait_ms=25.0,
    serve_queue_depth=16,
    heartbeat_interval=0.0,
)


@pytest.fixture(scope="module")
def cachestack(tmp_path_factory):
    import os

    import cv2
    import jax

    from sat_tpu import runtime, telemetry
    from sat_tpu.config import Config
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    root = str(tmp_path_factory.mktemp("encode_cache"))
    vocab_file = os.path.join(root, "vocabulary.csv")
    vocabulary = Vocabulary(size=30)
    vocabulary.build(["a man riding a horse.", "a cat on a table."])
    vocabulary.save(vocab_file)
    config = Config(
        **TINY_MODEL,
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        save_dir=os.path.join(root, "models"),
        summary_dir=os.path.join(root, "summary"),
        encode_cache="on",
        encode_cache_mb=4,
    )
    os.makedirs(config.save_dir, exist_ok=True)
    tel = telemetry.enable(capacity=16384)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
    state, _source = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    # off-knob twin on the same checkpoint: the bit-identity oracle
    off_config = config.replace(encode_cache="off")
    off_state, _ = load_serving_state(off_config)
    off_engine = ServeEngine(off_config, off_state, vocabulary, tel=tel)
    off_engine.warmup()

    rng = np.random.default_rng(0)
    jpegs = []
    for _ in range(8):
        img = rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        jpegs.append(bytes(buf))
    yield {
        "config": config,
        "engine": engine,
        "off_config": off_config,
        "off_engine": off_engine,
        "tel": tel,
        "jpegs": jpegs,
    }
    telemetry.disable()


def _boot(cachestack, on=True, **overrides):
    from sat_tpu.serve.server import CaptionServer

    which = "config" if on else "off_config"
    eng = "engine" if on else "off_engine"
    config = cachestack[which].replace(**overrides)
    return CaptionServer(config, cachestack[eng], port=0).start()


def _post(port, data, ctype="image/jpeg", headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/caption",
        data=data,
        method="POST",
        headers={"Content-Type": ctype, **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post_encode(port, data, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/encode",
        data=data,
        method="POST",
        headers={"Content-Type": "image/jpeg"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), r.headers.get("Content-Type")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type")


def _get(port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


def test_e2e_hit_bitwise_parity_stats_and_zero_recompiles(cachestack):
    """The acceptance pin: a repeat request is served from the ring with
    a caption byte-identical to its cold encode, the /stats cache block
    reflects it, and the whole exchange compiles nothing."""
    tel = cachestack["tel"]
    engine = cachestack["engine"]
    server = _boot(cachestack)
    try:
        jpeg = cachestack["jpegs"][0]
        status, cold = _post(server.port, jpeg)  # miss: encodes + inserts
        assert status == 200
        compiles0 = tel.counters().get("jax/compiles", 0)
        h0 = engine.encode_cache.hits
        status, warm = _post(server.port, jpeg)  # hit: gather only
        assert status == 200
        assert engine.encode_cache.hits > h0
        # bitwise caption parity: words AND scores identical
        assert warm["captions"] == cold["captions"]
        status, raw = _get(server.port, "/stats")
        stats = json.loads(raw)
        block = stats["encode_cache"]
        assert block["entries"] >= 1 and block["hits"] >= 1
        assert block["bytes"] <= block["capacity_bytes"]
        assert block["warm_widths"] == [1, 4]
        assert 0.0 < block["hit_ratio"] <= 1.0
        assert stats["tier"] == "both"
        # zero steady-state recompiles through miss AND hit paths
        assert tel.counters().get("jax/compiles", 0) == compiles0
        status, health = _get(server.port, "/healthz")
        assert json.loads(health)["tier"] == "both"
        # /metrics: cache residency gauges + counters exported
        _s, text = _get(server.port, "/metrics")
        assert 'sat_gauge{name="serve/cache_entries"}' in text
        assert 'sat_counter_total{name="serve/cache_hits"}' in text
    finally:
        server.shutdown()


def test_e2e_off_knob_bit_identity_zero_compile_delta(cachestack):
    """--encode_cache off serves the byte-identical caption the cached
    server produced, with no cache block and zero compile delta."""
    tel = cachestack["tel"]
    jpeg = cachestack["jpegs"][0]
    on_server = _boot(cachestack)
    try:
        _status, on_payload = _post(on_server.port, jpeg)
    finally:
        on_server.shutdown()
    off_server = _boot(cachestack, on=False)
    try:
        assert cachestack["off_engine"].encode_cache is None
        compiles0 = tel.counters().get("jax/compiles", 0)
        status, off_payload = _post(off_server.port, jpeg)
        assert status == 200
        assert off_payload["captions"] == on_payload["captions"]
        assert tel.counters().get("jax/compiles", 0) == compiles0
        stats = json.loads(_get(off_server.port, "/stats")[1])
        assert "encode_cache" not in stats
    finally:
        off_server.shutdown()


def test_e2e_single_flight_coalescing_burst(cachestack):
    """A concurrent burst of one NEW image triggers exactly one encode:
    the first plan registers the key, everyone else coalesces or hits."""
    engine = cachestack["engine"]
    server = _boot(cachestack)
    try:
        jpeg = cachestack["jpegs"][1]
        m0 = engine.encode_cache.misses
        s0 = engine.encode_cache.hits + engine.encode_cache.coalesced
        n = 4
        barrier = threading.Barrier(n)
        results = [None] * n

        def client(i):
            barrier.wait()
            results[i] = _post(server.port, jpeg)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert all(r is not None and r[0] == 200 for r in results)
        captions = [r[1]["captions"] for r in results]
        assert all(c == captions[0] for c in captions)
        # exactly ONE miss for the new key; the other three rode it
        assert engine.encode_cache.misses == m0 + 1
        assert engine.encode_cache.hits + engine.encode_cache.coalesced >= (
            s0 + n - 1
        )
    finally:
        server.shutdown()


def test_e2e_encode_endpoint_and_grid_caption_parity(cachestack):
    """The tier handoff end-to-end on one replica: /encode mints a
    framed grid, /caption accepts it (grid content type) and answers
    with the byte-identical caption the image path produces."""
    server = _boot(cachestack)
    try:
        jpeg = cachestack["jpegs"][2]
        status, image_payload = _post(server.port, jpeg)
        assert status == 200
        status, frame, ctype = _post_encode(server.port, jpeg)
        assert status == 200 and ctype == handoff.GRID_CONTENT_TYPE
        grid, header = handoff.decode_grid(frame)
        engine = cachestack["engine"]
        assert tuple(grid.shape) == engine.ctx_row_shape
        assert header["step"] == engine.step
        status, grid_payload = _post(
            server.port, frame, ctype=handoff.GRID_CONTENT_TYPE
        )
        assert status == 200
        assert grid_payload["captions"] == image_payload["captions"]
        stats = json.loads(_get(server.port, "/stats")[1])
        assert stats["counters"].get("serve/grid_requests", 0) >= 1
    finally:
        server.shutdown()


def test_e2e_grid_rejections_crc_aval_stale(cachestack):
    """Corrupt frames never reach the device: flipped payload bit → 400
    (crc), wrong aval → 400, cross-generation step → 409."""
    engine = cachestack["engine"]
    server = _boot(cachestack)
    try:
        jpeg = cachestack["jpegs"][3]
        status, frame, _ctype = _post_encode(server.port, jpeg)
        assert status == 200
        corrupt = bytearray(frame)
        corrupt[-1] ^= 0x01
        status, payload = _post(
            server.port, bytes(corrupt), ctype=handoff.GRID_CONTENT_TYPE
        )
        assert status == 400 and payload["error"] == "bad grid"
        assert "crc32c" in payload["detail"]
        bad_aval = handoff.encode_grid(
            np.zeros((3, 5), np.float32), step=engine.step
        )
        status, payload = _post(
            server.port, bad_aval, ctype=handoff.GRID_CONTENT_TYPE
        )
        assert status == 400 and "aval mismatch" in payload["detail"]
        grid, _header = handoff.decode_grid(frame)
        stale = handoff.encode_grid(np.asarray(grid), step=engine.step + 7)
        status, payload = _post(
            server.port, stale, ctype=handoff.GRID_CONTENT_TYPE
        )
        assert status == 409
    finally:
        server.shutdown()


def test_promote_flushes_cache_and_fingerprint_keys(cachestack):
    """Lifecycle coherence: promoting a candidate flushes the ring, and
    the param fingerprint in every key changes with the serving step, so
    a pre-promote entry could never have served a post-promote hit."""
    engine = cachestack["engine"]
    server = _boot(cachestack)
    try:
        jpeg = cachestack["jpegs"][4]
        status, before = _post(server.port, jpeg)
        assert status == 200
        assert engine.encode_cache.stats()["entries"] >= 1
        fp0 = engine.param_fingerprint()
        old_step = engine.step
        flushes0 = engine.encode_cache.flushes
        # stage the incumbent's own trees as a "new" candidate and flip
        engine.install_candidate(
            engine._variables, engine._decoder_params,
            step=old_step + 1, source="test",
        )
        try:
            assert engine.promote_candidate() == old_step + 1
            assert engine.encode_cache.flushes == flushes0 + 1
            assert engine.encode_cache.stats()["entries"] == 0
            assert engine.param_fingerprint() != fp0
            # re-request: a fresh miss under the new generation, same
            # caption (identical params)
            m0 = engine.encode_cache.misses
            status, after = _post(server.port, jpeg)
            assert status == 200
            assert engine.encode_cache.misses == m0 + 1
            assert after["captions"] == before["captions"]
        finally:
            # restore the original generation for later tests
            engine.step = old_step
            engine.encode_cache.flush()
    finally:
        server.shutdown()


def test_e2e_encode_tier_server_warms_before_ready(cachestack):
    """A serve_tier=encode replica warms its width-1 executable before
    ready: the first /encode request compiles nothing, and its tier
    shows on /healthz for the router's poller."""
    tel = cachestack["tel"]
    server = _boot(cachestack, serve_tier="encode")
    try:
        status, health = _get(server.port, "/healthz")
        assert json.loads(health)["tier"] == "encode"
        compiles0 = tel.counters().get("jax/compiles", 0)
        status, frame, ctype = _post_encode(
            server.port, cachestack["jpegs"][5]
        )
        assert status == 200 and ctype == handoff.GRID_CONTENT_TYPE
        handoff.decode_grid(frame)  # verifies the frame end-to-end
        assert tel.counters().get("jax/compiles", 0) == compiles0
        assert json.loads(_get(server.port, "/stats")[1])["tier"] == "encode"
    finally:
        server.shutdown()
