"""Fixed-capacity paged slot pool — device state for continuous batching.

The TPU analogue of Ragged Paged Attention's block pool (PAPERS.md,
arXiv:2604.15464): decode state lives in a fixed ``[slots, ...]`` carry
(``ops.beam_search.SlotCarry``); admission runs through fixed-width
**encode lanes** — the expensive encoder is AOT-compiled at each
power-of-two width up to ``page_width``, a burst of admitted images is
encoded at the smallest lane that fits (so a single straggler admission
costs a 1-wide encode, not a padded full-page one), and one
``init_slots`` gather-seed scatters the lane into whichever slots are
free.  Every decode step is one ``decode_step`` dispatch over the whole
pool; finished slots are merged by ``harvest_slots`` and freed.  All
programs are AOT-compiled ONCE per pool geometry at warmup via
``jit.lower(...).compile()`` — the serving zero-recompile guarantee
extends to the stepped path unchanged.

The pool owns device state and host bookkeeping (free-slot set, slot →
request binding) only; scheduling policy — when to admit, when to step,
the wedge watchdog — belongs to ``serve.batcher.ContinuousBatcher``.
"""

from __future__ import annotations

import sys
import time
from typing import Any, List, Optional, Tuple

import numpy as np

# the ops package re-exports the beam_search FUNCTION, which shadows the
# submodule on attribute import — import the names directly
from ..ops.beam_search import (
    decode_multi_step,
    harvest_slots,
    init_slot_pool,
    init_slots,
    retire_slots,
)


def _lane_widths(page_width: int) -> List[int]:
    """Powers of two up to ``page_width``, plus ``page_width`` itself —
    the fixed set of encode-lane shapes warmed at startup."""
    widths = []
    w = 1
    while w < page_width:
        widths.append(w)
        w *= 2
    widths.append(page_width)
    return widths


class PagedSlotPool:
    """``pages × page_width`` decode slots over a ``ServeEngine``'s frozen
    params.  Not thread-safe: one owner thread (the batcher loop) drives
    admit/step/harvest."""

    def __init__(
        self,
        engine,
        pages: Optional[int] = None,
        page_width: Optional[int] = None,
        tel=None,
        param_slot: str = "incumbent",
    ) -> None:
        config = engine.config
        self.engine = engine
        self.config = config
        # which engine param slot this pool's dispatches run against; the
        # lifecycle canary pool is a clone_warmed(param_slot="canary")
        self.param_slot = param_slot
        self._occ_gauge = (
            "serve/slot_occupancy"
            if param_slot == "incumbent"
            else f"serve/slot_occupancy_{param_slot}"
        )
        self.pages = int(
            pages if pages is not None else config.serve_slot_pages
        )
        self.width = int(
            page_width if page_width is not None else config.serve_page_width
        )
        self.slots = self.pages * self.width
        self.beam_size = config.beam_size
        self.max_len = config.max_caption_length
        self.valid_size = len(engine.vocabulary.words)
        self.eos_id = engine.eos_id
        self._tel = tel if tel is not None else engine._tel
        # host bookkeeping: free-slot set + slot -> opaque payload binding
        # (the batcher binds its Request objects; the pool treats them as
        # opaque except for one duck-typed hook — a payload carrying a
        # ``cost`` attribute gets its encode-lane share attributed, see
        # telemetry/metering.py; bulk's int payloads simply skip it)
        self._free = set(range(self.slots))
        self._payload = {}
        self._mask = np.zeros((self.slots,), np.bool_)
        self._carry = None
        self.lane_widths = _lane_widths(self.width)
        # fused decode window (docs/SERVING.md): ONE multi-step
        # executable per geometry — the window depth is a dynamic
        # operand of the on-device while_loop, so every ladder depth
        # rides the same program; decode_depths is the value set the
        # adaptive policy may pick (config validation pins depths[0]==1,
        # the burst depth)
        self.decode_depths = tuple(config.serve_decode_depth)
        self._enc_execs = {}
        self._seed_execs = {}
        self._multi_exec = None
        self._reset_exec = None
        self._harvest_exec = None
        self._retire_exec = None
        self.warm_compiles = 0
        self.warm_seconds = 0.0
        self.compiles_at_ready = 0

    # -- startup / recovery ------------------------------------------------

    def warmup(self) -> None:
        """AOT-compile the pool programs for this geometry and build the
        empty carry.  Idempotent and cheap to re-run (persistent compile
        cache) — the wedge re-warm path calls it again to prove the
        device answers before health recovers."""
        import jax

        from ..models.captioner import encode

        engine, config = self.engine, self.config
        size = config.image_size
        S, K = self.slots, self.beam_size

        def encode_fn(variables, images):
            contexts, _ = encode(variables, config, images, train=False)
            return contexts

        compiles0 = self._tel.counters().get("jax/compiles", 0)
        t0 = time.perf_counter()

        # quality-on carries per-slot alphas through the pool so the
        # detok boundary can read coverage/entropy off the same drain;
        # off keeps the pre-quality carry footprint bit-for-bit
        want_alphas = config.serve_quality == "on"
        pool_statics = dict(
            config=config, slots=S, beam_size=K, max_len=self.max_len,
            return_alphas=want_alphas,
        )
        reset_jit = jax.jit(
            init_slot_pool,
            static_argnames=(
                "config", "slots", "beam_size", "max_len",
                "return_alphas", "alpha_width",
            ),
        )
        self._reset_exec = reset_jit.lower(**pool_statics).compile()
        # the concrete empty carry doubles as the sample argument for the
        # remaining lowers (jax.eval_shape can't see static_argnames)
        carry_sd = self._reset_exec()
        mask_sd = jax.ShapeDtypeStruct((S,), np.bool_)
        src_sd = jax.ShapeDtypeStruct((S,), np.int32)

        enc_jit = jax.jit(encode_fn)
        seed_jit = jax.jit(init_slots, static_argnames=("config", "beam_size"))
        for L in self.lane_widths:
            images_sd = jax.ShapeDtypeStruct(
                (L, size, size, 3), engine._image_dtype
            )
            ctx_sd = jax.eval_shape(enc_jit, engine._variables, images_sd)
            self._enc_execs[L] = enc_jit.lower(
                engine._variables, images_sd
            ).compile()
            self._seed_execs[L] = seed_jit.lower(
                engine._decoder_params, config, carry_sd, ctx_sd,
                src_sd, mask_sd, beam_size=K,
            ).compile()
        # the decode tier validates handoff grids against this aval, and
        # context-seeded admissions stack into it
        engine.ctx_row_shape = tuple(int(d) for d in ctx_sd.shape[1:])
        engine.ctx_row_dtype = np.dtype(ctx_sd.dtype)
        cache = getattr(engine, "encode_cache", None)
        if cache is not None:
            # ring geometry + insert/gather executables for every
            # admission lane, warmed pre-ready like everything else here
            cache.ensure_store(
                engine.ctx_row_shape, engine.ctx_row_dtype,
                min_rows=max(self.lane_widths),
            )
            cache.warm(self.lane_widths)
        # ONE decode executable serves every depth: the fused window takes
        # the depth as a runtime operand, so step() is just depth 1 of the
        # same program — compiling a separate single-step lane would double
        # the warmup cost for a body the window already contains
        self._multi_exec = (
            jax.jit(
                decode_multi_step,
                static_argnames=("config", "eos_id", "beam_size", "valid_size"),
            )
            .lower(
                engine._decoder_params, config, carry_sd, mask_sd,
                self.eos_id, jax.ShapeDtypeStruct((), np.int32),
                beam_size=K, valid_size=self.valid_size,
            )
            .compile()
        )
        self._harvest_exec = (
            jax.jit(harvest_slots, static_argnames=("return_alphas",))
            .lower(carry_sd, return_alphas=want_alphas)
            .compile()
        )
        self._retire_exec = (
            jax.jit(retire_slots).lower(carry_sd, mask_sd).compile()
        )

        self.reset()
        jax.block_until_ready(self._carry.t)  # sync-ok: warmup, before ready — proves the device answers
        self.warm_seconds = time.perf_counter() - t0
        counters = self._tel.counters()
        self.compiles_at_ready = counters.get("jax/compiles", 0)
        self.warm_compiles = self.compiles_at_ready - compiles0
        # extend the engine's zero-recompile ledger past the pool warmup
        # so "compiles_since_ready" in /stats covers both paths
        engine.compiles_at_ready = max(
            engine.compiles_at_ready, self.compiles_at_ready
        )
        self._tel.gauge("serve/slot_pool_slots", self.slots)
        self._tel.gauge("serve/slot_pool_pages", self.pages)
        self._tel.gauge("serve/pool_warm_compiles", self.warm_compiles)
        self._tel.gauge("serve/pool_warm_seconds", round(self.warm_seconds, 3))
        print(
            f"sat_tpu: slot pool warmup — {self.pages}x{self.width} slots, "
            f"lanes {self.lane_widths}, decode depths "
            f"{list(self.decode_depths)}, {self.warm_compiles} XLA compiles "
            f"in {self.warm_seconds:.1f}s (cached compiles are free)",
            file=sys.stderr,
            flush=True,
        )

    def reset(self) -> None:
        """Fresh empty carry + all slots free (startup and wedge
        recovery).  Any payload bindings must have been failed/handed off
        by the caller first."""
        self._carry = self._reset_exec()
        self._free = set(range(self.slots))
        self._payload.clear()
        self._mask[:] = False
        self._tel.gauge(self._occ_gauge, 0)

    def clone_warmed(self, param_slot: str) -> "PagedSlotPool":
        """A second pool over the SAME warmed executables but a fresh
        carry, dispatching against ``param_slot``.  The AOT programs take
        the params as runtime arguments, so the canary pool costs zero
        compiles — exactly the property the lifecycle zero-recompile
        invariant needs.  Must be called after warmup()."""
        if self._reset_exec is None:
            raise RuntimeError("clone_warmed before warmup()")
        clone = PagedSlotPool(
            self.engine, pages=self.pages, page_width=self.width,
            tel=self._tel, param_slot=param_slot,
        )
        clone._enc_execs = self._enc_execs
        clone._seed_execs = self._seed_execs
        clone._multi_exec = self._multi_exec
        clone._reset_exec = self._reset_exec
        clone._harvest_exec = self._harvest_exec
        clone._retire_exec = self._retire_exec
        clone.compiles_at_ready = self.compiles_at_ready
        clone._carry = clone._reset_exec()
        return clone

    # -- host bookkeeping --------------------------------------------------

    def occupancy(self) -> int:
        return self.slots - len(self._free)

    def free_count(self) -> int:
        return len(self._free)

    def inflight_payloads(self) -> List[Any]:
        """Every bound payload in slot order (wedge containment: the
        batcher fails these with 500s before reset())."""
        return [self._payload[s] for s in sorted(self._payload)]

    # -- device programs ---------------------------------------------------

    def admit(self, items: List[Tuple[np.ndarray, Any]]) -> int:
        """Seed up to ``free_count()`` (image_row, payload) pairs into
        free slots; returns how many were admitted (surplus stays with
        the caller).  Items are encoded in admission lanes — the
        smallest warmed width that fits each burst — then one
        ``init_slots`` gather scatters the lane into the free slots.
        Both dispatches are async, so the host returns to the step loop
        while the device encodes."""
        import jax

        admitted = 0
        free = sorted(self._free)
        cache = getattr(self.engine, "encode_cache", None)

        def _is_ctx(item) -> bool:
            # a payload carrying a pre-encoded grid (the tier handoff)
            # seeds from it directly; bulk's int payloads simply say no
            return getattr(item[1], "context", None) is not None

        while admitted < len(items) and free:
            # a chunk is a run of same-kind items (image vs pre-encoded
            # context): the two kinds reach the seed exec through
            # different sources, but the seed itself is shared
            is_ctx = _is_ctx(items[admitted])
            run = 1
            while (
                admitted + run < len(items)
                and _is_ctx(items[admitted + run]) == is_ctx
            ):
                run += 1
            chunk = min(run, len(free), self.width)
            lane = next(w for w in self.lane_widths if w >= chunk)
            slot_src = np.zeros((self.slots,), np.int32)
            admit_mask = np.zeros((self.slots,), np.bool_)
            chunk_payloads = []
            chunk_rows = []
            for j in range(chunk):
                image, payload = items[admitted]
                admitted += 1
                s = free.pop(0)
                chunk_rows.append(
                    payload.context if is_ctx else image
                )
                slot_src[s] = j
                admit_mask[s] = True
                self._free.discard(s)
                self._payload[s] = payload
                self._mask[s] = True
                chunk_payloads.append(payload)
            if is_ctx:
                contexts = self._ctx_lane(lane, chunk_rows)
            elif cache is not None:
                contexts = self._encode_lane_cached(
                    lane, chunk_rows, chunk_payloads
                )
            else:
                contexts = self._encode_lane(
                    lane, chunk, chunk_rows, chunk_payloads
                )
            self._carry = self._seed_execs[lane](
                self.engine.slot_decoder_params(self.param_slot),
                self._carry,
                contexts,
                jax.device_put(slot_src),
                jax.device_put(admit_mask),
            )
        self._tel.gauge(self._occ_gauge, self.occupancy())
        return admitted

    def _encode_lane(self, lane, chunk, chunk_rows, chunk_payloads):
        """The pre-cache encode lane, byte-for-byte: stack, encode at the
        lane width, attribute the measured window (--encode_cache off
        takes exactly this path, the bit-identity knob pins it)."""
        import jax

        size = self.config.image_size
        images = np.zeros((lane, size, size, 3), self.engine._image_dtype)
        for j, row in enumerate(chunk_rows):
            images[j] = row
        t0 = time.perf_counter_ns()
        contexts = self._enc_execs[lane](
            self.engine.slot_variables(self.param_slot),
            jax.device_put(images),
        )
        if self._tel.enabled:
            # per-lane encode timing (serve/encode_ms introspection):
            # the seed exec consumes the contexts immediately, so with
            # telemetry on we wait the encode out here; with telemetry
            # off the admission path stays fully async
            jax.block_until_ready(contexts)  # sync-ok: opt-in telemetry encode timing, gated on tel.enabled
            dur = time.perf_counter_ns() - t0
            self._tel.record("serve/encode", t0, dur)
            self._tel.record(f"serve/encode_lane{lane}", t0, dur)
            # cost attribution (telemetry/metering.py): each request
            # in the chunk is charged an equal share of this lane's
            # measured window; padded lane slots bill nobody but feed
            # the encode-lane-fill capacity gauge
            share = dur // chunk
            for payload in chunk_payloads:
                cost = getattr(payload, "cost", None)
                if cost is not None:
                    cost.add_encode(share)
            self._tel.count("serve/encode_images", chunk)
            self._tel.count("serve/encode_lane_slots", lane)
        return contexts

    def _encode_lane_cached(self, lane, chunk_rows, chunk_payloads):
        """Cache-routed admission lane: plan ring rows for the chunk's
        content keys, encode only the unique misses (at the smallest
        lane that holds them), insert, then gather the whole chunk from
        the ring.  Hit rows are the exact bits their original encode
        produced; hit/coalesced requests are charged zero encode
        device-ms — only the miss requests split the measured window."""
        import jax

        from ..utils.summary import crc32c

        engine = self.engine
        cache = getattr(engine, "encode_cache", None)
        size = self.config.image_size
        gen = engine.param_fingerprint(self.param_slot)
        keys = []
        for row, payload in zip(chunk_rows, chunk_payloads):
            key = getattr(payload, "key", None)
            if key is None:
                # bulk / direct-admit payloads carry no precomputed key;
                # hash the preprocessed row here (same digest the server
                # stamps on requests)
                key = crc32c(np.ascontiguousarray(row).tobytes())
            keys.append((key, gen))
        plan = cache.plan(keys)
        try:
            if plan.n_miss:
                enc_lane = next(
                    w for w in self.lane_widths if w >= plan.n_miss
                )
                images = np.zeros(
                    (enc_lane, size, size, 3), engine._image_dtype
                )
                for j, pos in enumerate(plan.miss_pos):
                    images[j] = chunk_rows[pos]
                t0 = time.perf_counter_ns()
                lane_ctx = self._enc_execs[enc_lane](
                    engine.slot_variables(self.param_slot),
                    jax.device_put(images),
                )
                if self._tel.enabled:
                    jax.block_until_ready(lane_ctx)  # sync-ok: opt-in telemetry encode timing, gated on tel.enabled
                    dur = time.perf_counter_ns() - t0
                    self._tel.record("serve/encode", t0, dur)
                    self._tel.record(f"serve/encode_lane{enc_lane}", t0, dur)
                    share = dur // plan.n_miss
                    for pos in plan.miss_pos:
                        cost = getattr(chunk_payloads[pos], "cost", None)
                        if cost is not None:
                            cost.add_encode(share)
                    self._tel.count("serve/encode_images", plan.n_miss)
                    self._tel.count("serve/encode_lane_slots", enc_lane)
                cache.insert(enc_lane, lane_ctx, plan.miss_rows)
            t0 = time.perf_counter_ns()
            contexts = cache.gather(lane, plan.rows)
            if self._tel.enabled:
                # hit-path latency probe (the cache block's p95); its own
                # span, NOT a BUSY_SPAN, so metering identity is untouched
                jax.block_until_ready(contexts)  # sync-ok: opt-in telemetry gather timing, gated on tel.enabled
                self._tel.record(
                    "serve/cache_gather", t0, time.perf_counter_ns() - t0
                )
        except Exception:
            # the plan registered the miss keys before the encode landed;
            # their rows hold garbage, so un-plan them before propagating
            cache.drop(plan.miss_keys)
            raise
        return contexts

    def _ctx_lane(self, lane, chunk_rows):
        """Decode-tier admission: stack pre-encoded handoff grids into
        the lane's context shape (aval-checked at ingress) — no encode,
        no cache, zero encode device-ms charged."""
        import jax

        engine = self.engine
        batch = np.zeros(
            (lane,) + tuple(engine.ctx_row_shape), engine.ctx_row_dtype
        )
        for j, grid in enumerate(chunk_rows):
            batch[j] = grid
        self._tel.count("serve/context_images", len(chunk_rows))
        return jax.device_put(batch)

    def step(self):
        """One decode step over the whole pool — the fused window at
        depth 1 (same executable, ``k`` is a runtime operand).  Returns
        the [S] done flags STILL ON DEVICE — the caller owns the drain
        (and bounds it with the wedge watchdog)."""
        import jax

        self._carry, done, _ = self._multi_exec(
            self.engine.slot_decoder_params(self.param_slot),
            self._carry,
            jax.device_put(self._mask.copy()),
            jax.device_put(np.int32(1)),
        )
        return done

    def multi_step(self, k: int):
        """Up to ``k`` fused decode steps in ONE dispatch (the warmed
        ``decode_multi_step`` executable; the depth is a runtime operand,
        so every ladder value rides the same program).  Returns
        ``(done, steps_run)`` STILL ON DEVICE: ``done`` [S] flags every
        slot that sealed anywhere inside the window, ``steps_run`` the
        inner iterations actually executed (< k when the pool drained
        mid-window — the on-device early exit).  ``k`` must be a ladder
        value (``decode_depths``) — the policy contract is the ladder,
        and an off-ladder depth raises rather than silently widening it."""
        import jax

        if k not in self.decode_depths:
            raise KeyError(
                f"decode depth {k} not in ladder {list(self.decode_depths)}"
            )
        self._carry, done, steps_run = self._multi_exec(
            self.engine.slot_decoder_params(self.param_slot),
            self._carry,
            jax.device_put(self._mask.copy()),
            jax.device_put(np.int32(k)),
        )
        return done, steps_run

    def harvest(self, done: np.ndarray):
        """Drain and free the slots flagged in ``done`` (host bool [S]).

        Returns ``(payloads, words, lengths, scores, steps, alphas)``
        with one row per harvested slot, in slot order (``alphas`` is
        None unless the pool was warmed quality-on).  Whole-array
        transfers
        sliced on the HOST — a device-side gather at a varying row set
        would compile per distinct pattern (same rationale as
        ``ServeEngine.drain_output``)."""
        import jax

        ids = [int(s) for s in np.nonzero(done)[0] if self._mask[s]]
        out = self._harvest_exec(self._carry)
        words = np.asarray(out.words)  # sync-ok: continuous detok boundary — harvested results drained once
        lengths = np.asarray(out.lengths)  # sync-ok: continuous detok boundary
        scores = np.asarray(out.log_scores)  # sync-ok: continuous detok boundary
        steps = np.asarray(out.steps_run)  # sync-ok: continuous detok boundary
        alphas = None
        if out.alphas is not None:
            # same drain, one more leaf of the harvested pytree
            alphas = np.asarray(out.alphas)  # sync-ok: continuous detok boundary, rides the harvest drain
        retire = np.zeros((self.slots,), np.bool_)
        payloads = []
        for s in ids:
            retire[s] = True
            payloads.append(self._payload.pop(s))
            self._mask[s] = False
            self._free.add(s)
        self._carry = self._retire_exec(
            self._carry, jax.device_put(retire)
        )
        self._tel.gauge(self._occ_gauge, self.occupancy())
        return (
            payloads, words[ids], lengths[ids], scores[ids], steps[ids],
            None if alphas is None else alphas[ids],
        )
