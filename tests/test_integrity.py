"""Data-plane immune system: record integrity, quarantine, repair.

Covers the detection half (data/integrity.py: crc32c sidecars,
verify-on-gather modes, --repair_shards), the containment half
(resilience/quarantine.py: ledger, deterministic substitution, the
systemic-corruption ceiling and its exit code), the hardened prefetch
path (data/images.py), the satellites (prefetch error context, vocab
compatibility guard, serve bad-input handling), and — as one
subprocess test — the chaos-campaign acceptance e2e plus the
regression-gate contract of its report.

Everything but the campaign test is in-process and jax-free.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

from sat_tpu import telemetry
from sat_tpu.data import integrity
from sat_tpu.data.integrity import (
    SAMPLE_EVERY,
    VERIFY_MODES,
    crc32c_rows,
    read_row_crcs,
    repair_shards,
    sidecar_path,
    write_row_crcs,
)
from sat_tpu.data.shards import ShardCache, build_shard_cache, cache_dir_for
from sat_tpu.resilience.quarantine import (
    DATA_CORRUPTION_EXIT_CODE,
    MIN_RECORDS_FOR_CEILING,
    QuarantineManager,
    SystemicCorruption,
    ledger_path_for,
)
from sat_tpu.resilience.watchdog import WATCHDOG_EXIT_CODE
from sat_tpu.utils import summary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubLoader:
    """Deterministic cv2-free image source keyed on basename."""

    def __init__(self, size: int = 16):
        self.size = size
        self.raw = True
        self.calls: list = []

    def load_raw(self, image_file: str) -> np.ndarray:
        self.calls.append(image_file)
        seed = zlib.crc32(os.path.basename(image_file).encode())
        rng = np.random.default_rng(seed)
        return rng.integers(0, 256, (self.size, self.size, 3), dtype=np.uint8)


def _build_cache(tmp_path, n=10, size=16, rows_per_shard=4):
    files = [str(tmp_path / f"img_{i:03d}.jpg") for i in range(n)]
    loader = StubLoader(size)
    cache_dir = str(tmp_path / "cache")
    build_shard_cache(files, cache_dir, size, rows_per_shard=rows_per_shard,
                      loader=loader)
    return files, loader, cache_dir, ShardCache.open(cache_dir, size)


def _corrupt_row(cache_dir: str, shard: int = 0, row: int = 1) -> None:
    path = os.path.join(cache_dir, f"shard-{shard:05d}.npy")
    mm = np.load(path, mmap_mode="r+")
    mm[row, 0, 0, :] ^= 0xFF
    mm.flush()
    del mm


@pytest.fixture
def tel():
    t = telemetry.enable(capacity=4096)
    yield t
    telemetry.disable()


# ---------------------------------------------------------------------------
# crc32c batching
# ---------------------------------------------------------------------------


def test_crc32c_rows_matches_scalar_oracle(rng):
    # lengths below/above the vectorization threshold, power-of-two
    # lanes, and ragged tails must all agree with the scalar crc
    for L in (1, 16, 1023, 4096, 4097, 12288):
        rows = rng.integers(0, 256, (3, L), dtype=np.uint8)
        got = crc32c_rows(rows)
        want = np.array(
            [summary.crc32c(rows[i].tobytes()) for i in range(3)], np.uint32
        )
        np.testing.assert_array_equal(got, want, err_msg=f"L={L}")
    assert crc32c_rows(np.empty((0, 8), np.uint8)).shape == (0,)


def test_crc32c_rows_accepts_image_shaped_input(rng):
    rows = rng.integers(0, 256, (2, 16, 16, 3), dtype=np.uint8)
    flat = rows.reshape(2, -1)
    np.testing.assert_array_equal(crc32c_rows(rows), crc32c_rows(flat))


# ---------------------------------------------------------------------------
# sidecars
# ---------------------------------------------------------------------------


def test_build_writes_sidecars_matching_shard_bytes(tmp_path):
    _, _, cache_dir, cache = _build_cache(tmp_path)
    shard_files = sorted(
        f for f in os.listdir(cache_dir)
        if f.startswith("shard-") and f.endswith(".npy")
        and not f.endswith(integrity.CRC_SUFFIX)
    )
    assert len(shard_files) == 3  # 10 rows / 4 per shard
    for name in shard_files:
        path = os.path.join(cache_dir, name)
        assert os.path.exists(sidecar_path(path))
        crcs = read_row_crcs(path)
        data = np.asarray(np.load(path, mmap_mode="r"))
        np.testing.assert_array_equal(crcs, crc32c_rows(data))


def test_sidecar_roundtrip_and_missing(tmp_path):
    shard = str(tmp_path / "shard-00000.npy")
    assert read_row_crcs(shard) is None
    crcs = np.array([1, 2, 0xFFFFFFFF], np.uint32)
    assert write_row_crcs(shard, crcs) == sidecar_path(shard)
    np.testing.assert_array_equal(read_row_crcs(shard), crcs)


def test_legacy_cache_sidecar_retrofit(tmp_path):
    files, _, cache_dir, _ = _build_cache(tmp_path, n=4)
    sc = sidecar_path(os.path.join(cache_dir, "shard-00000.npy"))
    os.unlink(sc)  # pretend the cache predates sidecars
    cache = ShardCache.open(cache_dir, 16)
    cache.enable_integrity("full")
    cache.gather(files[:4])  # first verify retrofits the sidecar
    assert os.path.exists(sc)


# ---------------------------------------------------------------------------
# verify-on-gather
# ---------------------------------------------------------------------------


def test_full_mode_detects_and_fallback_recovers(tmp_path, tel):
    files, loader, cache_dir, cache = _build_cache(tmp_path)
    clean = cache.gather(files)
    _corrupt_row(cache_dir, shard=0, row=1)
    cache = ShardCache.open(cache_dir, 16)  # fresh mmaps
    cache.enable_integrity("full")
    bad_rows: list = []
    out = cache.gather(files, fallback=loader.load_raw, bad_rows=bad_rows)
    # the fallback re-decode IS the canonical row: recovery is bitwise
    np.testing.assert_array_equal(out, clean)
    assert bad_rows == []  # fallback succeeded: nothing to quarantine
    counters = tel.counters()
    assert counters.get("data/corrupt_rows", 0) >= 1
    assert counters.get("data/decode_fallback", 0) >= 1


def test_full_mode_without_fallback_raises(tmp_path):
    files, _, cache_dir, _ = _build_cache(tmp_path)
    _corrupt_row(cache_dir)
    cache = ShardCache.open(cache_dir, 16)
    cache.enable_integrity("full")
    with pytest.raises(KeyError, match="crc_mismatch"):
        cache.gather(files)


def test_full_mode_fallback_failure_reports_bad_row(tmp_path):
    files, _, cache_dir, _ = _build_cache(tmp_path)
    _corrupt_row(cache_dir, row=2)
    cache = ShardCache.open(cache_dir, 16)
    cache.enable_integrity("full")

    def broken(_f):
        raise ValueError("decoder down")

    bad_rows: list = []
    out = cache.gather(files, fallback=broken, bad_rows=bad_rows)
    assert len(bad_rows) == 1
    i, f, reason, exc = bad_rows[0]
    assert i == 2 and f == files[2]
    assert reason == "crc_mismatch+live_decode_failed"
    assert isinstance(exc, ValueError)
    assert not out[2].any()  # zero-filled for the quarantine substitution


def test_open_mode_scans_each_shard_once(tmp_path):
    files, loader, cache_dir, _ = _build_cache(tmp_path)
    _corrupt_row(cache_dir, shard=0, row=1)
    cache = ShardCache.open(cache_dir, 16)
    cache.enable_integrity("open")
    bad_rows: list = []
    cache.gather(files, bad_rows=bad_rows)
    assert [(i, r) for i, _, r, _ in bad_rows] == [(1, "crc_mismatch")]
    # shard 0 is now known: later gathers consult the cached bad-row
    # set without re-hashing, and clean shards report nothing
    assert cache.integrity._bad_rows[0] == {1}
    bad_rows2: list = []
    cache.gather(files[4:], bad_rows=bad_rows2)
    assert bad_rows2 == []
    bad_rows3: list = []
    cache.gather([files[1]], bad_rows=bad_rows3)
    assert [(i, r) for i, _, r, _ in bad_rows3] == [(0, "crc_mismatch")]


def test_sample_mode_scrubs_on_cadence(tmp_path, tel):
    files, loader, cache_dir, _ = _build_cache(tmp_path, n=4)
    _corrupt_row(cache_dir, row=0)
    cache = ShardCache.open(cache_dir, 16)
    cache.enable_integrity("sample")
    for _ in range(SAMPLE_EVERY * 2):
        cache.gather([files[0]], fallback=loader.load_raw)
    # single-row batches: the rotating cursor always lands on the bad
    # row, and exactly every SAMPLE_EVERY-th gather pays a verification
    counters = tel.counters()
    assert counters.get("data/corrupt_rows", 0) == 2
    assert counters.get("data/verify_rows", 0) == 2


def test_verify_mode_vocabulary(tmp_path):
    assert VERIFY_MODES == ("off", "sample", "open", "full")
    _, _, _, cache = _build_cache(tmp_path, n=4)
    with pytest.raises(ValueError, match="verify_shards"):
        cache.enable_integrity("sometimes")


def test_config_rejects_bad_integrity_knobs(coco_fixture):
    config = coco_fixture["config"]
    with pytest.raises(ValueError, match="verify_shards"):
        config.replace(verify_shards="sometimes")
    with pytest.raises(ValueError, match="quarantine_max_fraction"):
        config.replace(quarantine_max_fraction=0.0)


# ---------------------------------------------------------------------------
# quarantine ledger
# ---------------------------------------------------------------------------


def test_ledger_roundtrip_dedup_and_torn_tail(tmp_path):
    path = str(tmp_path / "q.jsonl")
    q = QuarantineManager(path)
    q.note_rows(100)
    q.quarantine("/data/b.jpg", "decode_failed", exc=ValueError("boom"))
    q.quarantine("/data/./b.jpg", "decode_failed")  # same file: deduped
    q.quarantine("", "caption_all_oov", kind="caption", pos=(0, 3, 1))
    with open(path) as f:
        entries = [json.loads(line) for line in f]
    assert len(entries) == 2
    assert entries[0]["reason"] == "decode_failed"
    assert entries[0]["error"] == "ValueError: boom"
    assert entries[1]["kind"] == "caption" and entries[1]["pos"] == [0, 3, 1]
    with open(path, "a") as f:
        f.write('{"file": "/torn')  # crash mid-append
    q2 = QuarantineManager(path)
    assert q2.total == 2  # torn tail tolerated, good lines preloaded
    assert q2.known_bad_file("/data/b.jpg")
    assert q2.known_bad_pos(0, 3, 1)
    assert q2.files() == [os.path.normpath("/data/b.jpg")]


def test_ledger_path_for(coco_fixture):
    config = coco_fixture["config"]
    assert ledger_path_for(config) == os.path.join(
        config.summary_dir, "quarantine.jsonl"
    )
    explicit = config.replace(quarantine_ledger="/runs/led.jsonl")
    assert ledger_path_for(explicit) == "/runs/led.jsonl"


def test_ceiling_needs_min_records(tmp_path):
    q = QuarantineManager(str(tmp_path / "q.jsonl"), max_fraction=0.1)
    q.note_rows(4)
    for i in range(MIN_RECORDS_FOR_CEILING - 1):
        q.quarantine(f"/rot/{i}.jpg", "decode_failed")  # sporadic: no abort


def test_ceiling_trips_with_distinct_exit_code(tmp_path):
    assert DATA_CORRUPTION_EXIT_CODE == 87
    assert DATA_CORRUPTION_EXIT_CODE != WATCHDOG_EXIT_CODE
    q = QuarantineManager(str(tmp_path / "q.jsonl"), max_fraction=0.5)
    q.note_rows(10)
    with pytest.raises(SystemicCorruption, match="systemic data corruption"):
        for i in range(MIN_RECORDS_FOR_CEILING + 1):
            q.quarantine(f"/rot/{i}.jpg", "decode_failed")
    # the abort happened ON the tripping quarantine, which was ledgered
    assert q.total == MIN_RECORDS_FOR_CEILING


def test_substitute_index_stable_and_in_range():
    for key in ("image:/a/b.jpg", "caption:0:3:1", ""):
        for n in (1, 2, 7, 64):
            j = QuarantineManager.substitute_index(key, n)
            assert 0 <= j < n
            assert j == QuarantineManager.substitute_index(key, n)


# ---------------------------------------------------------------------------
# hardened prefetch path
# ---------------------------------------------------------------------------


def _fixture_files(coco_fixture):
    d = coco_fixture["train_img_dir"]
    return [os.path.join(d, f) for f in sorted(os.listdir(d))]


def _caption_batch(files, T=6):
    word_idxs = np.tile(np.arange(1, T + 1, dtype=np.int32), (len(files), 1))
    masks = np.ones((len(files), T), np.float32)
    masks[:, -1] = 0.0  # below the overlength threshold
    return (list(files), word_idxs, masks)


def test_prefetch_error_carries_file_and_coordinates(tmp_path):
    from sat_tpu.data.images import ImageLoader, PrefetchDecodeError, PrefetchLoader

    missing = str(tmp_path / "missing.jpg")
    loader = PrefetchLoader(
        [[missing]], ImageLoader(size=16, raw=True), num_workers=1
    )
    with pytest.raises(PrefetchDecodeError) as ei:
        list(loader)
    err = ei.value
    assert err.image_file == missing
    assert err.batch_index == 0 and err.row == 0
    assert isinstance(err.__cause__, FileNotFoundError)
    assert missing in str(err) and "batch 0, row 0" in str(err)


def test_decode_failure_quarantined_and_replay_is_bitwise(
    coco_fixture, tmp_path, monkeypatch
):
    from sat_tpu.data.images import ImageLoader, PrefetchLoader
    from sat_tpu.resilience.faultinject import reset_io_faults

    files = _fixture_files(coco_fixture)
    bad = [f for f in files
           if zlib.crc32(os.path.basename(f).encode()) % 6 == 0]
    assert len(bad) == 1  # SAT_FI_BAD_IMAGE_EVERY=6 poisons one fixture file
    batch_files = [files[0], bad[0], files[1], files[2]]
    ledger = str(tmp_path / "led.jsonl")

    def run_pass():
        loader = PrefetchLoader(
            [_caption_batch(batch_files)],
            ImageLoader(size=32, raw=True),
            num_workers=2,
            quarantine=QuarantineManager(ledger),
        )
        batches = list(loader)
        assert len(batches) == 1
        return batches[0]

    monkeypatch.setenv("SAT_FI_BAD_IMAGE_EVERY", "6")
    b1 = run_pass()
    monkeypatch.delenv("SAT_FI_BAD_IMAGE_EVERY")
    reset_io_faults()

    with open(ledger) as f:
        entries = [json.loads(line) for line in f]
    assert len(entries) == 1
    assert entries[0]["kind"] == "image"
    assert entries[0]["reason"] == "decode_failed"
    assert "injected decode failure" in entries[0]["error"]
    assert entries[0]["file"] == os.path.normpath(bad[0])

    # geometry preserved; the bad row now carries a healthy batchmate
    assert b1["images"].shape == (4, 32, 32, 3)
    assert b1["files"][1] != bad[0] and b1["files"][1] in batch_files

    # replay with the SAME ledger and no fault armed: the known-bad file
    # is substituted proactively (never re-decoded) and the batch is
    # bitwise-identical — and the ledger is not re-appended
    b2 = run_pass()
    assert b2["files"] == b1["files"]
    np.testing.assert_array_equal(b2["images"], b1["images"])
    np.testing.assert_array_equal(b2["word_idxs"], b1["word_idxs"])
    np.testing.assert_array_equal(b2["masks"], b1["masks"])
    with open(ledger) as f:
        assert len(f.readlines()) == 1


def test_caption_anomalies_quarantined_by_position(coco_fixture, tmp_path):
    from sat_tpu.data.images import ImageLoader, PrefetchLoader

    files = _fixture_files(coco_fixture)[:4]
    batch = _caption_batch(files)
    batch[2][1] = 1.0  # row 1: every mask slot set -> overlength
    batch[2][2] = 0.0  # row 2: no valid token -> all-OOV
    ledger = str(tmp_path / "led.jsonl")
    loader = PrefetchLoader(
        [batch], ImageLoader(size=32, raw=True), num_workers=2,
        quarantine=QuarantineManager(ledger),
    )
    out = list(loader)[0]
    with open(ledger) as f:
        entries = [json.loads(line) for line in f]
    assert [(e["kind"], e["reason"], e["pos"]) for e in entries] == [
        ("caption", "caption_overlength", [0, 0, 1]),
        ("caption", "caption_all_oov", [0, 0, 2]),
    ]
    # both rows were substituted wholesale from a healthy batchmate
    for row in (1, 2):
        j = out["files"].index(out["files"][row])
        assert out["files"][row] in (files[0], files[3])
        np.testing.assert_array_equal(out["masks"][row], out["masks"][j])
        assert out["masks"][row, -1] == 0.0


def test_all_rows_bad_is_systemic(coco_fixture, tmp_path):
    from sat_tpu.data.images import ImageLoader, PrefetchLoader

    files = _fixture_files(coco_fixture)[:2]
    batch = _caption_batch(files)
    batch[2][:] = 0.0  # every caption row is anomalous
    loader = PrefetchLoader(
        [batch], ImageLoader(size=32, raw=True), num_workers=2,
        quarantine=QuarantineManager(str(tmp_path / "led.jsonl")),
    )
    with pytest.raises(SystemicCorruption, match="no healthy row"):
        list(loader)


# ---------------------------------------------------------------------------
# --repair_shards
# ---------------------------------------------------------------------------


def test_repair_shards_rebuilds_only_suspects_bitwise(coco_fixture, tmp_path):
    size = 16
    config = coco_fixture["config"].replace(
        image_size=size,
        shard_cache_dir=str(tmp_path / "shards"),
        quarantine_ledger=str(tmp_path / "led.jsonl"),
    )
    files = [str(tmp_path / f"src_{i:03d}.jpg") for i in range(8)]
    loader = StubLoader(size)
    cache_dir = cache_dir_for(config)
    build_shard_cache(files, cache_dir, size, rows_per_shard=4, loader=loader)
    reference_dir = str(tmp_path / "reference")
    build_shard_cache(files, reference_dir, size, rows_per_shard=4,
                      loader=StubLoader(size))

    # shard 0: silent bit-rot; shard 1: a ledger-quarantined source file
    _corrupt_row(cache_dir, shard=0, row=2)
    QuarantineManager(config.quarantine_ledger).quarantine(
        files[5], "decode_failed"
    )
    report = repair_shards(config, loader=loader)
    assert report["shards_rebuilt"] == 2
    assert report["rows_rebuilt"] == 8
    assert report["unrepairable"] == []
    suspects = {s["shard"]: s for s in report["suspect_shards"]}
    assert suspects["shard-00000.npy"]["crc_mismatch_rows"] == [2]
    assert suspects["shard-00001.npy"]["quarantined_files"] == [
        os.path.normpath(files[5])
    ]

    # repaired cache is bitwise-identical to a clean rebuild, sidecars
    # included, and reopens with a consistent manifest
    for name in ("shard-00000.npy", "shard-00001.npy"):
        got = np.load(os.path.join(cache_dir, name))
        want = np.load(os.path.join(reference_dir, name))
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(
            read_row_crcs(os.path.join(cache_dir, name)),
            read_row_crcs(os.path.join(reference_dir, name)),
        )
    cache = ShardCache.open(cache_dir, size)
    cache.enable_integrity("full")
    bad_rows: list = []
    np.testing.assert_array_equal(
        cache.gather(files, bad_rows=bad_rows),
        ShardCache.open(reference_dir, size).gather(files),
    )
    assert bad_rows == []

    # a second repair: the crc-mismatch shard is clean now, but the
    # ledgered file stays suspect (append-only evidence) until the
    # operator clears the ledger — only ITS shard is rebuilt again
    report2 = repair_shards(config, loader=loader)
    assert report2["shards_rebuilt"] == 1
    assert [s["shard"] for s in report2["suspect_shards"]] == [
        "shard-00001.npy"
    ]
    assert report2["suspect_shards"][0]["crc_mismatch_rows"] == []


def test_repair_shards_without_cache_raises(coco_fixture, tmp_path):
    config = coco_fixture["config"].replace(
        shard_cache_dir=str(tmp_path / "nowhere")
    )
    with pytest.raises(FileNotFoundError):
        repair_shards(config, loader=StubLoader())


# ---------------------------------------------------------------------------
# fault injection knobs
# ---------------------------------------------------------------------------


def test_fault_plan_data_knobs(tmp_path, monkeypatch):
    from sat_tpu.resilience.faultinject import (
        FaultPlan,
        consume_caption_fault,
        consume_decode_fault,
        reset_io_faults,
    )

    plan = FaultPlan.from_env({
        "SAT_FI_CORRUPT_SHARD_ROW": "2",
        "SAT_FI_BAD_IMAGE_EVERY": "3",
        "SAT_FI_BAD_CAPTION_AT": "7",
    })
    assert not plan.inert
    assert (plan.corrupt_shard_row, plan.bad_image_every,
            plan.bad_caption_at) == (2, 3, 7)
    assert FaultPlan.from_env({}).inert

    # shard corruption is idempotent: arming it across a restart must
    # not corrupt a second row
    cache_dir = str(tmp_path / "cache")
    build_shard_cache(
        [str(tmp_path / f"f{i}.jpg") for i in range(4)],
        cache_dir, 8, rows_per_shard=4, loader=StubLoader(8),
    )
    armed = FaultPlan.from_env({"SAT_FI_CORRUPT_SHARD_ROW": "1"})
    armed.maybe_corrupt_shard_row(cache_dir)
    once = open(os.path.join(cache_dir, "shard-00000.npy"), "rb").read()
    armed.maybe_corrupt_shard_row(cache_dir)
    twice = open(os.path.join(cache_dir, "shard-00000.npy"), "rb").read()
    assert once == twice

    # decode faults key on the file BASENAME hash: stable under
    # thread-pool reordering and path prefixes
    monkeypatch.setenv("SAT_FI_BAD_IMAGE_EVERY", "6")
    bad = "COCO_fixture_000000000008.jpg"
    assert zlib.crc32(bad.encode()) % 6 == 0
    with pytest.raises(ValueError, match="injected decode failure"):
        consume_decode_fault(f"/anywhere/{bad}")
    consume_decode_fault("/anywhere/COCO_fixture_000000000000.jpg")
    monkeypatch.delenv("SAT_FI_BAD_IMAGE_EVERY")

    monkeypatch.setenv("SAT_FI_BAD_CAPTION_AT", "3")
    reset_io_faults()
    assert [consume_caption_fault() for _ in range(5)] == [
        False, False, True, False, False,
    ]
    monkeypatch.delenv("SAT_FI_BAD_CAPTION_AT")
    reset_io_faults()


# ---------------------------------------------------------------------------
# vocab/checkpoint compatibility guard (satellite)
# ---------------------------------------------------------------------------


def test_vocab_fingerprint_and_restore_guard(tmp_path):
    from sat_tpu.data.vocabulary import Vocabulary, vocab_fingerprint
    from sat_tpu.resilience import lineage
    from sat_tpu.train.checkpoint import VocabMismatchError, _check_vocab

    vocab_file = str(tmp_path / "vocabulary.csv")
    v = Vocabulary(50)
    v.build(["a man rides a horse .", "a dog runs fast .",
             "the horse jumps ."])
    v.save(vocab_file)
    fp = vocab_fingerprint(vocab_file, 50)
    assert set(fp) == {"sha256", "size"} and fp["size"] == len(v.words)
    assert vocab_fingerprint(vocab_file, 50) == fp  # memoized, stable
    assert vocab_fingerprint(str(tmp_path / "absent.csv"), 50) is None

    ckpt = str(tmp_path / "3.npz")
    with open(ckpt, "wb") as f:
        f.write(b"not really a checkpoint")
    lineage.write_sidecar(ckpt, vocab=fp)
    assert lineage.read_sidecar_meta(ckpt)["vocab"] == fp

    _check_vocab(ckpt, fp)  # matching fingerprint: silent
    _check_vocab(ckpt, None)  # run without a fingerprint: checks nothing
    other = {"sha256": "0" * 64, "size": 999}
    with pytest.raises(VocabMismatchError, match=r"vocab mismatch \(got 999"):
        _check_vocab(ckpt, other)

    legacy = str(tmp_path / "6.npz")
    with open(legacy, "wb") as f:
        f.write(b"older checkpoint")
    lineage.write_sidecar(legacy)  # pre-vocab sidecar: nothing recorded
    _check_vocab(legacy, fp)  # and therefore nothing to mismatch


# ---------------------------------------------------------------------------
# serve bad-input handling (satellite)
# ---------------------------------------------------------------------------


def test_serve_rejects_undecodable_post_cleanly(coco_fixture, tel):
    from sat_tpu.serve.server import CaptionServer

    class StubEngine:
        def __init__(self, config):
            self.config = config

        def preprocess(self, body):
            raise ValueError("not a JPEG/PNG")

    config = coco_fixture["config"]
    server = CaptionServer(config, StubEngine(config))
    assert server.handle_caption(b"\xff\xd8garbage")[0] == 503  # not ready
    server._ready = True
    status, payload = server.handle_caption(b"\xff\xd8garbage")
    assert status == 400
    assert payload["error"] == "bad image"
    assert "cannot decode image bytes" in payload["detail"]
    assert tel.counters().get("serve/bad_input", 0) == 1


# ---------------------------------------------------------------------------
# chaos campaign + regression gate (acceptance e2e)
# ---------------------------------------------------------------------------


def test_chaos_campaign_acceptance_and_regression_gate(tmp_path):
    """One command runs the poison e2e (shard rot + decode faults ->
    clean completion, populated ledger, heartbeat gauges, bitwise
    replay) and the systemic-abort scenario (exit 87, supervisor does
    not restart), emitting a report check_regression.py accepts."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SAT_FI_")}
    report = tmp_path / "chaos_report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "chaos_campaign.py"),
         "--only", "poison_quarantine_replay,systemic_no_restart",
         "--out", str(report), "--workdir", str(tmp_path / "wd")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    rows = json.loads(report.read_text())
    metrics = {r["metric"]: r for r in rows}
    assert metrics["chaos_poison_quarantine_replay"]["value"] == 1.0
    assert metrics["chaos_systemic_no_restart"]["value"] == 1.0
    assert metrics["chaos_pass_rate"]["value"] == 1.0
    assert metrics["chaos_pass_rate"]["scenarios"] == 2
    assert all("schema_version" in r for r in rows)

    gate = subprocess.run(
        [sys.executable, os.path.join("scripts", "check_regression.py"),
         str(report)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=60,
    )
    assert gate.returncode == 0, gate.stdout + gate.stderr


def test_bench_integrity_contract(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "bench_integrity.py"),
         "--iters", "256", "--files", "16", "--batch", "4", "--size", "32",
         "--workdir", str(tmp_path / "bench")],
        cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "integrity_verify_overhead"
    assert row["unit"] == "%_of_step"
    assert row["value"] < 1.0  # the gate bench_integrity itself enforces
    assert "schema_version" in row and "vs_baseline" in row
