"""Penn-Treebank-style word tokenizer, dependency-free.

The reference tokenizes in two places, both via external dependencies we
replace here with a single native implementation:

* vocabulary building / caption indexing uses ``nltk.word_tokenize``
  (/root/reference/utils/vocabulary.py:21,49);
* metric evaluation shells out to Stanford CoreNLP's ``PTBTokenizer`` jar
  with ``-preserveLines -lowerCase`` and then drops punctuation tokens
  (/root/reference/utils/coco/pycocoevalcap/tokenizer/ptbtokenizer.py:18-69).

Both are Treebank tokenizers, so one rule set serves both call sites.
"""

from __future__ import annotations

import re
from typing import Iterable, List

# Punctuation tokens the eval pipeline removes after tokenization,
# mirroring PUNCTUATIONS in the reference's ptbtokenizer wrapper
# (/root/reference/utils/coco/pycocoevalcap/tokenizer/ptbtokenizer.py:21-22).
PUNCTUATIONS = frozenset({
    "''", "'", "``", "`", "-LRB-", "-RRB-", "-LCB-", "-RCB-",
    ".", "?", "!", ",", ":", "-", "--", "...", ";",
})

# Treebank contraction suffixes: don't -> do n't, it's -> it 's, etc.
_CONTRACTIONS = re.compile(r"([^' ])('ll|'re|'ve|n't|'s|'m|'d)\b", re.IGNORECASE)
# Multi-word contractions treated as single splits by Treebank rules.
_CONTRACTIONS2 = [
    (re.compile(r"\b(can)(not)\b", re.IGNORECASE), r"\1 \2"),
    (re.compile(r"\b(gon)(na)\b", re.IGNORECASE), r"\1 \2"),
    (re.compile(r"\b(got)(ta)\b", re.IGNORECASE), r"\1 \2"),
    (re.compile(r"\b(wan)(na)\b", re.IGNORECASE), r"\1 \2"),
    (re.compile(r"\b(lem)(me)\b", re.IGNORECASE), r"\1 \2"),
]

_RULES = [
    # Starting quotes.
    (re.compile(r'^\"'), r"``"),
    (re.compile(r"(``)"), r" \1 "),
    (re.compile(r'([ (\[{<])(\"|\'{2})'), r"\1 `` "),
    # Ellipsis before other period handling.
    (re.compile(r"\.\.\."), r" ... "),
    # Most punctuation.
    (re.compile(r"([;@#$%&?!])"), r" \1 "),
    # Sentence-internal periods followed by whitespace (nltk's word_tokenize
    # sentence-splits first, so "a dog. runs." yields a separate '.').
    (re.compile(r"([^\.])(\.)(?=\s)"), r"\1 \2 "),
    (re.compile(r"([^\.])(\.)([\]\)}>\"\']*)\s*$"), r"\1 \2\3 "),  # final period
    (re.compile(r"([:,])([^\d])"), r" \1 \2"),   # comma/colon not in numbers
    (re.compile(r"([:,])$"), r" \1 "),
    # Parens, brackets.
    (re.compile(r"([\]\[\(\)\{\}<>])"), r" \1 "),
    (re.compile(r"--"), r" -- "),
    # Ending quotes.
    (re.compile(r'"'), r" '' "),
    (re.compile(r"(\S)(\'\')"), r"\1 \2 "),
    (re.compile(r"([^' ])(' )"), r"\1 ' "),
]


def tokenize_pure(text: str, lower: bool = True) -> List[str]:
    """Pure-Python Treebank tokenization (reference rule set)."""
    if lower:
        text = text.lower()
    text = " " + text.strip() + " "
    for pattern, sub in _RULES:
        text = pattern.sub(sub, text)
    text = _CONTRACTIONS.sub(r"\1 \2", text)
    for pattern, sub in _CONTRACTIONS2:
        text = pattern.sub(sub, text)
    return text.split()


def _native_eligible(text: str, lower: bool) -> bool:
    """The C++ tokenizer is byte-wise ASCII and implements only the
    lowercased rule path; route anything else to the Python rules so the
    two backends can never disagree on the same input."""
    return lower and text.isascii()


def tokenize(text: str, lower: bool = True) -> List[str]:
    """Tokenize one sentence into Treebank-style word tokens.  Uses the
    C++ tokenizer (sat_tpu/native) when built, else the Python rules —
    the two are golden-tested for identical output."""
    from .. import native

    if _native_eligible(text, lower) and native.available():
        return native.tokenize(text, lower=lower)
    return tokenize_pure(text, lower=lower)


def tokenize_no_punct(text: str, lower: bool = True) -> List[str]:
    """Tokenize and drop punctuation tokens — the metric-eval flavour
    (reference ptbtokenizer.py:65-66 removes PUNCTUATIONS post-hoc)."""
    from .. import native

    if _native_eligible(text, lower) and native.available():
        return native.tokenize(text, lower=lower, strip_punct=True)
    return [t for t in tokenize_pure(text, lower=lower) if t not in PUNCTUATIONS]


def tokenize_captions(captions: Iterable[str]) -> List[str]:
    """Batch variant used by the eval stack: each caption becomes one
    space-joined line of punctuation-free lowercase tokens, matching the
    reference's ``-preserveLines -lowerCase`` jar invocation."""
    return [" ".join(tokenize_no_punct(c)) for c in captions]
