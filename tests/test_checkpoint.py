"""Checkpoint save/restore/import/trim (SURVEY.md §2.12, §2.29, §3.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models.captioner import init_variables
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    load_flat,
    load_pretrained_cnn,
    restore_checkpoint,
    save_checkpoint,
    state_to_flat,
    trim_checkpoint,
)
from sat_tpu.train.step import create_train_state, make_jit_train_step


TINY = dict(
    image_size=32,
    vocabulary_size=50,
    dim_embedding=8,
    num_lstm_units=8,
    dim_initialize_layer=8,
    dim_attend_layer=8,
    dim_decode_layer=16,
    max_caption_length=5,
    compute_dtype="float32",
)


def _tiny_config(**kw):
    return Config(**{**TINY, **kw})


def _batch(config, rng, B=2):
    T = config.max_caption_length
    return {
        "images": jnp.asarray(
            rng.normal(size=(B, config.image_size, config.image_size, 3)).astype(
                np.float32
            )
        ),
        "word_idxs": jnp.asarray(
            rng.integers(0, config.vocabulary_size, size=(B, T)).astype(np.int32)
        ),
        "masks": jnp.ones((B, T), jnp.float32),
    }


def test_save_restore_roundtrip(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))

    path = save_checkpoint(state, config)
    assert path.endswith("1.npz")
    assert latest_checkpoint(str(tmp_path)) == path

    fresh = create_train_state(jax.random.PRNGKey(7), config)
    restored, count = restore_checkpoint(fresh, save_dir=str(tmp_path))
    assert count > 0
    assert int(restored.step) == 1

    want = state_to_flat(state)
    got = state_to_flat(restored)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_allclose(want[k], got[k], err_msg=k)

    # restored state must keep training (optimizer slots intact)
    restored2, _ = step(restored, _batch(config, rng), jax.random.PRNGKey(2))
    assert int(restored2.step) == 2


def test_restore_latest_picks_newest(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    save_checkpoint(state, config)                     # 0.npz
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    save_checkpoint(state, config)                     # 1.npz
    assert latest_checkpoint(str(tmp_path)).endswith("1.npz")


def test_trimmed_checkpoint_partial_restores(tmp_path, rng):
    """Trim drops optimizer slots; the slim file still restores params —
    the reference's trim_model.py + tolerant load path."""
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    path = save_checkpoint(state, config)

    slim = str(tmp_path / "slim.npz")
    kept = trim_checkpoint(path, slim)
    flat = load_flat(slim)
    assert kept == len(flat)
    assert not any(k.startswith("optimizer/") for k in flat)
    assert any(k.startswith("params/") for k in flat)

    fresh = create_train_state(jax.random.PRNGKey(9), config)
    restored, count = restore_checkpoint(fresh, model_file=slim)
    assert count > 0
    want = state_to_flat(state)
    got = state_to_flat(restored)
    for k in want:
        if k.startswith("params/") or k == "global_step":
            np.testing.assert_allclose(want[k], got[k], err_msg=k)


@pytest.mark.parametrize("cnn", ["vgg16", "resnet50"])
def test_pretrained_cnn_import(tmp_path, cnn):
    """Nested {op: {param: arr}} npy import — the reference's
    vgg16_no_fc.npy / resnet50_no_fc.npy format (base_model.py:280-297)."""
    config = _tiny_config(cnn=cnn, image_size=64)
    variables = init_variables(jax.random.PRNGKey(0), config)

    if cnn == "vgg16":
        kshape = tuple(variables["params"]["cnn"]["conv1_1"]["conv"]["kernel"].shape)
        nested = {
            "conv1_1": {
                "weights": np.full(kshape, 0.5, np.float32),
                "biases": np.full((kshape[-1],), 0.25, np.float32),
            },
            "not_a_layer": {"weights": np.zeros((3, 3, 1, 1), np.float32)},
        }
        want_loaded = 2
    else:
        k1 = tuple(variables["params"]["cnn"]["conv1"]["conv"]["kernel"].shape)
        k2 = tuple(
            variables["params"]["cnn"]["res2a"]["res2a_branch2a"]["conv"]["kernel"].shape
        )
        c = k1[-1]
        nested = {
            "conv1": {"weights": np.full(k1, 0.5, np.float32)},
            "bn_conv1": {
                "scale": np.full((c,), 2.0, np.float32),
                "offset": np.full((c,), 0.1, np.float32),
                "mean": np.full((c,), 0.3, np.float32),
                "variance": np.full((c,), 0.9, np.float32),
            },
            "res2a_branch2a": {"weights": np.full(k2, 0.25, np.float32)},
        }
        want_loaded = 6

    path = str(tmp_path / f"{cnn}_no_fc.npy")
    np.save(path, np.array(nested, dtype=object), allow_pickle=True)

    new_vars, count = load_pretrained_cnn(variables, path)
    assert count == want_loaded

    if cnn == "vgg16":
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["conv1_1"]["conv"]["kernel"]), 0.5
        )
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["conv1_1"]["conv"]["bias"]), 0.25
        )
    else:
        np.testing.assert_allclose(
            np.asarray(new_vars["params"]["cnn"]["bn_conv1"]["scale"]), 2.0
        )
        np.testing.assert_allclose(
            np.asarray(new_vars["batch_stats"]["bn_conv1"]["mean"]), 0.3
        )
        np.testing.assert_allclose(
            np.asarray(
                new_vars["params"]["cnn"]["res2a"]["res2a_branch2a"]["conv"]["kernel"]
            ),
            0.25,
        )


def test_torn_config_json_falls_back_to_scan(tmp_path, rng):
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    path = save_checkpoint(state, config)
    with open(tmp_path / "config.json", "w") as f:
        f.write('{"phase": "tr')  # torn mid-write
    assert latest_checkpoint(str(tmp_path)) == path


def test_global_step_alone_is_not_a_restore(tmp_path, rng):
    """count==0 must mean 'no tensors restored' — the always-present
    global_step entry may not inflate the count."""
    np.savez(tmp_path / "7.npz", global_step=np.asarray(7, np.int32))

    config = _tiny_config(save_dir=str(tmp_path))
    fresh = create_train_state(jax.random.PRNGKey(1), config)
    restored, count = restore_checkpoint(fresh, model_file=str(tmp_path / "7.npz"))
    assert count == 0
    assert int(restored.step) == 7


def test_stale_config_pointer_does_not_shadow_newer_checkpoint(tmp_path, rng):
    """Preemption between the npz rename and the config.json update must
    not lose the newest checkpoint."""
    config = _tiny_config(save_dir=str(tmp_path))
    state = create_train_state(jax.random.PRNGKey(0), config)
    step = make_jit_train_step(config)
    save_checkpoint(state, config)                     # 0.npz + pointer→0
    state, _ = step(state, _batch(config, rng), jax.random.PRNGKey(1))
    save_checkpoint(state, config)                     # 1.npz + pointer→1
    config.replace(global_step=0).save(str(tmp_path / "config.json"))  # stale
    assert latest_checkpoint(str(tmp_path)).endswith("1.npz")
