"""COCO captions index.

A from-scratch, dependency-light equivalent of the reference's vendored and
modified COCO toolkit (/root/reference/utils/coco/coco.py:68-364), keeping
its behavioral contract:

* optional ``max_ann_num`` cap applied to the first N annotations
  (coco.py:119-124);
* caption normalization at load: lowercase + ensure trailing ``'.'``
  (``process_dataset``, coco.py:316-321);
* ``filter_by_cap_len`` keeps annotations whose caption tokenizes to at
  most N tokens (coco.py:323-339);
* ``filter_by_words`` keeps annotations fully covered by a vocabulary
  (coco.py:341-361) — unlike the reference we also drop images left with
  no annotations (the reference keeps them due to a counting bug at
  coco.py:352);
* ``load_results`` validates a predictions JSON against the ground-truth
  image set and wraps it in a new index (``loadRes``, coco.py:263-290);
* ``download`` fetches any missing images by ``coco_url`` (coco.py:292-314).

Tokenization uses our native Treebank tokenizer instead of nltk.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set

from ..utils.fileio import read_json
from .tokenizer import tokenize


class CocoCaptions:
    def __init__(
        self,
        annotation_file: Optional[str] = None,
        max_ann_num: Optional[int] = None,
    ):
        self.dataset: Dict = {"images": [], "annotations": []}
        self.anns: Dict[int, Dict] = {}
        self.imgs: Dict[int, Dict] = {}
        self.img_to_anns: Dict[int, List[Dict]] = {}
        self.img_name_to_id: Dict[str, int] = {}
        self.max_ann_num = max_ann_num

        if annotation_file is not None:
            # retrying read: caption JSONs usually live on the same shared
            # filesystem as the shards, where transient EIO/ESTALE is a
            # backoff, not a crash (resilience.retry)
            self.dataset = read_json(
                annotation_file, desc=f"read captions {annotation_file}"
            )
            self._normalize_captions()
            self.create_index(max_ann_num)

    # -- aliases so call sites written against the reference API work --
    @property
    def imgToAnns(self) -> Dict[int, List[Dict]]:  # noqa: N802
        return self.img_to_anns

    def _normalize_captions(self) -> None:
        for ann in self.dataset.get("annotations", []):
            q = ann["caption"].lower()
            if not q.endswith("."):
                q = q + "."
            ann["caption"] = q

    def create_index(self, max_ann_num: Optional[int] = None) -> None:
        anns: Dict[int, Dict] = {}
        img_to_anns: Dict[int, List[Dict]] = {}
        annotations = self.dataset.get("annotations", [])
        if max_ann_num is not None:
            annotations = annotations[:max_ann_num]
        for ann in annotations:
            anns[ann["id"]] = ann
            img_to_anns.setdefault(ann["image_id"], []).append(ann)

        imgs: Dict[int, Dict] = {}
        img_name_to_id: Dict[str, int] = {}
        for img in self.dataset.get("images", []):
            imgs[img["id"]] = img
            if "file_name" in img:
                img_name_to_id[img["file_name"]] = img["id"]

        self.anns = anns
        self.img_to_anns = img_to_anns
        self.imgs = imgs
        self.img_name_to_id = img_name_to_id

    # ---- filters (rebuild the index afterwards, like the reference) ----

    def filter_by_cap_len(self, max_cap_len: int) -> None:
        keep = [
            ann
            for ann in self.dataset["annotations"]
            if len(tokenize(ann["caption"])) <= max_cap_len
        ]
        self._apply_ann_filter(keep)

    def filter_by_words(self, vocab: Set[str]) -> None:
        keep = [
            ann
            for ann in self.dataset["annotations"]
            if all(w in vocab for w in tokenize(ann["caption"]))
        ]
        self._apply_ann_filter(keep)

    def _apply_ann_filter(self, kept_anns: List[Dict]) -> None:
        kept_img_ids = {ann["image_id"] for ann in kept_anns}
        self.dataset["annotations"] = kept_anns
        self.dataset["images"] = [
            img for img in self.dataset["images"] if img["id"] in kept_img_ids
        ]
        self.create_index()

    # ---- accessors ----

    def all_captions(self) -> List[str]:
        return [ann["caption"] for ann in self.anns.values()]

    def get_img_ids(self) -> List[int]:
        return list(self.imgs.keys())

    # ---- results wrapping for evaluation ----

    def load_results(self, res_file_or_list) -> "CocoCaptions":
        """Build a result index from a predictions JSON file or list of
        ``{'image_id': int, 'caption': str}`` dicts."""
        if isinstance(res_file_or_list, str):
            with open(res_file_or_list) as f:
                anns = json.load(f)
        else:
            # copy so assigning result ids never mutates the caller's dicts
            anns = [dict(a) for a in res_file_or_list]
        if not isinstance(anns, list):
            raise ValueError("results must be a list of objects")
        if not anns or "caption" not in anns[0]:
            raise ValueError("results must contain captions")
        res_img_ids = {ann["image_id"] for ann in anns}
        missing = res_img_ids - set(self.imgs.keys())
        if missing:
            raise ValueError(
                f"results reference unknown image ids: {sorted(missing)[:5]}"
            )

        res = CocoCaptions()
        res.dataset["images"] = [
            img for img in self.dataset["images"] if img["id"] in res_img_ids
        ]
        for i, ann in enumerate(anns):
            ann["id"] = i + 1
        res.dataset["annotations"] = anns
        res.create_index()
        return res

    loadRes = load_results  # reference-API alias  # noqa: N815

    # ---- image download (idempotent, like reference coco.py:292-314) ----

    def download(self, target_dir: str, img_ids: Sequence[int] = ()) -> int:
        from urllib.request import urlretrieve

        imgs = (
            [self.imgs[i] for i in img_ids] if len(img_ids) else list(self.imgs.values())
        )
        os.makedirs(target_dir, exist_ok=True)
        fetched = 0
        failed = 0
        for img in imgs:
            fname = os.path.join(target_dir, img["file_name"])
            if not os.path.exists(fname):
                if "coco_url" not in img:
                    continue
                try:
                    urlretrieve(img["coco_url"], fname)
                    fetched += 1
                except OSError:
                    # keep going: a missing image surfaces later with a
                    # clear FileNotFoundError naming the file
                    failed += 1
        if failed:
            print(f"warning: failed to download {failed} missing images")
        return fetched
