"""Per-host input sharding for multi-process training.

The reference's distributed mode has every worker read the whole dataset
and rely on asynchrony to decorrelate (/root/reference/main_distributed.py:
67-79).  The SPMD design instead gives each host a disjoint slice of the
global batch: the per-host DataSet below yields ``global_batch /
process_count`` items per step, and ``make_global_batch`` (collectives.py)
stitches the host shards into one data-sharded global array.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np

from ..data.dataset import DataSet


def mesh_data_shard(mesh) -> Tuple[int, int]:
    """Map THIS process to its slot along the mesh's 'data' axis.

    Returns ``(shard_index, num_shards)`` for the per-host input feed.
    The feed must be keyed on the DATA-axis layout, not the process
    count: when the 'model' axis spans processes (context parallelism or
    cross-host TP), several processes hold the same data row and must
    feed identical replicas of it — `jax.make_array_from_process_local_data`
    maps each process's local rows onto the rows its devices own.

    * every process's devices in one data row (model axis across hosts):
      that row's index, out of dp rows — pure-CP meshes give (0, 1),
      every host feeding the full batch;
    * one-or-more rows per process and dp == global layout (the plain DP
      case, incl. several rows per process): falls back to
      ``(process_index, process_count)`` — the contiguous-block ownership
      of the data-major device order.
    """
    axes = list(mesh.axis_names)
    devs = np.moveaxis(np.asarray(mesh.devices), axes.index("data"), 0)
    dp = devs.shape[0]
    rows = {
        r
        for r in range(dp)
        for d in devs[r].flat
        if d.process_index == jax.process_index()
    }
    if len(rows) == 1:
        return rows.pop(), dp
    # multi-row fallback: only valid when this process owns EXACTLY the
    # contiguous row block implied by (process_index, process_count) — a
    # straddling layout (devices-per-process not a multiple of the model
    # axis) would silently map the wrong dataset rows onto the owned
    # shards, so fail loudly instead
    pi, pc = jax.process_index(), jax.process_count()
    if dp % pc == 0 and rows == set(range(pi * (dp // pc), (pi + 1) * (dp // pc))):
        return pi, pc
    raise ValueError(
        f"process {pi}'s devices straddle data rows {sorted(rows)} of {dp} "
        f"(mesh {dict(mesh.shape)} over {pc} processes) — the per-host feed "
        "cannot map dataset rows onto this layout; use a mesh where each "
        "process's devices sit in one data row or an exact row block"
    )


def pad_dataset_for_processes(dataset: DataSet, process_count: int) -> DataSet:
    """Pad an *unshuffled* eval/test DataSet to a count divisible by
    ``process_count`` by repeating trailing rows, so every host's shard has
    the same number of batches (a short shard would desynchronize the SPMD
    decode collectives).  The padding rows are duplicates of real images;
    result assembly cuts at the original count, mirroring the fake_count
    convention (reference dataset.py:51-54)."""
    pad = (-dataset.count) % process_count
    if pad == 0:
        return dataset
    # modulo tiling: pad may exceed count (tiny dataset, many hosts)
    idx = list(range(dataset.count)) + [i % dataset.count for i in range(pad)]
    return DataSet(
        dataset.image_ids[idx],
        dataset.image_files[idx],
        dataset.batch_size,
        None if dataset.word_idxs is None else dataset.word_idxs[idx],
        None if dataset.masks is None else dataset.masks[idx],
        is_train=dataset.is_train,
        shuffle=False,
        seed=dataset.seed,
    )


def process_local_dataset(
    dataset: DataSet,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> DataSet:
    """Slice a *global* DataSet down to this process's shard.

    Rows ``process_index::process_count`` with a per-host batch size of
    ``global_batch // process_count``; every host sees the same number of
    batches so the synchronous step count agrees across the slice.
    Single-process runs return the dataset unchanged.
    """
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if pc == 1:
        return dataset
    if dataset.batch_size % pc:
        raise ValueError(
            f"global batch {dataset.batch_size} not divisible by "
            f"{pc} processes"
        )
    # Truncate every shard to the common length: unequal shards would give
    # hosts different num_batches, desynchronizing the SPMD collectives
    # (one host in the checkpoint all-gather while others are in the
    # gradient all-reduce ⇒ hang).  Drops at most pc-1 trailing samples.
    n = (len(dataset.image_ids) // pc) * pc
    sel = slice(pi, n, pc)
    return DataSet(
        dataset.image_ids[sel],
        dataset.image_files[sel],
        dataset.batch_size // pc,
        None if dataset.word_idxs is None else dataset.word_idxs[sel],
        None if dataset.masks is None else dataset.masks[sel],
        is_train=dataset.is_train,
        shuffle=dataset.shuffle,
        # decorrelated per-shard shuffle, still keyed on the run's base
        # seed so config.seed controls the full multi-host batch stream
        seed=dataset.seed * 1009 + pi,
    )
