"""Replica lifecycle for the serving fleet (docs/SERVING.md).

The router (``router.py``) fronts N captioning replicas; this module
owns how those replicas come to exist and die.  Two modes:

* **local spawn** — :class:`LocalFleet` launches N ``--phase serve``
  subprocesses of the standard CLI over a port range, each with its own
  summary/telemetry directory (so per-replica ``access.jsonl`` and
  heartbeats never interleave), waits for every ``/healthz`` to go
  ready, and can SIGTERM one replica into its drain-to-completion
  sequence (server.py's shutdown path) for deploys.
* **pre-started endpoints** — :func:`parse_endpoints` turns a
  ``host:port,host:port`` spec into the same :class:`Endpoint` records
  the router polls; lifecycle stays with whoever started them.

Deliberately jax-free (enforced by tests/test_device_diag.py): the
router process must survive exactly the failures a wedged accelerator
runtime causes, so — like the ``--supervise`` parent — it never imports
the device stack.  Subprocesses inherit the environment, so a
``JAX_PLATFORMS=cpu`` run spawns CPU replicas.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..config import Config


# the tier values a replica may advertise (config.serve_tier): "both"
# runs the full pipeline; "encode" only answers POST /encode; "decode"
# only seeds slots from handed-off grids (plus grid-ingress /caption)
TIERS = ("both", "encode", "decode")


class Endpoint:
    """One replica's address + identity, however it came to exist."""

    __slots__ = ("name", "host", "port", "tier")

    def __init__(
        self, name: str, host: str, port: int, tier: str = "both"
    ) -> None:
        self.name = name
        self.host = host
        self.port = int(port)
        self.tier = tier

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def __repr__(self) -> str:  # log-friendly
        suffix = "" if self.tier == "both" else f"={self.tier}"
        return f"Endpoint({self.name}={self.address}{suffix})"


def parse_endpoints(spec: str) -> List[Endpoint]:
    """``host:port[,host:port=tier,...]`` -> named endpoints (r0, r1, ...).

    The optional ``=tier`` suffix (``encode``/``decode``/``both``)
    declares a disaggregated fleet member's role to the router before
    the first /healthz poll confirms it.  Fail-fast on malformed
    entries: a router silently fronting half the fleet the operator
    asked for is worse than not starting."""
    endpoints: List[Endpoint] = []
    for i, raw in enumerate(s for s in spec.split(",") if s.strip()):
        raw = raw.strip()
        tier = "both"
        if "=" in raw:
            raw, _, tier = raw.rpartition("=")
            if tier not in TIERS:
                raise ValueError(
                    f"--replicas entry {raw!r}={tier!r}: tier must be "
                    f"one of {TIERS}"
                )
        host, sep, port = raw.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"--replicas entry {raw!r}: expected host:port[=tier]"
            )
        try:
            endpoints.append(Endpoint(f"r{i}", host, int(port), tier=tier))
        except ValueError:
            raise ValueError(
                f"--replicas entry {raw!r}: port must be an integer"
            ) from None
    if not endpoints:
        raise ValueError(f"--replicas {spec!r} names no endpoints")
    return endpoints


def free_port(host: str = "127.0.0.1") -> int:
    """An ephemeral port the OS just handed out.  Best-effort (another
    process can race for it between release and bind) — used by the
    bench/chaos harnesses, not production, where the port range is
    configured."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def probe_health(
    endpoint: Endpoint, timeout_s: float = 2.0
) -> Optional[Dict]:
    """One ``GET /healthz``; the parsed payload (with ``_status_code``)
    or None when unreachable/unparseable.  Stdlib http.client so the
    probe shares no state with the router's pooled proxy connections."""
    conn = http.client.HTTPConnection(
        endpoint.host, endpoint.port, timeout=timeout_s
    )
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        if not isinstance(payload, dict):
            return None
        payload["_status_code"] = resp.status
        return payload
    except (OSError, ValueError):
        return None
    finally:
        conn.close()


class ReplicaProcess:
    """One locally spawned ``--phase serve`` subprocess."""

    def __init__(
        self,
        endpoint: Endpoint,
        popen: subprocess.Popen,
        workdir: str,
        log_path: str,
    ) -> None:
        self.endpoint = endpoint
        self.popen = popen
        self.workdir = workdir
        self.log_path = log_path

    @property
    def alive(self) -> bool:
        return self.popen.poll() is None

    @property
    def returncode(self) -> Optional[int]:
        return self.popen.poll()

    def drain(self) -> None:
        """SIGTERM: the replica runs its drain-to-completion sequence
        (readiness flips, admitted work finishes, listener closes)."""
        if self.alive:
            self.popen.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        """SIGKILL — the chaos path: no drain, sockets die mid-flight."""
        if self.alive:
            self.popen.kill()

    def wait(self, timeout_s: float = 60.0) -> Optional[int]:
        try:
            return self.popen.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None


class LocalFleet:
    """Spawn and own N serve replicas of one Config on one machine.

    Each replica gets its own save-adjacent workdir (summary + telemetry
    under ``<root>/replica_<i>/``) and a config JSON recording exactly
    what it ran — the same auditability contract as ``--config`` runs.
    Params load through the shared ``save_dir`` lineage, so every
    replica serves the same LAST_GOOD step."""

    def __init__(
        self,
        config: Config,
        count: int,
        root: str,
        host: str = "127.0.0.1",
        base_port: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        tiers: Optional[List[str]] = None,
    ) -> None:
        self.config = config
        self.root = root
        self.host = host
        self.env = env
        # per-index tier assignment for a disaggregated fleet; a
        # respawned replica keeps its index and therefore its tier
        if tiers is not None and len(tiers) != count:
            raise ValueError(
                f"tiers names {len(tiers)} replicas, fleet has {count}"
            )
        self.tiers: List[str] = list(tiers) if tiers else ["both"] * count
        for tier in self.tiers:
            if tier not in TIERS:
                raise ValueError(f"tier {tier!r}: must be one of {TIERS}")
        self.replicas: List[ReplicaProcess] = []
        os.makedirs(root, exist_ok=True)
        ports = (
            [base_port + i for i in range(count)]
            if base_port
            else [free_port(host) for _ in range(count)]
        )
        for i, port in enumerate(ports):
            self.replicas.append(self._spawn(i, port))

    @property
    def endpoints(self) -> List[Endpoint]:
        return [r.endpoint for r in self.replicas]

    def by_name(self, name: str) -> Optional[ReplicaProcess]:
        for r in self.replicas:
            if r.endpoint.name == name:
                return r
        return None

    def _spawn(self, index: int, port: int) -> ReplicaProcess:
        workdir = os.path.join(self.root, f"replica_{index}")
        os.makedirs(workdir, exist_ok=True)
        tier = self.tiers[index]
        cfg = self.config.replace(
            phase="serve",
            serve_host=self.host,
            serve_port=port,
            serve_tier=tier,
            summary_dir=os.path.join(workdir, "summary"),
            telemetry_dir=os.path.join(workdir, "telemetry"),
        )
        cfg_path = os.path.join(workdir, "serve_config.json")
        cfg.save(cfg_path)
        log_path = os.path.join(workdir, "serve.log")
        log = open(log_path, "ab")
        try:
            popen = subprocess.Popen(
                [sys.executable, "-m", "sat_tpu.cli", "--config", cfg_path],
                stdout=log,
                stderr=subprocess.STDOUT,
                env=(
                    {**os.environ, **self.env}
                    if self.env is not None
                    else None
                ),
            )
        finally:
            log.close()  # the child holds its own descriptor
        return ReplicaProcess(
            Endpoint(f"r{index}", self.host, port, tier=tier),
            popen,
            workdir,
            log_path,
        )

    def respawn(self, name: str) -> ReplicaProcess:
        """Relaunch a drained/dead replica on its old port (the deploy
        runbook's 'bring it back' step)."""
        for i, r in enumerate(self.replicas):
            if r.endpoint.name == name:
                if r.alive:
                    raise RuntimeError(f"replica {name} is still running")
                self.replicas[i] = self._spawn(i, r.endpoint.port)
                return self.replicas[i]
        raise KeyError(name)

    def wait_ready(self, timeout_s: float = 300.0) -> None:
        """Block until every replica's /healthz answers 200, or raise
        with the dead replica's log tail — a fleet that half-boots must
        fail loudly, not route around its own deploy."""
        deadline = time.time() + timeout_s
        pending = list(self.replicas)
        while pending:
            for r in list(pending):
                if not r.alive:
                    raise RuntimeError(
                        f"replica {r.endpoint.name} exited rc="
                        f"{r.returncode} during boot\n{self._log_tail(r)}"
                    )
                h = probe_health(r.endpoint)
                if h and h.get("_status_code") == 200 and h.get("ready"):
                    pending.remove(r)
            if pending and time.time() > deadline:
                names = ", ".join(r.endpoint.name for r in pending)
                raise TimeoutError(
                    f"replicas not ready after {timeout_s:.0f}s: {names}\n"
                    + "\n".join(self._log_tail(r) for r in pending)
                )
            if pending:
                time.sleep(0.25)

    @staticmethod
    def _log_tail(r: ReplicaProcess, lines: int = 15) -> str:
        try:
            with open(r.log_path, errors="replace") as f:
                tail = f.readlines()[-lines:]
            return f"--- {r.endpoint.name} log tail ---\n" + "".join(tail)
        except OSError:
            return f"--- {r.endpoint.name} log unreadable ---"

    def stop_all(self, timeout_s: float = 60.0) -> None:
        """Drain every replica (SIGTERM), escalate to SIGKILL on the
        stragglers past the timeout."""
        for r in self.replicas:
            r.drain()
        deadline = time.time() + timeout_s
        for r in self.replicas:
            remaining = max(0.5, deadline - time.time())
            if r.wait(remaining) is None:
                r.kill()
                r.wait(10.0)
