"""Fused soft-attention step as a batched Pallas TPU kernel.

At decode time the attention step is (reference attend,
/root/reference/model.py:395-436, 2-layer variant):

    temp   = t1 + t2[:, None, :]     # [B, N, da]  (t1 hoisted, loop-invariant)
    logits = temp @ w2               # [B, N]
    alpha  = softmax(logits)         # [B, N]
    ctx    = alpha @ contexts        # [B, D]

The op is bandwidth-bound: the matvec against w2 gives it an arithmetic
intensity of ~1 flop/byte, so the win is HBM traffic, not MXU time.  XLA
materializes intermediates between fusions; this kernel streams one batch
tile's t1/contexts through VMEM exactly once — add, scoring reduction,
softmax, and the weighted context sum all happen in a single residency and
only alpha [B,N] and the context vector [B,D] go back to HBM.

Layout: the grid tiles the *batch* axis (``block_b`` rows per program, 8 by
default) so one program covers a [block_b·N, da] volume rather than the
per-image slivers of the round-1 kernel.  N stays the sublane axis, da/D
the lane axis; reductions are lane-axis (scoring, context sum) or
sublane-axis (softmax) — both Mosaic-native.  The context-grid axis is
padded to a multiple of 8 with a -inf logit bias masking the pad rows out
of the softmax; the batch axis is padded to a multiple of ``block_b``.

Used at inference (beam search / greedy); training keeps the XLA path
(per-step dropout on contexts invalidates the t1 hoist there).
``interpret=True`` runs the same kernel on CPU for tests.

VMEM budget per program at flagship shapes (N=196→200, da=D=512, block_b=8,
fp32): t1 3.3 MB + contexts 3.3 MB + outputs ≈ 6.8 MB — comfortably inside
the ~16 MB/core budget (see /opt/skills/guides/pallas_guide.md).

Measured on the real v5e chip (scripts/bench_pallas.py, on-device
fori_loop timing, B=48 flagship shapes): ~400 µs vs 421-474 µs for XLA's
fusion across runs (1.06-1.17x), with strictly better numerics — context
max-error 9.5e-7 vs the XLA path's 1.7e-2 against an fp32 ground truth
(the kernel's softmax and weighted-sum run in full fp32 on the VPU,
whereas the XLA path's fp32 einsum lowers to default-precision bf16 MXU
passes).  block_b=8 wins the {4, 8, 16} sweep (4 fails Mosaic's
sublane-divisibility rule).  Enabled by default via
config.use_pallas_attention.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30

# Test hook: route attend_with_precomputed through the kernel in interpret
# mode even off-TPU (production non-TPU uses the XLA fallback instead).
FORCE_INTERPRET = False

# Batch rows per program.  8 keeps the VMEM residency ~7 MB at flagship
# shapes while giving Mosaic full-width vector work on every axis.
DEFAULT_BLOCK_B = 8


def _make_kernel(compute_dtype):
    dt = jnp.dtype(compute_dtype)

    def _kernel(t1_ref, t2_ref, w2_ref, bias_ref, ctx_ref,
                out_ctx_ref, out_alpha_ref):
        # blocks: t1 [Bt,Np,da], t2 [Bt,1,da], w2 [1,da], bias [1,Np],
        #         ctx [Bt,Np,D], out_ctx [Bt,D], out_alpha [Bt,Np]
        temp = t1_ref[...] + t2_ref[...]                           # [Bt,Np,da]
        # scoring: temp·w2 contracted over the lane axis.  A [.,da]@[da,1]
        # matvec cannot fill the MXU; an elementwise-mul + lane reduction
        # is the same flops on the VPU without the degenerate-matmul
        # layout.  Mirror _dense's dtype story: bf16 multiply, fp32
        # accumulate, round through dt like XLA's bf16 matmul output.
        prod = temp.astype(dt).astype(jnp.float32) * w2_ref[0].astype(
            dt
        ).astype(jnp.float32)
        logits = jnp.sum(prod, axis=-1).astype(dt).astype(jnp.float32)
        logits = logits + bias_ref[...]                            # [Bt,Np]
        m = jnp.max(logits, axis=1, keepdims=True)                 # [Bt,1]
        e = jnp.exp(logits - m)
        alpha = e / jnp.sum(e, axis=1, keepdims=True)              # [Bt,Np]
        out_alpha_ref[...] = alpha
        # weighted context sum: lane-preserving sublane reduction
        out_ctx_ref[...] = jnp.sum(
            alpha[:, :, None] * ctx_ref[...], axis=1
        )                                                          # [Bt,D]

    return _kernel


def _make_masked_kernel(compute_dtype):
    """Row-masked variant for slot-pool geometry (stepped decode).

    Dead pool rows carry whatever the retired slot last held — possibly
    non-finite after many steps of garbage arithmetic — so the mask must
    neutralize them INSIDE the kernel: scores are zeroed before the
    softmax (no exp of garbage) and alpha/context are zeroed after, so a
    dead row can never emit or propagate a NaN.  Live rows take the
    ``where`` true-branch everywhere and stay bitwise identical to the
    unmasked kernel.
    """
    dt = jnp.dtype(compute_dtype)

    def _kernel(t1_ref, t2_ref, w2_ref, bias_ref, ctx_ref, mask_ref,
                out_ctx_ref, out_alpha_ref):
        # blocks: as the unmasked kernel, plus mask [Bt,1] fp32 (>0 ⇒ live)
        valid = mask_ref[...] > 0.0                                # [Bt,1]
        temp = t1_ref[...] + t2_ref[...]                           # [Bt,Np,da]
        prod = temp.astype(dt).astype(jnp.float32) * w2_ref[0].astype(
            dt
        ).astype(jnp.float32)
        logits = jnp.sum(prod, axis=-1).astype(dt).astype(jnp.float32)
        logits = jnp.where(valid, logits, 0.0) + bias_ref[...]     # [Bt,Np]
        m = jnp.max(logits, axis=1, keepdims=True)                 # [Bt,1]
        e = jnp.exp(logits - m)
        alpha = e / jnp.sum(e, axis=1, keepdims=True)              # [Bt,Np]
        alpha = jnp.where(valid, alpha, 0.0)
        out_alpha_ref[...] = alpha
        ctxsum = jnp.sum(alpha[:, :, None] * ctx_ref[...], axis=1)  # [Bt,D]
        out_ctx_ref[...] = jnp.where(valid, ctxsum, 0.0)

    return _kernel


@partial(
    jax.jit, static_argnames=("compute_dtype", "interpret", "block_b")
)
def fused_attend(
    t1: jnp.ndarray,
    t2: jnp.ndarray,
    w2: jnp.ndarray,
    contexts: jnp.ndarray,
    row_mask: "jnp.ndarray | None" = None,
    compute_dtype: str = "float32",
    interpret: bool = False,
    block_b: int = DEFAULT_BLOCK_B,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(context [B,D], alpha [B,N]) from hoisted attention inputs.

    t1: [B, N, da] fp32 — tanh(fc_1a(contexts)), loop-invariant.
    t2: [B, da]    fp32 — tanh(fc_1b(output)) for the current step.
    w2: [da, 1]    fp32 — second-layer projection.
    contexts: [B, N, D] fp32.
    row_mask: optional [B] bool — slot-pool geometry (stepped decode):
        False rows are dead slots whose inputs may be stale garbage; the
        masked kernel zeroes their scores/alpha/context so nothing
        non-finite propagates, while True rows stay bitwise identical to
        the unmasked call.  ``None`` keeps the original kernel program
        (the monolithic serve path) byte-for-byte.
    compute_dtype: the scoring multiply dtype (the model's MXU dtype).
    """
    B, N, da = t1.shape
    D = contexts.shape[-1]
    n_pad = (-N) % 8
    Np = N + n_pad
    bt = max(1, min(block_b, B))
    b_pad = (-B) % bt
    Bp = B + b_pad

    t1 = jnp.pad(t1.astype(jnp.float32), ((0, b_pad), (0, n_pad), (0, 0)))
    contexts_p = jnp.pad(
        contexts.astype(jnp.float32), ((0, b_pad), (0, n_pad), (0, 0))
    )
    t2 = jnp.pad(t2.astype(jnp.float32), ((0, b_pad), (0, 0))).reshape(
        Bp, 1, da
    )
    w2_row = w2.astype(jnp.float32).reshape(1, da)
    # padding grid rows get -inf logits so they vanish from the softmax
    bias = jnp.where(
        (jnp.arange(Np) < N)[None, :], 0.0, _NEG_INF
    ).astype(jnp.float32)                                          # [1, Np]

    if row_mask is not None:
        # batch-pad rows are dead by construction (pad with 0 = masked)
        mask_col = jnp.pad(
            row_mask.astype(jnp.float32), ((0, b_pad),)
        ).reshape(Bp, 1)
        out_ctx, out_alpha = pl.pallas_call(
            _make_masked_kernel(compute_dtype),
            grid=(Bp // bt,),
            in_specs=[
                pl.BlockSpec((bt, Np, da), lambda b: (b, 0, 0)),
                pl.BlockSpec((bt, 1, da), lambda b: (b, 0, 0)),
                pl.BlockSpec((1, da), lambda b: (0, 0)),
                pl.BlockSpec((1, Np), lambda b: (0, 0)),
                pl.BlockSpec((bt, Np, D), lambda b: (b, 0, 0)),
                pl.BlockSpec((bt, 1), lambda b: (b, 0)),
            ],
            out_specs=[
                pl.BlockSpec((bt, D), lambda b: (b, 0)),
                pl.BlockSpec((bt, Np), lambda b: (b, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((Bp, D), jnp.float32),
                jax.ShapeDtypeStruct((Bp, Np), jnp.float32),
            ],
            interpret=interpret,
        )(t1, t2, w2_row, bias, contexts_p, mask_col)
        return out_ctx[:B], out_alpha[:B, :N]

    out_ctx, out_alpha = pl.pallas_call(
        _make_kernel(compute_dtype),
        grid=(Bp // bt,),
        in_specs=[
            pl.BlockSpec((bt, Np, da), lambda b: (b, 0, 0)),
            pl.BlockSpec((bt, 1, da), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, da), lambda b: (0, 0)),
            pl.BlockSpec((1, Np), lambda b: (0, 0)),
            pl.BlockSpec((bt, Np, D), lambda b: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, D), lambda b: (b, 0)),
            pl.BlockSpec((bt, Np), lambda b: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, D), jnp.float32),
            jax.ShapeDtypeStruct((Bp, Np), jnp.float32),
        ],
        interpret=interpret,
    )(t1, t2, w2_row, bias, contexts_p)
    return out_ctx[:B], out_alpha[:B, :N]


def fused_attend_reference(
    t1: jnp.ndarray,
    t2: jnp.ndarray,
    w2: jnp.ndarray,
    contexts: jnp.ndarray,
    row_mask: "jnp.ndarray | None" = None,
    compute_dtype: str = "float32",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Plain-XLA twin of :func:`fused_attend` (correctness oracle)."""
    dt = jnp.dtype(compute_dtype)
    temp = t1.astype(jnp.float32) + t2.astype(jnp.float32)[:, None, :]
    logits = (
        temp.astype(dt) @ w2.astype(dt)
    ).astype(jnp.float32)[..., 0]
    if row_mask is not None:
        valid = row_mask.reshape(-1, 1)
        logits = jnp.where(valid, logits, 0.0)
    alpha = jax.nn.softmax(logits, axis=-1)
    if row_mask is not None:
        alpha = jnp.where(valid, alpha, 0.0)
    ctx = jnp.einsum("bn,bnd->bd", alpha, contexts.astype(jnp.float32))
    if row_mask is not None:
        ctx = jnp.where(valid, ctx, 0.0)
    return ctx, alpha
