"""Weighted deficit-round-robin admission scheduler.

Replaces the batcher's single FIFO ``queue.Queue`` with per-tenant
sub-queues drained in deficit order (docs/SERVING.md "Multi-tenant
serving"): each tenant carries a deficit counter replenished by
``quantum x weight`` every rotation visit, and a request pops only when
its tenant holds a whole unit of deficit.  Pops are what grant
slot-pool admission and encode-lane seats, so a flooding tenant can
only consume its weighted share of decode steps while others have work
queued — and the rotation is naturally **work-conserving**: with a
single non-empty sub-queue the rotation degenerates to that queue and
it drains at full speed (deficit replenishes every visit, nothing is
held back for an idle tenant).

Deficit does **not** bank across idle periods: when a sub-queue
empties, its deficit resets to 0 and the tenant leaves the rotation.
A tenant returning from idle starts from the same deficit as everyone
else — fairness is over *contended* intervals, not lifetime totals.

**Starvation-freedom**: every tenant in the rotation gains
``quantum x weight > 0`` per full rotation, so any positive-weight
tenant accumulates a unit of deficit in at most ``ceil(1/weight)``
rotations regardless of how adversarially other tenants arrive (pinned
by tests/test_tenants.py).

The queue-compatible surface (``put_nowait`` raising ``queue.Full``,
``get``/``get_nowait`` raising ``queue.Empty``, ``qsize``, ``maxsize``)
keeps both batchers' control flow unchanged, and a single-tenant
scheduler pops in exact FIFO order — the degenerate case is
bit-identical to the ``queue.Queue`` it replaced (the no-``--tenants``
parity guarantee).  ``maxsize`` bounds each tenant's sub-queue
independently: a full sub-queue is a *tenant-scoped* overload (the
frontend sheds it with ``X-Shed-Scope: tenant``) and cannot crowd out
another tenant's admission — with one tenant this is exactly the old
global bound.

Items only need a ``tenant`` attribute (missing → the default lane).
jax-free by contract (gated by tests/test_device_diag.py).
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Deque, Dict, List, Optional

DEFAULT_TENANT = "default"


class DeficitRoundRobin:
    """Per-tenant FIFO sub-queues drained in weighted deficit order.

    ``weights`` maps tenant name → scheduling weight; tenants absent
    from the map (including the default lane) run at weight 1.0.
    ``maxsize`` bounds each sub-queue (0 = unbounded), matching
    ``queue.Queue`` semantics for the single-tenant case."""

    def __init__(
        self,
        maxsize: int = 0,
        weights: Optional[Dict[str, float]] = None,
        quantum: float = 1.0,
        default: str = DEFAULT_TENANT,
    ) -> None:
        self.maxsize = int(maxsize)
        self.quantum = float(quantum)  # sync-ok: host config scalar
        self.default = default
        self._weights = dict(weights or {})
        for name, w in self._weights.items():
            if w <= 0:
                raise ValueError(
                    f"scheduler weight for {name!r} must be > 0 (got {w})"
                )
        # more than one declared weight lane => tenant-scoped sub-queue
        # bounds (the frontend picks the shed scope off this flag)
        self.multi = len(self._weights) > 1
        self._queues: Dict[str, Deque] = {}
        self._deficit: Dict[str, float] = {}
        # rotation over tenants with queued work; head is the tenant
        # currently spending its deficit
        self._rotation: Deque[str] = deque()
        self._size = 0
        # cumulative pops per tenant: each pop is one granted admission
        # (a decode seat), so this is the scheduler-side input to the
        # per-tenant metering rollup
        self._admitted: Dict[str, int] = {}
        self._cond = threading.Condition()

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    # -- producer side (HTTP worker threads) -------------------------------

    def put_nowait(self, item) -> None:
        """Enqueue onto the item's tenant lane; raises ``queue.Full``
        when that lane is at ``maxsize`` (a tenant-scoped bound — one
        tenant's backlog never consumes another's queue space)."""
        tenant = getattr(item, "tenant", None) or self.default
        with self._cond:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._deficit.setdefault(tenant, 0.0)
            if self.maxsize > 0 and len(q) >= self.maxsize:
                raise queue.Full
            if not q:
                self._rotation.append(tenant)
            q.append(item)
            self._size += 1
            self._cond.notify()

    # -- consumer side (the batcher loop thread) ---------------------------

    def _pop_locked(self):
        """One DRR pop.  The head tenant spends deficit while it has a
        whole unit; otherwise it replenishes (quantum x weight) and
        rotates to the tail.  Terminates because every full rotation
        strictly raises some tenant's deficit."""
        while True:
            tenant = self._rotation[0]
            q = self._queues[tenant]
            if self._deficit[tenant] >= 1.0:
                item = q.popleft()
                self._size -= 1
                self._deficit[tenant] -= 1.0
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
                if not q:
                    # leaving the rotation resets the deficit: no
                    # banking across idle periods
                    self._rotation.popleft()
                    self._deficit[tenant] = 0.0
                return item
            self._deficit[tenant] += self.quantum * self.weight(tenant)
            self._rotation.rotate(-1)

    def get(self, timeout: Optional[float] = None):
        """Blocking pop in deficit order; raises ``queue.Empty`` on
        timeout (mirrors ``queue.Queue.get``)."""
        with self._cond:
            if self._size == 0 and not self._cond.wait_for(
                lambda: self._size > 0, timeout=timeout
            ):
                raise queue.Empty
            return self._pop_locked()

    def get_nowait(self):
        with self._cond:
            if self._size == 0:
                raise queue.Empty
            return self._pop_locked()

    # -- read side ---------------------------------------------------------

    def qsize(self) -> int:
        with self._cond:
            return self._size

    def depths(self) -> Dict[str, int]:
        """Per-tenant queued depth (the /stats + heartbeat feed)."""
        with self._cond:
            return {t: len(q) for t, q in self._queues.items() if q}

    def admitted(self) -> Dict[str, int]:
        """Cumulative per-tenant admissions (pops) since boot — the
        scheduler's contribution to the tenants-cost rollup: queue-side
        counts to reconcile against the ledger's completed counts."""
        with self._cond:
            return dict(self._admitted)

    def drain_all(self) -> List:
        """Pop everything in deficit order (shutdown paths)."""
        out = []
        with self._cond:
            while self._size > 0:
                out.append(self._pop_locked())
        return out
