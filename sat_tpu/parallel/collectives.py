"""Explicit collective helpers over the mesh.

The reference has NO collectives — parameter traffic is implicit gRPC
reads/writes against ps processes (/root/reference/clusterone_config.py:
111-124).  In the SPMD design, XLA inserts the gradient all-reduce
automatically from sharding annotations; the helpers here are the small
set of *explicit* collectives the runtime still wants:

* ``cross_replica_mean`` — psum-based averaging of per-replica values
  (one value per data-mesh row, e.g. per-shard host-side timings);
* ``make_global_batch``  — per-host input feed: every process contributes
  its local shard of the global batch (the multi-host replacement for the
  reference's every-worker-reads-everything input path);
* ``all_gather_batch``   — pull a data-sharded array host-side in full.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import batch_sharding, shard_batch


def cross_replica_mean(tree: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Mean over the data axis of per-replica stacked values.

    Each leaf must have leading dim == mesh.shape[axis] (one slice per
    replica).  Runs as a real `lax.psum` over ICI inside shard_map — the
    explicit form of the all-reduce XLA inserts for gradients.  Outputs
    drop the leading axis and come back replicated.
    """
    size = mesh.shape[axis]

    def body(t):
        # each shard holds [1, ...]; sum locally then psum across the axis
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x.sum(axis=0), axis) / size, t
        )

    f = jax.shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
    )
    sharded = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))), tree
    )
    return f(sharded)


def all_gather_batch(x: jax.Array) -> np.ndarray:
    """Fetch a (possibly data-sharded) device array fully to host.

    Resharding to replicated via device_put (no per-call jit compile);
    covers multi-host arrays whose shards are not all addressable."""
    from ..utils.dist import gather_tree_replicated

    return np.asarray(gather_tree_replicated(x))


def make_global_batch(mesh: Mesh, local_batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
    """Assemble the global on-device batch from per-process local shards.

    Each host loads only its slice of the global batch (per-host sharded
    file lists, SURVEY.md §7 step 8); this stitches them into one global
    jax.Array sharded over 'data'.  On single-process runs it degrades to
    a plain scatter.
    """
    if jax.process_count() == 1:
        return shard_batch(local_batch, mesh)
    sh = batch_sharding(mesh)
    return {
        k: jax.make_array_from_process_local_data(sh, v)
        for k, v in local_batch.items()
    }
