"""Mesh-path runtime: SPMD training through runtime.train on the 8-device
CPU mesh, per-host data sharding, distributed checkpoint gather."""

import numpy as np
import jax
import pytest

from sat_tpu import runtime
from sat_tpu.data.dataset import DataSet
from sat_tpu.parallel.data import process_local_dataset
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    state_to_flat,
)
from sat_tpu.train.step import create_train_state

from tests.test_runtime import SMALL_MODEL


def test_train_on_mesh_end_to_end(coco_fixture, tmp_path):
    """runtime.train with mesh_shape=(4,2): dp over batch, tp over the
    vocab dims, checkpoint written from the sharded state and restorable
    into a plain single-device state."""
    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "mesh_shape": (4, 2)}
    )
    state = runtime.train(config)
    assert int(np.asarray(state.step)) == 6

    ckpt = latest_checkpoint(config.save_dir)
    assert ckpt is not None and ckpt.endswith("6.npz")

    plain = config.replace(mesh_shape=(1, 1))
    fresh = create_train_state(jax.random.PRNGKey(9), plain)
    restored, count = restore_checkpoint(fresh, model_file=ckpt)
    assert count > 0

    want = state_to_flat(state)
    got = state_to_flat(restored)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], err_msg=k, rtol=1e-6)

    # and the restored single-device state evaluates (full path reuse)
    scores = runtime.evaluate(config.replace(mesh_shape=(1, 1)), state=restored)
    assert "Bleu_4" in scores


def test_mesh_and_single_device_training_agree(coco_fixture, tmp_path):
    """Same data, same init, same dropout keys: the dp+tp mesh run's loss
    trajectory must track the single-device run.  (Bitwise param equality
    is NOT expected — psum/matmul reduction order differs and Adam
    amplifies that on near-zero params; single-step numeric parity is
    pinned separately in test_parallel.py.)"""
    import json
    import os

    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "num_epochs": 1,
           "summary_dir": str(tmp_path / "s1"),
           "save_dir": str(tmp_path / "m1"),
           "save_period": 0}
    )
    runtime.train(base.replace(mesh_shape=(1, 1)), seed=0)
    runtime.train(
        base.replace(
            mesh_shape=(2, 2),
            summary_dir=str(tmp_path / "s2"),
            save_dir=str(tmp_path / "m2"),
        ),
        seed=0,
    )

    def losses(d):
        rows = [json.loads(x) for x in open(os.path.join(d, "metrics.jsonl"))]
        return np.array([r["total_loss"] for r in rows])

    a, b = losses(str(tmp_path / "s1")), losses(str(tmp_path / "s2"))
    assert a.shape == b.shape and len(a) == 6
    np.testing.assert_allclose(b, a, rtol=5e-2)


@pytest.mark.parametrize("mesh_shape", [(2, 1), (1, 2), (2, 2)])
def test_mesh_eval_matches_single_device(coco_fixture, tmp_path, mesh_shape):
    """decode_dataset routes through make_parallel_beam_search on a mesh;
    parallel eval — dp-only, vocab-TP-only (embedding/softmax sharded over
    'model'), and combined — must produce the SAME captions and scores as
    the single-device path end-to-end (VERDICT r1 item 5)."""
    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "res1.json"),
           "beam_size": 2}
    )
    state = runtime.train(base.replace(mesh_shape=(1, 1)))

    if mesh_shape[1] > 1:
        # the TP variants must actually shard: the placement rule keys on
        # config.vocabulary_size (param logit width), which divides the
        # model axis here — guard against silently-replicated 'TP'
        from sat_tpu.parallel import make_mesh
        from sat_tpu.parallel.sharding import param_partition_specs

        cfg_m = base.replace(mesh_shape=mesh_shape)
        specs = param_partition_specs(
            {"params": state.params}, cfg_m, make_mesh(cfg_m)
        )
        flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: "model" in str(s), specs)
        )
        assert any(flat), "vocab-TP rule placed nothing on the model axis"

    single = runtime.evaluate(base.replace(mesh_shape=(1, 1)), state=state)
    mesh = runtime.evaluate(
        base.replace(mesh_shape=mesh_shape, eval_result_file=str(tmp_path / "res2.json")),
        state=state,
    )
    assert single.keys() == mesh.keys()
    for k in single:
        np.testing.assert_allclose(mesh[k], single[k], rtol=1e-6, err_msg=k)

    import json
    r1 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res1.json"))}
    r2 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res2.json"))}
    assert r1 == r2 and len(r1) > 0


def test_multihost_decode_assembly_matches_single_host(coco_fixture, tmp_path):
    """Simulate the 2-process mesh decode: per-host interleaved dataset
    shards, per-host beam blocks stacked in process order (the
    make_global_batch layout), then _assemble_mesh_results — captions must
    equal the single-device decode_dataset output, padding rows and
    process-duplicate rows dropped."""
    from sat_tpu.data.dataset import prepare_eval_data
    from sat_tpu.data.images import ImageLoader, PrefetchLoader
    from sat_tpu.models.captioner import encode
    from sat_tpu.ops.beam_search import beam_search_jit
    from sat_tpu.parallel.data import pad_dataset_for_processes
    from sat_tpu.runtime import _assemble_mesh_results, _eos_id, decode_dataset
    from sat_tpu.train.step import create_train_state

    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL, "beam_size": 2, "batch_size": 4}
    )
    coco, full_ds, vocab = prepare_eval_data(config)
    # 5 images: exercises both the process pad (5→6) and per-host
    # fake_count (3 local rows / local batch 2)
    ds = DataSet(full_ds.image_ids[:5], full_ds.image_files[:5], 4)
    config = config.replace(vocabulary_size=len(vocab.words))
    state = create_train_state(jax.random.PRNGKey(0), config)
    eos = _eos_id(vocab)

    want = decode_dataset(config, state, ds, vocab)

    pc = 2
    padded = pad_dataset_for_processes(ds, pc)
    assert padded.count == 6
    locals_ = [
        process_local_dataset(padded, process_index=p, process_count=pc)
        for p in range(pc)
    ]
    assert {l.count for l in locals_} == {3}

    variables = {"params": state.params}
    blocks = []           # blocks[h][b] = (words, lengths, scores)
    for l in locals_:
        loader = PrefetchLoader(l, ImageLoader(size=config.image_size), num_workers=2)
        host_blocks = []
        for batch in loader:
            contexts, _ = encode(variables, config, batch["images"], train=False)
            out = beam_search_jit(
                state.params["decoder"], config, contexts, eos,
                beam_size=config.beam_size, valid_size=len(vocab.words),
            )
            host_blocks.append(
                (np.asarray(out.words[:, 0]), np.asarray(out.lengths[:, 0]),
                 np.asarray(out.log_scores[:, 0]))
            )
        blocks.append(host_blocks)

    num_batches = len(blocks[0])
    gathered = [
        tuple(
            np.concatenate([blocks[h][b][k] for h in range(pc)], axis=0)
            for k in range(3)
        )
        for b in range(num_batches)
    ]
    got = _assemble_mesh_results(ds, vocab, gathered, pc, locals_[0].count)

    assert [r["image_id"] for r in got] == [r["image_id"] for r in want]
    assert [r["caption"] for r in got] == [r["caption"] for r in want]
    np.testing.assert_allclose(
        [r["prob"] for r in got], [r["prob"] for r in want], rtol=1e-5
    )


def test_process_local_dataset_slices_disjointly():
    ids = np.arange(24)
    files = np.array([f"f{i}.jpg" for i in ids])
    w = np.arange(24 * 5).reshape(24, 5)
    m = np.ones((24, 5), np.float32)
    global_ds = DataSet(ids, files, 8, w, m, is_train=True, shuffle=False)

    shards = [
        process_local_dataset(global_ds, process_index=p, process_count=4)
        for p in range(4)
    ]
    seen = np.concatenate([s.image_ids for s in shards])
    assert sorted(seen.tolist()) == ids.tolist()          # disjoint cover
    for s in shards:
        assert s.batch_size == 2                          # 8 global / 4 hosts
        assert s.num_batches == global_ds.num_batches     # same step count

    with pytest.raises(ValueError, match="not divisible"):
        process_local_dataset(global_ds, process_index=0, process_count=3)


@pytest.mark.parametrize(
    "extra_args,banner",
    [
        ([], "MULTIHOST OK (data-parallel)"),
        (["--cp"], "MULTIHOST OK (context-parallel)"),
        (["--tp"], "MULTIHOST OK (tensor-parallel)"),
    ],
    ids=["dp", "cp", "tp"],
)
def test_multihost_demo_two_real_processes(tmp_path, extra_args, banner):
    """The full multi-process story, for real: two OS processes bootstrap a
    jax.distributed cluster over a loopback coordinator, train SPMD, and
    run multi-host mesh eval with cross-host result gather — both hosts
    must finish rc=0 with identical scores and full panel coverage.

    dp: per-host data shards with XLA gradient all-reduce.  cp: the MODEL
    axis spans the processes — context-parallel training and beam-search
    decode whose distributed-softmax psums cross a real process boundary
    (loopback DCN), every host feeding identical full batches
    (mesh_data_shard).  tp: same spanning axis, spent instead on the
    embedding/softmax vocab dimension (GSPMD-inserted cross-host
    collectives)."""
    import os
    import signal
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:  # free coordinator port (xdist/CI safe)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(repo, "scripts", "multihost_demo.py"),
            "--root", str(tmp_path / "demo"), "--port", str(port),
            "--join-timeout", "420", *extra_args,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=repo,
        start_new_session=True,  # own process group: timeout kills workers too
    )
    try:
        out, err = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        raise AssertionError(f"demo timed out\n{out[-2000:]}\n{err[-1500:]}")
    assert proc.returncode == 0, f"{out[-3000:]}\n--- stderr ---\n{err[-1500:]}"
    assert banner in out


def test_mesh_data_shard_maps_model_axis_processes_to_one_row():
    """Single-process sanity of the feed-shard mapping: dp rows with the
    whole mesh addressable fall back to (process 0 of 1); a data axis of
    size 1 maps to (0, 1) — the pure-CP every-host-feeds-everything case."""
    from sat_tpu.parallel.data import mesh_data_shard
    from sat_tpu.parallel.mesh import mesh_from_devices

    devs = jax.devices()[:8]
    assert mesh_data_shard(
        mesh_from_devices(devs, (2, 4), ("data", "model"))
    ) == (0, 1)
    assert mesh_data_shard(
        mesh_from_devices(devs[:2], (1, 2), ("data", "model"))
    ) == (0, 1)
    assert mesh_data_shard(
        mesh_from_devices(devs[:2], (2, 1), ("data", "model"))
    ) == (0, 1)


def test_pad_dataset_for_processes_handles_pad_beyond_count():
    """pad > count (tiny dataset, many hosts) must tile with modulo, not
    silently under-pad into a non-divisible (→ empty-shard) dataset."""
    from sat_tpu.parallel.data import pad_dataset_for_processes

    ids = np.arange(3)
    files = np.array([f"f{i}.jpg" for i in ids])
    ds = DataSet(ids, files, 8)
    padded = pad_dataset_for_processes(ds, 8)
    assert padded.count == 8
    assert set(padded.image_ids.tolist()) == set(ids.tolist())
    shards = [
        process_local_dataset(padded, process_index=p, process_count=8)
        for p in range(8)
    ]
    assert all(s.count == 1 for s in shards)


def test_process_local_dataset_equalizes_uneven_shards():
    """25 samples / 4 hosts: shards truncate to a common length so every
    host runs the same number of synchronous steps."""
    ids = np.arange(25)
    files = np.array([f"f{i}.jpg" for i in ids])
    global_ds = DataSet(ids, files, 8)
    shards = [
        process_local_dataset(global_ds, process_index=p, process_count=4)
        for p in range(4)
    ]
    assert {s.count for s in shards} == {6}
    assert {s.num_batches for s in shards} == {3}


def test_cp_eval_decodes_under_trained_replicated_placement(coco_fixture, tmp_path):
    """A context-parallel config trains with params replicated (the 'model'
    axis is spent on the context grid, runtime.train); eval must decode
    under that SAME placement instead of silently re-sharding to vocab-TP
    (VERDICT r2 weak #4) — and still produce the single-device captions."""
    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "res1.json"),
           "beam_size": 2}
    )
    state = runtime.train(base.replace(mesh_shape=(1, 1)))

    cfg_cp = base.replace(mesh_shape=(2, 2), context_parallel=2)
    # the placement decode_dataset uses for CP: fully replicated — nothing
    # may land on the 'model' axis (mirrors train()'s vocabulary_size=-1)
    from sat_tpu.parallel import make_mesh
    from sat_tpu.parallel.sharding import param_partition_specs

    specs = param_partition_specs(
        {"params": state.params},
        cfg_cp.replace(vocabulary_size=-1),
        make_mesh(cfg_cp),
    )
    on_model = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: "model" in str(s), specs)
    )
    assert not any(on_model)

    single = runtime.evaluate(base.replace(mesh_shape=(1, 1)), state=state)
    cp = runtime.evaluate(
        cfg_cp.replace(eval_result_file=str(tmp_path / "res2.json")),
        state=state,
    )
    assert single.keys() == cp.keys()
    for k in single:
        np.testing.assert_allclose(cp[k], single[k], rtol=1e-6, err_msg=k)

    import json
    r1 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res1.json"))}
    r2 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res2.json"))}
    assert r1 == r2 and len(r1) > 0


def test_multihost_attention_map_gather_renders_panels(coco_fixture, tmp_path):
    """Beam-0 alphas ride the cross-host gather (VERDICT r2 weak #5): the
    simulated 2-process assembly must carry per-word attention maps equal
    to the single-host decode's, and panels must render from them."""
    from sat_tpu.data.dataset import prepare_eval_data
    from sat_tpu.data.images import ImageLoader, PrefetchLoader
    from sat_tpu.models.captioner import encode
    from sat_tpu.ops.beam_search import beam_search_jit
    from sat_tpu.parallel.data import pad_dataset_for_processes
    from sat_tpu.runtime import (
        _assemble_mesh_results,
        _eos_id,
        _save_attention_panels,
        decode_dataset,
    )
    from sat_tpu.train.step import create_train_state

    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL, "beam_size": 2, "batch_size": 4,
           "save_attention_maps": True}
    )
    coco, full_ds, vocab = prepare_eval_data(config)
    ds = DataSet(full_ds.image_ids[:5], full_ds.image_files[:5], 4)
    config = config.replace(vocabulary_size=len(vocab.words))
    state = create_train_state(jax.random.PRNGKey(0), config)
    eos = _eos_id(vocab)

    want = decode_dataset(config, state, ds, vocab)
    assert all("alphas" in r for r in want)

    pc = 2
    padded = pad_dataset_for_processes(ds, pc)
    locals_ = [
        process_local_dataset(padded, process_index=p, process_count=pc)
        for p in range(pc)
    ]
    variables = {"params": state.params}
    blocks = []
    for l in locals_:
        loader = PrefetchLoader(l, ImageLoader(size=config.image_size), num_workers=2)
        host_blocks = []
        for batch in loader:
            contexts, _ = encode(variables, config, batch["images"], train=False)
            out = beam_search_jit(
                state.params["decoder"], config, contexts, eos,
                beam_size=config.beam_size, valid_size=len(vocab.words),
                return_alphas=True,
            )
            host_blocks.append(tuple(
                np.asarray(a[:, 0])
                for a in (out.words, out.lengths, out.log_scores, out.alphas)
            ))
        blocks.append(host_blocks)

    gathered = [
        tuple(
            np.concatenate([blocks[h][b][k] for h in range(pc)], axis=0)
            for k in range(4)
        )
        for b in range(len(blocks[0]))
    ]
    got = _assemble_mesh_results(ds, vocab, gathered, pc, locals_[0].count)

    assert [r["caption"] for r in got] == [r["caption"] for r in want]
    for rg, rw in zip(got, want):
        assert rg["words"] == rw["words"]
        np.testing.assert_allclose(rg["alphas"], rw["alphas"], rtol=1e-5)

    out_dir = tmp_path / "panels"
    out_dir.mkdir()
    _save_attention_panels(got, str(out_dir))
    panels = list(out_dir.glob("*_attention.jpg"))
    assert len(panels) == len(got) and all(p.stat().st_size > 0 for p in panels)
