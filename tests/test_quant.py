"""Quantized-encoder caption-parity gate (docs/SERVING.md §Precision).

The PTQ pass (sat_tpu/nn/quant.py) ships behind this harness: int8 is
only a legal serve config because these tests bound its divergence from
the fp32 encoder at every level the caption can feel —

* unit: per-channel kernel round-trip error, BN folding math;
* context grid: bounded relative divergence per backbone and mode,
  with ``off`` pinned BITWISE to the unquantized flax path;
* per-step decoder logits over quantized contexts: bounded drift;
* captions: a trained fixture checkpoint served through an int8 engine
  must agree with the fp32 engine (BLEU-proxy unigram-F1 bound);
* the serving guarantees survive quantization: zero steady-state XLA
  compiles in batch AND continuous mode, fp32 CNN params evicted from
  the serve tree, /stats + /metrics surface the quant config.
"""

import os
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models import captioner
from sat_tpu.models.decoder import decoder_step, init_state, precompute_attend
from sat_tpu.nn import quant
from sat_tpu.ops.beam_search import beam_search
from sat_tpu.serve.engine import ServeEngine, load_serving_state
from sat_tpu.serve.server import CaptionServer
from sat_tpu.serve.slot_pool import PagedSlotPool

from tests.test_serve import (  # noqa: F401  (fixture re-export)
    _fixture_files,
    _get,
    _post,
    served,
)


def _cfg(cnn="vgg16", **kw):
    base = dict(
        cnn=cnn,
        image_size=32 if cnn == "vgg16" else 64,
        vocabulary_size=30,
        dim_embedding=8,
        num_lstm_units=16,
        dim_initialize_layer=8,
        dim_attend_layer=16,
        dim_decode_layer=16,
        max_caption_length=6,
        beam_size=2,
        compute_dtype="float32",
    )
    return Config(**{**base, **kw})


def _images(config, n=2, seed=0):
    """Deterministic mean-subtracted fp32 images (the encode contract)."""
    from sat_tpu.data.images import ILSVRC_2012_MEAN

    s = config.image_size
    raw = np.random.default_rng(seed).integers(
        0, 256, size=(n, s, s, 3)
    ).astype(np.float32)
    return jnp.asarray(raw - np.asarray(ILSVRC_2012_MEAN, np.float32))


def _variables(config, seed=0):
    return captioner.init_variables(jax.random.PRNGKey(seed), config)


def _quant_variables(variables, config):
    """The serve-tree shape ServeEngine builds at load: decoder params +
    the quantized encoder, fp32 cnn/batch_stats evicted."""
    qcnn = quant.quantize_encoder(variables, config)
    return {"params": {"decoder": variables["params"]["decoder"]},
            "qcnn": qcnn}


# ---------------------------------------------------------------------------
# Unit: kernel round-trip + BN folding
# ---------------------------------------------------------------------------


def test_quantize_kernel_roundtrip_and_shapes(rng):
    k = jnp.asarray(rng.normal(size=(3, 3, 8, 16)).astype(np.float32))
    q, scale = quant.quantize_kernel(k)
    assert q.dtype == jnp.int8 and q.shape == k.shape
    assert scale.shape == (16,)
    assert int(jnp.abs(q).max()) <= 127
    err = jnp.abs(q.astype(jnp.float32) * scale - k)
    # symmetric per-channel: error ≤ half a quantization step per channel
    assert bool((err <= 0.5 * scale[None, None, None, :] + 1e-7).all())


def test_quantize_kernel_zero_channel_is_safe():
    k = jnp.zeros((1, 1, 4, 3), jnp.float32)
    q, scale = quant.quantize_kernel(k)
    assert bool((q == 0).all()) and bool((scale > 0).all())  # _EPS floor


def test_fold_bn_matches_bn_math(rng):
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 4)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(3, 3, 4, 6)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))
    gamma = jnp.asarray(rng.uniform(0.5, 2.0, size=(6,)).astype(np.float32))
    beta = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))
    mean = jnp.asarray(rng.normal(size=(6,)).astype(np.float32))
    var = jnp.asarray(rng.uniform(0.1, 2.0, size=(6,)).astype(np.float32))
    eps = 1e-3

    kf, bf = quant.fold_bn(k, b, gamma, beta, mean, var, eps=eps)
    y_folded = quant._conv2d(x, kf, 1) + bf
    y_bn = (quant._conv2d(x, k, 1) + b - mean) * gamma / jnp.sqrt(
        var + eps
    ) + beta
    np.testing.assert_allclose(y_folded, y_bn, rtol=1e-5, atol=1e-5)


def test_quantize_encoder_rejects_off():
    config = _cfg(encoder_quant="off")
    with pytest.raises(ValueError):
        quant.quantize_encoder(_variables(config), config)


# ---------------------------------------------------------------------------
# Context-grid divergence (per backbone, per mode) + `off` bitwise pin
# ---------------------------------------------------------------------------


def test_off_is_bitwise_unchanged():
    """encoder_quant='off' must run the exact flax path — same program,
    same bits — as a config that predates the knob."""
    base = _cfg()
    off = _cfg(encoder_quant="off")
    variables = _variables(base)
    images = _images(base)
    want, _ = captioner.encode(variables, base, images)
    got, _ = captioner.encode(variables, off, images)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# measured headroom (random-init tiny models, CPU): int8 max relative
# context error ≈ 3%, bf16 ≈ 1% — bounds carry ~3× slack so the gate
# trips on real regressions (wrong scale axis, missing dequant), not
# on RNG drift
_CTX_BOUNDS = {"int8": 0.10, "bf16": 0.05}


@pytest.mark.parametrize("cnn", ["vgg16", "resnet50"])
@pytest.mark.parametrize("mode", ["int8", "bf16"])
def test_context_divergence_bounded(cnn, mode):
    config = _cfg(cnn=cnn, encoder_quant=mode)
    variables = _variables(config)
    images = _images(config)
    want, _ = captioner.encode(variables, config.replace(
        encoder_quant="off"
    ), images)
    qvars = _quant_variables(variables, config)
    got, _ = captioner.encode(qvars, config, images)
    assert got.shape == want.shape and got.dtype == jnp.float32
    scale = float(jnp.abs(want).max())
    rel = float(jnp.abs(got - want).max()) / max(scale, 1e-6)
    assert rel <= _CTX_BOUNDS[mode], (cnn, mode, rel)


# ---------------------------------------------------------------------------
# Per-step logit divergence + caption agreement at the model layer
# ---------------------------------------------------------------------------


def test_per_step_logit_divergence_bounded():
    """Decoder logits over int8 contexts vs fp32 contexts: the decode
    loop sees bounded drift at every step (not just the first)."""
    config = _cfg(encoder_quant="int8")
    variables = _variables(config)
    images = _images(config)
    ctx_fp, _ = captioner.encode(
        variables, config.replace(encoder_quant="off"), images
    )
    ctx_q, _ = captioner.encode(_quant_variables(variables, config), config, images)
    params = variables["params"]["decoder"]

    word = jnp.zeros((images.shape[0],), jnp.int32)
    st_fp = init_state(params, config, ctx_fp, train=False)
    st_q = init_state(params, config, ctx_q, train=False)
    proj_fp = precompute_attend(params, config, ctx_fp)
    proj_q = precompute_attend(params, config, ctx_q)
    worst = 0.0
    for _ in range(4):
        st_fp, logit_fp, _ = decoder_step(
            params, config, ctx_fp, st_fp, word, ctx_proj=proj_fp
        )
        st_q, logit_q, _ = decoder_step(
            params, config, ctx_q, st_q, word, ctx_proj=proj_q
        )
        spread = float(logit_fp.max() - logit_fp.min())
        worst = max(
            worst, float(jnp.abs(logit_q - logit_fp).max()) / max(spread, 1e-6)
        )
        word = jnp.argmax(logit_fp, axis=-1)  # follow the fp32 trajectory
    # measured ≈ 2-4% of the logit spread on random-init models; 20%
    # would already flip argmaxes wholesale
    assert worst <= 0.20, worst


def _unigram_f1(a, b):
    """BLEU proxy at the gate's granularity: token-multiset F1."""
    from collections import Counter

    ca, cb = Counter(a), Counter(b)
    overlap = sum((ca & cb).values())
    if not a and not b:
        return 1.0
    if overlap == 0:
        return 0.0
    p, r = overlap / max(len(b), 1), overlap / max(len(a), 1)
    return 2 * p * r / (p + r)


def test_model_level_caption_agreement():
    """Beam search over int8 vs fp32 contexts, same decoder: the top
    beams must stay substantially aligned even on a random-init model
    (contexts differ by <10%, so trajectories rarely diverge early)."""
    config = _cfg(encoder_quant="int8")
    variables = _variables(config)
    images = _images(config, n=4)
    ctx_fp, _ = captioner.encode(
        variables, config.replace(encoder_quant="off"), images
    )
    ctx_q, _ = captioner.encode(_quant_variables(variables, config), config, images)
    params = variables["params"]["decoder"]
    fp = beam_search(params, config, ctx_fp, eos_id=2)
    qq = beam_search(params, config, ctx_q, eos_id=2)
    f1s = []
    for i in range(images.shape[0]):
        a = list(np.asarray(fp.words)[i, 0, : int(np.asarray(fp.lengths)[i, 0])])
        b = list(np.asarray(qq.words)[i, 0, : int(np.asarray(qq.lengths)[i, 0])])
        f1s.append(_unigram_f1(a, b))
    assert float(np.mean(f1s)) >= 0.5, f1s


# ---------------------------------------------------------------------------
# Engine-level gate over the trained fixture checkpoint
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def int8_engine(served):
    """A second engine over the SAME trained checkpoint, quantized int8."""
    config = served["config"].replace(encoder_quant="int8")
    state, _ = load_serving_state(config)
    engine = ServeEngine(
        config, state, served["vocabulary"], tel=served["tel"]
    )
    engine.warmup()
    return engine


def test_int8_engine_drops_fp32_cnn_and_quantizes_once(int8_engine):
    assert int8_engine.encoder_quant == "int8"
    assert int8_engine.quantize_seconds > 0.0
    assert "qcnn" in int8_engine._variables
    assert "cnn" not in int8_engine._variables["params"]
    assert "batch_stats" not in int8_engine._variables
    for spec in int8_engine._variables["qcnn"].values():
        assert spec["kernel"].dtype == jnp.int8


def test_int8_engine_score_parity_and_zero_recompile(served, int8_engine):
    """Fixture-checkpoint parity: the int8 engine's top-beam log-scores
    track fp32 within the measured quantization budget, and the request
    phase stays at ZERO XLA compiles.

    The gate is score-level here because the 6-step fixture checkpoint
    has a logit spread of ~0.05 — its argmax captions flip under ANY
    perturbation, including bf16, so token identity carries no signal.
    The token-level BLEU-proxy bound lives at the model layer
    (test_model_level_caption_agreement), where trajectories are stable."""
    engine, tel = served["engine"], served["tel"]
    files = _fixture_files(served, 3)
    images = [engine.loader.load_image(f) for f in files]

    batch, _ = engine.pad_batch(images)
    fp32 = engine.decode_output(engine.dispatch(batch), len(images))

    compiles0 = tel.counters().get("jax/compiles", 0)
    batch_q, _ = int8_engine.pad_batch(images)
    q = int8_engine.decode_output(
        int8_engine.dispatch(batch_q), len(images)
    )
    assert tel.counters().get("jax/compiles", 0) == compiles0

    for row_fp, row_q in zip(fp32, q):
        a = row_fp["captions"][0]["log_prob"]
        b = row_q["captions"][0]["log_prob"]
        # measured drift ≈ 0.02 nats/step × 8 steps on this fixture;
        # 1.0 nat total would mean the search found a different basin
        assert abs(a - b) <= 1.0, (a, b)
        assert row_q["captions"][0]["caption"]  # non-empty detok


def test_int8_continuous_pool_zero_recompile(served, int8_engine):
    """The zero-steady-state-recompile assertion holds in continuous
    mode with quant on: pool warmup compiles against the quantized
    tree, then admit/step/harvest/reseed compile nothing."""
    tel = served["tel"]
    pool = PagedSlotPool(int8_engine, pages=1, page_width=2, tel=tel)
    pool.warmup()
    s = int8_engine.config.image_size
    img = np.zeros((s, s, 3), int8_engine._image_dtype)
    compiles0 = tel.counters().get("jax/compiles", 0)
    assert pool.admit([(img, "a"), (img, "b")]) == 2
    for _ in range(int8_engine.config.max_caption_length):
        done = np.asarray(pool.step())  # sync-ok: test drain
        if done.any():
            pool.harvest(done)
    assert pool.occupancy() == 0
    assert pool.admit([(img, "again")]) == 1
    np.asarray(pool.step())  # sync-ok: test drain
    assert tel.counters().get("jax/compiles", 0) == compiles0


def test_server_stats_surface_quant_and_encode_ms(served, int8_engine):
    """Satellite: GET /stats carries the engine block (encoder_quant +
    per-lane encode percentiles) and /metrics exports serve/encode_ms."""
    config = int8_engine.config
    server = CaptionServer(config, int8_engine, port=0).start()
    try:
        port = server.port
        jpeg = open(_fixture_files(served, 1)[0], "rb").read()
        status, payload = _post(port, jpeg)
        assert status == 200 and payload["captions"]

        status, stats = _get(port, "/stats")
        assert status == 200
        eng = stats["engine"]
        assert eng["encoder_quant"] == "int8"
        assert eng["quantize_seconds"] > 0
        assert eng["encode_ms"]["count"] >= 1
        assert eng["encode_ms"]["p50"] <= eng["encode_ms"]["p95"]
        assert any(
            v["count"] >= 1 for v in eng["encode_lanes_ms"].values()
        )

        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30
        ).read().decode()
        assert 'sat_gauge{name="serve/encode_ms"}' in body
        assert 'sat_gauge{name="serve/encode_ms_p95"}' in body
    finally:
        server.shutdown()
