"""Synthetic smoke-scale fixtures.

The reference ships no annotation JSONs (only 11 val JPEGs); its de-facto
fast test mode is the max_*_ann_num config caps (SURVEY.md §4).  We go one
step further: generate a fully self-contained COCO-format dataset with
procedurally drawn JPEG images, so end-to-end train/eval tests run with no
network and no external assets.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

CAPTIONS = [
    "a man riding a horse on the beach.",
    "a group of people standing around a kitchen.",
    "two dogs playing with a red ball in the grass.",
    "a plate of food with rice and vegetables.",
    "a bus driving down a city street.",
    "a cat sitting on top of a wooden table.",
    "a woman holding an umbrella in the rain.",
    "a young boy throwing a frisbee in the park.",
    "several boats floating in the harbor near the dock.",
    "a train traveling down the tracks near a station.",
    "a bird perched on a branch of a tree.",
    "a pizza with cheese and tomatoes on a plate.",
]


def _write_jpeg(path: str, seed: int, size: int = 64) -> None:
    import cv2

    rng = np.random.default_rng(seed)
    img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
    # some structure so resize interpolation is non-trivial
    img[:, : size // 2, 0] = 200
    img[size // 2 :, :, 2] = 60
    cv2.imwrite(path, img)


def make_coco_fixture(root: str, num_images: int = 12) -> Dict:
    """Create train/val image dirs + caption JSONs under `root`.
    Returns a dict of paths plus a ready Config."""
    from sat_tpu.config import Config

    train_img_dir = os.path.join(root, "train", "images")
    val_img_dir = os.path.join(root, "val", "images")
    os.makedirs(train_img_dir, exist_ok=True)
    os.makedirs(val_img_dir, exist_ok=True)

    images: List[Dict] = []
    annotations: List[Dict] = []
    for i in range(num_images):
        fname = f"COCO_fixture_{i:012d}.jpg"
        images.append({"id": i + 1, "file_name": fname})
        _write_jpeg(os.path.join(train_img_dir, fname), seed=i)
        _write_jpeg(os.path.join(val_img_dir, fname), seed=i)
        # two captions per image, cycling the pool
        for j in range(2):
            annotations.append(
                {
                    "id": 1000 + 2 * i + j,
                    "image_id": i + 1,
                    "caption": CAPTIONS[(i + j) % len(CAPTIONS)],
                }
            )

    train_json = os.path.join(root, "train", "captions_train.json")
    val_json = os.path.join(root, "val", "captions_val.json")
    payload = {"images": images, "annotations": annotations}
    for p in (train_json, val_json):
        with open(p, "w") as f:
            json.dump(payload, f)

    config = Config(
        batch_size=4,
        vocabulary_size=200,
        max_train_ann_num=None,
        max_eval_ann_num=8,
        num_epochs=1,
        train_image_dir=train_img_dir,
        train_caption_file=train_json,
        eval_image_dir=val_img_dir,
        eval_caption_file=val_json,
        vocabulary_file=os.path.join(root, "vocabulary.csv"),
        temp_annotation_file=os.path.join(root, "train", "anns.csv"),
        temp_data_file=os.path.join(root, "train", "data.npy"),
        eval_result_dir=os.path.join(root, "val", "results"),
        eval_result_file=os.path.join(root, "val", "results.json"),
        test_image_dir=val_img_dir,
        test_result_dir=os.path.join(root, "test_results"),
        test_result_file=os.path.join(root, "test_results.csv"),
        save_dir=os.path.join(root, "models"),
        summary_dir=os.path.join(root, "summary"),
        save_eval_result_as_image=False,
    )
    return {
        "root": root,
        "train_json": train_json,
        "val_json": val_json,
        "train_img_dir": train_img_dir,
        "val_img_dir": val_img_dir,
        "config": config,
    }
