"""Post-training quantization of the frozen CNN encoder (serve path).

``config.encoder_quant`` selects the serve-time precision of the frozen
VGG16/ResNet50 conv stack (docs/SERVING.md, "Precision & parity"):

* ``off``  — this module never runs; the path is bitwise the flax encoder.
* ``bf16`` — conv kernels are stored in bfloat16 (halving their HBM
  residency; the MXU compute already runs bf16 on the normal path).
* ``int8`` — conv kernels become per-output-channel *symmetric* int8 with
  fp32 scales (scale = absmax/127 per output channel), activations are
  quantized per-tensor against ranges measured by a one-time host-side
  calibration pass, and every conv runs as int8 x int8 -> int32 (MXU
  native) with the dequant fused into the bias add.  The [B, N, D]
  context output stays fp32, so the decoder sees the same interface.

ResNet50's frozen batch norms are folded into the preceding conv's kernel
and bias before quantization (standard PTQ: w' = w * gamma/sqrt(var+eps),
b' = beta - mean * gamma/sqrt(var+eps)), so the quantized graph is pure
conv+bias(+relu) for both backbones and the model files only have to
export a topology walker (``vgg16.quant_forward`` / ``resnet50.quant_forward``).

Quantization happens ONCE at param-load time (serve/engine.py), before
any AOT warmup, so every warmed executable — bucket ladder and slot-pool
encode lanes alike — compiles against the quantized weights and the
zero-steady-state-recompile guarantee is untouched.  The caption-parity
harness (tests/test_quant.py) bounds context-grid / per-step-logit /
caption divergence vs the fp32 path.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DN = ("NHWC", "HWIO", "NHWC")  # image, kernel, output layouts
_EPS = 1e-6  # absmax floor: an all-zero tensor quantizes to scale=eps/127


# ---------------------------------------------------------------------------
# Weight-side primitives
# ---------------------------------------------------------------------------


def quantize_kernel(kernel: jnp.ndarray):
    """[kh,kw,cin,cout] fp32 -> (int8 kernel, fp32 per-output-channel scales).

    Symmetric: q = round(w / scale), scale = absmax/127 over each output
    channel — zero-point-free, so the int32 accumulator needs no
    correction term and maps 1:1 onto the MXU's s8xs8->s32 path.
    """
    k = jnp.asarray(kernel, jnp.float32)  # sync-ok: one-time load transfer
    absmax = jnp.maximum(jnp.abs(k).max(axis=(0, 1, 2)), _EPS)  # [cout]
    scale = absmax / 127.0
    q = jnp.clip(jnp.round(k / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def fold_bn(kernel, bias, gamma, beta, mean, var, eps: float = 1e-3):
    """Fold a frozen batch norm into the preceding conv.

    y = gamma * (conv(x) + bias - mean)/sqrt(var+eps) + beta
      = conv(x) * s + (bias - mean) * s + beta,   s = gamma/sqrt(var+eps)
    """
    s = jnp.asarray(gamma, jnp.float32) / jnp.sqrt(  # sync-ok: load-time fold
        jnp.asarray(var, jnp.float32) + eps  # sync-ok: load-time fold
    )
    # broadcast over [kh,kw,cin,cout]
    k = jnp.asarray(kernel, jnp.float32) * s  # sync-ok: load-time fold
    b = jnp.zeros_like(s) if bias is None else jnp.asarray(bias, jnp.float32)  # sync-ok: load-time fold
    b = (b - jnp.asarray(mean, jnp.float32)) * s + jnp.asarray(beta, jnp.float32)  # sync-ok: load-time fold
    return k, b


# ---------------------------------------------------------------------------
# Param-tree flattening (flax module tree -> flat name -> leaves)
# ---------------------------------------------------------------------------


def _flatten_convs(tree: Dict[str, Any], out: Dict[str, Dict[str, Any]]):
    """Collect {conv_module_name: {'kernel', 'bias'?}} from a cnn param tree.

    A Conv wrapper is a module named e.g. ``conv1_1`` / ``res2a_branch2a``
    holding an inner nn.Conv named ``conv``; leaf names are unique across
    both backbones, so a flat namespace is safe.
    """
    for name, sub in tree.items():
        if not isinstance(sub, dict):
            continue
        inner = sub.get("conv")
        if isinstance(inner, dict) and "kernel" in inner:
            out[name] = inner
        else:
            _flatten_convs(sub, out)


def _flatten_bns(tree: Dict[str, Any], out: Dict[str, Dict[str, Any]]):
    """Collect {bn_name: {'scale','bias'} or {'mean','var'}} leaves."""
    for name, sub in tree.items():
        if not isinstance(sub, dict):
            continue
        if ("scale" in sub and "bias" in sub) or ("mean" in sub and "var" in sub):
            out.setdefault(name, {}).update(sub)
        else:
            _flatten_bns(sub, out)


def _bn_name_for(conv_name: str) -> str:
    """Reference scope naming: conv1 -> bn_conv1, resXy_brZ -> bnXy_brZ."""
    if conv_name == "conv1":
        return "bn_conv1"
    return "bn" + conv_name[len("res"):]


def folded_convs(variables: Dict[str, Any], config) -> Dict[str, Dict[str, Any]]:
    """Flat {name: {'kernel' fp32, 'bias' fp32}} with frozen BN folded in."""
    convs: Dict[str, Dict[str, Any]] = {}
    _flatten_convs(variables["params"]["cnn"], convs)
    bns: Dict[str, Dict[str, Any]] = {}
    _flatten_bns(variables["params"]["cnn"], bns)
    if "batch_stats" in variables:
        _flatten_bns(variables["batch_stats"], bns)
    out: Dict[str, Dict[str, Any]] = {}
    for name, leaves in convs.items():
        kernel = jnp.asarray(leaves["kernel"], jnp.float32)  # sync-ok: one-time load transfer
        bias = leaves.get("bias")
        bn = bns.get(_bn_name_for(name)) if config.cnn == "resnet50" else None
        if bn is not None:
            kernel, bias = fold_bn(
                kernel, bias, bn["scale"], bn["bias"], bn["mean"], bn["var"]
            )
        elif bias is None:
            bias = jnp.zeros((kernel.shape[-1],), jnp.float32)
        out[name] = {"kernel": kernel, "bias": jnp.asarray(bias, jnp.float32)}  # sync-ok: one-time load transfer
    return out


# ---------------------------------------------------------------------------
# Topology dispatch + conv-fn factories
# ---------------------------------------------------------------------------


def _walker(config):
    if config.cnn == "vgg16":
        from ..models import vgg16

        return vgg16.quant_forward
    from ..models import resnet50

    return resnet50.quant_forward


def _conv2d(x, kernel, strides: int, preferred=None):
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(strides, strides),
        padding="SAME",
        dimension_numbers=_DN,
        preferred_element_type=preferred,
    )


def _fp32_conv_fn(folded, observer: Optional[Dict[str, float]] = None) -> Callable:
    """fp32 conv+bias(+relu) over the folded graph; optionally records the
    per-layer input absmax (the calibration observer)."""

    def conv(name, x, strides=1, relu=False):
        if observer is not None:
            seen = float(jnp.abs(x).max())  # sync-ok: one-time host-side calibration at load, never a serve/train hot path
            observer[name] = max(observer.get(name, 0.0), seen)
        y = _conv2d(x.astype(jnp.float32), folded[name]["kernel"], strides)
        y = y + folded[name]["bias"]
        return jax.nn.relu(y) if relu else y

    return conv


def _bf16_conv_fn(qcnn) -> Callable:
    def conv(name, x, strides=1, relu=False):
        y = _conv2d(x.astype(jnp.bfloat16), qcnn[name]["kernel"], strides)
        y = y + qcnn[name]["bias"].astype(jnp.bfloat16)
        return jax.nn.relu(y) if relu else y

    return conv


def _int8_conv_fn(qcnn) -> Callable:
    def conv(name, x, strides=1, relu=False):
        spec = qcnn[name]
        s_act = spec["act_scale"]  # fp32 scalar
        xq = jnp.clip(
            jnp.round(x.astype(jnp.float32) / s_act), -127, 127
        ).astype(jnp.int8)
        y = _conv2d(xq, spec["kernel"], strides, preferred=jnp.int32)
        # fused dequant: one fp32 multiply-add per output element
        y = y.astype(jnp.float32) * (s_act * spec["w_scale"]) + spec["bias"]
        return jax.nn.relu(y) if relu else y

    return conv


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------


def calibration_batches(config, batches: Optional[Iterable] = None) -> List[np.ndarray]:
    """Mean-subtracted fp32 image batches for activation-range calibration.

    Prefers real rows from the preprocessed shard cache (the serve host
    usually has one; the rows ARE the live path's post-resize uint8
    intermediate); falls back to deterministic synthetic uint8 noise in
    the same value range when no cache is present, so quantization always
    succeeds at load time.
    """
    if batches is not None:
        return [np.asarray(b, np.float32) for b in batches]  # sync-ok: one-time load-time calibration input staging
    from ..data.images import ILSVRC_2012_MEAN

    n = config.encoder_quant_calib_batches
    b = config.encoder_quant_calib_batch_size
    s = config.image_size
    rows: Optional[np.ndarray] = None
    try:
        shard_files = sorted(
            glob.glob(os.path.join(config.shard_cache_dir, "*.npy"))
        )
        if shard_files and config.shard_cache != "off":
            arr = np.load(shard_files[0], mmap_mode="r")
            if arr.ndim == 4 and arr.shape[1:] == (s, s, 3):
                rows = np.asarray(arr[: n * b], np.uint8)  # sync-ok: host mmap read of shard rows at load time
    except Exception:
        rows = None  # unreadable/mismatched cache: synthetic fallback below
    if rows is None or len(rows) == 0:
        rng = np.random.default_rng(0)
        rows = rng.integers(0, 256, size=(n * b, s, s, 3)).astype(np.uint8)
    imgs = rows.astype(np.float32) - ILSVRC_2012_MEAN
    return [imgs[i * b : (i + 1) * b] for i in range(max(1, len(imgs) // b))]


def calibrate(folded, config, batches: Iterable[np.ndarray]) -> Dict[str, float]:
    """Run the fp32 folded graph over calibration batches, recording each
    conv's input absmax.  Eager host-driven execution: this is a one-time
    load-time pass over a handful of small batches, not a hot path."""
    observer: Dict[str, float] = {}
    walker = _walker(config)
    conv = _fp32_conv_fn(folded, observer)
    for batch in batches:
        walker(conv, jnp.asarray(batch, jnp.float32))  # sync-ok: calibration
    return observer


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def quantize_encoder(
    variables: Dict[str, Any],
    config,
    batches: Optional[Iterable] = None,
) -> Dict[str, Dict[str, jnp.ndarray]]:
    """Build the ``qcnn`` collection for ``config.encoder_quant``.

    Returns a flat {conv_name: spec} pytree of device arrays:
      bf16: {'kernel' bf16, 'bias' fp32}
      int8: {'kernel' int8, 'w_scale' fp32 [cout], 'bias' fp32 [cout],
             'act_scale' fp32 scalar}
    """
    mode = config.encoder_quant
    if mode == "off":
        raise ValueError("quantize_encoder called with encoder_quant='off'")
    folded = folded_convs(variables, config)
    if mode == "bf16":
        return {
            name: {
                "kernel": spec["kernel"].astype(jnp.bfloat16),
                "bias": spec["bias"],
            }
            for name, spec in folded.items()
        }
    ranges = calibrate(folded, config, calibration_batches(config, batches))
    qcnn: Dict[str, Dict[str, jnp.ndarray]] = {}
    for name, spec in folded.items():
        q, w_scale = quantize_kernel(spec["kernel"])
        act_scale = max(ranges.get(name, 0.0), _EPS) / 127.0
        qcnn[name] = {
            "kernel": q,
            "w_scale": w_scale,
            "bias": spec["bias"],
            "act_scale": jnp.float32(act_scale),
        }
    return qcnn


def quantized_encode(
    variables: Dict[str, Any], config, images: jnp.ndarray
) -> jnp.ndarray:
    """images [B,H,W,3] fp32 (mean-subtracted) -> contexts [B,N,D] fp32,
    through the quantized conv graph in ``variables['qcnn']``.  Traceable:
    this is what the serve path jits/AOT-compiles."""
    qcnn = variables["qcnn"]
    if config.encoder_quant == "bf16":
        conv = _bf16_conv_fn(qcnn)
    elif config.encoder_quant == "int8":
        conv = _int8_conv_fn(qcnn)
    else:
        raise ValueError(f"encoder_quant={config.encoder_quant!r}")
    return _walker(config)(conv, images)
