"""Mesh-path runtime: SPMD training through runtime.train on the 8-device
CPU mesh, per-host data sharding, distributed checkpoint gather."""

import os

import numpy as np
import jax
import pytest

from sat_tpu import runtime
from sat_tpu.data.dataset import DataSet
from sat_tpu.parallel.data import process_local_dataset
from sat_tpu.train.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    state_to_flat,
)
from sat_tpu.train.step import create_train_state

from tests.test_runtime import SMALL_MODEL


def test_train_on_mesh_end_to_end(coco_fixture, tmp_path):
    """runtime.train with mesh_shape=(4,2): dp over batch, tp over the
    vocab dims, checkpoint written from the sharded state and restorable
    into a plain single-device state."""
    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "mesh_shape": (4, 2)}
    )
    state = runtime.train(config)
    assert int(np.asarray(state.step)) == 6

    ckpt = latest_checkpoint(config.save_dir)
    assert ckpt is not None and ckpt.endswith("6.npz")

    plain = config.replace(mesh_shape=(1, 1))
    fresh = create_train_state(jax.random.PRNGKey(9), plain)
    restored, count = restore_checkpoint(fresh, model_file=ckpt)
    assert count > 0

    want = state_to_flat(state)
    got = state_to_flat(restored)
    assert set(want) == set(got)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], err_msg=k, rtol=1e-6)

    # and the restored single-device state evaluates (full path reuse)
    scores = runtime.evaluate(config.replace(mesh_shape=(1, 1)), state=restored)
    assert "Bleu_4" in scores


def test_mesh_and_single_device_training_agree(coco_fixture, tmp_path):
    """Same data, same init, same dropout keys: the dp+tp mesh run's loss
    trajectory must track the single-device run.  (Bitwise param equality
    is NOT expected — psum/matmul reduction order differs and Adam
    amplifies that on near-zero params; single-step numeric parity is
    pinned separately in test_parallel.py.)"""
    import json
    import os

    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "num_epochs": 1,
           "summary_dir": str(tmp_path / "s1"),
           "save_dir": str(tmp_path / "m1"),
           "save_period": 0}
    )
    runtime.train(base.replace(mesh_shape=(1, 1)), seed=0)
    runtime.train(
        base.replace(
            mesh_shape=(2, 2),
            summary_dir=str(tmp_path / "s2"),
            save_dir=str(tmp_path / "m2"),
        ),
        seed=0,
    )

    def losses(d):
        rows = [json.loads(x) for x in open(os.path.join(d, "metrics.jsonl"))]
        return np.array([r["total_loss"] for r in rows])

    a, b = losses(str(tmp_path / "s1")), losses(str(tmp_path / "s2"))
    assert a.shape == b.shape and len(a) == 6
    np.testing.assert_allclose(b, a, rtol=5e-2)


@pytest.mark.parametrize("mesh_shape", [(2, 1), (1, 2), (2, 2)])
def test_mesh_eval_matches_single_device(coco_fixture, tmp_path, mesh_shape):
    """decode_dataset routes through make_parallel_beam_search on a mesh;
    parallel eval — dp-only, vocab-TP-only (embedding/softmax sharded over
    'model'), and combined — must produce the SAME captions and scores as
    the single-device path end-to-end (VERDICT r1 item 5)."""
    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "res1.json"),
           "beam_size": 2}
    )
    state = runtime.train(base.replace(mesh_shape=(1, 1)))

    if mesh_shape[1] > 1:
        # the TP variants must actually shard: the placement rule keys on
        # config.vocabulary_size (param logit width), which divides the
        # model axis here — guard against silently-replicated 'TP'
        from sat_tpu.parallel import make_mesh
        from sat_tpu.parallel.sharding import param_partition_specs

        cfg_m = base.replace(mesh_shape=mesh_shape)
        specs = param_partition_specs(
            {"params": state.params}, cfg_m, make_mesh(cfg_m)
        )
        flat = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: "model" in str(s), specs)
        )
        assert any(flat), "vocab-TP rule placed nothing on the model axis"

    single = runtime.evaluate(base.replace(mesh_shape=(1, 1)), state=state)
    mesh = runtime.evaluate(
        base.replace(mesh_shape=mesh_shape, eval_result_file=str(tmp_path / "res2.json")),
        state=state,
    )
    assert single.keys() == mesh.keys()
    for k in single:
        np.testing.assert_allclose(mesh[k], single[k], rtol=1e-6, err_msg=k)

    import json
    r1 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res1.json"))}
    r2 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res2.json"))}
    assert r1 == r2 and len(r1) > 0


def test_multihost_decode_assembly_matches_single_host(coco_fixture, tmp_path):
    """Simulate the 2-process mesh decode: per-host block shards of each
    global batch, per-host beam blocks stacked in process order (the
    make_global_batch layout), then _assemble_mesh_results — captions must
    equal the single-device decode_dataset output, fake_count padding rows
    dropped."""
    from sat_tpu.data.dataset import prepare_eval_data
    from sat_tpu.data.images import ImageLoader, PrefetchLoader
    from sat_tpu.models.captioner import encode
    from sat_tpu.ops.beam_search import beam_search_jit
    from sat_tpu.runtime import _assemble_mesh_results, _eos_id, decode_dataset
    from sat_tpu.train.step import create_train_state

    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL, "beam_size": 2, "batch_size": 4}
    )
    coco, full_ds, vocab = prepare_eval_data(config)
    # 5 images / global batch 4: exercises the trailing fake_count pad
    # (positions 5..7 of the 2-batch global order)
    ds = DataSet(full_ds.image_ids[:5], full_ds.image_files[:5], 4)
    config = config.replace(vocabulary_size=len(vocab.words))
    state = create_train_state(jax.random.PRNGKey(0), config)
    eos = _eos_id(vocab)

    want = decode_dataset(config, state, ds, vocab)

    pc = 2
    locals_ = [
        process_local_dataset(ds, process_index=p, process_count=pc)
        for p in range(pc)
    ]
    # the view keeps global bookkeeping (count/num_batches) and a local
    # batch size — every host runs the same number of whole batches
    assert {l.count for l in locals_} == {5}
    assert {l.num_batches for l in locals_} == {2}
    assert {l.batch_size for l in locals_} == {2}

    variables = {"params": state.params}
    blocks = []           # blocks[h][b] = (words, lengths, scores)
    for l in locals_:
        loader = PrefetchLoader(l, ImageLoader(size=config.image_size), num_workers=2)
        host_blocks = []
        for batch in loader:
            contexts, _ = encode(variables, config, batch["images"], train=False)
            out = beam_search_jit(
                state.params["decoder"], config, contexts, eos,
                beam_size=config.beam_size, valid_size=len(vocab.words),
            )
            host_blocks.append(
                (np.asarray(out.words[:, 0]), np.asarray(out.lengths[:, 0]),
                 np.asarray(out.log_scores[:, 0]))
            )
        blocks.append(host_blocks)

    num_batches = len(blocks[0])
    gathered = [
        tuple(
            np.concatenate([blocks[h][b][k] for h in range(pc)], axis=0)
            for k in range(3)
        )
        for b in range(num_batches)
    ]
    got = _assemble_mesh_results(ds, vocab, gathered)

    assert [r["image_id"] for r in got] == [r["image_id"] for r in want]
    assert [r["caption"] for r in got] == [r["caption"] for r in want]
    np.testing.assert_allclose(
        [r["prob"] for r in got], [r["prob"] for r in want], rtol=1e-5
    )


def test_process_local_dataset_slices_disjointly():
    ids = np.arange(24)
    files = np.array([f"f{i}.jpg" for i in ids])
    w = np.arange(24 * 5).reshape(24, 5)
    m = np.ones((24, 5), np.float32)
    global_ds = DataSet(ids, files, 8, w, m, is_train=True, shuffle=False)

    shards = [
        process_local_dataset(global_ds, process_index=p, process_count=4)
        for p in range(4)
    ]
    for s in shards:
        assert s.batch_size == 2                          # 8 global / 4 hosts
        assert s.num_batches == global_ds.num_batches     # same step count
    # per global batch, shard p yields block p — stitched in process
    # order they reproduce the global batch exactly (unshuffled: identity)
    streams = [[f for f, _, _ in s] for s in shards]
    for b in range(global_ds.num_batches):
        stitched = np.concatenate([streams[p][b] for p in range(4)])
        assert stitched.tolist() == files[b * 8:(b + 1) * 8].tolist()

    with pytest.raises(ValueError, match="not divisible"):
        process_local_dataset(global_ds, process_index=0, process_count=3)


@pytest.mark.parametrize(
    "extra_args,banner",
    [
        ([], "MULTIHOST OK (data-parallel)"),
        (["--cp"], "MULTIHOST OK (context-parallel)"),
        (["--tp"], "MULTIHOST OK (tensor-parallel)"),
        pytest.param(
            ["--mesh", "2,2", "--cp", "--check-loss-parity"],
            "MULTIHOST OK (mesh 2x2 context-parallel)",
            marks=pytest.mark.skipif(
                (os.cpu_count() or 1) < 2,
                reason="4-process 2D-mesh gloo communicator rendezvous "
                "(fixed ~30s peer window) is unreliable on a 1-core host "
                "— an artifact of the CPU collectives emulation, not of "
                "the mesh code (TPU multi-host rides ICI/DCN); run "
                "`python scripts/multihost_demo.py --mesh 2,2 --cp "
                "--check-loss-parity` standalone (passing artifact: "
                "runs/multihost_2x2/)",
            ),
        ),
        pytest.param(
            ["--mesh", "2,2", "--tp", "--check-loss-parity"],
            "MULTIHOST OK (mesh 2x2 tensor-parallel)",
            marks=pytest.mark.skipif(
                (os.cpu_count() or 1) < 2,
                reason="see the dp_x_cp_4proc skip rationale",
            ),
        ),
    ],
    ids=["dp", "cp", "tp", "dp_x_cp_4proc", "dp_x_tp_4proc"],
)
def test_multihost_demo_two_real_processes(tmp_path, extra_args, banner):
    """The full multi-process story, for real: N OS processes bootstrap a
    jax.distributed cluster over a loopback coordinator, train SPMD, and
    run multi-host mesh eval with cross-host result gather — all hosts
    must finish rc=0 with identical scores and full panel coverage.

    dp: per-host data shards with XLA gradient all-reduce.  cp: the MODEL
    axis spans the processes — context-parallel training and beam-search
    decode whose distributed-softmax psums cross a real process boundary
    (loopback DCN), every host feeding identical full batches
    (mesh_data_shard).  tp: same spanning axis, spent instead on the
    embedding/softmax vocab dimension (GSPMD-inserted cross-host
    collectives).  The 2x2 four-process cases combine dp WITH cp/tp — the
    first layouts where a data row spans multiple model-axis processes
    AND multiple data shards feed different row blocks — and additionally
    assert the loss trajectory tracks a single-process control (the shard
    views feed the identical global batch stream, VERDICT r03 #7)."""
    import os
    import signal
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:  # free coordinator port (xdist/CI safe)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(repo, "scripts", "multihost_demo.py"),
            "--root", str(tmp_path / "demo"), "--port", str(port),
            "--join-timeout", "420", *extra_args,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, cwd=repo,
        start_new_session=True,  # own process group: timeout kills workers too
    )
    try:
        # generous: the demo retries up to 3 fresh clusters when the CPU
        # gloo backend's communicator rendezvous flakes (its in-script
        # comment explains the 1-core-CI failure mode)
        out, err = proc.communicate(timeout=1500)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        raise AssertionError(f"demo timed out\n{out[-2000:]}\n{err[-1500:]}")
    assert proc.returncode == 0, f"{out[-3000:]}\n--- stderr ---\n{err[-1500:]}"
    assert banner in out


def test_mesh_data_shard_maps_model_axis_processes_to_one_row():
    """Single-process sanity of the feed-shard mapping: dp rows with the
    whole mesh addressable fall back to (process 0 of 1); a data axis of
    size 1 maps to (0, 1) — the pure-CP every-host-feeds-everything case."""
    from sat_tpu.parallel.data import mesh_data_shard
    from sat_tpu.parallel.mesh import mesh_from_devices

    devs = jax.devices()[:8]
    assert mesh_data_shard(
        mesh_from_devices(devs, (2, 4), ("data", "model"))
    ) == (0, 1)
    assert mesh_data_shard(
        mesh_from_devices(devs[:2], (1, 2), ("data", "model"))
    ) == (0, 1)
    assert mesh_data_shard(
        mesh_from_devices(devs[:2], (2, 1), ("data", "model"))
    ) == (0, 1)


def test_tiny_dataset_many_hosts_pads_via_global_order():
    """3 images / 8 hosts / global batch 8: the shard view's global order
    (identity + keyed fake_count resampling) gives every host exactly one
    whole 1-row batch — no separate process padding or truncation."""
    ids = np.arange(3)
    files = np.array([f"f{i}.jpg" for i in ids])
    ds = DataSet(ids, files, 8)
    shards = [
        process_local_dataset(ds, process_index=p, process_count=8)
        for p in range(8)
    ]
    assert {s.num_batches for s in shards} == {1}
    assert {s.batch_size for s in shards} == {1}
    stitched = np.concatenate([next(iter(s)) for s in shards])
    # first 3 rows are the real images in dataset order; the rest are the
    # keyed resampling draws — identical to the single-process pad batch
    assert stitched[:3].tolist() == files.tolist()
    assert stitched.tolist() == next(iter(ds)).tolist()


def test_shard_views_assemble_to_global_stream():
    """THE layout-invariance contract: for a shuffled train DataSet, the
    per-process shard views stitched in process order reproduce the
    single-process batch stream bitwise — every epoch, uneven final batch
    included, and across a mid-epoch seek (elastic resume on a different
    process count replays the same global stream)."""
    ids = np.arange(25)                        # 25 rows / batch 8 → fake 7
    files = np.array([f"f{i}.jpg" for i in ids])
    w = np.arange(25 * 5).reshape(25, 5)
    m = np.ones((25, 5), np.float32)

    def make(seed=3):
        return DataSet(ids, files, 8, w, m, is_train=True, shuffle=True,
                       seed=seed)

    global_ds = make()
    shards = [
        process_local_dataset(make(), process_index=p, process_count=4)
        for p in range(4)
    ]
    assert {s.num_batches for s in shards} == {4}
    for epoch in range(2):                     # two epochs: fresh orders
        global_batches = list(global_ds)
        shard_batches = [list(s) for s in shards]
        for b in range(global_ds.num_batches):
            for k in range(3):                 # files / word_idxs / masks
                stitched = np.concatenate(
                    [shard_batches[p][b][k] for p in range(4)]
                )
                np.testing.assert_array_equal(
                    stitched, global_batches[b][k],
                    err_msg=f"epoch {epoch} batch {b} field {k}",
                )

    # mid-epoch seek: same (epoch, batch) cursor on every vehicle
    global_ds.seek(5, 2)
    for s in shards:
        s.seek(5, 2)
    g = list(global_ds)
    per = [list(s) for s in shards]
    assert len(g) == 2                         # batches 2..3 of epoch 5
    for b in range(len(g)):
        stitched = np.concatenate([per[p][b][0] for p in range(4)])
        np.testing.assert_array_equal(stitched, g[b][0])


def test_cp_eval_decodes_under_trained_replicated_placement(coco_fixture, tmp_path):
    """A context-parallel config trains with params replicated (the 'model'
    axis is spent on the context grid, runtime.train); eval must decode
    under that SAME placement instead of silently re-sharding to vocab-TP
    (VERDICT r2 weak #4) — and still produce the single-device captions."""
    base = coco_fixture["config"].replace(
        **{**SMALL_MODEL,
           "save_dir": str(tmp_path / "models"),
           "summary_dir": str(tmp_path / "summary"),
           "eval_result_file": str(tmp_path / "res1.json"),
           "beam_size": 2}
    )
    state = runtime.train(base.replace(mesh_shape=(1, 1)))

    cfg_cp = base.replace(mesh_shape=(2, 2), context_parallel=2)
    # the placement decode_dataset uses for CP: fully replicated — nothing
    # may land on the 'model' axis (mirrors train()'s vocabulary_size=-1)
    from sat_tpu.parallel import make_mesh
    from sat_tpu.parallel.sharding import param_partition_specs

    specs = param_partition_specs(
        {"params": state.params},
        cfg_cp.replace(vocabulary_size=-1),
        make_mesh(cfg_cp),
    )
    on_model = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: "model" in str(s), specs)
    )
    assert not any(on_model)

    single = runtime.evaluate(base.replace(mesh_shape=(1, 1)), state=state)
    cp = runtime.evaluate(
        cfg_cp.replace(eval_result_file=str(tmp_path / "res2.json")),
        state=state,
    )
    assert single.keys() == cp.keys()
    for k in single:
        np.testing.assert_allclose(cp[k], single[k], rtol=1e-6, err_msg=k)

    import json
    r1 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res1.json"))}
    r2 = {r["image_id"]: r["caption"] for r in json.load(open(tmp_path / "res2.json"))}
    assert r1 == r2 and len(r1) > 0


def test_multihost_attention_map_gather_renders_panels(coco_fixture, tmp_path):
    """Beam-0 alphas ride the cross-host gather (VERDICT r2 weak #5): the
    simulated 2-process assembly must carry per-word attention maps equal
    to the single-host decode's, and panels must render from them."""
    from sat_tpu.data.dataset import prepare_eval_data
    from sat_tpu.data.images import ImageLoader, PrefetchLoader
    from sat_tpu.models.captioner import encode
    from sat_tpu.ops.beam_search import beam_search_jit
    from sat_tpu.runtime import (
        _assemble_mesh_results,
        _eos_id,
        _save_attention_panels,
        decode_dataset,
    )
    from sat_tpu.train.step import create_train_state

    config = coco_fixture["config"].replace(
        **{**SMALL_MODEL, "beam_size": 2, "batch_size": 4,
           "save_attention_maps": True}
    )
    coco, full_ds, vocab = prepare_eval_data(config)
    ds = DataSet(full_ds.image_ids[:5], full_ds.image_files[:5], 4)
    config = config.replace(vocabulary_size=len(vocab.words))
    state = create_train_state(jax.random.PRNGKey(0), config)
    eos = _eos_id(vocab)

    want = decode_dataset(config, state, ds, vocab)
    assert all("alphas" in r for r in want)

    pc = 2
    locals_ = [
        process_local_dataset(ds, process_index=p, process_count=pc)
        for p in range(pc)
    ]
    variables = {"params": state.params}
    blocks = []
    for l in locals_:
        loader = PrefetchLoader(l, ImageLoader(size=config.image_size), num_workers=2)
        host_blocks = []
        for batch in loader:
            contexts, _ = encode(variables, config, batch["images"], train=False)
            out = beam_search_jit(
                state.params["decoder"], config, contexts, eos,
                beam_size=config.beam_size, valid_size=len(vocab.words),
                return_alphas=True,
            )
            host_blocks.append(tuple(
                np.asarray(a[:, 0])
                for a in (out.words, out.lengths, out.log_scores, out.alphas)
            ))
        blocks.append(host_blocks)

    gathered = [
        tuple(
            np.concatenate([blocks[h][b][k] for h in range(pc)], axis=0)
            for k in range(4)
        )
        for b in range(len(blocks[0]))
    ]
    got = _assemble_mesh_results(ds, vocab, gathered)

    assert [r["caption"] for r in got] == [r["caption"] for r in want]
    for rg, rw in zip(got, want):
        assert rg["words"] == rw["words"]
        np.testing.assert_allclose(rg["alphas"], rw["alphas"], rtol=1e-5)

    out_dir = tmp_path / "panels"
    out_dir.mkdir()
    _save_attention_panels(got, str(out_dir))
    panels = list(out_dir.glob("*_attention.jpg"))
    assert len(panels) == len(got) and all(p.stat().st_size > 0 for p in panels)
