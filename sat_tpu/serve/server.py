"""HTTP frontend for the captioning service (docs/SERVING.md).

A stdlib ``ThreadingHTTPServer`` — one Python thread per in-flight
request, which is exactly the concurrency this workload wants: request
threads spend their time in the JPEG codec (releases the GIL) or parked
on an Event while the batcher owns the device, so host preprocessing of
request n+1 overlaps device decode of batch n with no async framework.

Endpoints:

* ``POST /caption`` — body: JPEG/PNG bytes.  200 → ``{"captions": [{
  "caption", "log_prob", "prob"}, ...beam-ordered], "bucket",
  "model_step"}``.  400 undecodable body, 429 queue full (shed), 503
  draining, 504 deadline/timeout.  ``X-Deadline-Ms`` (integer) overrides
  ``Config.serve_deadline_ms`` per request.
* ``GET /healthz`` — readiness + the run-health heartbeat payload
  (telemetry.Heartbeat — same fields watchers poll from heartbeat.json).
  200 ready, 503 draining/stopped: a load balancer needs only the code.
* ``GET /stats`` — queue depth, bucket histogram, serve counters, and
  p50/p95/p99 latency per serve span (queue_wait / preprocess / dispatch
  / detok / request) from the telemetry ring.

Shutdown: SIGTERM/SIGINT (via ``resilience.preempt.GracefulShutdown``)
or ``request_shutdown()`` triggers the drain sequence — readiness flips
first, the batcher rejects new work and completes everything admitted,
then the listener and heartbeat close and ``serve()`` returns 0.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import telemetry
from ..config import Config
from ..data.vocabulary import Vocabulary
from ..resilience.preempt import GracefulShutdown
from ..telemetry.heartbeat import Heartbeat
from .batcher import MicroBatcher, Rejected
from .engine import ServeEngine, load_serving_state

_LATENCY_SPANS = (
    "serve/request",
    "serve/queue_wait",
    "serve/preprocess",
    "serve/dispatch",
    "serve/detok",
)


def _percentiles_ms(tel, name: str) -> Optional[Dict[str, Any]]:
    """p50/p95/p99 (ms) of a span's recorded durations; None when empty.
    Host-side accounting over the telemetry ring — no device data."""
    data = np.asarray(tel.durations_ns(name), np.float64)  # sync-ok: host telemetry ring, not device data
    if data.size == 0:
        return None
    data = np.sort(data) / 1e6
    def pct(p: float) -> float:
        idx = min(data.size - 1, int(p / 100.0 * data.size))
        return round(float(data[idx]), 3)  # sync-ok: host numpy percentile
    return {
        "count": int(data.size),
        "p50": pct(50),
        "p95": pct(95),
        "p99": pct(99),
    }


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "sat-serve"

    def log_message(self, fmt, *args):  # stderr per-request noise: off
        pass

    def _reply(self, status: int, payload: Dict[str, Any]) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        app = self.server.app
        if self.path.startswith("/healthz"):
            payload, status = app.healthz()
            self._reply(status, payload)
        elif self.path.startswith("/stats"):
            self._reply(200, app.stats())
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        app = self.server.app
        if not self.path.startswith("/caption"):
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = 0
        if length <= 0:
            self._reply(400, {"error": "empty body; POST image bytes"})
            return
        body = self.rfile.read(length)
        status, payload = app.handle_caption(
            body, deadline_ms=self.headers.get("X-Deadline-Ms")
        )
        self._reply(status, payload)


class CaptionServer:
    """Wires engine + micro-batcher + HTTP listener + heartbeat; owns the
    readiness flag and the drain sequence."""

    # ceiling on how long a handler thread waits for its result when the
    # request carries no deadline (a wedged device must not strand
    # connections forever)
    DEFAULT_WAIT_S = 120.0

    def __init__(
        self,
        config: Config,
        engine: ServeEngine,
        host: Optional[str] = None,
        port: Optional[int] = None,
    ) -> None:
        self.config = config
        self.engine = engine
        self._tel = telemetry.get()
        # admission knobs come from THIS server's config (which may be a
        # replace() of the engine's — e.g. a tighter queue for the same
        # warmed engine), not the engine's defaults
        self.batcher = MicroBatcher(
            engine,
            max_batch=config.serve_max_batch,
            max_wait_ms=config.serve_max_wait_ms,
            queue_depth=config.serve_queue_depth,
            tel=self._tel,
            on_wedge=self._on_wedge,
            wedge_timeout_ms=config.serve_wedge_timeout_ms,
        )
        self._host = host if host is not None else config.serve_host
        self._requested_port = (
            port if port is not None else config.serve_port
        )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._ready = False
        # wedged-batch degraded state (docs/SERVING.md): /healthz reports
        # 503 "degraded" while the engine re-warms after a stuck in-flight
        # batch; requests are still admitted (the batcher is alive) — only
        # the balancer-facing health flips
        self._degraded = False
        self._t_start = time.time()
        self.heartbeat: Optional[Heartbeat] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def ready(self) -> bool:
        return self._ready

    # -- request handlers (HTTP worker threads) ----------------------------

    def handle_caption(
        self, body: bytes, deadline_ms=None
    ) -> Tuple[int, Dict[str, Any]]:
        t_req0 = time.perf_counter_ns()
        if not self._ready:
            return 503, {"error": "server is draining; not accepting work"}
        try:
            with self._tel.span("serve/preprocess"):
                image = self.engine.preprocess(body)
        except Exception as e:
            # undecodable POST body: a client problem, not a server crash —
            # counted so a flood of garbage uploads shows in the heartbeat
            self._tel.count("serve/bad_input")
            return 400, {
                "error": "bad image",
                "detail": f"cannot decode image bytes: {e}",
            }
        if deadline_ms is None or deadline_ms == "":
            budget_ms = self.config.serve_deadline_ms
        else:
            try:
                budget_ms = int(deadline_ms)
            except (TypeError, ValueError):
                return 400, {
                    "error": "X-Deadline-Ms must be integer milliseconds"
                }
        deadline_unix = (
            time.time() + budget_ms / 1e3 if budget_ms > 0 else None
        )
        try:
            req = self.batcher.submit(image, deadline_unix=deadline_unix)
        except Rejected as e:
            return e.status, {"error": e.reason}
        wait_s = (
            budget_ms / 1e3 + 5.0 if deadline_unix else self.DEFAULT_WAIT_S
        )
        if not req.done.wait(timeout=wait_s):
            self._tel.count("serve/timeouts")
            return 504, {"error": "request timed out in service"}
        if req.error is not None:
            return req.error[0], {"error": req.error[1]}
        self._tel.record(
            "serve/request", t_req0, time.perf_counter_ns() - t_req0
        )
        payload = dict(req.result)
        payload["bucket"] = req.bucket
        payload["model_step"] = self.engine.step
        return 200, payload

    def healthz(self) -> Tuple[Dict[str, Any], int]:
        payload = self.heartbeat.payload() if self.heartbeat else {}
        degraded = self._degraded
        payload.update(
            {
                "ready": self._ready,
                "status": (
                    "degraded"
                    if degraded
                    else ("ok" if self._ready else "draining")
                ),
                "uptime_s": round(time.time() - self._t_start, 1),
                "queue_depth": self.batcher.queue_depth(),
                "buckets": list(self.engine.buckets),
                "model_step": self.engine.step,
            }
        )
        return payload, (200 if self._ready and not degraded else 503)

    # -- wedge containment (called from the batcher thread) ----------------

    def _on_wedge(self) -> None:
        """A stuck in-flight batch was just failed with 500s: flip health
        to 503 "degraded" so the balancer routes away, and re-warm the
        engine in the background — the AOT warmup rebuilds the compiled
        ladder (cheap under the persistent compile cache) and proves the
        device answers again before health recovers."""
        self._degraded = True
        self._tel.gauge("serve/degraded", 1)
        threading.Thread(
            target=self._rewarm, name="sat-serve-rewarm", daemon=True
        ).start()

    def _rewarm(self) -> None:
        try:
            self.engine.warmup()
        except Exception as e:
            # still wedged — stay degraded; the next wedge timeout (or an
            # operator) escalates
            print(
                f"sat_tpu: serve re-warm failed ({e!r}); staying degraded",
                file=sys.stderr,
                flush=True,
            )
            return
        self._tel.count("serve/rewarms")
        self._degraded = False
        self._tel.gauge("serve/degraded", 0)
        print(
            "sat_tpu: serve engine re-warmed after wedged batch; health "
            "restored",
            file=sys.stderr,
            flush=True,
        )

    def stats(self) -> Dict[str, Any]:
        counters = self._tel.counters()
        prefix = "serve/bucket_"
        histogram = {
            k[len(prefix):]: v
            for k, v in counters.items()
            if k.startswith(prefix)
        }
        latency = {}
        for name in _LATENCY_SPANS:
            p = _percentiles_ms(self._tel, name)
            if p:
                latency[name] = p
        return {
            "ready": self._ready,
            "queue_depth": self.batcher.queue_depth(),
            "buckets": list(self.engine.buckets),
            "bucket_histogram": histogram,
            "warm_compiles": self.engine.warm_compiles,
            "compiles_since_ready": counters.get("jax/compiles", 0)
            - self.engine.compiles_at_ready,
            "counters": {
                k: v
                for k, v in counters.items()
                if k.startswith(("serve/", "jax/"))
            },
            "latency_ms": latency,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CaptionServer":
        self.batcher.start()
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler
        )
        self._httpd.app = self
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="sat-serve-http",
            daemon=True,
        )
        self._http_thread.start()
        if self.config.heartbeat_interval > 0:
            hb_dir = self.config.telemetry_dir or os.path.join(
                self.config.summary_dir, "telemetry"
            )
            try:
                os.makedirs(hb_dir, exist_ok=True)
                self.heartbeat = Heartbeat(
                    os.path.join(hb_dir, "heartbeat.json"),
                    self.config.heartbeat_interval,
                    self._tel,
                    static={
                        "phase": "serve",
                        "port": self.port,
                        "buckets": list(self.engine.buckets),
                        "model_step": self.engine.step,
                    },
                ).start()
            except OSError:
                self.heartbeat = None  # health still served from /healthz
        self._ready = True
        self._tel.gauge("serve/ready", 1)
        return self

    def request_shutdown(self) -> None:
        """Programmatic twin of SIGTERM (tests, embedding)."""
        self._stop.set()

    def shutdown(self) -> None:
        """Drain sequence: readiness flips first (the balancer stops
        routing), the batcher rejects new work and completes everything
        admitted, then the listener and heartbeat close."""
        if self._httpd is None:
            return
        self._ready = False
        self._tel.gauge("serve/ready", 0)
        self.batcher.drain()
        self._httpd.shutdown()
        if self._http_thread is not None:
            self._http_thread.join(timeout=10.0)
            self._http_thread = None
        self._httpd.server_close()
        self._httpd = None
        if self.heartbeat is not None:
            self.heartbeat.stop()

    def serve_until_shutdown(self, shutdown=None, poll_s: float = 0.1) -> None:
        """Block until SIGTERM/SIGINT or request_shutdown(), then drain.
        ``shutdown`` accepts an externally managed GracefulShutdown (tests
        install one on the main thread); by default one is installed
        here."""
        own = shutdown is None
        sd = GracefulShutdown() if own else shutdown
        try:
            if own:
                sd.__enter__()
            while not sd.stop_requested and not self._stop.is_set():
                time.sleep(poll_s)
        finally:
            if own:
                sd.__exit__(None, None, None)
            self.shutdown()


def serve(config: Config, model_file: Optional[str] = None) -> int:
    """CLI entry point: ``python -m sat_tpu.cli --phase serve``.

    Lineage load → AOT bucket warmup → listen → drain on SIGTERM."""
    import jax

    tel = telemetry.get()
    if not tel.enabled:
        # /stats and /healthz are part of the serving contract: spans and
        # counters always record in this phase (host-side work only — the
        # tracing layer's measured overhead bar applies, no device syncs)
        tel = telemetry.enable(capacity=config.telemetry_buffer)
    from ..runtime import _install_compile_listener

    _install_compile_listener()
    from ..utils.compile_cache import enable as _enable_compile_cache

    _enable_compile_cache(jax, name=".jax_cache", min_compile_time_secs=0.5)

    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, source = load_serving_state(config, model_file=model_file)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    print(
        f"sat_tpu: serving params from {source} (step {engine.step})",
        file=sys.stderr,
        flush=True,
    )
    engine.warmup()
    server = CaptionServer(config, engine)
    server.start()
    print(
        f"sat_tpu: captioning server listening on "
        f"http://{config.serve_host}:{server.port}  "
        f"(buckets {engine.buckets}, max_batch {config.serve_max_batch}, "
        f"max_wait {config.serve_max_wait_ms}ms)",
        file=sys.stderr,
        flush=True,
    )
    server.serve_until_shutdown()
    print("sat_tpu: serve drained cleanly", file=sys.stderr, flush=True)
    return 0
