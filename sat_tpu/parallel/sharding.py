"""Sharding rules: how the caption model's state lays out over the mesh.

The reference's only placement policy is "Variables on ps, ops on workers"
(/root/reference/clusterone_config.py:116-124).  Here placement is a pure
function of array shape:

* batch arrays shard dim 0 over ``data`` (SPMD data parallelism — the
  synchronous upgrade of the reference's async PS strategy, §2.13);
* any parameter dimension equal to ``vocabulary_size`` shards over
  ``model`` — that covers the [V,E] embedding table and the [*,V] softmax
  projection (+ their Adam moments, which share shapes), the TP axis the
  5000-way softmax admits (SURVEY.md §2 parallelism checklist);
* everything else is replicated.

Because the rule keys on shapes it applies uniformly to params, optimizer
slots and batch stats with one tree_map — no per-layer annotations.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..train.step import TrainState


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard dim 0 (batch) over the data axis."""
    return NamedSharding(mesh, P("data"))


# Subtrees whose vocab-sized dims shard over 'model'.  Path-gated (not
# shape-only) so an unrelated dim that happens to equal vocabulary_size —
# e.g. 4*num_lstm_units in a small test config — never gets sharded.
_VOCAB_SHARDED_SCOPES = ("word_embedding", "decode")


def _path_keys(path):
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "name", entry)
        yield str(key)


def _leaf_spec(path, shape, config: Config, model_size: int) -> P:
    """Vocab-sized dims of embedding/softmax leaves → 'model'; else replicate.

    Applies uniformly to params AND their mirrors (Adam moments inside
    opt_state carry the same dict path suffix), so one rule places all.
    """
    if model_size > 1 and any(
        key in _VOCAB_SHARDED_SCOPES for key in _path_keys(path)
    ):
        for i, d in enumerate(shape):
            if d == config.vocabulary_size and d % model_size == 0:
                dims = [None] * len(shape)
                dims[i] = "model"
                return P(*dims)
    return P()


def param_partition_specs(params: Any, config: Config, mesh: Mesh) -> Any:
    """PartitionSpec pytree matching ``params`` (works for any pytree of
    arrays/ShapeDtypeStructs: params, opt_state, batch_stats)."""
    msize = mesh.shape.get("model", 1)
    return jax.tree_util.tree_map_with_path(
        lambda p, x: _leaf_spec(p, np.shape(x), config, msize), params
    )


def named_shardings(tree: Any, config: Config, mesh: Mesh) -> Any:
    """NamedSharding pytree for ANY pytree of arrays (params, a variables
    dict, opt_state) under the standard placement rules."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), param_partition_specs(tree, config, mesh)
    )


def train_state_shardings(state: TrainState, config: Config, mesh: Mesh) -> TrainState:
    """NamedSharding pytree with TrainState structure.  ``state`` may be a
    concrete TrainState or the jax.eval_shape abstraction of one."""
    specs = jax.tree_util.tree_map_with_path(
        lambda p, x: _leaf_spec(p, np.shape(x), config, mesh.shape.get("model", 1)),
        state,
    )
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)


def shard_train_state(state: TrainState, config: Config, mesh: Mesh) -> TrainState:
    """Place a host/replicated TrainState onto the mesh."""
    return jax.device_put(state, train_state_shardings(state, config, mesh))


def reshard_train_state(state: TrainState, config: Config, mesh: Mesh) -> TrainState:
    """Elastic resume: place a restored TrainState onto *whatever mesh the
    current process has* — which need not match the mesh the checkpoint
    was written under (the lineage sidecar records that one).

    This works because checkpoints are always host-flat FULL arrays
    (``train.checkpoint.state_to_flat`` all-gathers sharded leaves before
    the write), so a topology change is a pure re-placement decided by
    the same shape-keyed rules as a fresh start: an 8-chip checkpoint
    restored on 4 or 1 chips yields bitwise-identical state, just laid
    out differently.  Kept as a named entry point (rather than callers
    reusing :func:`shard_train_state`) so the elastic contract has a
    place to live and be tested against."""
    return shard_train_state(state, config, mesh)


def shard_batch(batch: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Place a global batch dict onto the mesh, dim 0 over 'data'.

    Single-host path: arrays are host-global, device_put scatters them.
    Multi-host: each process holds its LOCAL shard of the batch; use
    ``make_global_batch`` (collectives.py) instead.
    """
    sh = batch_sharding(mesh)
    return {k: jax.device_put(v, sh) for k, v in batch.items()}
