"""Telemetry subsystem tests: spans/counters/gauges, exporters, heartbeat,
ProfilerWindow coverage, crc32c vectorization parity, and the end-to-end
`--telemetry` train run (docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sat_tpu import telemetry
from sat_tpu.telemetry import exporters
from sat_tpu.telemetry.heartbeat import Heartbeat
from sat_tpu.telemetry.spans import NullTelemetry, Telemetry


@pytest.fixture(autouse=True)
def _reset_global_telemetry():
    """Every test leaves the process-global dispatch in the off state —
    the same invariant production code relies on (telemetry-off runs are
    bitwise-unchanged)."""
    yield
    telemetry.disable()


# ---------------------------------------------------------------------------
# spans core
# ---------------------------------------------------------------------------


def test_span_records_into_aggregates_and_window():
    tel = Telemetry(capacity=1024)
    with tel.span("a"):
        time.sleep(0.001)
    tel.record("b", 100, 500)
    agg = tel.aggregates()
    assert agg["a"][0] == 1 and agg["a"][1] >= 1_000_000  # >= 1 ms
    assert agg["b"] == (1, 500, 500)
    assert list(tel.durations_ns("b")) == [500]
    names, ids, t0s, durs, tids = tel.spans_snapshot()
    assert len(ids) == 2
    assert [names[i] for i in ids] == ["a", "b"]
    assert durs[0] >= 1_000_000 and durs[1] == 500


def test_capacity_rounds_to_power_of_two_min_256():
    assert Telemetry(capacity=1)._capacity == 256
    assert Telemetry(capacity=257)._capacity == 512
    assert Telemetry(capacity=1024)._capacity == 1024


def test_ring_overwrites_but_aggregates_stay_exact():
    tel = Telemetry(capacity=256)
    for i in range(1000):
        tel.record("x", i, i)
    count, total, mx = tel.aggregates()["x"]
    assert count == 1000
    assert total == sum(range(1000))
    assert mx == 999
    # window keeps only the newest `capacity` samples, oldest first
    win = tel.durations_ns("x")
    assert len(win) == 256
    assert list(win) == list(range(744, 1000))


def test_percentiles_come_from_window_not_all_time():
    tel = Telemetry(capacity=256)
    for i in range(300):
        tel.record("x", 0, 1_000_000 if i < 200 else 9_000_000)
    # the first 44 cheap samples fell off the ring; stats still count them
    assert tel.aggregates()["x"][0] == 300
    st = exporters._stats(*tel.aggregates()["x"], tel.durations_ns("x"))
    assert st["count"] == 300
    assert st["p95_ms"] == 9.0


def test_interning_grows_past_name_block():
    tel = Telemetry(capacity=256)
    for i in range(300):  # > _NAME_BLOCK distinct names
        tel.record(f"n{i}", 0, i + 1)
    agg = tel.aggregates()
    assert len(agg) == 300
    assert agg["n299"] == (1, 300, 300)


def test_counters_and_gauges():
    tel = Telemetry()
    tel.count("retries")
    tel.count("retries", 4)
    tel.gauge("step", 7)
    tel.gauge("step", 9)
    assert tel.counters() == {"retries": 5}
    assert tel.gauges() == {"step": 9}


def test_threaded_recording_smoke():
    tel = Telemetry(capacity=4096)
    n_threads, per_thread = 8, 500

    def work(k):
        for i in range(per_thread):
            tel.record(f"t{k}", i, 1)
            tel.count("events")

    threads = [threading.Thread(target=work, args=(k,)) for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # counters are lock-protected: exact.  Ring rows are slot-exclusive:
    # every record landed (4000 < capacity: nothing overwritten) and the
    # retained window holds only valid rows (no torn ids).
    assert tel.counters()["events"] == n_threads * per_thread
    names, ids, _, durs, _ = tel.spans_snapshot()
    assert len(ids) == n_threads * per_thread
    assert all(0 <= i < len(names) for i in ids)
    assert all(d == 1 for d in durs)
    assert sum(c for c, _, _ in tel.aggregates().values()) == n_threads * per_thread


def test_global_dispatch_enable_disable():
    assert isinstance(telemetry.get(), NullTelemetry)
    assert not telemetry.enabled()
    tel = telemetry.enable(512)
    assert telemetry.get() is tel and telemetry.enabled()
    with telemetry.span("x"):
        pass
    telemetry.count("c")
    telemetry.gauge("g", 1.5)
    assert "x" in tel.aggregates()
    assert tel.counters() == {"c": 1} and tel.gauges() == {"g": 1.5}
    # enable() again = fresh buffers (one recorder per run)
    tel2 = telemetry.enable(512)
    assert tel2 is not tel and tel2.aggregates() == {}
    telemetry.disable()
    assert isinstance(telemetry.get(), NullTelemetry)


def test_null_telemetry_is_inert():
    null = telemetry.get()
    assert isinstance(null, NullTelemetry)
    with null.span("x"):
        pass
    null.record("x", 0, 1)
    null.count("c")
    null.gauge("g", 1)
    assert null.counters() == {} and null.gauges() == {}
    assert null.aggregates() == {}
    assert null.durations_ns("x").size == 0
    names, ids, *_ = null.spans_snapshot()
    assert names == [] and ids.size == 0


def test_run_id_is_stable_within_process():
    assert telemetry.run_id() == telemetry.run_id()
    assert str(os.getpid()) in telemetry.run_id()


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_chrome_trace_export_is_loadable(tmp_path):
    tel = Telemetry(capacity=256)
    with tel.span("phase/one"):
        time.sleep(0.001)
    tel.count("c", 2)
    path = str(tmp_path / "trace.json")
    assert exporters.export_chrome_trace(tel, path) == path
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    assert meta and meta[0]["args"]["name"] == "sat_tpu host"
    assert len(xs) == 1
    e = xs[0]
    assert e["name"] == "phase/one"
    assert set(e) >= {"name", "cat", "ph", "pid", "tid", "ts", "dur"}
    assert e["dur"] >= 1000.0  # microseconds
    assert doc["otherData"]["run_id"] == telemetry.run_id()
    assert doc["otherData"]["counters"] == {"c": 2}


def test_export_failure_degrades_not_raises(tmp_path):
    tel = Telemetry(capacity=256)
    tel.record("x", 0, 1)
    bad = str(tmp_path / "f.txt" / "trace.json")
    (tmp_path / "f.txt").write_text("a file, not a dir")
    assert exporters.export_chrome_trace(tel, bad) is None


def test_telemetry_jsonl_rows(tmp_path):
    tel = Telemetry(capacity=256)
    tel.record("x", 0, 2_000_000)
    tel.gauge("g", 3)
    # target a not-yet-created subdir: the first heartbeat normally creates
    # the telemetry dir, but heartbeat_interval=0 runs must not depend on it
    path = str(tmp_path / "telemetry" / "telemetry.jsonl")
    exporters.append_jsonl(tel, path, step=5)
    exporters.append_jsonl(tel, path, step=10)
    rows = [json.loads(l) for l in open(path)]
    assert [r["step"] for r in rows] == [5, 10]
    for r in rows:
        assert r["run_id"] == telemetry.run_id()
        assert isinstance(r["wall_time"], float)
        assert isinstance(r["mono_ns"], int)
        assert r["gauges"] == {"g": 3}
        assert r["spans"]["x"]["count"] == 1
        assert r["spans"]["x"]["total_ms"] == 2.0


def test_step_breakdown_phase_sum_reconstructs_wall():
    tel = Telemetry(capacity=1024)
    # 10 steps of 10 ms: 4 ms data_wait + 5 ms dispatch + 1 ms untracked
    for i in range(10):
        tel.record("train/data_wait", 0, 4_000_000)
        tel.record("train/dispatch", 0, 5_000_000)
        tel.record("feed/device_put", 0, 1_000_000)  # nested inside data_wait
        tel.record("train/step", 0, 10_000_000)
    rep = exporters.step_breakdown(
        tel, "train/step",
        ("train/data_wait", "train/dispatch"),
        nested=("feed/device_put",),
    )
    assert rep["steps"] == 10
    assert rep["wall_s"] == pytest.approx(0.1)
    phases = rep["phases"]
    assert phases["train/data_wait"]["total_s"] == pytest.approx(0.04)
    assert phases["train/dispatch"]["total_s"] == pytest.approx(0.05)
    assert phases["other"]["total_s"] == pytest.approx(0.01)
    # the invariant the acceptance bar rides on: phase sum == wall
    assert rep["phase_total_s"] == pytest.approx(rep["wall_s"])
    # nested spans are visible but NOT part of the sum
    assert rep["nested"]["feed/device_put"]["total_s"] == pytest.approx(0.01)
    text = exporters.format_breakdown(rep)
    assert "train/dispatch" in text and "other" in text
    assert "feed/device_put" in text


def test_step_breakdown_none_when_no_steps():
    tel = Telemetry(capacity=256)
    assert exporters.step_breakdown(tel, "train/step", ()) is None


# ---------------------------------------------------------------------------
# heartbeat
# ---------------------------------------------------------------------------


def test_heartbeat_updates_at_interval(tmp_path):
    tel = Telemetry(capacity=256)
    tel.gauge("train/step", 0)
    path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(path, interval_s=0.05, tel=tel, static={"phase": "train"})
    with hb:
        deadline = time.time() + 5.0
        # first beat is immediate; wait for at least two more ticks
        while time.time() < deadline:
            if os.path.exists(path) and json.load(open(path))["seq"] >= 2:
                break
            time.sleep(0.02)
        tel.gauge("train/step", 42)
    final = json.load(open(path))
    assert final["seq"] >= 3  # stop() writes a final beat
    assert final["step"] == 42  # the final beat sees the last gauge
    assert final["phase"] == "train"
    assert final["pid"] == os.getpid()
    assert final["run_id"] == telemetry.run_id()
    assert final["rss_mb"] > 0
    # atomic writes: the file is always complete, valid JSON (checked by
    # every json.load above)


def test_heartbeat_throughput_between_ticks(tmp_path):
    tel = Telemetry(capacity=256)
    hb = Heartbeat(str(tmp_path / "hb.json"), 10.0, tel)
    tel.gauge("train/step", 100)
    hb.write_now()
    time.sleep(0.05)
    tel.gauge("train/step", 110)
    hb.write_now()
    d = json.load(open(hb.path))
    assert d["steps_per_s"] is not None and d["steps_per_s"] > 0


def test_heartbeat_write_failure_never_raises(tmp_path):
    tel = Telemetry(capacity=256)
    blocker = tmp_path / "f"
    blocker.write_text("not a dir")
    hb = Heartbeat(str(blocker / "hb.json"), 0.05, tel)
    hb.write_now()  # must warn, not raise
    hb.write_now()


# ---------------------------------------------------------------------------
# ProfilerWindow (satellite: previously zero tests referenced it)
# ---------------------------------------------------------------------------


@pytest.fixture
def fake_profiler(monkeypatch):
    """Replace jax.profiler start/stop and block_until_ready with a call
    recorder, so window logic is testable without a real trace backend."""
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    monkeypatch.setattr(
        jax, "block_until_ready", lambda x: calls.append(("sync", x))
    )
    return calls


def _window_config(**kw):
    from sat_tpu.config import Config

    return Config(**{"profile_dir": "/tmp/prof", "profile_start_step": 5,
                     "profile_num_steps": 3, **kw})


def test_profiler_window_resume_aware_start(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    with ProfilerWindow(_window_config()) as prof:
        # resumed run: first loop step is 100, already past start=5 —
        # the window must still open (">= start, once" semantics)
        for i in range(100, 110):
            prof.before_step(i)
            prof.after_step(i, f"sync{i}")
    starts = [c for c in fake_profiler if c[0] == "start"]
    stops = [c for c in fake_profiler if c[0] == "stop"]
    assert len(starts) == 1 and len(stops) == 1
    # window covered exactly profile_num_steps steps: opened at 100,
    # closed after 102 with a sync on 102's target
    stop_idx = fake_profiler.index(("stop",))
    assert fake_profiler[stop_idx - 1] == ("sync", "sync102")


def test_profiler_window_max_start_clamps_short_loops(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    # 3-batch decode with default start=5: without clamping the window
    # would never open
    with ProfilerWindow(_window_config(), max_start=2) as prof:
        for i in range(3):
            prof.before_step(i)
            prof.after_step(i, i)
    assert ("start", "/tmp/prof") in fake_profiler
    assert ("stop",) in fake_profiler


def test_profiler_window_exit_closes_early_loop_exit(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    with ProfilerWindow(_window_config(profile_start_step=0)) as prof:
        prof.before_step(0)
        prof.after_step(0, "s0")  # loop dies inside the window
    # __exit__ must stop the trace, syncing on the last after_step target
    assert fake_profiler[-1] == ("stop",)
    assert ("sync", "s0") in fake_profiler


def test_profiler_window_sweep_reentry_never_double_opens(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    # evaluate_sweep re-enters decode per checkpoint: each decode gets a
    # FRESH window; starts/stops must stay strictly paired
    for _ in range(3):
        with ProfilerWindow(_window_config(), max_start=1) as prof:
            for i in range(2):
                prof.before_step(i)
                prof.after_step(i, i)
    seq = [c[0] for c in fake_profiler if c[0] in ("start", "stop")]
    assert seq == ["start", "stop"] * 3


def test_profiler_window_off_when_no_dir(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    with ProfilerWindow(_window_config(profile_dir="")) as prof:
        for i in range(10):
            prof.before_step(i)
            prof.after_step(i, i)
    assert fake_profiler == []


def test_profiler_window_exit_idempotent(fake_profiler):
    from sat_tpu.runtime import ProfilerWindow

    w = ProfilerWindow(_window_config(profile_start_step=0))
    w.before_step(0)
    w.after_step(0, "s")
    w.__exit__()
    w.__exit__()  # second close is a no-op, not a double stop_trace
    assert [c[0] for c in fake_profiler].count("stop") == 1


def test_profiler_window_start_collision_degrades_and_never_stops(
    fake_profiler, monkeypatch, capsys
):
    """--profile_dir alongside an already-live trace (e.g. an outer
    jax.profiler session next to --trace_export): start_trace raises.
    The window must (a) not take the run down, (b) not retry the open on
    every later step, and (c) never issue the stop_trace that would
    close the OUTER trace."""
    import jax

    from sat_tpu.runtime import ProfilerWindow

    calls = []

    def boom(d):
        calls.append(("start", d))
        raise RuntimeError("Only one profile may be run at a time.")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with ProfilerWindow(_window_config(profile_start_step=0)) as prof:
        for i in range(10):
            prof.before_step(i)
            prof.after_step(i, i)
    assert calls == [("start", "/tmp/prof")]       # opened once, not per step
    assert ("stop",) not in fake_profiler          # outer trace left alone
    assert ("sync", 0) not in fake_profiler        # no close sync either
    assert "start_trace failed" in capsys.readouterr().err


def test_profiler_window_stop_failure_degrades_and_stays_closed(
    fake_profiler, monkeypatch, capsys
):
    """stop_trace raising (the trace was stopped under us) must not
    propagate into the train loop, and __exit__ must not try a second
    stop afterwards."""
    import jax

    from sat_tpu.runtime import ProfilerWindow

    stops = []

    def boom():
        stops.append("stop")
        raise RuntimeError("No profile started")

    monkeypatch.setattr(jax.profiler, "stop_trace", boom)
    with ProfilerWindow(_window_config(profile_start_step=0)) as prof:
        for i in range(5):
            prof.before_step(i)
            prof.after_step(i, i)   # window closes (and fails) at step 2
    assert stops == ["stop"]        # __exit__ saw a closed window: no retry
    assert "stop_trace failed" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# crc32c vectorization (satellite: bitwise parity with the scalar oracle)
# ---------------------------------------------------------------------------


def test_crc32c_vector_matches_scalar_oracle():
    from sat_tpu.utils.summary import _crc32c_scalar, crc32c

    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 255, 256, 4095, 4096, 4097, 8192, 65536, 65537, 200001):
        data = rng.integers(0, 256, n, np.uint8).tobytes()
        assert crc32c(data) == _crc32c_scalar(data) ^ 0xFFFFFFFF, n


def test_crc32c_known_vectors():
    from sat_tpu.utils.summary import crc32c

    # RFC 3720 appendix B.4 test vectors (Castagnoli)
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    assert crc32c(bytes(range(32))) == 0x46DD794E
    # and above the vector threshold: all-zero / patterned payloads
    assert crc32c(b"\x00" * 8192) == (
        __import__("sat_tpu.utils.summary", fromlist=["_crc32c_scalar"])
        ._crc32c_scalar(b"\x00" * 8192)
        ^ 0xFFFFFFFF
    )


def test_masked_crc_framing_unchanged():
    from sat_tpu.utils.summary import _masked_crc

    # the TFRecord mask of a known crc must be stable across the
    # vectorization (an 8-byte length header exercises the scalar path)
    import struct

    header = struct.pack("<Q", 24)
    assert _masked_crc(header) == _masked_crc(header)


# ---------------------------------------------------------------------------
# config / CLI wiring
# ---------------------------------------------------------------------------


def test_cli_telemetry_flags():
    from sat_tpu.cli import build_config

    c, _ = build_config(["--phase", "train"])
    assert c.telemetry is False  # off by default
    c, _ = build_config([
        "--phase", "train", "--telemetry",
        "--heartbeat_interval", "2.5", "--trace_export", "/tmp/t.json",
    ])
    assert c.telemetry is True
    assert c.heartbeat_interval == 2.5
    assert c.trace_export == "/tmp/t.json"


def test_config_validates_telemetry_knobs():
    from sat_tpu.config import Config

    with pytest.raises(ValueError, match="heartbeat_interval"):
        Config(heartbeat_interval=-1)
    with pytest.raises(ValueError, match="telemetry_buffer"):
        Config(telemetry_buffer=0)


def test_bench_telemetry_meets_overhead_bar(tmp_path):
    """The bench must run without jax, emit the BENCH JSON contract, and
    pass its own 0.5% gate."""
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "bench_telemetry.py")
    proc = subprocess.run(
        [sys.executable, script, "--iters", "5000",
         "--workdir", str(tmp_path)],
        capture_output=True, text=True, timeout=120,
        env={k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"},
    )
    assert proc.returncode == 0, proc.stderr
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "telemetry_hot_path_overhead"
    assert row["unit"] == "%_of_step"
    assert row["value"] <= row["vs_baseline"] == 0.5


# ---------------------------------------------------------------------------
# end-to-end: tier-1 CPU train run with --telemetry (acceptance criteria)
# ---------------------------------------------------------------------------

SMALL_MODEL = dict(
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    save_period=3,
    log_every=2,
    num_epochs=1,
    num_data_workers=2,
)


@pytest.fixture(scope="module")
def telemetry_run(coco_fixture, tmp_path_factory):
    """One telemetry-on train run shared by the artifact assertions."""
    from sat_tpu import runtime

    tmp = tmp_path_factory.mktemp("telemetry_run")
    config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=str(tmp / "models"),
        summary_dir=str(tmp / "summary"),
        telemetry=True,
        heartbeat_interval=0.1,
        telemetry_buffer=4096,
    )
    t0 = time.perf_counter()
    state = runtime.train(config)
    wall_s = time.perf_counter() - t0
    telemetry.disable()
    return config, state, wall_s


def test_e2e_trace_json_is_perfetto_loadable(telemetry_run):
    config, state, _ = telemetry_run
    trace = os.path.join(config.summary_dir, "telemetry", "trace.json")
    doc = json.load(open(trace))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert xs, "no span events in the trace"
    by_name = {e["name"] for e in xs}
    assert {"train/step", "train/data_wait", "train/dispatch",
            "train/log_sync"} <= by_name
    for e in xs:
        assert e["dur"] >= 0 and isinstance(e["ts"], float)
    assert doc["otherData"]["anchor_unix"] > 0


def test_e2e_heartbeat_is_valid_and_final(telemetry_run):
    config, state, _ = telemetry_run
    hb = json.load(
        open(os.path.join(config.summary_dir, "telemetry", "heartbeat.json"))
    )
    assert hb["step"] == int(state.step) == 6
    assert hb["phase"] == "train"
    assert hb["backend"] == "cpu"
    assert hb["interval_s"] == pytest.approx(0.1)
    assert hb["seq"] >= 1
    assert hb["last_checkpoint_step"] == 6
    assert hb["last_checkpoint_age_s"] is not None
    assert hb["rss_mb"] > 0


def test_e2e_breakdown_phase_sum_within_5pct_of_wall(telemetry_run):
    config, state, _ = telemetry_run
    report = json.load(
        open(os.path.join(config.summary_dir, "telemetry", "breakdown.json"))
    )
    assert report["steps"] == 6
    # phase sum reconstructs the measured step wall time (acceptance bar:
    # within 5%; the residual "other" phase makes it exact by construction)
    assert report["phase_total_s"] == pytest.approx(
        report["wall_s"], rel=0.05
    )
    assert "train/dispatch" in report["phases"]
    assert report["phases"]["train/dispatch"]["count"] == 6


def test_e2e_telemetry_jsonl_rows_at_log_boundaries(telemetry_run):
    config, state, _ = telemetry_run
    path = os.path.join(config.summary_dir, "telemetry", "telemetry.jsonl")
    rows = [json.loads(l) for l in open(path)]
    # log_every=2 over 6 steps -> boundaries at 2, 4, 6
    assert [r["step"] for r in rows] == [2, 4, 6]
    for r in rows:
        assert r["run_id"] == telemetry.run_id()
        assert "train/step" in r["spans"] or r["step"] == 2


def test_e2e_metrics_jsonl_stamps_join_with_telemetry(telemetry_run):
    config, state, _ = telemetry_run
    rows = [
        json.loads(l)
        for l in open(os.path.join(config.summary_dir, "metrics.jsonl"))
    ]
    assert all(r["run_id"] == telemetry.run_id() for r in rows)
    mono = [r["mono_ns"] for r in rows]
    assert mono == sorted(mono)


def test_e2e_compile_accounting_counted(telemetry_run):
    """jax.monitoring feeds compile events into the heartbeat/trace."""
    config, state, _ = telemetry_run
    hb = json.load(
        open(os.path.join(config.summary_dir, "telemetry", "heartbeat.json"))
    )
    # the tiny model still compiles at least the train step
    assert hb["compile_count"] >= 1
    assert hb["compile_seconds"] > 0


def test_telemetry_off_leaves_no_artifacts(coco_fixture, tmp_path):
    """Default (off) runs must neither record spans nor write telemetry
    artifacts — the bitwise-unchanged guarantee rides on this."""
    from sat_tpu import runtime

    config = coco_fixture["config"].replace(
        **SMALL_MODEL,
        save_dir=str(tmp_path / "models"),
        summary_dir=str(tmp_path / "summary"),
        max_steps=2,
    )
    runtime.train(config)
    assert not os.path.exists(os.path.join(config.summary_dir, "telemetry"))
    assert isinstance(telemetry.get(), NullTelemetry)
