from .beam_search import BeamResult, beam_search, beam_search_jit, greedy_decode

__all__ = ["BeamResult", "beam_search", "beam_search_jit", "greedy_decode"]
