from .layers import (
    BatchNorm,
    Conv,
    Dense,
    conv_kernel_init,
    dropout,
    fc_kernel_init,
    max_pool2d,
    regularization_loss,
)

__all__ = [
    "BatchNorm",
    "Conv",
    "Dense",
    "conv_kernel_init",
    "dropout",
    "fc_kernel_init",
    "max_pool2d",
    "regularization_loss",
]
