#!/usr/bin/env python
"""One-command summarizer for black-box postmortem bundles.

``telemetry/blackbox.py`` drops a ``postmortem_<run_id>/`` directory on
every abnormal exit path (watchdog 86, data-corruption 87, sentinel
trip, uncaught exception, SIGTERM mid-checkpoint).  This script turns
that directory back into an incident narrative: what the run was doing
(span tail + journal timeline), what it looked like (final counters and
gauges, fleet view), and a probable cause keyed on the exit code and the
last recorded phase — the part a paged human wants first.

Usage::

    python scripts/analyze_postmortem.py <bundle-or-telemetry-dir> [--json]
    python scripts/analyze_postmortem.py run/telemetry   # newest bundle

``--json`` emits a machine-readable summary (CI and the chaos campaign
assert on ``probable_cause`` / ``wedged_phase``).  Exit codes: 0 =
summarized, 1 = no bundle found / unreadable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

WATCHDOG_RC = 86
CORRUPTION_RC = 87


def _find_bundle(path: str) -> Optional[str]:
    """``path`` itself when it holds a manifest, else the newest
    ``postmortem_*`` directory below it."""
    if os.path.isfile(os.path.join(path, "manifest.json")):
        return path
    candidates = sorted(
        glob.glob(os.path.join(path, "postmortem_*")),
        key=lambda p: os.path.getmtime(p) if os.path.isdir(p) else 0,
    )
    return candidates[-1] if candidates else None


def _read_json(bundle: str, name: str):
    try:
        with open(os.path.join(bundle, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _read_jsonl(path: str) -> List[Dict]:
    out: List[Dict] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn line — expected at the crash edge
    except OSError:
        pass
    return out


def _ring_records(bundle: str) -> List[Dict]:
    records: List[Dict] = []
    for seg in sorted(glob.glob(os.path.join(bundle, "blackbox", "seg_*.jsonl"))):
        records.extend(_read_jsonl(seg))
    records.sort(key=lambda r: r.get("t", 0))
    return records


def probable_cause(manifest: Dict, bundle: str) -> Dict:
    """The heuristics: exit code first, then reason, then the last phase
    the span tail recorded.  Returns the machine summary dict."""
    reason = manifest.get("reason", "unknown")
    rc = manifest.get("exit_code")
    wedged = manifest.get("phase") or manifest.get("last_phase")
    out: Dict = {
        "reason": reason,
        "exit_code": rc,
        "last_phase": manifest.get("last_phase"),
        "wedged_phase": None,
        "probable_cause": f"abnormal exit ({reason})",
        "evidence": [],
    }
    if rc == WATCHDOG_RC or reason == "watchdog_wedge":
        out["wedged_phase"] = wedged
        over = manifest.get("overdue_s")
        out["probable_cause"] = (
            f"run wedged in phase '{wedged}'"
            + (f" ({over}s past its deadline)" if over is not None else "")
            + " — the watchdog aborted it (exit 86)"
        )
        if os.path.isfile(os.path.join(bundle, "watchdog_stacks.txt")):
            out["evidence"].append(
                "watchdog_stacks.txt holds the all-thread stacks at dump time"
            )
    elif rc == CORRUPTION_RC or reason == "systemic_corruption":
        rows = _read_jsonl(os.path.join(bundle, "quarantine.jsonl"))
        shards = sorted({r.get("shard", "?") for r in rows if isinstance(r, dict)})
        out["probable_cause"] = (
            "systemic input-data corruption — the quarantine ceiling "
            "tripped (exit 87); restarting will NOT help, repair the data"
        )
        if rows:
            out["evidence"].append(
                f"quarantine.jsonl tail: {len(rows)} records, shards {shards[:5]}"
            )
    elif reason == "anomaly_rollback":
        out["probable_cause"] = (
            "non-finite/spiking metrics tripped the anomaly sentinel "
            f"at step {manifest.get('step')} — training rolled back to "
            "LAST_GOOD"
        )
        if manifest.get("reason_detail") or manifest.get("reason"):
            out["evidence"].append(f"sentinel: {manifest.get('reason')}")
    elif reason == "sigterm_during_checkpoint":
        final = manifest.get("final_checkpoint") or ""
        out["probable_cause"] = (
            f"{manifest.get('signal', 'SIGTERM')} during the final "
            "checkpoint window — "
            + (
                f"the final write landed ({final})"
                if final
                else "no final checkpoint path was recorded"
            )
        )
    elif reason == "uncaught_exception":
        out["probable_cause"] = (
            f"uncaught exception: {manifest.get('error', '<unrecorded>')}"
        )
    elif reason in ("checkpoint_write_failed", "simulated_preemption"):
        out["probable_cause"] = (
            f"{reason.replace('_', ' ')}: {manifest.get('error', '')}".strip()
        )
    fleet = _read_json(bundle, "fleet.json")
    if fleet:
        verdict = fleet.get("straggler") or {}
        if verdict.get("verdict"):
            out["straggler"] = {
                "process_index": verdict.get("process_index"),
                "host": verdict.get("host"),
                "skew": verdict.get("skew"),
            }
            out["evidence"].append(
                f"fleet.json names p{verdict.get('process_index')} "
                f"({verdict.get('host')}) as a straggler "
                f"({verdict.get('skew')}x the fleet median)"
            )
    flooder = _flooding_tenant(_read_json(bundle, "state.json") or {})
    if flooder:
        out["flooding_tenant"] = flooder["tenant"]
        out["evidence"].append(
            f"tenant {flooder['tenant']!r} dominates the shed counters: "
            f"{flooder['shed']} shed of {flooder['requests']} requests "
            f"({flooder['shed_share']:.0%} of all tenant sheds) — "
            "probable flooding tenant"
        )
    return out


def _flooding_tenant(state: Dict) -> Optional[Dict]:
    """Name the tenant behind an overload from the per-tenant counters
    the multi-tenant serve/route planes emit (``serve/tenant_<t>_shed``
    etc.).  Returns the tenant holding the majority of tenant-scoped
    sheds, or None when the run was single-tenant / nothing shed."""
    counters = state.get("counters") or {}
    shed: Dict[str, int] = {}
    requests: Dict[str, int] = {}
    for key, value in counters.items():
        for prefix in ("serve/tenant_", "route/tenant_"):
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix):]
            name, _, kind = rest.rpartition("_")
            if not name or name == "unknown":
                continue
            if kind == "shed":
                shed[name] = shed.get(name, 0) + int(value)
            elif kind == "requests":
                requests[name] = requests.get(name, 0) + int(value)
    total_shed = sum(shed.values())
    if total_shed <= 0:
        return None
    worst = max(shed, key=shed.get)
    share = shed[worst] / total_shed
    if share < 0.5:
        return None  # no single tenant dominates — not a flood story
    return {
        "tenant": worst,
        "shed": shed[worst],
        "requests": requests.get(worst, 0),
        "shed_share": share,
    }


def _fmt_ts(t: float, base: float) -> str:
    return f"t+{t - base:8.3f}s"


def summarize(bundle: str) -> Dict:
    manifest = _read_json(bundle, "manifest.json") or {}
    summary = probable_cause(manifest, bundle)
    summary["bundle"] = bundle
    summary["run_id"] = manifest.get("run_id")
    summary["time_unix"] = manifest.get("time_unix")
    return summary


def print_report(bundle: str, summary: Dict) -> None:
    manifest = _read_json(bundle, "manifest.json") or {}
    print(f"postmortem bundle: {bundle}")
    print(
        f"  run {manifest.get('run_id')} — reason={summary['reason']} "
        f"exit_code={summary['exit_code']}"
    )
    print(f"\nPROBABLE CAUSE: {summary['probable_cause']}")
    for ev in summary.get("evidence", []):
        print(f"  * {ev}")

    records = _ring_records(bundle)
    events = [r for r in records if r.get("kind") == "event"]
    if records:
        base = records[0].get("t", 0.0)
        print(f"\ntimeline (black-box ring, {len(records)} records):")
        shown = events[-12:] if events else records[-12:]
        skip = ("t", "mono_ns", "kind", "event", "counters", "gauges")
        for r in shown:
            desc = r.get("event", r.get("kind", "?"))
            detail = " ".join(
                f"{k}={v}" for k, v in r.items() if k not in skip
            )
            print(f"  {_fmt_ts(r.get('t', base), base)}  {desc}  {detail}")
        journals = [r for r in records if r.get("kind") == "snapshot"]
        if journals:
            print(
                f"  last journal: step={journals[-1].get('step')} "
                f"(of {len(journals)} snapshots retained)"
            )

    spans = _read_json(bundle, "spans_tail.json") or []
    if spans:
        print(f"\nfinal {manifest.get('span_tail_s', 30)}s of host spans "
              f"({len(spans)} spans, most recent last):")
        for s in spans[-10:]:
            print(
                f"  {s.get('name', '?'):24s} {s.get('dur_ms', 0):10.3f} ms"
            )

    state = _read_json(bundle, "state.json") or {}
    gauges = state.get("gauges", {})
    if gauges:
        interesting = {
            k: v
            for k, v in sorted(gauges.items())
            if k.split("/")[0]
            in ("train", "fleet", "watchdog", "data", "slo", "supervisor",
                "serve", "route")
        }
        if interesting:
            print("\nfinal gauges:")
            for k, v in list(interesting.items())[:20]:
                print(f"  {k} = {v}")

    present = sorted(os.listdir(bundle))
    print(f"\nbundle contents: {', '.join(present)}")


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="bundle dir, or a telemetry dir to search")
    ap.add_argument(
        "--json",
        action="store_true",
        help="machine-readable summary on stdout (CI asserts on this)",
    )
    args = ap.parse_args(argv)

    bundle = _find_bundle(args.path)
    if bundle is None:
        print(
            f"analyze_postmortem: no postmortem_* bundle under {args.path}",
            file=sys.stderr,
        )
        return 1
    summary = summarize(bundle)
    if args.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print_report(bundle, summary)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
