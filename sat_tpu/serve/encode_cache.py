"""Device-resident, content-addressed LRU of encoder feature grids.

The conv encoder is the single biggest serve-path cost and a pure
function of the preprocessed image — yet every duplicate image pays it
again.  PR 18's live ``EncodeCacheSketch`` probe measured a 0.77
would-be hit ratio under Zipf traffic, so this module closes the loop:
a fixed-geometry HBM ring of ``[rows, N, D]`` context grids, keyed by
``(image crc32c, param fingerprint, quant mode)``, with host-side LRU
bookkeeping and two AOT-warmed device programs per dispatch width —

* **gather** ``store[idx] -> [w, N, D]`` feeds the existing seed/beam
  executables the exact bits a fresh encode would have produced (rows
  are written once and read verbatim, so hit-path captions are bitwise
  identical to the encode path);
* **insert** ``store.at[idx].set(ctx)`` scatters a miss lane's freshly
  encoded rows into their assigned ring rows (pad rows land in a
  scratch row nobody reads).

Both are compiled at warmup for every dispatch width the server can
see (the bucket ladder in batch mode, the admission lanes in
continuous mode), so steady state never recompiles — the same
zero-recompile contract as the rest of the serve path.

Single-flight coalescing falls out of the planning discipline: one
batcher/pool thread owns all plans, a plan dedupes repeated keys within
its chunk (one encode, N seeds), and the host map is updated at plan
time, so N concurrent requests for one image trigger exactly one
encode however they land across chunks.

Capacity comes from ``--encode_cache_mb``; ``--encode_cache off``
never constructs this class, keeping serving bit-identical to the
pre-cache path with zero compile delta (pinned by
tests/test_encode_cache.py).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np


class CachePlan(object):
    """One chunk's resolved lookup: a ring row per request, plus the
    unique misses that must be encoded (first occurrence wins; repeats
    within the chunk are coalesced onto the same row)."""

    __slots__ = ("rows", "miss_keys", "miss_rows", "miss_pos", "hits",
                 "coalesced")

    def __init__(self) -> None:
        self.rows: List[int] = []       # ring row per chunk item
        self.miss_keys: List[Hashable] = []  # unique keys to encode
        self.miss_rows: List[int] = []  # ring row per unique miss
        self.miss_pos: List[int] = []   # chunk position of each first miss
        self.hits = 0
        self.coalesced = 0

    @property
    def n_miss(self) -> int:
        return len(self.miss_keys)


class EncodeCache(object):
    """Fixed-geometry HBM ring + host LRU map + AOT gather/insert.

    Device geometry is decided once at warmup (``ensure_store``) from
    the context-row aval and the MB budget, and never changes; the host
    map is guarded by a small lock because ``/stats`` scrapes read it
    from HTTP threads while the single batcher thread plans against it.
    """

    def __init__(self, capacity_mb: int, tel=None) -> None:
        self.capacity_mb = int(capacity_mb)
        self._tel = tel
        self._lock = threading.Lock()
        self._store = None          # device [rows+1, N, D]; row `rows` = scratch
        self.rows = 0               # usable ring rows (excludes scratch)
        self.row_shape: Optional[Tuple[int, ...]] = None
        self.row_dtype = None
        self.row_bytes = 0
        self._map: "OrderedDict[Hashable, int]" = OrderedDict()
        self._free: List[int] = []
        self._gather_execs: Dict[int, Any] = {}
        self._insert_execs: Dict[int, Any] = {}
        # lifetime counters (the /stats cache block; tel counters mirror
        # them so /metrics exports ride promtext for free)
        self.hits = 0
        self.misses = 0
        self.coalesced = 0
        self.evictions = 0
        self.inserts = 0
        self.flushes = 0

    # -- warmup (called from engine/pool warmup, before ready) -------------

    def ensure_store(
        self, row_shape: Sequence[int], row_dtype, min_rows: int
    ) -> None:
        """Allocate the ring once: ``capacity_mb`` worth of rows, floored
        at ``min_rows + 1`` so one dispatch-width chunk of distinct
        misses can always be planned without evicting a row the same
        plan just pinned.  Idempotent for a matching row aval (the
        re-warm path); a different aval means different params geometry
        and raises — the cache must be rebuilt, not silently reshaped."""
        import jax.numpy as jnp

        row_shape = tuple(int(d) for d in row_shape)
        if self._store is not None:
            if row_shape != self.row_shape or np.dtype(row_dtype) != np.dtype(
                self.row_dtype
            ):
                raise ValueError(
                    f"encode cache store is {self.row_shape}/{self.row_dtype} "
                    f"but warmup now wants {row_shape}/{row_dtype}"
                )
            return
        self.row_shape = row_shape
        self.row_dtype = np.dtype(row_dtype)
        self.row_bytes = int(
            np.prod(row_shape, dtype=np.int64) * self.row_dtype.itemsize
        )
        budget_rows = int(self.capacity_mb * 1e6) // max(1, self.row_bytes)
        self.rows = max(int(min_rows) + 1, budget_rows)
        self._store = jnp.zeros(
            (self.rows + 1,) + row_shape, self.row_dtype
        )
        self._free = list(range(self.rows))
        if self._tel is not None:
            self._tel.gauge("serve/cache_rows", self.rows)
            self._tel.gauge(
                "serve/cache_capacity_bytes", self.rows * self.row_bytes
            )

    def warm(self, widths: Sequence[int]) -> None:
        """AOT-compile gather + insert for every dispatch width; called
        after ``ensure_store``.  ``jit.lower(...).compile()`` like every
        other serve program, so the executables only ever run at their
        compiled shapes and steady state cannot recompile."""
        import jax

        if self._store is None:
            raise RuntimeError("EncodeCache.warm before ensure_store")
        store_sd = jax.ShapeDtypeStruct(
            (self.rows + 1,) + self.row_shape, self.row_dtype
        )

        def gather_fn(store, idx):
            return store[idx]

        def insert_fn(store, ctx, idx):
            # duplicate scratch indices are fine: scratch is write-only
            return store.at[idx].set(ctx)

        gather_jit = jax.jit(gather_fn)
        # the store is donated so an insert rewrites the ring in place
        # instead of copying capacity_mb per miss chunk (a no-op warning
        # on backends without donation, e.g. the CPU test container)
        insert_jit = jax.jit(insert_fn, donate_argnums=0)
        for w in widths:
            w = int(w)
            if w in self._gather_execs:
                continue
            idx_sd = jax.ShapeDtypeStruct((w,), np.int32)
            ctx_sd = jax.ShapeDtypeStruct(
                (w,) + self.row_shape, self.row_dtype
            )
            self._gather_execs[w] = gather_jit.lower(
                store_sd, idx_sd
            ).compile()
            self._insert_execs[w] = insert_jit.lower(
                store_sd, ctx_sd, idx_sd
            ).compile()

    @property
    def warm_widths(self) -> Tuple[int, ...]:
        return tuple(sorted(self._gather_execs))

    # -- planning (single batcher/pool thread) -----------------------------

    def plan(self, keys: Sequence[Hashable]) -> CachePlan:
        """Resolve one chunk of content keys to ring rows, assigning LRU
        rows to the unique misses (the single-flight dedup: a key
        repeated within the chunk coalesces onto its first row).  The
        map is updated NOW — before the encode lands — because one
        thread owns all plans, so a later chunk referencing the same
        key must hit, not re-encode.  Callers that fail the dispatch
        must ``drop`` the planned miss keys."""
        plan = CachePlan()
        with self._lock:
            pinned = set()
            seen_miss: Dict[Hashable, int] = {}
            for i, key in enumerate(keys):
                row = self._map.get(key)
                if row is not None and key not in seen_miss:
                    self._map.move_to_end(key)
                    plan.hits += 1
                    plan.rows.append(row)
                    pinned.add(row)
                    continue
                if key in seen_miss:
                    plan.coalesced += 1
                    plan.rows.append(plan.miss_rows[seen_miss[key]])
                    continue
                row = self._alloc_row(pinned)
                seen_miss[key] = len(plan.miss_keys)
                plan.miss_keys.append(key)
                plan.miss_rows.append(row)
                plan.miss_pos.append(i)
                self._map[key] = row
                self._map.move_to_end(key)
                pinned.add(row)
                plan.rows.append(row)
            self.hits += plan.hits
            self.misses += plan.n_miss
            self.coalesced += plan.coalesced
        if self._tel is not None:
            if plan.hits:
                self._tel.count("serve/cache_hits", plan.hits)
            if plan.n_miss:
                self._tel.count("serve/cache_misses", plan.n_miss)
            if plan.coalesced:
                self._tel.count("serve/cache_coalesced", plan.coalesced)
        return plan

    def _alloc_row(self, pinned) -> int:
        """A free row, else evict the least-recently-used entry whose row
        is not pinned by the current plan (``ensure_store`` floors the
        ring at one row past the widest chunk, so one always exists)."""
        if self._free:
            return self._free.pop()
        for key, row in self._map.items():  # oldest first
            if row not in pinned:
                del self._map[key]
                self.evictions += 1
                if self._tel is not None:
                    self._tel.count("serve/cache_evictions")
                return row
        raise RuntimeError(
            "encode cache has no evictable row (ring smaller than one "
            "dispatch chunk — ensure_store floor violated)"
        )

    def drop(self, keys: Sequence[Hashable]) -> None:
        """Un-plan miss keys whose encode/insert failed: their rows hold
        garbage, so the entries must not serve hits."""
        with self._lock:
            for key in keys:
                row = self._map.pop(key, None)
                if row is not None:
                    self._free.append(row)

    # -- device programs ---------------------------------------------------

    def insert(self, width: int, lane_ctx, rows: Sequence[int]):
        """Scatter a freshly encoded ``[width, N, D]`` lane into the ring
        at ``rows`` (pad lane rows land in the scratch row).  Rebinding
        the donated store keeps device-stream ordering: any gather
        dispatched after this insert sees the new rows."""
        import jax

        idx = np.full((int(width),), self.rows, np.int32)
        idx[: len(rows)] = rows
        self._store = self._insert_execs[int(width)](
            self._store, lane_ctx, jax.device_put(idx)
        )
        self.inserts += len(rows)

    def gather(self, width: int, rows: Sequence[int]):
        """``[width, N, D]`` of ring rows (pad positions read the scratch
        row — beam search is row-independent, so scratch garbage never
        perturbs real rows, exactly like zero-padded encode lanes)."""
        import jax

        idx = np.full((int(width),), self.rows, np.int32)
        idx[: len(rows)] = rows
        return self._gather_execs[int(width)](
            self._store, jax.device_put(idx)
        )

    # -- invalidation (lifecycle/quant coherence) --------------------------

    def flush(self) -> None:
        """Forget every entry (model promote/rollback): keys carry the
        param fingerprint so stale entries could never hit anyway, but
        flushing returns their rows to the free list immediately instead
        of waiting out LRU churn.  Device rows become unreferenced
        garbage — no device work."""
        with self._lock:
            self._map.clear()
            self._free = list(range(self.rows))
            self.flushes += 1
        if self._tel is not None:
            self._tel.count("serve/cache_flushes")

    # -- observability -----------------------------------------------------

    @property
    def lookups(self) -> int:
        return self.hits + self.misses + self.coalesced

    def hit_ratio(self) -> float:
        """Fraction of lookups that skipped the encode lane — coalesced
        requests rode another request's single-flight encode, so they
        count as hits (matching what the would-hit sketch observes)."""
        n = self.lookups
        return (self.hits + self.coalesced) / n if n else 0.0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            entries = len(self._map)
        return {
            "entries": entries,
            "rows": self.rows,
            "bytes": entries * self.row_bytes,
            "capacity_bytes": self.rows * self.row_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "coalesced": self.coalesced,
            "evictions": self.evictions,
            "inserts": self.inserts,
            "flushes": self.flushes,
            "hit_ratio": round(self.hit_ratio(), 4),
            "warm_widths": list(self.warm_widths),
        }
