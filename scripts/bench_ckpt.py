"""Resilience-layer cost accounting: checkpoint lineage + sentinel overhead.

docs/RESILIENCE.md claims the subsystem is effectively free on the hot
path: the anomaly sentinel reads host floats the ``log_every`` fetch
already paid for, the fault-injection hooks are inert compares, and the
lineage tail (sha256 sidecar + post-write verify + retention) runs on the
async writer's thread, overlapped with training.  This bench puts numbers
on each piece —

* ``save``: atomic npz write of a synthetic flat checkpoint (``--mb``
  controls the Adam-slots-included size) — the work the async worker does.
* ``lineage``: sidecar hash + post-write verify + LAST_GOOD advance —
  the tail this PR added to every save.
* ``sentinel``/``hooks``: per-step host-side cost of an armed
  AnomalySentinel check and the inert ``FaultPlan``/``consume_io_fault``
  compares, expressed against a ``--step-ms`` device step.

Prints BENCH-contract JSON lines on stdout ({"metric", "value", "unit",
"vs_baseline", ...extras}).  ``value`` is the hot-path overhead of the
resilience layer in percent of a step (< 2 is the acceptance bar; the
lineage tail is reported separately because the async writer hides it).
No jax import anywhere: this is a pure host-side measurement and must
never wedge on an unreachable accelerator backend.

Usage: python scripts/bench_ckpt.py [--mb 64] [--step-ms 30]
       [--iters 20000] [--workdir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from sat_tpu.resilience import lineage
from sat_tpu.resilience.faultinject import FaultPlan, consume_io_fault
from sat_tpu.resilience.retry import retry_io
from sat_tpu.resilience.sentinel import AnomalySentinel
from sat_tpu.utils.fileio import atomic_write

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_ckpt +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _fake_flat(total_mb: float, seed: int = 0) -> dict:
    """A flat checkpoint dict shaped like a real run: a few big kernels,
    many small biases, float32 throughout (params + 2 Adam slots is what
    makes real checkpoints ~3x the param bytes)."""
    rng = np.random.default_rng(seed)
    total = int(total_mb * (1 << 20) // 4)
    flat, i = {}, 0
    while total > 0:
        n = min(total, max(1024, total // 3))
        flat[f"leaf_{i}"] = rng.normal(size=(n,)).astype(np.float32)
        total -= n
        i += 1
    flat["global_step"] = np.asarray(1000, np.int64)
    return flat


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mb", type=float, default=64.0,
                    help="synthetic checkpoint size (params + Adam slots)")
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="device step time the overheads are judged against")
    ap.add_argument("--iters", type=int, default=20000,
                    help="hot-path hook iterations (timed per-call)")
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_ckpt_")
    made_workdir = args.workdir is None
    save_dir = os.path.join(workdir, "ckpt")
    os.makedirs(save_dir, exist_ok=True)
    try:
        flat = _fake_flat(args.mb)
        nbytes = sum(v.nbytes for v in flat.values())
        log(f"synthetic checkpoint: {len(flat)} leaves, "
            f"{nbytes / (1 << 20):.1f} MB")

        # --- the async worker's write, then the lineage tail ------------
        path = os.path.join(save_dir, "1000.npz")
        t0 = time.perf_counter()
        retry_io(
            lambda: atomic_write(path, "wb", lambda f: np.savez(f, **flat)),
            desc=f"write checkpoint {path}",
        )
        save_ms = 1e3 * (time.perf_counter() - t0)
        t0 = time.perf_counter()
        lineage.write_sidecar(path)
        ok = lineage.finalize_save(save_dir, path, 1000, healthy=True, keep=3)
        lineage_ms = 1e3 * (time.perf_counter() - t0)
        assert ok, "post-write verify failed on a freshly written file"
        log(f"npz write {save_ms:.1f} ms, lineage tail {lineage_ms:.1f} ms "
            f"(sha256 + verify + retention)")

        t0 = time.perf_counter()
        restorable = lineage.last_good_checkpoint(save_dir)
        walk_ms = 1e3 * (time.perf_counter() - t0)
        assert restorable and restorable.endswith("1000.npz")

        # --- hot-path hooks: what EVERY step pays -----------------------
        sentinel = AnomalySentinel("warn", spike_factor=10.0)
        metrics = {"loss": 2.0, "accuracy": 0.5}
        plan = FaultPlan.from_env()
        assert plan.inert, "SAT_FI_* leaked into the bench environment"

        t0 = time.perf_counter()
        for step in range(args.iters):
            sentinel.check(step, metrics)
        sentinel_us = 1e6 * (time.perf_counter() - t0) / args.iters

        t0 = time.perf_counter()
        for step in range(args.iters):
            plan.maybe_kill(step)
            consume_io_fault("hot-path probe")
        hooks_us = 1e6 * (time.perf_counter() - t0) / args.iters

        per_step_ms = (sentinel_us + hooks_us) / 1e3
        overhead_pct = 100.0 * per_step_ms / args.step_ms
        log(f"sentinel check {sentinel_us:.2f} us, inert hooks "
            f"{hooks_us:.2f} us -> {overhead_pct:.4f}% of a "
            f"{args.step_ms:.0f} ms step")

        # the lineage tail runs on the writer thread; amortize it over a
        # save_period of 1000 steps to show the honest worst case where
        # the host core is shared (single-core hosts DO pay it)
        lineage_amortized_pct = 100.0 * (lineage_ms / 1000.0) / args.step_ms

        result = {
            "metric": "resilience_hot_path_overhead",
            "value": round(overhead_pct, 4),
            "unit": "%_of_step",
            "vs_baseline": 2.0,  # the acceptance bar (ISSUE: < 2%)
            "sentinel_us_per_step": round(sentinel_us, 3),
            "inert_hooks_us_per_step": round(hooks_us, 3),
            "step_ms_assumed": args.step_ms,
            "ckpt_mb": round(nbytes / (1 << 20), 1),
            "npz_write_ms": round(save_ms, 1),
            "lineage_tail_ms": round(lineage_ms, 1),
            "lineage_amortized_pct_at_save_period_1000":
                round(lineage_amortized_pct, 4),
            "last_good_walk_ms": round(walk_ms, 2),
        }
        from sat_tpu.telemetry import bench_stamp

        result.update(bench_stamp())
        print(json.dumps(result), flush=True)
        return 0 if overhead_pct < 2.0 else 1
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
