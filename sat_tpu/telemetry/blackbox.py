"""Black-box flight recorder: a bounded on-disk ring + postmortem bundles.

Logs answer "what happened" only when somebody thought to log it; the
black box answers "what were the last 30 seconds like" for runs that die
without warning.  Two pieces:

* :class:`BlackBox` — a bounded on-disk ring of rotating JSONL segments
  (``<dir>/seg_000.jsonl`` ...).  The instrumented loops journal a
  counters/gauges snapshot per log boundary and one-line events on
  notable transitions (sentinel trips, SIGTERM during checkpoint); disk
  use is capped at ``segments * segment_bytes`` no matter how long the
  run lives.  Appends are plain ``O_APPEND`` writes — no fsync, no
  device syncs — and readers skip torn lines, so a process killed
  mid-write costs at most one record.

* :func:`dump_postmortem` — on any abnormal path (watchdog exit 86,
  data-corruption exit 87, non-finite sentinel trip, uncaught exception,
  SIGTERM mid-checkpoint) assemble ``postmortem_<run_id>/`` under the
  telemetry dir: manifest + probable-phase, last-N-seconds span tail,
  ring segments, counters/gauges, heartbeat + fleet history, watchdog
  stacks, quarantine-ledger / slo.jsonl / telemetry.jsonl tails,
  compile_report.json, and the config snapshot.  One directory a human
  (or ``scripts/analyze_postmortem.py``) can read cold.

Shutdown ordering is the subtle part: the watchdog aborts with
``os._exit`` (atexit never runs) and exception paths unwind ExitStacks
that stop exporters.  Every teardown therefore goes through ONE
registered finalizer chain (:func:`register_finalizer` /
:func:`run_finalizers` — idempotent flush-style callbacks), and
:func:`dump` flushes that chain *before* reading any file, so no path
can tear a buffer down between the crash and the bundle.  jax-free,
degrade-don't-raise throughout: a recorder failure warns once and never
takes the run down.
"""

from __future__ import annotations

import atexit
import glob
import json
import os
import shutil
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.fileio import atomic_write
from . import SCHEMA_VERSION, run_id


class BlackBox:
    """Bounded rotating-segment JSONL journal (the on-disk ring)."""

    def __init__(
        self,
        dir: str,
        tel,
        segment_bytes: int = 1 << 20,
        segments: int = 4,
    ) -> None:
        self.dir = dir
        self._tel = tel
        self.segment_bytes = max(4096, int(segment_bytes))
        self.segments = max(2, int(segments))
        self._lock = threading.Lock()
        self._warned = False
        self._idx = 0
        try:
            os.makedirs(self.dir, exist_ok=True)
            # continue the ring across a supervisor restart: resume on the
            # most recently touched segment so the previous incarnation's
            # tail survives until the ring genuinely wraps past it
            existing = sorted(glob.glob(os.path.join(self.dir, "seg_*.jsonl")))
            if existing:
                newest = max(existing, key=os.path.getmtime)
                self._idx = int(os.path.basename(newest)[4:-6])
        except (OSError, ValueError) as e:
            self._warn(f"init failed: {e}")

    def _segment_path(self, idx: int) -> str:
        return os.path.join(self.dir, f"seg_{idx:03d}.jsonl")

    # -- write side --------------------------------------------------------

    def append(self, kind: str, fields: Dict) -> None:
        """One journal line; rotates (and truncates the oldest segment)
        when the current segment is full.  Never raises."""
        record = {
            "t": round(time.time(), 3),
            "mono_ns": time.perf_counter_ns(),
            "kind": kind,
            **fields,
        }
        try:
            line = json.dumps(record) + "\n"
        except (TypeError, ValueError) as e:
            self._warn(f"unserializable record ({kind}): {e}")
            return
        try:
            with self._lock:
                path = self._segment_path(self._idx)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
                if size >= self.segment_bytes:
                    self._idx = (self._idx + 1) % self.segments
                    path = self._segment_path(self._idx)
                    open(path, "w").close()  # reclaim the oldest slot
                with open(path, "a") as f:
                    f.write(line)
        except OSError as e:
            self._warn(f"append failed: {e}")

    def journal(self, step: Optional[int] = None) -> None:
        """The per-log-boundary snapshot: step + counters + gauges."""
        self.append(
            "snapshot",
            {
                "step": step,
                "counters": self._tel.counters(),
                "gauges": self._tel.gauges(),
            },
        )

    def event(self, event: str, **fields) -> None:
        """A one-line notable transition (sentinel trip, SIGTERM, ...)."""
        self.append("event", {"event": event, **fields})

    def flush(self) -> None:
        """Finalizer-chain hook.  Appends hit the OS directly (no
        userspace buffer), so this is a checkpoint in the ordering
        contract rather than real work; it must stay idempotent."""

    # -- read side ---------------------------------------------------------

    def read_all(self) -> Tuple[List[Dict], int]:
        """(records sorted by wall time, torn-line count).  Torn or
        garbage lines — a process killed mid-append — are skipped."""
        records: List[Dict] = []
        torn = 0
        for path in sorted(glob.glob(os.path.join(self.dir, "seg_*.jsonl"))):
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                            if not isinstance(rec, dict):
                                raise ValueError("not an object")
                            records.append(rec)
                        except ValueError:
                            torn += 1
            except OSError:
                continue
        records.sort(key=lambda r: r.get("t", 0))
        return records, torn

    def span_tail(self, seconds: float = 30.0) -> List[Dict]:
        """The recorder's span-ring entries from the last ``seconds``,
        with wall-clock start times (anchor_unix + monotonic offset)."""
        tel = self._tel
        names, ids, t0s, durs, tids = tel.spans_snapshot()
        if len(ids) == 0:
            return []
        cutoff = time.perf_counter_ns() - int(seconds * 1e9)
        anchor_ns = getattr(tel, "anchor_ns", 0)
        anchor_unix = getattr(tel, "anchor_unix", 0.0)
        out = []
        for k in range(len(ids)):
            if int(t0s[k]) < cutoff:
                continue
            out.append(
                {
                    "name": names[int(ids[k])],
                    "t_unix": round(
                        anchor_unix + (int(t0s[k]) - anchor_ns) / 1e9, 6
                    ),
                    "dur_ms": round(int(durs[k]) / 1e6, 4),
                    "tid": int(tids[k]),
                }
            )
        out.sort(key=lambda s: s["t_unix"])
        return out

    def _warn(self, msg: str) -> None:
        if not self._warned:
            self._warned = True
            print(
                f"sat_tpu: black box degraded ({self.dir}): {msg}",
                file=sys.stderr,
                flush=True,
            )


# ---------------------------------------------------------------------------
# the single finalizer chain (shutdown-ordering contract)
# ---------------------------------------------------------------------------

_FINALIZERS: List[Tuple[str, Callable[[], None]]] = []
_CHAIN_LOCK = threading.Lock()
_RUNNING = threading.local()


def register_finalizer(name: str, fn: Callable[[], None]) -> None:
    """Add an IDEMPOTENT flush-style callback to the process's one
    teardown chain.  The chain runs (in registration order) at atexit, at
    normal run teardown, and — crucially — inside :func:`dump` before the
    bundle reads any file, so an exit-86/87 path can never observe
    half-torn-down state."""
    with _CHAIN_LOCK:
        for i, (existing, _) in enumerate(_FINALIZERS):
            if existing == name:
                # re-registration (a second train() in the same process)
                # replaces the stale callback instead of stacking it
                _FINALIZERS[i] = (name, fn)
                return
        _FINALIZERS.append((name, fn))


def run_finalizers() -> None:
    """Run the chain; every failure is contained.  Safe to call more than
    once (callbacks are idempotent by contract) but never re-entrantly —
    a finalizer that crashes into dump() must not recurse."""
    if getattr(_RUNNING, "active", False):
        return
    _RUNNING.active = True
    try:
        with _CHAIN_LOCK:
            chain = list(_FINALIZERS)
        for name, fn in chain:
            try:
                fn()
            except Exception as e:
                print(
                    f"sat_tpu: finalizer {name!r} failed: {e}",
                    file=sys.stderr,
                    flush=True,
                )
    finally:
        _RUNNING.active = False


atexit.register(run_finalizers)


# ---------------------------------------------------------------------------
# process-wide install + postmortem dump
# ---------------------------------------------------------------------------

_INSTALLED: Optional[Dict] = None


def install(
    bb: BlackBox,
    *,
    telemetry_dir: str,
    fleet_dir: str = "",
    config_snapshot: Optional[Dict] = None,
    quarantine_ledger: str = "",
) -> None:
    """Make ``bb`` the process's postmortem source so far-away abnormal
    paths (watchdog abort, CLI exception handlers) can call :func:`dump`
    without plumbing.  Also threads the ring flush onto the finalizer
    chain — the ONE place teardown is allowed to touch it."""
    global _INSTALLED
    _INSTALLED = {
        "bb": bb,
        "telemetry_dir": telemetry_dir,
        "fleet_dir": fleet_dir or telemetry_dir,
        "config_snapshot": config_snapshot,
        "quarantine_ledger": quarantine_ledger,
    }
    register_finalizer("blackbox-ring", bb.flush)


def installed() -> Optional[BlackBox]:
    return _INSTALLED["bb"] if _INSTALLED else None


def uninstall() -> None:
    """Detach the recorder (tests; runs keep it until process exit so
    late aborts still dump)."""
    global _INSTALLED
    _INSTALLED = None


def _reset_for_tests() -> None:
    global _INSTALLED
    with _CHAIN_LOCK:
        _FINALIZERS.clear()
    _INSTALLED = None


def dump(reason: str, exit_code: Optional[int] = None, **fields) -> Optional[str]:
    """Assemble the postmortem bundle for the installed recorder (no-op
    when none is installed).  Returns the bundle path.  Never raises —
    this runs on paths that are already dying."""
    ctx = _INSTALLED
    if ctx is None:
        return None
    try:
        return dump_postmortem(
            reason,
            exit_code=exit_code,
            bb=ctx["bb"],
            telemetry_dir=ctx["telemetry_dir"],
            fleet_dir=ctx["fleet_dir"],
            config_snapshot=ctx["config_snapshot"],
            quarantine_ledger=ctx["quarantine_ledger"],
            extra=fields,
        )
    except Exception as e:
        print(
            f"sat_tpu: postmortem dump failed ({reason}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def _tail_lines(path: str, n: int = 200) -> Optional[List[str]]:
    try:
        with open(path) as f:
            return f.readlines()[-n:]
    except OSError:
        return None


def _copy_if_exists(src: str, dst_dir: str) -> None:
    try:
        if os.path.isfile(src):
            shutil.copy2(src, os.path.join(dst_dir, os.path.basename(src)))
    except OSError:
        pass


def _write_tail(src: str, dst: str, n: int = 200) -> None:
    lines = _tail_lines(src, n)
    if lines is not None:
        try:
            with open(dst, "w") as f:
                f.writelines(lines)
        except OSError:
            pass


def dump_postmortem(
    reason: str,
    exit_code: Optional[int],
    bb: BlackBox,
    telemetry_dir: str,
    fleet_dir: str = "",
    config_snapshot: Optional[Dict] = None,
    quarantine_ledger: str = "",
    span_tail_s: float = 30.0,
    extra: Optional[Dict] = None,
) -> str:
    """Build ``postmortem_<run_id>/`` under ``telemetry_dir``.  Every
    artifact copy is individually best-effort: a bundle with a hole beats
    no bundle.  Files the run owns are FLUSHED first via the finalizer
    chain, then only read — the ring is never truncated or rotated here."""
    run_finalizers()  # flush-before-read: the ordering contract
    fleet_dir = fleet_dir or telemetry_dir
    bundle = os.path.join(telemetry_dir, f"postmortem_{run_id()}")
    os.makedirs(bundle, exist_ok=True)

    spans = []
    try:
        spans = bb.span_tail(span_tail_s)
    except Exception:
        pass
    manifest = {
        "schema_version": SCHEMA_VERSION,
        "run_id": run_id(),
        "reason": reason,
        "exit_code": exit_code,
        "time_unix": round(time.time(), 3),
        "pid": os.getpid(),
        "span_tail_s": span_tail_s,
        "last_phase": spans[-1]["name"] if spans else None,
        **(extra or {}),
    }
    try:
        atomic_write(
            os.path.join(bundle, "manifest.json"),
            "w",
            lambda f: json.dump(manifest, f, indent=1),
        )
    except OSError:
        pass
    try:
        atomic_write(
            os.path.join(bundle, "spans_tail.json"),
            "w",
            lambda f: json.dump(spans, f, indent=1),
        )
    except OSError:
        pass
    try:
        state = {"counters": bb._tel.counters(), "gauges": bb._tel.gauges()}
        atomic_write(
            os.path.join(bundle, "state.json"),
            "w",
            lambda f: json.dump(state, f, indent=1),
        )
    except Exception:
        pass

    # the ring itself (copied, never moved: the run may still be writing)
    ring_dir = os.path.join(bundle, "blackbox")
    try:
        os.makedirs(ring_dir, exist_ok=True)
        for seg in sorted(glob.glob(os.path.join(bb.dir, "seg_*.jsonl"))):
            _copy_if_exists(seg, ring_dir)
    except OSError:
        pass

    # run-health artifacts other subsystems already maintain
    for name in (
        "heartbeat.json",
        "watchdog_stacks.txt",
        "compile_report.json",
        "breakdown.json",
    ):
        _copy_if_exists(os.path.join(telemetry_dir, name), bundle)
    _copy_if_exists(os.path.join(fleet_dir, "fleet.json"), bundle)
    for sidecar in sorted(glob.glob(os.path.join(fleet_dir, "heartbeat_p*.json"))):
        _copy_if_exists(sidecar, bundle)
    for name, src_dir in (
        ("slo.jsonl", telemetry_dir),
        ("telemetry.jsonl", telemetry_dir),
        ("fleet_history.jsonl", fleet_dir),
    ):
        _write_tail(
            os.path.join(src_dir, name), os.path.join(bundle, name)
        )
    if quarantine_ledger:
        _write_tail(
            quarantine_ledger, os.path.join(bundle, "quarantine.jsonl")
        )
    if config_snapshot is not None:
        try:
            atomic_write(
                os.path.join(bundle, "config.json"),
                "w",
                lambda f: json.dump(config_snapshot, f, indent=1, sort_keys=True),
            )
        except (OSError, TypeError, ValueError):
            pass
    print(
        f"sat_tpu: postmortem bundle written: {bundle} "
        f"(reason={reason}, exit_code={exit_code}) — summarize with "
        "scripts/analyze_postmortem.py",
        file=sys.stderr,
        flush=True,
    )
    return bundle
