"""Full-width vocabulary exercise: train + decode at V≈5000, with TP parity.

The reference's published configuration runs vocabulary_size=5000
(/root/reference/config.py:66-67), but the richest fixture corpus to date
builds ~1,100 words — so the full-width [5000, 512] embedding/softmax
(the tensors vocab-TP exists for) had never been trained at published
width on data, nor sharded at that width (VERDICT r04 missing #5 /
next-round #4).  This script closes that:

1. builds a rich-style corpus large enough that the top-5000 vocabulary
   cap BINDS (3 unique words/image: ~1700 images → >5000 distinct words),
2. builds the vocabulary (asserting the cap bound at exactly 5000),
3. trains the flagship decoder at V=5000 single-device for a bounded
   number of steps on real corpus batches,
4. repeats the identical run under vocab-TP on a (2,4) mesh of 8 virtual
   CPU devices (embedding + softmax + their Adam moments sharded 4-way
   over 'model': 5000 % 4 == 0 → 1250-row shards),
5. asserts per-step loss parity between the two trajectories,
6. beam-decodes (beam=3) a capped eval subset at V=5000 through the full
   eval pipeline (both single-device and on the mesh), and
7. writes runs/vocab5000/result.json with the parity numbers and scores.

CPU-only by design: the parity evidence needs the virtual 8-device mesh,
not the single tunneled chip.  Usage:
    python scripts/vocab5000_run.py [--out runs/vocab5000] [--steps 48]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# 8 virtual CPU devices BEFORE jax import (mirrors tests/conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root (sat_tpu)
sys.path.insert(0, _HERE)                   # sibling scripts

import numpy as np  # noqa: E402

from quality_run import make_rich_corpus  # noqa: E402


def _losses(summary_dir: str) -> np.ndarray:
    path = os.path.join(summary_dir, "metrics.jsonl")
    with open(path) as f:
        return np.array([json.loads(x)["total_loss"] for x in f])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="runs/vocab5000")
    ap.add_argument("--num-images", type=int, default=1700,
                    help="3 unique words/image; 1700 → >5100 distinct "
                    "words, so the top-5000 cap binds")
    ap.add_argument("--steps", type=int, default=48,
                    help="bounded train steps per arm (the exercise is "
                    "width + parity, not convergence)")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64,
                    help="small edge keeps the CPU VGG16 forward cheap; "
                    "the vocab-width tensors are full-size regardless")
    ap.add_argument("--eval-anns", type=int, default=24,
                    help="eval-subset cap for the beam=3 decode stage")
    args = ap.parse_args()

    t0 = time.time()

    def log(msg: str) -> None:
        print(f"[v5000 +{time.time()-t0:6.1f}s] {msg}", flush=True)

    root = os.path.abspath(args.out)
    os.makedirs(root, exist_ok=True)

    img_dir, caption_file, _ = make_rich_corpus(
        root, num_images=args.num_images, image_edge=args.image_size
    )
    log(f"corpus: {args.num_images} images, 2 captions each")

    import jax

    jax.config.update("jax_platforms", "cpu")
    from sat_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache(jax)
    assert len(jax.devices()) >= 8, jax.devices()

    from sat_tpu.cli import build_config
    from sat_tpu.data.dataset import build_vocabulary

    # ~steps*B annotations so one epoch is exactly the bounded run
    ann_cap = args.steps * args.batch_size
    common = [
        f"train_image_dir={img_dir}",
        f"train_caption_file={caption_file}",
        f"eval_image_dir={img_dir}",
        f"eval_caption_file={caption_file}",
        f"vocabulary_file={root}/vocabulary.csv",
        f"temp_annotation_file={root}/anns.csv",
        f"temp_data_file={root}/data.npy",
        f"eval_result_dir={root}/results",
        "vocabulary_size=5000",
        f"batch_size={args.batch_size}",
        f"image_size={args.image_size}",
        "num_epochs=1",
        f"max_train_ann_num={ann_cap}",
        f"max_eval_ann_num={args.eval_anns}",
        "save_period=0",
        "log_every=8",
        # deterministic trajectories for the parity comparison
        "fc_drop_rate=0.0",
        "lstm_drop_rate=0.0",
    ]

    def cfg(phase: str, *extra: str):
        set_args = [x for o in (*common, *extra) for x in ("--set", o)]
        config, _ = build_config([f"--phase={phase}"] + set_args)
        return config

    # 1) vocabulary from the FULL corpus (no ann cap) — the 5000 cap must
    # bind, which is the point of the exercise
    vocab_cfg = cfg("train", "max_train_ann_num=none")
    if not os.path.exists(vocab_cfg.vocabulary_file):
        vocabulary = build_vocabulary(vocab_cfg)
    else:
        from sat_tpu.data.vocabulary import Vocabulary

        vocabulary = Vocabulary(5000, vocab_cfg.vocabulary_file)
    vocab_words = len(vocabulary.words)
    log(f"vocabulary built: {vocab_words} words (cap 5000)")
    assert vocab_words == 5000, (
        f"corpus must overflow the top-5000 cap, built {vocab_words}"
    )

    from sat_tpu import runtime

    # 2) single-device trajectory
    single_cfg = cfg(
        "train",
        f"save_dir={root}/models_single",
        f"summary_dir={root}/summary_single",
        "mesh_shape=1,1",
    )
    log("training single-device at V=5000")
    state_single = runtime.train(single_cfg, seed=0)
    single_losses = _losses(f"{root}/summary_single")
    log(f"single-device done: {int(state_single.step)} steps, "
        f"loss {single_losses[0]:.4f} -> {single_losses[-1]:.4f}")

    # 3) vocab-TP (2 data × 4 model) trajectory, same seed and data
    tp_cfg = cfg(
        "train",
        f"save_dir={root}/models_tp",
        f"summary_dir={root}/summary_tp",
        "mesh_shape=2,4",
    )
    # guard against silently-replicated "TP": the embedding/softmax rows
    # must actually shard 4-way at this width
    from sat_tpu.parallel import make_mesh
    from sat_tpu.parallel.sharding import param_partition_specs

    specs = param_partition_specs(
        {"params": state_single.params}, tp_cfg, make_mesh(tp_cfg)
    )
    n_sharded = sum(
        "model" in str(s) for s in jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(str, specs)
        )
    )
    assert n_sharded > 0, "no parameter sharded over 'model' at V=5000"
    log(f"TP shards {n_sharded} param tensors over 'model'")

    log("training dp=2 x tp=4 mesh at V=5000 (same seed, same batches)")
    state_tp = runtime.train(tp_cfg, seed=0)
    tp_losses = _losses(f"{root}/summary_tp")
    log(f"mesh done: {int(state_tp.step)} steps, "
        f"loss {tp_losses[0]:.4f} -> {tp_losses[-1]:.4f}")

    # 4) per-step loss parity.  fp32 CPU, identical data order (the
    # per-process shard view is layout-invariant), dropout off: the only
    # divergence source is collective/matmul reduction order, which Adam
    # amplifies step over step — tolerance covers the measured multihost
    # demo band (tp 1.8e-7 first step) with growth room.
    assert single_losses.shape == tp_losses.shape and len(single_losses) > 0
    rel = np.abs(tp_losses - single_losses) / np.maximum(single_losses, 1e-9)
    log(f"loss parity: max rel diff {rel.max():.3e} over {len(rel)} records")
    # hard gate at the suite's trajectory band (test_parallel_runtime
    # uses rtol 5e-2 over 6 steps); the artifact records the exact value
    assert rel.max() < 5e-2, f"TP trajectory diverged: {rel.max()}"

    # 5) beam=3 decode at V=5000 through the full eval pipeline, both ways
    log("beam=3 eval decode, single-device")
    eval_single = runtime.evaluate(
        cfg("eval", f"summary_dir={root}/summary_single",
            f"eval_result_file={root}/results_single.json",
            "beam_size=3", "mesh_shape=1,1"),
        state=state_single,
    )
    log(f"single-device scores: { {k: round(v, 4) for k, v in eval_single.items()} }")
    log("beam=3 eval decode on the (2,4) mesh")
    eval_tp = runtime.evaluate(
        cfg("eval", f"summary_dir={root}/summary_tp",
            f"eval_result_file={root}/results_tp.json",
            "beam_size=3", "mesh_shape=2,4"),
        state=state_tp,
    )
    log(f"mesh scores: { {k: round(v, 4) for k, v in eval_tp.items()} }")

    payload = {
        "vocab_words": vocab_words,
        "vocabulary_cap_bound": True,
        "num_images": args.num_images,
        "image_size": args.image_size,
        "train_steps": int(state_single.step),
        "loss_single_first_last": [float(single_losses[0]), float(single_losses[-1])],
        "loss_tp_first_last": [float(tp_losses[0]), float(tp_losses[-1])],
        "loss_parity_max_rel": float(rel.max()),
        "mesh_shape": [2, 4],
        "tp_sharded_tensors": n_sharded,
        "scores_single": eval_single,
        "scores_tp": eval_tp,
        "total_seconds": round(time.time() - t0, 1),
    }
    with open(os.path.join(root, "result.json"), "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))
    return 0


if __name__ == "__main__":
    sys.exit(main())
