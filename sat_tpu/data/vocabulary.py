"""Frequency-sorted caption vocabulary.

Behavioral parity with the reference Vocabulary
(/root/reference/utils/vocabulary.py): index 0 is ``<start>``, the sentence
terminator is the literal ``'.'`` token, entries are the top-(size-1) words
by corpus frequency, frequencies are stored log-normalized, and the on-disk
format is the same pandas CSV (columns: index, frequency, index, word) so
the reference's prebuilt ``vocabulary.csv`` loads unchanged.

Differences by design: tokenization uses our native Treebank tokenizer
(sat_tpu.data.tokenizer) instead of nltk, and ``process_sentence`` can
optionally skip OOV words instead of raising (the reference raises KeyError
on OOV, vocabulary.py:50, relying on the corpus being pre-filtered).
"""

from __future__ import annotations

import os
import string
from typing import Dict, Iterable, List, Sequence

import numpy as np

from .tokenizer import tokenize


# vocab_fingerprint memo: keyed on (abspath, size, mtime, fsize) so the
# common case — every checkpoint save of a run fingerprinting the same
# unchanged CSV — parses it once.
_FINGERPRINT_CACHE: Dict[tuple, Dict[str, object]] = {}


def vocab_fingerprint(path: str, size: int) -> Dict[str, object] | None:
    """Content identity of the EFFECTIVE vocabulary a run decodes with:
    sha256 over the size-truncated word list plus its length.  Recorded
    into the checkpoint lineage sidecar and compared at restore/serve
    load, so a checkpoint trained against one vocabulary fails fast
    against another instead of silently skipping the mismatched
    embedding (see train.checkpoint._check_vocab).  None when the file
    is missing or unreadable (nothing to attest)."""
    import hashlib

    try:
        apath = os.path.abspath(path)
        st = os.stat(apath)
    except OSError:
        return None
    key = (apath, int(size), st.st_mtime_ns, st.st_size)
    got = _FINGERPRINT_CACHE.get(key)
    if got is None:
        try:
            vocab = Vocabulary(size, apath)
        except Exception:
            return None
        got = {
            "sha256": hashlib.sha256(
                "\n".join(vocab.words).encode("utf-8")
            ).hexdigest(),
            "size": len(vocab.words),
        }
        _FINGERPRINT_CACHE[key] = got
    return dict(got)


class Vocabulary:
    def __init__(self, size: int, save_file: str | None = None):
        self.words: List[str] = []
        self.word2idx: Dict[str, int] = {}
        self.word_frequencies: np.ndarray | List[float] = []
        self.size = size
        if save_file is not None:
            self.load(save_file)

    def build(self, sentences: Iterable[str]) -> None:
        word_counts: Dict[str, float] = {}
        for sentence in sentences:
            for w in tokenize(sentence):
                word_counts[w] = word_counts.get(w, 0) + 1.0

        # Shrink when the corpus has fewer distinct words than requested
        # (reference vocabulary.py:25-26).
        if self.size - 1 > len(word_counts):
            self.size = len(word_counts) + 1

        self.words = ["<start>"]
        self.word2idx = {"<start>": 0}
        freqs = [1.0]

        ranked = sorted(word_counts.items(), key=lambda kv: kv[1], reverse=True)
        for idx in range(self.size - 1):
            word, frequency = ranked[idx]
            self.words.append(word)
            self.word2idx[word] = idx + 1
            freqs.append(frequency)

        f = np.array(freqs, dtype=np.float64)
        f /= f.sum()
        f = np.log(f)
        f -= f.max()
        self.word_frequencies = f

    def process_sentence(self, sentence: str, skip_oov: bool = False) -> List[int]:
        """Tokenize and map to vocab indices (reference vocabulary.py:46-51)."""
        words = tokenize(sentence)
        if skip_oov:
            return [self.word2idx[w] for w in words if w in self.word2idx]
        return [self.word2idx[w] for w in words]

    def get_sentence(self, idxs: Sequence[int]) -> str:
        """Indices → detokenized sentence, truncated at the first '.'
        (reference vocabulary.py:53-63).

        Hardened for beam-search output rows, which are fixed-width [T]
        buffers: a hypothesis that terminated on its first step arrives
        eos-first, a padding row arrives all index-0, and masked logit
        columns can carry indices past the end of a shrunken word list.
        Index 0 (``<start>``, doubling as pad) and out-of-range indices
        are never words, and a result with no words at all returns ""
        instead of a bare "." or pad-token noise (the reference indexes
        its word list unguarded)."""
        words: List[str] = []
        for i in idxs:
            i = int(i)
            if i <= 0 or i >= len(self.words):
                continue  # <start>/pad or an overhang column with no entry
            word = self.words[i]
            if word == ".":
                break
            words.append(word)
        if not words:
            return ""
        words.append(".")
        sentence = "".join(
            " " + w if not w.startswith("'") and w not in string.punctuation else w
            for w in words
        ).strip()
        return sentence

    def save(self, save_file: str) -> None:
        import pandas as pd

        from ..utils.fileio import atomic_write

        # atomic: concurrent multi-host data prep must never read a
        # half-written vocabulary
        atomic_write(
            save_file,
            "w",
            lambda f: pd.DataFrame(
                {
                    "word": list(self.words),
                    "index": list(range(self.size)),
                    "frequency": list(np.asarray(self.word_frequencies)),  # sync-ok: host numpy
                }
            ).to_csv(f),
        )

    def load(self, save_file: str) -> None:
        import pandas as pd

        assert os.path.exists(save_file), save_file
        # keep_default_na: words like 'null'/'nan' must stay strings
        data = pd.read_csv(save_file, keep_default_na=False)
        # Truncate everything to the requested size so words, word2idx and
        # word_frequencies stay mutually consistent even when the CSV holds
        # more rows than this vocabulary is configured for.
        n = min(self.size, len(data))
        self.words = [str(w) for w in data["word"].values[:n]]
        self.word2idx = {w: i for i, w in enumerate(self.words)}
        self.word_frequencies = data["frequency"].values[:n]
        self.size = n
