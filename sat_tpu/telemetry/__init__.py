"""sat_tpu.telemetry — always-on host-side tracing and run-health metrics.

Complements ``jax.profiler`` (deep, short-windowed, device-centric) with a
cheap, whole-run, host-centric layer: ring-buffered spans + counters +
gauges (``spans``), Chrome-trace / JSONL / breakdown exporters
(``exporters``), and the pollable ``heartbeat.json`` writer
(``heartbeat``).  See docs/OBSERVABILITY.md.

This package is deliberately jax-free so host-only tools
(``scripts/bench_telemetry.py``) can use it without an accelerator
backend.  Only ``spans`` is imported eagerly; runtime imports the
exporters and heartbeat directly.
"""

from __future__ import annotations

import os
import time

from .spans import (  # noqa: F401
    NULL_SPAN,
    NULL_TELEMETRY,
    NullTelemetry,
    Telemetry,
    count,
    disable,
    enable,
    enabled,
    gauge,
    get,
    record,
    span,
)

# One id per process lifetime: every artifact a run writes (metrics.jsonl,
# telemetry.jsonl, heartbeat.json, trace JSON) carries it, so post-hoc
# joins never depend on file mtimes or directory layout.
RUN_ID = f"{int(time.time()):x}-{os.getpid()}"

# Version of the benchmark/report artifact contract (BENCH JSON rows,
# compile_report.json).  scripts/check_regression.py refuses to compare
# artifacts stamped with a different major version; bump it when a field
# changes meaning (not when fields are added).
SCHEMA_VERSION = 1


def run_id() -> str:
    return RUN_ID


def process_identity() -> tuple:
    """(process_index, process_count) for multi-host artifact stamping.

    Same contract as :func:`bench_stamp`: never imports jax — the facts
    are read via ``sys.modules`` only when the caller already initialized
    a backend, and a single-process / host-only caller gets (0, 1).  The
    fleet plane (telemetry/fleet.py), heartbeat.json, and bench rows all
    stamp through here so cross-host artifacts agree on who wrote them."""
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index()), int(jax.process_count())
        except Exception:
            pass
    return 0, 1


def bench_stamp() -> dict:
    """Provenance stamp shared by every ``scripts/bench_*.py`` JSON output
    and ``compile_report.json``: artifact schema version, git SHA, and a
    device/host descriptor — the fields ``check_regression.py`` needs to
    decide whether two artifacts are comparable at all.

    Deliberately import-light: no jax import ever (this package is
    jax-free); device facts are read only when the caller already
    initialized jax, and only via ``sys.modules`` so a host-only bench
    (bench_telemetry, bench_input) never drags a backend in.  Callers
    stamp at emit time — after their device work — so touching
    ``local_devices()`` here never triggers a fresh backend init."""
    import platform
    import subprocess
    import sys

    sha = None
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True,
            text=True,
            timeout=5,
        )
        sha = out.stdout.strip() or None
    except Exception:
        pass
    device = {
        "host": platform.node(),
        "machine": platform.machine(),
        "python": platform.python_version(),
    }
    if "jax" in sys.modules:
        try:
            jax = sys.modules["jax"]
            d0 = jax.local_devices()[0]
            device.update(
                platform=d0.platform,
                kind=d0.device_kind,
                device_count=jax.device_count(),
            )
        except Exception:
            pass
    process_index, process_count = process_identity()
    return {
        "schema_version": SCHEMA_VERSION,
        "git_sha": sha,
        "run_id": RUN_ID,
        "stamp_unix": round(time.time(), 3),
        "process_index": process_index,
        "process_count": process_count,
        "device": device,
    }
