"""Pallas fused attention + hoisted-projection decode paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sat_tpu.config import Config
from sat_tpu.models.decoder import (
    attend,
    attend_with_precomputed,
    init_decoder_params,
    init_state,
    precompute_attend,
)
from sat_tpu.ops.beam_search import beam_search
from sat_tpu.ops.pallas_attention import fused_attend, fused_attend_reference


def _cfg(**kw):
    base = dict(
        image_size=32,
        vocabulary_size=50,
        dim_embedding=8,
        num_lstm_units=8,
        dim_initialize_layer=8,
        dim_attend_layer=16,
        dim_decode_layer=16,
        max_caption_length=6,
        compute_dtype="float32",
    )
    return Config(**{**base, **kw})


def test_fused_attend_matches_reference(rng):
    B, N, da, D = 3, 17, 16, 24
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    want_ctx, want_alpha = fused_attend_reference(t1, t2, w2, ctx)
    got_ctx, got_alpha = fused_attend(t1, t2, w2, ctx, interpret=True)
    np.testing.assert_allclose(got_alpha, want_alpha, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_ctx, want_ctx, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_alpha).sum(-1), 1.0, rtol=1e-6)


@pytest.mark.parametrize("B,block_b", [(5, 4), (8, 8), (2, 8), (13, 4)])
def test_fused_attend_batch_tiling(rng, B, block_b):
    """Batch-tile grid: every (B, block_b) combination — including
    non-divisible and B < block_b — must pad internally and match."""
    N, da, D = 21, 16, 24
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    want_ctx, want_alpha = fused_attend_reference(t1, t2, w2, ctx)
    got_ctx, got_alpha = fused_attend(
        t1, t2, w2, ctx, interpret=True, block_b=block_b
    )
    np.testing.assert_allclose(got_alpha, want_alpha, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_ctx, want_ctx, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("layers", [1, 2])
def test_precomputed_attend_matches_plain(rng, layers):
    """Hoisting the context projection must be numerically exact in fp32."""
    config = _cfg(num_attend_layers=layers)
    params = init_decoder_params(jax.random.PRNGKey(0), config)
    B, N, D = 2, config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    output = jnp.asarray(
        rng.normal(size=(B, config.num_lstm_units)).astype(np.float32)
    )

    alpha_plain = attend(params, config, contexts, output, train=False)
    ctx_plain = (contexts * alpha_plain[..., None]).sum(axis=1)

    proj = precompute_attend(params, config, contexts)
    ctx_fast, alpha_fast = attend_with_precomputed(
        params, config, contexts, proj, output
    )
    np.testing.assert_allclose(alpha_fast, alpha_plain, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(ctx_fast, ctx_plain, rtol=1e-5, atol=1e-6)


def test_beam_search_hoisted_matches_per_step_oracle(rng):
    """Hoisting the attention projection out of the decode loop must not
    change the search at all (fp32: identical op sequence per step)."""
    config = _cfg(beam_size=3)
    params = init_decoder_params(jax.random.PRNGKey(1), config)
    B, N, D = 2, config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    fast = beam_search(params, config, contexts, eos_id=7, hoist_attention=True)
    oracle = beam_search(
        params, config, contexts, eos_id=7, hoist_attention=False
    )
    np.testing.assert_array_equal(np.asarray(fast.words), np.asarray(oracle.words))
    np.testing.assert_allclose(
        np.asarray(fast.log_scores), np.asarray(oracle.log_scores),
        rtol=1e-6, atol=1e-6,
    )


def test_beam_search_pallas_kernel_matches_xla(rng, monkeypatch):
    """The interpret-mode Pallas decode produces the same captions as the
    XLA combine (exercises the kernel through the full search off-TPU)."""
    from sat_tpu.ops import pallas_attention

    config = _cfg(beam_size=3, use_pallas_attention=True)
    params = init_decoder_params(jax.random.PRNGKey(1), config)
    B, N, D = 2, config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    base = beam_search(
        params, config.replace(use_pallas_attention=False), contexts, eos_id=7
    )
    monkeypatch.setattr(pallas_attention, "FORCE_INTERPRET", True)
    out = beam_search(params, config, contexts, eos_id=7)
    np.testing.assert_array_equal(np.asarray(out.words), np.asarray(base.words))
    np.testing.assert_allclose(
        np.asarray(out.log_scores), np.asarray(base.log_scores),
        rtol=1e-4, atol=1e-5,
    )


@pytest.mark.parametrize("B,block_b", [(3, 8), (7, 4), (8, 8), (13, 8)])
def test_fused_attend_row_mask_geometry(rng, B, block_b):
    """Slot-pool geometry: odd batch sizes with a dead-row mask.

    Dead rows (inputs poisoned with NaN, as a retired slot's stale carry
    could be) must come out exactly zero; live rows must stay BITWISE
    equal to the unmasked kernel; and the masked kernel must agree with
    the masked XLA reference."""
    N, da, D = 17, 16, 24
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    mask = jnp.asarray(rng.integers(0, 2, size=(B,)).astype(bool))

    t1p = t1.at[~mask].set(jnp.nan)
    t2p = t2.at[~mask].set(jnp.nan)
    ctxp = ctx.at[~mask].set(jnp.nan)

    got_ctx, got_alpha = fused_attend(
        t1p, t2p, w2, ctxp, row_mask=mask, interpret=True, block_b=block_b
    )
    want_ctx, want_alpha = fused_attend_reference(
        t1p, t2p, w2, ctxp, row_mask=mask
    )
    assert bool(jnp.isfinite(got_ctx).all() and jnp.isfinite(got_alpha).all())
    dead = np.asarray(~mask)
    assert (np.asarray(got_ctx)[dead] == 0).all()
    assert (np.asarray(got_alpha)[dead] == 0).all()
    np.testing.assert_allclose(got_alpha, want_alpha, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got_ctx, want_ctx, rtol=1e-5, atol=1e-5)

    live = np.asarray(mask)
    base_ctx, base_alpha = fused_attend(
        t1, t2, w2, ctx, interpret=True, block_b=block_b
    )
    np.testing.assert_array_equal(
        np.asarray(got_ctx)[live], np.asarray(base_ctx)[live]
    )
    np.testing.assert_array_equal(
        np.asarray(got_alpha)[live], np.asarray(base_alpha)[live]
    )


def test_fused_attend_all_dead_and_all_live_masks(rng):
    """Edge masks: all-live equals the unmasked call bitwise; all-dead is
    all-zero output (never NaN), even at a batch size that needs padding."""
    B, N, da, D = 5, 17, 16, 24
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    base_ctx, base_alpha = fused_attend(t1, t2, w2, ctx, interpret=True)
    ctx_l, alpha_l = fused_attend(
        t1, t2, w2, ctx, row_mask=jnp.ones((B,), bool), interpret=True
    )
    np.testing.assert_array_equal(np.asarray(ctx_l), np.asarray(base_ctx))
    np.testing.assert_array_equal(np.asarray(alpha_l), np.asarray(base_alpha))

    ctx_d, alpha_d = fused_attend(
        jnp.full_like(t1, jnp.nan), jnp.full_like(t2, jnp.nan), w2,
        jnp.full_like(ctx, jnp.nan), row_mask=jnp.zeros((B,), bool),
        interpret=True,
    )
    assert (np.asarray(ctx_d) == 0).all() and (np.asarray(alpha_d) == 0).all()


@pytest.mark.parametrize("layers", [1, 2])
def test_attend_with_precomputed_row_mask_xla_path(rng, layers):
    """The XLA fallback (and 1-layer path) apply the same masking
    semantics as the kernel: live rows bitwise-unchanged, dead rows
    zeroed even when their inputs are NaN."""
    config = _cfg(num_attend_layers=layers, use_pallas_attention=False)
    params = init_decoder_params(jax.random.PRNGKey(0), config)
    B, N, D = 5, config.num_ctx, config.dim_ctx
    contexts = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))
    output = jnp.asarray(
        rng.normal(size=(B, config.num_lstm_units)).astype(np.float32)
    )
    mask = jnp.asarray(np.array([True, False, True, False, True]))
    proj = precompute_attend(params, config, contexts)

    ctx_base, alpha_base = attend_with_precomputed(
        params, config, contexts, proj, output
    )
    contexts_p = contexts.at[~mask].set(jnp.nan)
    output_p = output.at[~mask].set(jnp.nan)
    proj_p = proj.at[~mask].set(jnp.nan)
    ctx_m, alpha_m = attend_with_precomputed(
        params, config, contexts_p, proj_p, output_p, row_mask=mask
    )
    live, dead = np.asarray(mask), np.asarray(~mask)
    assert bool(jnp.isfinite(ctx_m).all() and jnp.isfinite(alpha_m).all())
    assert (np.asarray(ctx_m)[dead] == 0).all()
    assert (np.asarray(alpha_m)[dead] == 0).all()
    np.testing.assert_array_equal(
        np.asarray(ctx_m)[live], np.asarray(ctx_base)[live]
    )
    np.testing.assert_array_equal(
        np.asarray(alpha_m)[live], np.asarray(alpha_base)[live]
    )


def test_fused_attend_bf16_scoring_matches_oracle(rng):
    """compute_dtype='bfloat16' must use bf16 for the scoring matmul in
    both the kernel and the oracle — the default-config path."""
    B, N, da, D = 2, 20, 16, 24
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    want_ctx, want_alpha = fused_attend_reference(
        t1, t2, w2, ctx, compute_dtype="bfloat16"
    )
    got_ctx, got_alpha = fused_attend(
        t1, t2, w2, ctx, compute_dtype="bfloat16", interpret=True
    )
    # bf16 scoring: kernel and XLA round at slightly different points, so
    # agreement is at bf16-rounding scale, not exact
    np.testing.assert_allclose(got_alpha, want_alpha, rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(got_ctx, want_ctx, rtol=5e-2, atol=5e-2)

    # and the bf16 kernel must be far closer to the bf16 oracle than the
    # fp32 oracle is (i.e. the dtype knob actually changes the matmul)
    fp32_ctx, fp32_alpha = fused_attend_reference(
        t1, t2, w2, ctx, compute_dtype="float32"
    )
    assert float(jnp.abs(got_alpha - want_alpha).max()) < float(
        jnp.abs(fp32_alpha - want_alpha).max()
    )
