"""Tenant registry: per-tenant identity, quota, weight and SLO targets.

The multi-tenant plane (docs/SERVING.md "Multi-tenant serving") hangs
off one small, jax-free table: a tenant key (the ``X-Tenant`` request
header; bare requests map to the registry's default tenant) resolves to

* a **resident model** — the alias of a device-resident param set
  (loaded once at boot through the lifecycle loader and aval-validated
  against the incumbent, so every resident shares the warmed AOT
  executables; ``X-Model`` overrides per request);
* a **token-bucket admission quota** (``rps`` + ``burst``) enforced at
  the HTTP edge — a dry bucket sheds with a *tenant-scoped* 429 whose
  ``Retry-After`` is the bucket's own refill time, before the request
  costs any preprocessing or queue space;
* a **scheduling weight** feeding the deficit-round-robin admission
  scheduler (serve/scheduler.py) — decode seats are granted in deficit
  order, so a flooding tenant only consumes its share;
* optional per-tenant **SLO targets** (p99 / error ratio) that grow
  their own burn-rate lanes in telemetry/slo.py.

Two spec formats behind ``--tenants``:

* a JSON file path::

      {"default": "free",
       "models": {"tuned": "runs/tuned/models/900.npz"},
       "tenants": [
         {"name": "free", "weight": 1, "rps": 10, "burst": 20},
         {"name": "pro",  "weight": 4, "rps": 100, "model": "tuned",
          "slo_p99_ms": 250}]}

* an inline ``name:weight:rps:burst`` comma-list (no models/SLOs)::

      --tenants "free:1:10:20,pro:4:100:200"

The empty spec ("" — the default) is the degenerate single-tenant
registry: one unlimited default tenant, weight 1, no resident models —
zero behavior change vs. pre-tenant serving (pinned by the parity test
in tests/test_tenants.py).

jax-free by contract: the fleet router imports this module for edge
quota enforcement (gated by tests/test_device_diag.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

DEFAULT_TENANT = "default"

# tenant names ride telemetry counter names, gauge names and slot keys:
# keep them to a conservative identifier charset so promtext label
# escaping and the heartbeat's prefix-stripping never see surprises
_NAME_OK = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-"
)


def _check_name(name: str, what: str = "tenant") -> str:
    if not name or not set(name) <= _NAME_OK:
        raise ValueError(
            f"{what} name {name!r}: must be non-empty [A-Za-z0-9_-]"
        )
    return name


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's declared shape.  ``rps=0`` means unlimited (no
    bucket); ``burst=0`` with a finite rate degrades to a capacity of
    ``max(1, rps)`` tokens — a tenant with *any* admission rate can
    always send at least one request (pinned by the burst==0 edge-case
    test).  ``model=""`` serves the incumbent checkpoint."""

    name: str
    weight: float = 1.0
    rps: float = 0.0
    burst: float = 0.0
    model: str = ""
    slo_p99_ms: float = 0.0
    slo_error_ratio: float = 0.0

    def __post_init__(self) -> None:
        _check_name(self.name)
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight={self.weight} must be > 0"
            )
        for knob in ("rps", "burst", "slo_p99_ms", "slo_error_ratio"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"tenant {self.name!r}: {knob}={getattr(self, knob)} "
                    "must be >= 0"
                )

    @property
    def capacity(self) -> float:
        """Bucket capacity: the declared burst, else one second of rate
        (never below 1 token when a rate is set at all)."""
        if self.burst > 0:
            return float(self.burst)  # sync-ok: host config scalar
        return max(1.0, float(self.rps))  # sync-ok: host config scalar

    @property
    def limited(self) -> bool:
        return self.rps > 0


class TokenBucket:
    """Thread-safe token bucket: ``capacity`` tokens, refilled at
    ``rate`` tokens/s.  ``rate <= 0`` disables limiting entirely.
    ``clock`` is injectable for deterministic refill tests."""

    def __init__(
        self,
        rate: float,
        capacity: float,
        clock=time.monotonic,
    ) -> None:
        self.rate = float(rate)  # sync-ok: host config scalar
        self.capacity = float(capacity)  # sync-ok: host config scalar
        self._clock = clock
        self._tokens = self.capacity
        self._t_last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        dt = now - self._t_last
        self._t_last = now
        if dt > 0:
            self._tokens = min(self.capacity, self._tokens + dt * self.rate)

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; False when the bucket is dry
        (the caller sheds with a tenant-scoped 429)."""
        if self.rate <= 0:
            return True
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        if self.rate <= 0:
            return float("inf")  # sync-ok: host-side sentinel, no device value
        with self._lock:
            self._refill_locked()
            return self._tokens

    def retry_after_s(self) -> float:
        """Seconds until the next whole token exists — the per-tenant
        Retry-After hint.  0 when unlimited or already holding a token
        (the frontend's never-0s clamp applies on top)."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self.rate


class TenantRegistry:
    """The parsed ``--tenants`` table plus live per-tenant buckets.

    ``multi`` is False only for the degenerate empty spec — the
    single-tenant fast path that must stay bitwise-identical to
    pre-tenant serving (no buckets, no per-tenant counters, no extra
    SLO lanes)."""

    def __init__(
        self,
        specs: List[TenantSpec],
        default: str = DEFAULT_TENANT,
        models: Optional[Dict[str, str]] = None,
        source: str = "",
        clock=time.monotonic,
    ) -> None:
        if not specs:
            specs = [TenantSpec(name=DEFAULT_TENANT)]
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"tenants: duplicate tenant names in {names}")
        self._specs: Dict[str, TenantSpec] = {s.name: s for s in specs}
        if default not in self._specs:
            raise ValueError(
                f"tenants: default tenant {default!r} is not declared "
                f"(have {sorted(self._specs)})"
            )
        self.default = default
        self.models: Dict[str, str] = dict(models or {})
        for alias, path in self.models.items():
            _check_name(alias, what="model")
            if not path or not isinstance(path, str):
                raise ValueError(
                    f"tenants: model {alias!r} needs a checkpoint path"
                )
        for s in specs:
            if s.model and s.model not in self.models:
                raise ValueError(
                    f"tenant {s.name!r}: model {s.model!r} is not in the "
                    f"registry's models map (have {sorted(self.models)})"
                )
        self.source = source
        self._buckets: Dict[str, TokenBucket] = {
            s.name: TokenBucket(s.rps, s.capacity, clock=clock)
            for s in specs
            if s.limited
        }
        # single default tenant, unlimited, no models: the degenerate
        # registry with zero behavior change
        self.multi = not (
            len(specs) == 1
            and specs[0].name == DEFAULT_TENANT
            and not specs[0].limited
            and not self.models
        )

    # -- parsing -----------------------------------------------------------

    @classmethod
    def parse(cls, spec: str, clock=time.monotonic) -> "TenantRegistry":
        """``Config.tenants`` → registry.  "" is the degenerate
        single-tenant table; a path to an existing file parses as JSON;
        anything else parses as the inline ``name:weight:rps:burst``
        comma-list (first entry is the default tenant)."""
        spec = (spec or "").strip()
        if not spec:
            return cls([], clock=clock)
        if os.path.isfile(spec):
            try:
                with open(spec) as f:
                    doc = json.load(f)
            except (OSError, ValueError) as e:
                raise ValueError(f"tenants file {spec!r}: {e}") from None
            return cls._from_doc(doc, source=spec, clock=clock)
        return cls._from_inline(spec, clock=clock)

    @classmethod
    def _from_doc(
        cls, doc: Dict, source: str = "", clock=time.monotonic
    ) -> "TenantRegistry":
        if not isinstance(doc, dict) or "tenants" not in doc:
            raise ValueError(
                f"tenants file {source or '<doc>'}: expected an object "
                'with a "tenants" list'
            )
        allowed = {
            "name", "weight", "rps", "burst", "model",
            "slo_p99_ms", "slo_error_ratio",
        }
        specs = []
        for entry in doc["tenants"]:
            if not isinstance(entry, dict) or "name" not in entry:
                raise ValueError(
                    f"tenants file {source}: each tenant needs a name "
                    f"(got {entry!r})"
                )
            unknown = set(entry) - allowed
            if unknown:
                raise ValueError(
                    f"tenant {entry.get('name')!r}: unknown keys "
                    f"{sorted(unknown)} (allowed: {sorted(allowed)})"
                )
            specs.append(TenantSpec(**entry))
        default = doc.get("default", specs[0].name if specs else DEFAULT_TENANT)
        return cls(
            specs,
            default=default,
            models=doc.get("models"),
            source=source,
            clock=clock,
        )

    @classmethod
    def _from_inline(cls, spec: str, clock=time.monotonic) -> "TenantRegistry":
        specs = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) > 4:
                raise ValueError(
                    f"tenants entry {part!r}: expected "
                    "name[:weight[:rps[:burst]]]"
                )
            name = fields[0]
            try:
                weight = float(fields[1]) if len(fields) > 1 else 1.0  # sync-ok: host config scalar
                rps = float(fields[2]) if len(fields) > 2 else 0.0  # sync-ok: host config scalar
                burst = float(fields[3]) if len(fields) > 3 else 0.0  # sync-ok: host config scalar
            except ValueError:
                raise ValueError(
                    f"tenants entry {part!r}: weight/rps/burst must be "
                    "numbers"
                ) from None
            specs.append(
                TenantSpec(name=name, weight=weight, rps=rps, burst=burst)
            )
        if not specs:
            raise ValueError(f"tenants spec {spec!r}: no tenants parsed")
        return cls(specs, default=specs[0].name, clock=clock)

    # -- resolution (HTTP worker threads) ----------------------------------

    def resolve(self, header: Optional[str]) -> TenantSpec:
        """``X-Tenant`` header value → spec.  Bare requests and unknown
        tenants map to the default tenant — an unknown key is a client
        mistake, not a free ride around the default tenant's quota."""
        if header:
            spec = self._specs.get(header.strip())
            if spec is not None:
                return spec
        return self._specs[self.default]

    def known(self, header: Optional[str]) -> bool:
        return bool(header) and header.strip() in self._specs

    def try_admit(self, name: str) -> bool:
        """Take one token from ``name``'s bucket; True when admitted
        (unlimited tenants always admit)."""
        bucket = self._buckets.get(name)
        return True if bucket is None else bucket.try_take()

    def retry_after_s(self, name: str) -> float:
        bucket = self._buckets.get(name)
        return 0.0 if bucket is None else bucket.retry_after_s()

    def tokens(self, name: str) -> Optional[float]:
        """Current token balance (None for unlimited tenants) — a
        /stats + heartbeat gauge feed, not an admission path."""
        bucket = self._buckets.get(name)
        return None if bucket is None else bucket.tokens()

    def use_clock(self, clock) -> None:
        """Rebind every bucket's refill clock (test hook).  Registries
        built inside a booted server own their buckets, so timing tests
        freeze refill *after* boot by swapping in an injectable clock —
        each bucket re-anchors its last-refill time on the new clock so
        no retroactive refill is credited at the swap."""
        for bucket in self._buckets.values():
            with bucket._lock:
                bucket._clock = clock
                bucket._t_last = clock()

    # -- read side ---------------------------------------------------------

    def specs(self) -> List[TenantSpec]:
        return list(self._specs.values())

    def names(self) -> List[str]:
        return list(self._specs)

    def get(self, name: str) -> Optional[TenantSpec]:
        return self._specs.get(name)

    def weights(self) -> Dict[str, float]:
        """Tenant → scheduling weight, the DRR scheduler's table."""
        return {s.name: s.weight for s in self._specs.values()}

    def slo_lanes(
        self, default_p99_ms: float = 0.0, default_error_ratio: float = 0.0
    ) -> List[Tuple[str, float, float]]:
        """Per-tenant SLO lane targets ``(name, p99_ms, error_ratio)``
        for ``telemetry.slo.objectives_from_config``: a tenant's own
        target wins, else it inherits the serve-phase default.  Empty
        for the degenerate single-tenant registry — no extra lanes, no
        behavior change."""
        if not self.multi:
            return []
        out = []
        for s in self._specs.values():
            p99 = s.slo_p99_ms if s.slo_p99_ms > 0 else default_p99_ms
            err = (
                s.slo_error_ratio
                if s.slo_error_ratio > 0
                else default_error_ratio
            )
            out.append((s.name, p99, err))
        return out

    def describe(self) -> Dict[str, Dict]:
        """Static per-tenant shape for /stats (quota/weight/model —
        live counters ride telemetry)."""
        return {
            s.name: {
                "weight": s.weight,
                "rps": s.rps,
                "burst": s.capacity if s.limited else 0.0,
                "model": s.model,
            }
            for s in self._specs.values()
        }
