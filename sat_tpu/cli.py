"""Command-line driver: ``python -m sat_tpu.cli --phase=train|eval|test``.

Flag-for-flag parity with the reference CLI (/root/reference/main.py:15-36):
``--phase --load --model_file --load_cnn --cnn_model_file --train_cnn
--beam_size``, dispatching to the runtime layer (main.py:45-72).  Any other
Config field can be overridden with ``--set key=value`` pairs (the
reference requires editing config.py for those).

One extra input-pipeline flag beyond the reference surface:
``--shard_cache auto|on|off`` selects the mmap'd preprocessed-shard
cache (docs/DATA_PIPELINE.md); ``--set`` spellings of the same field
still win, flag defaults never clobber them.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
from typing import List, Optional

from .config import Config


# The reference's config attributes are literally misspelled
# (/root/reference/config.py:12-13: "num_initalize_layers",
# "dim_initalize_layer"); accept those spellings so its users' override
# lists port verbatim.
_REFERENCE_KEY_ALIASES = {
    "num_initalize_layers": "num_initialize_layers",
    "dim_initalize_layer": "dim_initialize_layer",
}


def _parse_override(config: Config, key: str, raw: str):
    fields = {f.name: f for f in dataclasses.fields(Config)}
    if key not in fields:
        raise SystemExit(f"--set {key}: unknown Config field")
    current = getattr(config, key)
    if raw.lower() == "none":  # Optional[int] caps: 'none' clears the cap
        return None
    if isinstance(current, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(current, int):
        return int(raw)
    if isinstance(current, float):
        return float(raw)
    if isinstance(current, tuple):
        return tuple(int(x) for x in raw.split(","))
    if current is None:  # field currently None: best-effort int, else str
        try:
            return int(raw)
        except ValueError:
            return raw
    return raw


def build_config(argv: Optional[List[str]] = None):
    """Returns (Config, cli_options_dict)."""
    p = argparse.ArgumentParser(
        prog="sat_tpu",
        description="TPU-native Show, Attend and Tell",
    )
    p.add_argument(
        "--phase", default=None,
        choices=["train", "eval", "test", "serve", "route", "bulk"],
        help="default: train, or the --config file's phase when one is given",
    )
    p.add_argument(
        "--load", action="store_true",
        help="resume from the latest checkpoint in save_dir",
    )
    p.add_argument("--model_file", default=None, help="explicit checkpoint file")
    p.add_argument(
        "--load_cnn", action="store_true",
        help="import a pretrained CNN before training",
    )
    p.add_argument(
        "--cnn_model_file", default="./vgg16_no_fc.npy",
        help="pretrained CNN npy (reference nested format)",
    )
    p.add_argument(
        "--train_cnn", action="store_true",
        help="jointly train CNN + RNN (default: RNN only)",
    )
    p.add_argument("--beam_size", type=int, default=None)
    p.add_argument(
        "--shard_cache", default=None, choices=["auto", "on", "off"],
        help="preprocessed-image shard cache (data.shards): 'auto' "
             "(default) uses a valid existing cache and falls back to "
             "live JPEG decode otherwise, 'on' builds/extends the cache "
             "before the run, 'off' forces live decode",
    )
    p.add_argument(
        "--verify_shards", default=None,
        choices=["off", "sample", "open", "full"],
        help="verify gathered shard rows against their per-row crc32c "
             "sidecars (data/integrity.py): 'sample' scrubs one rotating "
             "row every few gathers (≪1%% of a step), 'open' fully "
             "verifies each shard on first touch, 'full' verifies every "
             "row every batch; corrupt rows fall back to live decode and, "
             "failing that, are quarantined (docs/DATA_PIPELINE.md)",
    )
    p.add_argument(
        "--repair_shards", action="store_true",
        help="rebuild only the shard files holding crc-mismatching or "
             "quarantined rows by re-decoding their source images "
             "(bitwise-identical to a clean rebuild), print a JSON "
             "report, and exit — no accelerator needed",
    )
    p.add_argument(
        "--anomaly_policy", default=None,
        choices=["off", "warn", "skip", "rollback"],
        help="anomaly-sentinel response to NaN/Inf or spiking metrics at "
             "each log_every check (docs/RESILIENCE.md): 'warn' (default) "
             "reports and stops blessing LAST_GOOD, 'skip' also suppresses "
             "checkpoint writes while unhealthy, 'rollback' restores "
             "LAST_GOOD and fast-forwards past the poison step, 'off' "
             "disarms the sentinel",
    )
    p.add_argument(
        "--keep_checkpoints", type=int, default=None, metavar="N",
        help="checkpoint retention: keep the newest N plus the LAST_GOOD "
             "target, delete the rest (default 0 = keep everything)",
    )
    p.add_argument(
        "--io_retries", type=int, default=None, metavar="N",
        help="retry budget for transient IO errors (EIO/EAGAIN/ESTALE...) "
             "on checkpoint/shard/manifest/caption reads and writes, with "
             "jittered exponential backoff (default 3; 0 disables)",
    )
    p.add_argument(
        "--telemetry", action="store_true",
        help="enable host-side span tracing: per-phase step-time "
             "breakdown at end of run, heartbeat.json run-health file, "
             "telemetry.jsonl snapshots, Chrome trace JSON "
             "(docs/OBSERVABILITY.md; adds no device syncs)",
    )
    p.add_argument(
        "--heartbeat_interval", type=float, default=None, metavar="SEC",
        help="seconds between heartbeat.json rewrites when --telemetry is "
             "on (default 10; 0 disables the heartbeat thread)",
    )
    p.add_argument(
        "--diag_level", default=None, choices=("off", "basic", "full"),
        help="in-graph model-health taps (grad/update/param norms, "
             "attention entropy, alpha-coverage deviation, logit max) "
             "merged into the train metrics at the existing log sync — "
             "zero extra device syncs; 'full' adds per-layer-group norms "
             "(docs/OBSERVABILITY.md)",
    )
    p.add_argument(
        "--metrics_port", type=int, default=None, metavar="PORT",
        help="train phase: read-only Prometheus /metrics + /healthz "
             "scrape endpoint riding the heartbeat payload (default 0 = "
             "off; the serve phase exposes /metrics on its own port)",
    )
    p.add_argument(
        "--trace_export", default=None, metavar="PATH",
        help="Chrome trace-event JSON output path (default "
             "<summary_dir>/telemetry/trace.json when --telemetry is on); "
             "load in Perfetto or chrome://tracing",
    )
    p.add_argument(
        "--fleet_telemetry", action="store_true",
        help="cross-host fleet plane (docs/OBSERVABILITY.md): each "
             "process writes a heartbeat_p<i>.json sidecar at the log "
             "boundary and process 0 merges them into fleet.json with "
             "per-host rows, skew ratios, and a straggler verdict; "
             "implies --telemetry (shared dir via --set fleet_dir=...)",
    )
    p.add_argument(
        "--blackbox", action="store_true",
        help="black-box flight recorder (docs/OBSERVABILITY.md): journal "
             "recent counters/gauges/events to a bounded on-disk ring and "
             "dump a postmortem_<run_id>/ bundle on abnormal exits "
             "(watchdog 86, data corruption 87, sentinel trips, uncaught "
             "exceptions); implies --telemetry",
    )
    p.add_argument(
        "--straggler_factor", type=float, default=None, metavar="X",
        help="fleet straggler threshold: name the worst host when its "
             "step-time p95 exceeds the fleet median by this factor "
             "(default 2.0)",
    )
    p.add_argument(
        "--port", type=int, default=None, metavar="PORT",
        help="serve phase: HTTP listen port (default Config.serve_port; "
             "0 picks an ephemeral port)",
    )
    p.add_argument(
        "--max_batch", type=int, default=None, metavar="N",
        help="serve phase: most requests per dispatched micro-batch "
             "(padded up to the bucket ladder, --set serve_buckets=...)",
    )
    p.add_argument(
        "--max_wait_ms", type=float, default=None, metavar="MS",
        help="serve phase: how long the batcher holds an underfull batch "
             "open waiting for more arrivals (latency/throughput knob, "
             "docs/SERVING.md)",
    )
    p.add_argument(
        "--replicas", default=None, metavar="HOST:PORT,...",
        help="route phase: front these pre-started serve replicas instead "
             "of spawning a local fleet (sat_tpu/serve/router.py)",
    )
    p.add_argument(
        "--num_replicas", type=int, default=None, metavar="N",
        help="route phase: size of the locally spawned replica fleet "
             "(ignored when --replicas is given; default "
             "Config.route_num_replicas)",
    )
    p.add_argument(
        "--serve_mode", choices=("batch", "continuous"), default=None,
        help="serve phase: 'batch' dispatches whole padded micro-batches "
             "(the correctness oracle); 'continuous' admits requests into "
             "a paged slot pool between decode steps and retires finished "
             "beams early (docs/SERVING.md)",
    )
    p.add_argument(
        "--serve_decode_depth", default=None, metavar="K1,K2,...",
        help="serve phase (continuous): the fused decode window ladder — "
             "comma-separated K values the adaptive policy may pick "
             "(the depth is a runtime operand of one AOT-warmed "
             "multi-step executable); the batcher runs the deepest K "
             "when the admission queue is idle and K=1 under burst "
             "(must start at 1; default "
             "Config.serve_decode_depth=1,2,4,8; docs/SERVING.md 'Fused "
             "decode window')",
    )
    p.add_argument(
        "--tenants", default=None, metavar="SPEC",
        help="serve/route phase: multi-tenant registry — a JSON file path "
             "or an inline 'name[:weight[:rps[:burst]]],...' list (first "
             "entry = the default tenant for requests without X-Tenant). "
             "Tenants get weighted deficit-round-robin scheduling, "
             "token-bucket admission quotas, per-tenant SLO burn lanes, "
             "and optional per-tenant resident models (docs/SERVING.md "
             "'Multi-tenant serving'; default Config.tenants='' = "
             "single-tenant)",
    )
    p.add_argument(
        "--serve_metering", choices=("on", "off"), default=None,
        help="serve phase: per-request cost attribution + per-tenant "
             "metering ledger + online capacity model (telemetry/"
             "metering.py, telemetry/capacity.py; docs/OBSERVABILITY.md "
             "'Cost attribution'). Only active when telemetry is on; "
             "default Config.serve_metering=True",
    )
    p.add_argument(
        "--encode_cache", choices=("on", "off"), default=None,
        help="serve phase: device-resident content-addressed LRU of "
             "encoder feature grids keyed by (image crc32c, param "
             "fingerprint, quant mode) — a hit skips the encode lane, a "
             "miss encodes once with single-flight coalescing "
             "(docs/SERVING.md 'Encode cache & tiered fleets'; default "
             "Config.encode_cache='off', bit-identical to pre-cache "
             "serving)",
    )
    p.add_argument(
        "--encode_cache_mb", type=int, default=None,
        help="serve phase: HBM budget for the encode-cache feature-grid "
             "ring (fixed geometry, sized at warmup; default "
             "Config.encode_cache_mb=64)",
    )
    p.add_argument(
        "--serve_tier", choices=("both", "encode", "decode"), default=None,
        help="serve phase: fleet tier this replica advertises — 'encode' "
             "(stateless POST /encode feature-grid tier), 'decode' "
             "(latency tier fed grids), or 'both' (default; untiered). "
             "Routing metadata only: every replica still answers direct "
             "image captions (docs/SERVING.md 'Encode cache & tiered "
             "fleets')",
    )
    p.add_argument(
        "--serve_quality", choices=("on", "off"), default=None,
        help="serve phase: caption-quality observability plane — "
             "per-request quality signals at the detok boundary, "
             "streaming PSI drift vs a frozen reference, exemplar "
             "flight recorder + bitwise replay (telemetry/quality.py, "
             "telemetry/exemplar.py; docs/OBSERVABILITY.md 'Caption "
             "quality'). Default Config.serve_quality='off' — off is "
             "bit-identical to the pre-quality serve path",
    )
    p.add_argument(
        "--quality_reference", default=None, metavar="JSON",
        help="serve phase: quality_reference.json to load as the frozen "
             "drift reference (exported by GET /quality_reference); "
             "default '' freezes the reference from the first "
             "serve_quality_window live requests",
    )
    p.add_argument(
        "--slo_quality_psi", type=float, default=None, metavar="PSI",
        help="serve phase: quality_drift SLO lane — gauge_ceiling over "
             "quality/psi_max (population-stability drift score); "
             "diagnostic like tenant lanes (/healthz stays ok while it "
             "burns); 0 disables; default Config.slo_quality_psi=0",
    )
    p.add_argument(
        "--slo_quality_unk", type=float, default=None, metavar="RATE",
        help="serve phase: quality_unk SLO lane — gauge_ceiling over the "
             "windowed quality/unk_rate; 0 disables; default "
             "Config.slo_quality_unk=0",
    )
    p.add_argument(
        "--slo_capacity_headroom_pct", type=float, default=None,
        metavar="PCT",
        help="serve phase: capacity_headroom SLO objective — alert when "
             "the capacity model's headroom gauge falls below PCT "
             "(gauge_floor kind; 0 disables; default "
             "Config.slo_capacity_headroom_pct=0)",
    )
    p.add_argument(
        "--encoder_quant", choices=("off", "bf16", "int8"), default=None,
        help="serve phase: post-training quantization of the frozen CNN "
             "encoder at param load, before AOT warmup (docs/SERVING.md "
             "'Precision & parity').  'int8' = per-output-channel symmetric "
             "int8 kernels + calibrated activation scales, convs run "
             "int8xint8->int32 on the MXU with fused dequant; 'bf16' = "
             "bfloat16 kernel storage; 'off' (default) is bitwise the "
             "unquantized path",
    )
    p.add_argument(
        "--model_reload", type=float, default=None, metavar="SEC",
        help="serve phase: poll the lineage LAST_GOOD pointer every SEC "
             "seconds (jittered) and hot-swap new checkpoints through a "
             "canary stage without restarting the server (0 = off, the "
             "load-once default; docs/SERVING.md 'Model lifecycle')",
    )
    p.add_argument(
        "--canary_fraction", type=float, default=None, metavar="F",
        help="serve phase: fraction of requests routed to the candidate "
             "params during the canary window, sticky per X-Request-Id "
             "(default Config.canary_fraction)",
    )
    p.add_argument(
        "--canary_window_s", type=float, default=None, metavar="SEC",
        help="serve phase: canary qualification window length before "
             "promote/rollback is decided (default Config.canary_window_s)",
    )
    p.add_argument(
        "--promote_policy", choices=("auto", "manual"), default=None,
        help="serve phase: 'auto' promotes a candidate whose canary window "
             "elapsed without the canary SLO burning; 'manual' holds in "
             "CANARY until POST /promote or /rollback",
    )
    p.add_argument(
        "--bulk_input", default=None, metavar="PATH",
        help="bulk phase: image corpus — a directory tree (recursively "
             "walked for images; non-image files are skipped and counted) "
             "or a text file listing one image path per line "
             "(docs/BULK.md)",
    )
    p.add_argument(
        "--bulk_output", default=None, metavar="DIR",
        help="bulk phase: output directory for captions_<shard>.jsonl + "
             "crc sidecars and the bulk_manifest.json resume frontier",
    )
    p.add_argument(
        "--bulk_shard_rows", type=int, default=None, metavar="N",
        help="bulk phase: images per output shard — the resume grain; a "
             "killed run re-decodes at most one shard (default "
             "Config.bulk_shard_rows)",
    )
    p.add_argument(
        "--supervise", action="store_true",
        help="crash-only restart loop (docs/RESILIENCE.md): keep this "
             "process jax-free and run the real work in a child; a child "
             "that crashes, is killed, or is aborted by the hang watchdog "
             "(exit code 86) is relaunched with --load so it resumes from "
             "the LAST_GOOD checkpoint, with jittered exponential backoff "
             "and a bounded restart budget",
    )
    p.add_argument(
        "--max_restarts", type=int, default=None, metavar="N",
        help="--supervise restart budget (default "
             "Config.supervise_max_restarts)",
    )
    p.add_argument(
        "--watchdog", type=float, default=None, metavar="SEC",
        help="arm the hang/wedge watchdog with this observer poll interval "
             "(sets watchdog_interval; per-phase deadlines via --set "
             "watchdog_step_s=... etc.; 0 disables — the default)",
    )
    p.add_argument(
        "--config", default=None, metavar="JSON",
        help="load a Config JSON (e.g. the save_dir sidecar a checkpoint "
             "rode with) as the base instead of built-in defaults; "
             "--set/--phase still override it",
    )
    p.add_argument(
        "--sweep", action="store_true",
        help="eval phase: score EVERY checkpoint under save_dir "
             "(the reference's eval.sh loop), writing <step>.txt dumps",
    )
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="override any Config field, repeatable",
    )
    p.add_argument(
        "--print_config", action="store_true",
        help="print the fully resolved Config as JSON and exit (audits "
             "--set stacks and env path re-rooting without running)",
    )
    args = p.parse_args(argv)
    if args.sweep and (args.model_file or args.load):
        raise SystemExit(
            "--sweep scores every checkpoint under save_dir; it conflicts "
            "with --model_file/--load"
        )

    if args.config:
        # file values are the base; only EXPLICIT flags override them
        # (each flag's absent-default is a sentinel; train_cnn is a
        # store_true — absent means "keep the file's value")
        config = Config.load(args.config)
        if args.phase is not None:
            config = config.replace(phase=args.phase)
        if args.train_cnn:
            config = config.replace(train_cnn=True)
        if args.beam_size is not None:
            config = config.replace(beam_size=args.beam_size)
    else:
        config = Config(
            phase=args.phase if args.phase is not None else "train",
            train_cnn=args.train_cnn,
            beam_size=args.beam_size if args.beam_size is not None else 3,
        )
    if args.shard_cache is not None:
        config = config.replace(shard_cache=args.shard_cache)
    if args.verify_shards is not None:
        config = config.replace(verify_shards=args.verify_shards)
    if args.anomaly_policy is not None:
        config = config.replace(anomaly_policy=args.anomaly_policy)
    if args.keep_checkpoints is not None:
        config = config.replace(keep_checkpoints=args.keep_checkpoints)
    if args.io_retries is not None:
        config = config.replace(io_retries=args.io_retries)
    if args.telemetry:
        config = config.replace(telemetry=True)
    if args.fleet_telemetry:
        # both ride the span recorder, so they imply the base layer
        config = config.replace(fleet_telemetry=True, telemetry=True)
    if args.blackbox:
        config = config.replace(blackbox=True, telemetry=True)
    if args.straggler_factor is not None:
        config = config.replace(straggler_factor=args.straggler_factor)
    if args.heartbeat_interval is not None:
        config = config.replace(heartbeat_interval=args.heartbeat_interval)
    if args.metrics_port is not None:
        config = config.replace(metrics_port=args.metrics_port)
    if args.trace_export is not None:
        config = config.replace(trace_export=args.trace_export)
    if args.diag_level is not None:
        config = config.replace(diag_level=args.diag_level)
    if args.replicas is not None:
        # naming endpoints implies the route phase (before --port below,
        # which binds to the router in route phase)
        config = config.replace(phase="route", route_replicas=args.replicas)
    if args.num_replicas is not None:
        config = config.replace(route_num_replicas=args.num_replicas)
    if args.port is not None:
        # one --port flag, two listeners: in route phase it is the
        # router's own port, otherwise the replica's
        if config.phase == "route":
            config = config.replace(route_port=args.port)
        else:
            config = config.replace(serve_port=args.port)
    if args.max_batch is not None:
        config = config.replace(serve_max_batch=args.max_batch)
    if args.max_wait_ms is not None:
        config = config.replace(serve_max_wait_ms=args.max_wait_ms)
    if args.serve_mode is not None:
        config = config.replace(serve_mode=args.serve_mode)
    if args.serve_decode_depth is not None:
        config = config.replace(serve_decode_depth=tuple(
            int(k) for k in args.serve_decode_depth.split(",") if k
        ))
    if args.tenants is not None:
        config = config.replace(tenants=args.tenants)
    if args.serve_metering is not None:
        config = config.replace(serve_metering=args.serve_metering == "on")
    if args.encode_cache is not None:
        config = config.replace(encode_cache=args.encode_cache)
    if args.encode_cache_mb is not None:
        config = config.replace(encode_cache_mb=args.encode_cache_mb)
    if args.serve_tier is not None:
        config = config.replace(serve_tier=args.serve_tier)
    if args.serve_quality is not None:
        config = config.replace(serve_quality=args.serve_quality)
    if args.quality_reference is not None:
        config = config.replace(serve_quality_reference=args.quality_reference)
    if args.slo_quality_psi is not None:
        config = config.replace(slo_quality_psi=args.slo_quality_psi)
    if args.slo_quality_unk is not None:
        config = config.replace(slo_quality_unk=args.slo_quality_unk)
    if args.slo_capacity_headroom_pct is not None:
        config = config.replace(
            slo_capacity_headroom_pct=args.slo_capacity_headroom_pct
        )
    if args.encoder_quant is not None:
        config = config.replace(encoder_quant=args.encoder_quant)
    if args.model_reload is not None:
        config = config.replace(model_reload=args.model_reload)
    if args.canary_fraction is not None:
        config = config.replace(canary_fraction=args.canary_fraction)
    if args.canary_window_s is not None:
        config = config.replace(canary_window_s=args.canary_window_s)
    if args.promote_policy is not None:
        config = config.replace(promote_policy=args.promote_policy)
    if args.bulk_input is not None:
        config = config.replace(bulk_input=args.bulk_input)
    if args.bulk_output is not None:
        config = config.replace(bulk_output=args.bulk_output)
    if args.bulk_shard_rows is not None:
        config = config.replace(bulk_shard_rows=args.bulk_shard_rows)
    if args.watchdog is not None:
        config = config.replace(watchdog_interval=args.watchdog)
    overrides = {}
    for item in args.set:
        if "=" not in item:
            raise SystemExit(f"--set expects KEY=VALUE, got {item!r}")
        key, raw = item.split("=", 1)
        key = _REFERENCE_KEY_ALIASES.get(key, key)
        overrides[key] = _parse_override(config, key, raw)
    if overrides:
        config = config.replace(**overrides)
    # env-driven path re-rooting (SAT_DATA_ROOT / SAT_LOG_ROOT); explicit
    # --set overrides win because re-rooting only touches default values
    config = config.apply_env_paths()
    # checked against the RESOLVED phase so `--sweep --config <eval cfg>`
    # works without restating --phase
    if args.sweep and config.phase != "eval":
        raise SystemExit("--sweep only applies to --phase=eval")

    cli = {
        "load": args.load,
        "model_file": args.model_file,
        "load_cnn": args.load_cnn,
        "cnn_model_file": args.cnn_model_file,
        "sweep": args.sweep,
        "print_config": args.print_config,
        "supervise": args.supervise,
        "max_restarts": args.max_restarts,
        "repair_shards": args.repair_shards,
    }
    return config, cli


def _postmortem(reason: str, exit_code: "Optional[int]" = None, **fields) -> None:
    """Best-effort black-box bundle on an abnormal CLI exit path — a
    no-op unless the run installed a recorder (``--blackbox``)."""
    try:
        from .telemetry import blackbox as _blackbox

        _blackbox.dump(reason, exit_code=exit_code, **fields)
    except Exception:
        pass  # the process is already dying; forensics must not mask why


def _arm_device_watchdog() -> "callable":
    """Warn (don't abort) when device initialization stalls.

    A wedged TPU tunnel makes jax.devices() block uninterruptibly with no
    output (observed repeatedly in this environment); without a hint the
    CLI looks hung for no reason.  SAT_DEVICE_WATCHDOG_S tunes the delay
    (default 180s, 0 disables).  Returns a disarm callback."""
    import os
    import threading

    delay = float(os.environ.get("SAT_DEVICE_WATCHDOG_S", "180"))
    done = threading.Event()
    if delay <= 0:
        return done.set

    def monitor():
        if not done.wait(delay):
            print(
                f"sat_tpu: device initialization has taken >{delay:.0f}s — "
                "the TPU backend may be unreachable. For a CPU run, set "
                "JAX_PLATFORMS=cpu; to silence this, set "
                "SAT_DEVICE_WATCHDOG_S=0.",
                file=sys.stderr,
                flush=True,
            )

    threading.Thread(target=monitor, daemon=True).start()
    return done.set


def main(argv: Optional[List[str]] = None) -> int:
    config, cli = build_config(argv)

    if cli["print_config"]:
        import json

        print(json.dumps(config.to_dict(), indent=2, sort_keys=True))
        return 0

    if cli["repair_shards"]:
        # jax-free maintenance mode: rot repair touches only the shard
        # files and manifest (data/integrity.py)
        import json

        from .data.integrity import repair_shards

        try:
            report = repair_shards(config)
        except FileNotFoundError:
            print(
                "sat_tpu: --repair_shards: no shard cache exists for this "
                f"config (looked under {config.shard_cache_dir!r})",
                file=sys.stderr,
                flush=True,
            )
            return 2
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0

    if cli["supervise"]:
        # the supervisor parent must NEVER import jax: the failure it
        # exists to outlive is device init wedging uninterruptibly, so
        # dispatch to the restart loop before the jax bootstrap below.
        # The child re-enters this CLI without --supervise/--max_restarts.
        from .resilience.supervisor import supervise

        return supervise(
            list(argv) if argv is not None else list(sys.argv[1:]),
            max_restarts=(
                cli["max_restarts"]
                if cli["max_restarts"] is not None
                else config.supervise_max_restarts
            ),
            backoff_base_s=config.supervise_backoff_s,
        )

    if config.phase == "route":
        # the fleet router is jax-free by the same contract as the
        # supervisor parent: it must outlive a replica whose device
        # runtime wedges, so dispatch before the jax bootstrap below —
        # the replicas it spawns re-enter this CLI in --phase serve and
        # own the device stack themselves.
        from .serve.router import route

        return route(config)

    # multi-host bootstrap first, before any other jax use (no-op unless a
    # launcher/env signals a cluster — see parallel.mesh)
    from .parallel import initialize_distributed

    initialize_distributed()

    disarm = _arm_device_watchdog()
    import jax

    # Honor JAX_PLATFORMS even when a sitecustomize force-registered a
    # different PJRT plugin over it (observed in this environment: the
    # env var alone loses the race and a JAX_PLATFORMS=cpu run still
    # hangs inside a dead TPU tunnel's device init).
    want = os.environ.get("JAX_PLATFORMS", "")
    if want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass  # backend already initialized

    jax.devices()  # force backend init under the watchdog
    disarm()

    from . import runtime
    from .resilience import CheckpointWriteError, SimulatedPreemption
    from .resilience import retry as _retry
    from .resilience.quarantine import (
        DATA_CORRUPTION_EXIT_CODE,
        SystemicCorruption,
    )

    # process-wide IO-retry knobs for every phase (train re-applies them,
    # but eval/test read shards and caption files through retry_io too)
    _retry.configure(config.io_retries, config.io_retry_base_s)

    if config.phase == "train":
        state = runtime.setup_state(
            config,
            load=cli["load"],
            model_file=cli["model_file"],
            load_cnn=cli["load_cnn"],
            cnn_model_file=cli["cnn_model_file"],
        )
        try:
            runtime.train(config, state=state)
        except CheckpointWriteError as e:
            # the run trained but a checkpoint it depends on did not land
            # — warn + non-zero exit instead of a swallowed queue failure
            # or a bare traceback (docs/RESILIENCE.md)
            print(f"sat_tpu: WARNING: {e}", file=sys.stderr, flush=True)
            _postmortem("checkpoint_write_failed", 1, error=str(e))
            return 1
        except SimulatedPreemption as e:
            # injected die-at-step-k: behave like the preempted process
            # the injection simulates (non-zero exit; supervisor relaunches
            # with --load)
            print(f"sat_tpu: {e}", file=sys.stderr, flush=True)
            _postmortem("simulated_preemption", 1, error=str(e))
            return 1
        except SystemicCorruption as e:
            # the quarantine ceiling tripped: the input data is rotten,
            # not the process — a distinct exit code the supervisor
            # refuses to restart (a rerun re-reads the same rot)
            print(f"sat_tpu: FATAL: {e}", file=sys.stderr, flush=True)
            _postmortem(
                "systemic_corruption", DATA_CORRUPTION_EXIT_CODE, error=str(e)
            )
            return DATA_CORRUPTION_EXIT_CODE
        except Exception as e:
            # any other crash: leave forensics behind, then fail loudly
            # with the original traceback
            _postmortem("uncaught_exception", None, error=repr(e))
            raise
        # graceful SIGTERM/SIGINT: train() drained and returned normally —
        # fall through to exit 0 so the supervisor relaunches into --load
    elif config.phase == "serve":
        from .serve.server import serve as _serve

        return _serve(config, model_file=cli["model_file"])
    elif config.phase == "bulk":
        from .bulk.runner import run_bulk

        try:
            return run_bulk(config, model_file=cli["model_file"])
        except SimulatedPreemption as e:
            # injected die-at-step-k: behave like a real preemption — the
            # supervisor relaunches and the manifest frontier resumes
            print(f"sat_tpu: {e}", file=sys.stderr, flush=True)
            _postmortem("simulated_preemption", 1, error=str(e))
            return 1
        except SystemicCorruption as e:
            # quarantine ceiling: the corpus is rotten, not the process —
            # exit 87, which the supervisor refuses to restart
            print(f"sat_tpu: FATAL: {e}", file=sys.stderr, flush=True)
            _postmortem(
                "systemic_corruption", DATA_CORRUPTION_EXIT_CODE, error=str(e)
            )
            return DATA_CORRUPTION_EXIT_CODE
        except Exception as e:
            _postmortem("uncaught_exception", None, error=repr(e))
            raise
    elif config.phase == "eval":
        if cli["sweep"]:
            sweep = runtime.evaluate_sweep(config)
            for step in sorted(sweep):
                line = "  ".join(f"{k}={v:.4f}" for k, v in sweep[step].items())
                print(f"step {step}: {line}")
            return 0
        state = runtime.setup_state(
            config, load=True, model_file=cli["model_file"]
        )
        scores = runtime.evaluate(config, state=state)
        for k, v in scores.items():
            print(f"{k}: {v:.4f}")
    else:
        state = runtime.setup_state(
            config, load=True, model_file=cli["model_file"]
        )
        runtime.test(config, state=state)
    return 0


if __name__ == "__main__":
    sys.exit(main())
