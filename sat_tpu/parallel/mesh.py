"""Device mesh construction and multi-host bootstrap.

The reference builds its cluster from PS_HOSTS/WORKER_HOSTS/JOB_NAME/
TASK_INDEX env vars and starts one gRPC `tf.train.Server` per process
(/root/reference/clusterone_config.py:39-61,106-114).  The TPU-native
equivalent is a GSPMD device mesh: every process runs the SAME program,
`jax.distributed.initialize` wires DCN coordination, and the `Mesh` lays
the global device set out as named axes:

* ``data``  — batch sharding; gradient psum rides ICI along this axis;
* ``model`` — parameter sharding (vocab-dim embedding/softmax, the
  TP-style axis SURVEY.md §2 calls for).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

from ..config import Config


# Env vars whose presence signals a multi-process launch worth wiring up.
_MULTIHOST_ENV_SIGNALS = (
    "JAX_COORDINATOR_ADDRESS",      # explicit JAX bootstrap
    "TPU_WORKER_HOSTNAMES",         # Cloud TPU pod slice
    "MEGASCALE_COORDINATOR_ADDRESS",  # multi-slice DCN
    "SLURM_STEP_NODELIST",          # SLURM launcher
)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Multi-host bootstrap (replaces the reference's tf.train.Server +
    ClusterSpec plumbing, clusterone_config.py:106-114).

    Call once per process BEFORE any other jax use.  Whether to wire a
    cluster is decided purely from the arguments and launcher env vars —
    never by querying the (not-yet-initialized) backend.  Returns True if
    `jax.distributed.initialize` was invoked.  Plain single-host runs are
    a no-op, mirroring the reference's single-machine fallback
    (clusterone_config.py:91-93).
    """
    explicit = coordinator_address is not None or num_processes is not None
    env_signal = any(os.environ.get(k) for k in _MULTIHOST_ENV_SIGNALS)
    if not explicit and not env_signal:
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def mesh_from_devices(
    devices: Sequence[jax.Device],
    shape: Tuple[int, ...],
    axes: Tuple[str, ...],
) -> Mesh:
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, only {len(devices)} available"
        )
    return Mesh(np.asarray(devices[:n]).reshape(shape), axes)


def make_mesh(config: Config, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the (data, model) mesh from config.mesh_shape.

    ``mesh_shape=(0, m)`` means "all remaining devices on the data axis" —
    the common case where a checked-in config runs unchanged on any slice
    size (a deliberate upgrade over the reference's host-count env vars).
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(config.mesh_shape)
    axes = tuple(config.mesh_axes)
    if len(shape) != len(axes):
        raise ValueError(f"mesh_shape {shape} / mesh_axes {axes} length mismatch")
    if 0 in shape:
        fixed = int(np.prod([s for s in shape if s != 0]))
        if len([s for s in shape if s == 0]) != 1 or len(devices) % fixed:
            raise ValueError(f"cannot infer mesh {shape} over {len(devices)} devices")
        shape = tuple(len(devices) // fixed if s == 0 else s for s in shape)
    return mesh_from_devices(devices, shape, axes)


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get("data", 1)


def model_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get("model", 1)
