"""Record-level shard integrity: crc32c sidecars, verify-on-gather, repair.

The shard cache (``data.shards``) turned batch assembly into mmap
fancy-indexing — and thereby inherited storage's failure modes: a torn
write or flipped bit in a shard row is served to the model silently,
forever (the manifest's per-shard sha256 is only checked by hand).
This module is the detection half of the data-plane immune system
(``resilience.quarantine`` is the containment half):

* ``build_shard_cache`` writes a **per-row crc32c sidecar**
  (``shard-00000.crc.npy``, a uint32 array) next to every shard, using
  the same Castagnoli implementation the TFRecord writer already
  vectorized (``utils.summary``), batched here across rows;
* ``gather`` verifies rows against the sidecar per ``--verify_shards``:

  - ``off``    — nothing (default; trust the storage);
  - ``sample`` — one rotating row every :data:`SAMPLE_EVERY` gathers,
    amortized ≪1% of a step (scripts/bench_integrity.py gates it);
  - ``open``   — full verify of each shard the first time a gather
    touches it, cached bad-row set consulted thereafter;
  - ``full``   — every gathered row, every batch (audit mode);

* a detected-corrupt row is routed to the live-decode ``fallback``
  (the shard row IS the live path's post-resize uint8, so recovery is
  bitwise) and, failing that, quarantined;
* ``repair_shards`` (CLI ``--repair_shards``) rebuilds ONLY the shards
  holding crc-mismatching or ledger-quarantined rows, by re-decoding
  their source images in row order — bitwise-identical to a clean
  rebuild, without paying for one.

Sidecars are retrofitted lazily for caches built before this module
existed: the first verification of a legacy shard computes and writes
its sidecar from the current bytes (the best available truth).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..utils.summary import (
    _CRC_TABLE_NP,
    _CRC_VECTOR_MIN,
    _ADV1,
    _crc32c_scalar,
    _gf2_matmul,
    _matvec_vec,
    crc32c,
)

CRC_SUFFIX = ".crc.npy"
VERIFY_MODES = ("off", "sample", "open", "full")
# sample mode verifies one row every this many gather calls: with the
# ~3 ms cost of one 224px-row crc, cadence 16 amortizes to ~0.2 ms per
# step — under the 1%-of-30ms budget bench_integrity.py enforces
SAMPLE_EVERY = 16


def sidecar_path(shard_path: str) -> str:
    base = shard_path[:-4] if shard_path.endswith(".npy") else shard_path
    return base + CRC_SUFFIX


# ---------------------------------------------------------------------------
# Batched crc32c: one pass over [N, L] uint8 rows -> uint32[N].
#
# utils.summary.crc32c vectorizes ONE payload across K interleaved
# lanes; calling it per row would pay its ~3 ms GF(2) stitch setup per
# row.  Here the identical lane scheme runs with an extra leading batch
# axis — the byte loop is lane_rows iterations over an [N, K] state
# array — and the stitch matrices are memoized per (K, lane_rows), so
# N rows cost one setup.  Bitwise-identical to summary.crc32c per row
# (the oracle test in tests/test_integrity.py holds it to that).
# ---------------------------------------------------------------------------

_STITCH_CACHE: Dict[Tuple[int, int], List[np.ndarray]] = {}


def _stitch_chain(K: int, lane_rows: int) -> List[np.ndarray]:
    """Zero-advance matrices for the halving stitch: level i advances a
    lane over ``lane_rows * 2**i`` bytes (advance-by-lane_rows, squared
    per level)."""
    key = (K, lane_rows)
    chain = _STITCH_CACHE.get(key)
    if chain is None:
        adv_span = None
        bit_m = _ADV1
        r = lane_rows
        while r:
            if r & 1:
                adv_span = (
                    bit_m if adv_span is None else _gf2_matmul(bit_m, adv_span)
                )
            r >>= 1
            if r:
                bit_m = _gf2_matmul(bit_m, bit_m)
        chain = []
        m = adv_span
        k = K
        while k > 1:
            chain.append(m)
            k //= 2
            if k > 1:
                m = _gf2_matmul(m, m)
        _STITCH_CACHE[key] = chain
    return chain


def crc32c_rows(rows: np.ndarray) -> np.ndarray:
    """crc32c of each row of a [N, ...] uint8 array, vectorized across
    both the lane axis and the batch axis."""
    if len(rows) == 0:
        return np.empty(0, np.uint32)
    arr = np.ascontiguousarray(rows, dtype=np.uint8).reshape(len(rows), -1)
    N, L = arr.shape
    if L < _CRC_VECTOR_MIN:
        return np.array(
            [crc32c(arr[i].tobytes()) for i in range(N)], np.uint32
        )
    K = 1 << max(8, min(16, (L // 256).bit_length() - 1))
    lane_rows = L // K
    chunk = lane_rows * K
    # lane k of a row holds its CONTIGUOUS bytes [k*lane_rows, (k+1)*lane_rows)
    cols = arr[:, :chunk].reshape(N, K, lane_rows)
    states = np.zeros((N, K), np.uint32)
    states[:, 0] = 0xFFFFFFFF
    for j in range(lane_rows):
        states = _CRC_TABLE_NP[
            (states ^ cols[:, :, j]) & np.uint32(0xFF)
        ] ^ (states >> np.uint32(8))
    for m in _stitch_chain(K, lane_rows):
        left, right = states[:, 0::2], states[:, 1::2]
        states = _matvec_vec(m, left) ^ right
    crcs = states[:, 0]
    if chunk < L:
        out = np.empty(N, np.uint32)
        tail = arr[:, chunk:]
        for i in range(N):
            out[i] = _crc32c_scalar(tail[i].tobytes(), int(crcs[i])) ^ 0xFFFFFFFF
        return out
    return crcs ^ np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# sidecar IO
# ---------------------------------------------------------------------------


def write_row_crcs(shard_path: str, crcs: np.ndarray) -> str:
    """Atomic (tmp + rename) sidecar write; returns the sidecar path."""
    path = sidecar_path(shard_path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, np.asarray(crcs, np.uint32))  # sync-ok: host numpy
    os.replace(tmp, path)
    return path


def read_row_crcs(shard_path: str) -> Optional[np.ndarray]:
    """The sidecar's uint32 row crcs, or None when absent/unreadable."""
    path = sidecar_path(shard_path)
    try:
        return np.asarray(np.load(path), np.uint32)  # sync-ok: host numpy
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# verify-on-gather
# ---------------------------------------------------------------------------


class ShardIntegrity:
    """Per-cache verification state, attached to a ``ShardCache`` by
    ``enable_integrity`` and consulted from ``gather``.  Returns the
    *local* indices (into the gathered row list) that fail their crc;
    the gather routes those through fallback/quarantine."""

    def __init__(self, cache, mode: str) -> None:
        if mode not in VERIFY_MODES:
            raise ValueError(
                f"verify_shards must be one of {VERIFY_MODES}, got {mode!r}"
            )
        self.cache = cache
        self.mode = mode
        self._crcs: Dict[int, np.ndarray] = {}
        self._bad_rows: Dict[int, set] = {}
        self._opened: set = set()
        self._calls = 0
        self._cursor = 0

    def crcs_for(self, shard_idx: int) -> np.ndarray:
        crcs = self._crcs.get(shard_idx)
        if crcs is None:
            shard_path = os.path.join(
                self.cache.cache_dir, self.cache._shard_files[shard_idx]
            )
            crcs = read_row_crcs(shard_path)
            if crcs is None:
                # legacy cache (pre-sidecar): retrofit from current bytes
                crcs = crc32c_rows(
                    np.asarray(self.cache._shard(shard_idx))  # sync-ok: host numpy
                )
                write_row_crcs(shard_path, crcs)
            self._crcs[shard_idx] = crcs
        return crcs

    def _check(
        self,
        shard_idx: int,
        row_ids: Sequence[int],
        gathered: np.ndarray,
        local: Optional[Sequence[int]] = None,
    ) -> List[int]:
        """Compare gathered rows (the bytes about to be trained on)
        against the sidecar; returns mismatching local indices."""
        crcs = self.crcs_for(shard_idx)
        if local is None:
            local = range(len(row_ids))
        local = [i for i in local if row_ids[i] < len(crcs)]
        if not local:
            return []
        want = crcs[[row_ids[i] for i in local]]
        got = crc32c_rows(gathered[list(local)])
        telemetry.count("data/verify_rows", len(local))
        bad = [local[j] for j in np.nonzero(got != want)[0]]
        if bad:
            telemetry.count("data/corrupt_rows", len(bad))
        return bad

    def verify_gather(
        self, shard_idx: int, row_ids: Sequence[int], gathered: np.ndarray
    ) -> List[int]:
        if self.mode == "off" or not len(row_ids):
            return []
        if self.mode == "full":
            return self._check(shard_idx, row_ids, gathered)
        if self.mode == "open":
            if shard_idx not in self._opened:
                self._opened.add(shard_idx)
                mm = self.cache._shard(shard_idx)
                whole = self._check(
                    shard_idx,
                    list(range(len(mm))),
                    np.asarray(mm),  # sync-ok: host numpy
                )
                self._bad_rows[shard_idx] = set(whole)
            bad = self._bad_rows.get(shard_idx, ())
            return [i for i, r in enumerate(row_ids) if r in bad]
        # sample: one deterministically rotating row every SAMPLE_EVERY
        # gather calls — a slow scrub that costs ~nothing per step
        self._calls += 1
        if self._calls % SAMPLE_EVERY:
            return []
        i = self._cursor % len(row_ids)
        self._cursor += 1
        return self._check(shard_idx, row_ids, gathered, [i])


# ---------------------------------------------------------------------------
# --repair_shards
# ---------------------------------------------------------------------------


def _ledger_files(ledger_path: str) -> set:
    """Normalized file paths of image-kind entries in a quarantine
    ledger (caption-kind entries are positional, not file rot)."""
    files = set()
    try:
        with open(ledger_path) as f:
            lines = f.readlines()
    except OSError:
        return files
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            entry = json.loads(line)
        except ValueError:
            continue
        if entry.get("kind") != "caption" and entry.get("file"):
            files.add(os.path.normpath(os.path.abspath(entry["file"])))
    return files


def repair_shards(config, loader=None) -> Dict:
    """Rebuild only the shards holding crc-mismatching or quarantined
    rows; returns a JSON-able report.  Jax-free (CLI dispatches it
    before any backend init).

    Raises FileNotFoundError when no cache exists for this config."""
    from ..resilience.quarantine import ledger_path_for
    from ..utils.fileio import atomic_write
    from .shards import (
        MANIFEST_NAME,
        ShardCache,
        _file_sha256,
        _manifest_hash,
        cache_dir_for,
    )

    cache_dir = cache_dir_for(config)
    cache = ShardCache.open(cache_dir, config.image_size)
    quarantined = _ledger_files(ledger_path_for(config))

    # reverse the manifest: shard -> [(row, file)] (entry keys ARE the
    # normalized absolute source paths)
    shard_rows: Dict[int, List[Tuple[int, str]]] = {}
    for key, (si, row) in cache._entries.items():
        shard_rows.setdefault(si, []).append((row, key))

    if loader is None:
        from .images import ImageLoader

        loader = ImageLoader(size=config.image_size, raw=True)

    report: Dict = {
        "cache_dir": cache_dir,
        "shards_total": len(cache._shard_files),
        "shards_rebuilt": 0,
        "rows_rebuilt": 0,
        "suspect_shards": [],
        "unrepairable": [],
    }
    manifest = cache.manifest
    for si, name in enumerate(cache._shard_files):
        shard_path = os.path.join(cache_dir, name)
        mm = cache._shard(si)
        data = np.asarray(mm)  # sync-ok: host numpy
        crcs = read_row_crcs(shard_path)
        if crcs is None:
            # no sidecar: the current bytes are the only truth — write
            # one so future corruption is at least detectable
            write_row_crcs(shard_path, crc32c_rows(data))
            crcs = read_row_crcs(shard_path)
        got = crc32c_rows(data)
        mismatches = sorted(int(r) for r in np.nonzero(got != crcs)[0])
        rows = sorted(shard_rows.get(si, []))
        quarantined_here = sorted(
            f for _, f in rows if f in quarantined
        )
        if not mismatches and not quarantined_here:
            continue
        report["suspect_shards"].append(
            {
                "shard": name,
                "crc_mismatch_rows": mismatches,
                "quarantined_files": quarantined_here,
            }
        )
        tmp = shard_path + ".repair.tmp"
        new = np.lib.format.open_memmap(
            tmp, mode="w+", dtype=np.uint8, shape=mm.shape
        )
        try:
            for row, f in rows:
                try:
                    new[row] = loader.load_raw(f)
                    report["rows_rebuilt"] += 1
                except Exception as e:
                    # keep the old bytes: a source image that can't be
                    # re-decoded is the quarantine's problem, not a
                    # reason to lose the rest of the shard
                    new[row] = data[row]
                    report["unrepairable"].append(
                        {"file": f, "error": f"{type(e).__name__}: {e}"}
                    )
            new.flush()
        except BaseException:
            del new
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        del new
        cache._mmaps[si] = None  # drop the stale mmap before the swap
        os.replace(tmp, shard_path)
        write_row_crcs(
            shard_path,
            crc32c_rows(np.asarray(np.load(shard_path, mmap_mode="r"))),  # sync-ok: host numpy
        )
        manifest["shards"][si]["sha256"] = _file_sha256(shard_path)
        report["shards_rebuilt"] += 1
    if report["shards_rebuilt"]:
        manifest["content_hash"] = _manifest_hash(manifest)
        atomic_write(
            os.path.join(cache_dir, MANIFEST_NAME),
            "w",
            lambda f: json.dump(manifest, f),
        )
    return report
