#!/bin/bash
# One-shot TPU measurement session: run everything that needs the real
# chip, in priority order, each stage logged. Usage:
#   bash scripts/tpu_session.sh [outdir]
set -u
OUT=${1:-/tmp/tpu_session}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

echo "=== stage 0: device probe (compute round-trip) ==="
# bench.py --probe is the single source of the reachability check: a real
# matmul round-trip, because the tunneled backend has been observed
# returning the device list while all computation hangs
timeout 180 python bench.py --probe || { echo "TPU unreachable; aborting"; exit 3; }

FAILED=""

echo "=== stage 1: bench batch sweep (MFU) ==="
for B in 32 64 128; do
  echo "--- BENCH_BATCH=$B ---"
  BENCH_BATCH=$B BENCH_WATCHDOG_S=480 timeout 500 python bench.py \
    2>"$OUT/bench_B$B.log" | tee "$OUT/bench_B$B.json"
  rc=${PIPESTATUS[0]}
  if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_B$B.json" ]; then
    echo "STAGE FAILED: bench B=$B (rc=$rc) — see $OUT/bench_B$B.log"
    FAILED="$FAILED bench_B$B"
  fi
done

echo "=== stage 1a2: joint CNN+RNN training throughput ==="
BENCH_TRAIN_CNN=1 BENCH_WATCHDOG_S=480 timeout 500 python bench.py \
  2>"$OUT/bench_joint.log" | tee "$OUT/bench_joint.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_joint.json" ]; then
  echo "STAGE FAILED: bench joint (rc=$rc)"; FAILED="$FAILED bench_joint"
fi

echo "=== stage 1b: eval decode throughput (beam=3, B=32 and B=64) ==="
for EB in 32 64; do
  timeout 500 python scripts/bench_eval.py --batch $EB \
    2>"$OUT/bench_eval_B$EB.log" | tee "$OUT/bench_eval_B$EB.json"
  rc=${PIPESTATUS[0]}
  if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_eval_B$EB.json" ]; then
    echo "STAGE FAILED: bench_eval B=$EB (rc=$rc)"; FAILED="$FAILED bench_eval_B$EB"
  fi
done

echo "=== stage 1c: A/B knobs (dropout PRNG, decoder/encoder remat, resnet50) ==="
for label in "rng_threefry BENCH_RNG_IMPL=threefry2x32" \
             "remat_decoder BENCH_REMAT=1" \
             "remat_cnn_joint BENCH_TRAIN_CNN=1 BENCH_REMAT_CNN=1" \
             "resnet50 BENCH_CNN=resnet50" \
             "ce_bf16 BENCH_CE_DTYPE=bfloat16 BENCH_BATCH=128"; do
  name=${label%% *}; envs=${label#* }
  echo "--- $name ($envs) ---"
  env $envs BENCH_EVAL=0 BENCH_WATCHDOG_S=480 timeout 500 python bench.py \
    2>"$OUT/bench_$name.log" | tee "$OUT/bench_$name.json"
  rc=${PIPESTATUS[0]}
  if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_$name.json" ]; then
    echo "STAGE FAILED: bench_$name (rc=$rc)"; FAILED="$FAILED bench_$name"
  fi
done

echo "=== stage 1d: eval-throughput A/B (fresh vs train-resident process) ==="
# outer timeout > sum of internal budgets: 6 arms x 420s
timeout 2600 python scripts/bench_eval_ab.py --budget-s 420 \
  --out "$OUT/bench_eval_ab.json" >/dev/null 2>"$OUT/bench_eval_ab.log"
rc=$?
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_eval_ab.json" ]; then
  echo "STAGE FAILED: bench_eval_ab (rc=$rc)"; FAILED="$FAILED bench_eval_ab"
fi

echo "=== stage 1e: serving smoke (AOT warmup + micro-batched load) ==="
# boots the full serving stack on the chip: lineage load, per-bucket AOT
# warmup, closed+open-loop load, then the continuous arms — fused-ladder
# single stream + near-capacity open loop and the K=1 A/B arm; exits
# nonzero if ANY lane recompiled in steady state (budget covers the
# extra continuous boot the K-ladder A/B adds)
timeout 900 python scripts/bench_serve.py \
  2>"$OUT/bench_serve.log" | tee "$OUT/bench_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_serve.json" ]; then
  echo "STAGE FAILED: bench_serve (rc=$rc) — see $OUT/bench_serve.log"
  FAILED="$FAILED bench_serve"
fi

echo "=== stage 1e2: fused decode window (K-lane parity on the chip) ==="
# decode_multi_step's lax.while_loop through the REAL compiler: bitwise
# K-lane parity vs stepped K=1, on-device early exit, and the ladder
# warmup's zero-recompile contract (the CPU container only proves the
# host side of these)
timeout 600 python -m pytest tests/test_continuous.py -q \
  -k "fused or multi_step or adaptive" 2>&1 | tee "$OUT/fused_decode.txt"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ]; then
  echo "STAGE FAILED: fused_decode (rc=$rc) — see $OUT/fused_decode.txt"
  FAILED="$FAILED fused_decode"
fi

echo "=== stage 1f: quantized-encoder A/B (int8 eval decode + serve closed loop) ==="
timeout 600 python scripts/bench_eval.py --batch 32 --encoder-quant int8 \
  2>"$OUT/bench_quant_eval.log" | tee "$OUT/bench_quant_eval.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_quant_eval.json" ]; then
  echo "STAGE FAILED: bench_quant_eval (rc=$rc)"; FAILED="$FAILED bench_quant_eval"
fi
# second engine boot on top of the base run, hence ~2x the stage-1e budget
timeout 1300 python scripts/bench_serve.py --quant-ab int8 \
  2>"$OUT/bench_quant_serve.log" | tee "$OUT/bench_quant_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_quant_serve.json" ]; then
  echo "STAGE FAILED: bench_quant_serve (rc=$rc) — see $OUT/bench_quant_serve.log"
  FAILED="$FAILED bench_quant_serve"
fi

echo "=== stage 1g: fleet serve (router-fronted goodput scaling at 1/2/4 replicas) ==="
# spawns max(fleet-sizes) replica subprocesses once, then open-loop load
# through the jax-free router per fleet size, then boots a second 2-replica
# encode/decode tiered fleet for the disaggregated arm; exits nonzero if
# any replica recompiled in steady state (budget: replica boots + 4 arms)
timeout 1500 python scripts/bench_serve.py --fleet \
  2>"$OUT/fleet_serve.log" | tee "$OUT/fleet_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/fleet_serve.json" ]; then
  echo "STAGE FAILED: fleet_serve (rc=$rc) — see $OUT/fleet_serve.log"
  FAILED="$FAILED fleet_serve"
fi

echo "=== stage 1h: bulk offline captioning (throughput + resume overhead) ==="
# three CLI child runs (seed checkpoint, decode, resume); exits nonzero
# if the decode loop recompiled in steady state
timeout 900 python scripts/bench_bulk.py \
  2>"$OUT/bench_bulk.log" | tee "$OUT/bench_bulk.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/bench_bulk.json" ]; then
  echo "STAGE FAILED: bench_bulk (rc=$rc) — see $OUT/bench_bulk.log"
  FAILED="$FAILED bench_bulk"
fi

echo "=== stage 1i: lifecycle serve (hot-swap reload -> canary -> promote) ==="
# a full zero-downtime reload cycle on the chip: candidate load + canary
# routing under open-loop load, operator promote with the drain-measured
# swap blackout; exits nonzero on any steady-state recompile or dropped
# request across the cycle
timeout 900 python scripts/bench_serve.py --lifecycle \
  2>"$OUT/lifecycle_serve.log" | tee "$OUT/lifecycle_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/lifecycle_serve.json" ]; then
  echo "STAGE FAILED: lifecycle_serve (rc=$rc) — see $OUT/lifecycle_serve.log"
  FAILED="$FAILED lifecycle_serve"
fi

echo "=== stage 1j: multi-tenant serve (SLO isolation + DRR fair share) ==="
# one continuous-mode server with a victim/peer/flood registry: victim
# p99 under a 5x-quota flood vs alone, then a contended fair-share
# window; exits nonzero on any steady-state recompile, victim-lane
# shed/error or flood 5xx
timeout 900 python scripts/bench_serve.py --tenants \
  2>"$OUT/tenant_serve.log" | tee "$OUT/tenant_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/tenant_serve.json" ]; then
  echo "STAGE FAILED: tenant_serve (rc=$rc) — see $OUT/tenant_serve.log"
  FAILED="$FAILED tenant_serve"
fi

echo "=== stage 1k: cost attribution + capacity probe (metering overhead gate) ==="
# charge-path microbench priced against the live p50 (hard gate 0.5%),
# then unique vs Zipf open-loop arms for the would-be encode-cache
# probe; exits nonzero on overhead over gate, accounting-identity error
# over 5%, any steady-state recompile, or a dead/false probe
timeout 900 python scripts/bench_serve.py --metering \
  2>"$OUT/metering_serve.log" | tee "$OUT/metering_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/metering_serve.json" ]; then
  echo "STAGE FAILED: metering_serve (rc=$rc) — see $OUT/metering_serve.log"
  FAILED="$FAILED metering_serve"
fi

echo "=== stage 1k2: encode cache (content-addressed HBM ring, Zipf vs unique) ==="
# one continuous server with the device-resident encode cache armed:
# cold/hot bitwise caption parity, then unique and Zipf open-loop arms;
# exits nonzero on any parity mismatch, steady-state recompile, Zipf hit
# ratio under 0.6, or a unique-arm ratio over 0.05 (false hits)
timeout 900 python scripts/bench_serve.py --encode-cache \
  2>"$OUT/cache_serve.log" | tee "$OUT/cache_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/cache_serve.json" ]; then
  echo "STAGE FAILED: cache_serve (rc=$rc) — see $OUT/cache_serve.log"
  FAILED="$FAILED cache_serve"
fi

echo "=== stage 1l: caption-quality plane (drift overhead gate) ==="
# quality-on live arm (zero-recompile assert, frozen reference) plus the
# signal-extraction+sketch microbench priced against the live p50; exits
# nonzero on overhead over the 0.5% gate, any steady-state recompile, or
# a dead quality block
timeout 900 python scripts/bench_quality.py \
  2>"$OUT/quality_serve.log" | tee "$OUT/quality_serve.json"
rc=${PIPESTATUS[0]}
if [ "$rc" -ne 0 ] || [ ! -s "$OUT/quality_serve.json" ]; then
  echo "STAGE FAILED: quality_serve (rc=$rc) — see $OUT/quality_serve.log"
  FAILED="$FAILED quality_serve"
fi

echo "=== stage 2: pallas attention measurement ==="
timeout 1800 python scripts/bench_pallas.py 2>&1 | tee "$OUT/pallas.txt"
rc=${PIPESTATUS[0]}
[ "$rc" -ne 0 ] && { echo "STAGE FAILED: pallas (rc=$rc)"; FAILED="$FAILED pallas"; }

echo "=== stage 2a: fused serve-path attention parity on the chip ==="
# slot-pool geometries (masked rows, odd batches) through the compiled
# Mosaic kernel — the CPU container only interpret-modes these, so this
# is the one place the masked pallas_call's lowering is actually tested
timeout 600 python -m pytest tests/test_continuous.py tests/test_pallas.py \
  -q -k pallas 2>&1 | tee "$OUT/pallas_serve.txt"
rc=${PIPESTATUS[0]}
[ "$rc" -ne 0 ] && { echo "STAGE FAILED: pallas_serve (rc=$rc)"; FAILED="$FAILED pallas_serve"; }

echo "=== stage 2b: jax.profiler trace of the train hot loop ==="
# one real trace backing the step-time/PrefetchLoader claims (r1 ask #8);
# profile_trace.sh owns the capture AND the artifact contract
# (profile_done.txt) shared with tpu_retry.sh
timeout 1200 bash scripts/profile_trace.sh "$OUT"
rc=$?
if [ "$rc" -ne 0 ]; then
  echo "STAGE FAILED: profiler trace (rc=$rc) — see $OUT/profile_train.log"
  FAILED="$FAILED profile"
fi

echo "=== stage 3: flagship quality run ==="
timeout 1200 python scripts/quality_run.py --steps 300 \
  2>&1 | tee "$OUT/quality.txt" | tail -20
rc=${PIPESTATUS[0]}
[ "$rc" -ne 0 ] && { echo "STAGE FAILED: quality run (rc=$rc)"; FAILED="$FAILED quality"; }

echo "=== stage 4 (optional, TPU_SESSION_RICH=1): rich-corpus quality + import-finetune ==="
if [ "${TPU_SESSION_RICH:-0}" = "1" ]; then
  # 224px -> the full 196-position context grid (VERDICT r4 next-round
  # #3): the 0.853 teacher-forced-accuracy plateau was localized to the
  # tiny grid a frozen encoder exposes at CPU image sizes; dropout 0 is
  # the saturation protocol (memorization-protocol dropout caps accuracy,
  # RESULTS.md rich-corpus-r4).  Affordable only on the chip.
  timeout 3600 python scripts/quality_run.py --corpus rich --frozen-cnn \
    --image-size 224 --batch-size 16 --steps 4000 --beam-compare \
    --extra-set fc_drop_rate=0.0 --extra-set lstm_drop_rate=0.0 \
    --out runs/quality_rich_224 2>&1 | tee "$OUT/quality_rich.txt" | tail -15
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { echo "STAGE FAILED: rich quality (rc=$rc)"; FAILED="$FAILED quality_rich"; }
  timeout 1800 python scripts/import_finetune_run.py 2>&1 \
    | tee "$OUT/import_ft.txt" | tail -8
  rc=${PIPESTATUS[0]}
  [ "$rc" -ne 0 ] && { echo "STAGE FAILED: import-finetune (rc=$rc)"; FAILED="$FAILED import_ft"; }
fi

if [ -n "$FAILED" ]; then
  echo "=== session finished with FAILED stages:$FAILED — artifacts in $OUT ==="
  exit 1
fi
echo "=== session complete; artifacts in $OUT ==="
