"""Exemplar flight recorder — bounded capture of outlier requests.

The quality plane's black box (``blackbox.py``'s rotating-segment
discipline applied to whole requests): when the streaming monitor
flags a request as an outlier — low beam margin, high unk rate, drift
contribution, eos truncation, shed/timeout — the recorder tail-samples
it into ``<dir>/seg_NNN.jsonl`` plus a crc32c-named copy of the raw
request image bytes, enough for ``scripts/replay_exemplar.py`` to boot
a fresh engine and reproduce the caption bitwise.

Bounded by construction: segments rotate at a fixed count x size, image
payloads share one disk budget with oldest-first eviction, and capture
is rate-limited so an anomaly storm records a sample, not the storm.
Appends are O_APPEND JSON lines; readers tolerate torn tails (a process
killed mid-append).  Jax-free and never raises into the serve path.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

META_FILE = "meta.json"


def _crc32c_hex(data: bytes) -> str:
    # zlib.crc32 (not the castagnoli polynomial) would be a different
    # checksum family than the shard sidecars use; route through the
    # same helper so "crc32c-named" means one thing repo-wide
    from ..utils.summary import crc32c

    return f"{crc32c(data):08x}"


def alphas_digest(alphas) -> Optional[str]:
    """A stable 8-hex digest of a request's drained attention maps —
    enough to tell two replays produced identical alphas without
    storing the full [K, T, N] tensor per exemplar."""
    if alphas is None:
        return None
    import numpy as np

    a = np.ascontiguousarray(np.asarray(alphas, np.float32))  # sync-ok: host numpy, already drained
    return f"{zlib.crc32(a.tobytes()) & 0xFFFFFFFF:08x}"


class ExemplarRecorder:
    """Rotating on-disk capture of outlier requests.

    One instance per serve process; ``record`` is called from the detok
    thread (outliers) and the HTTP error paths (shed/timeout), so it
    takes a lock, does bounded I/O, and swallows every failure — a full
    disk degrades capture, never serving.
    """

    def __init__(
        self,
        dir: str,
        *,
        budget_mb: float = 64.0,
        segment_rows: int = 64,
        segments: int = 8,
        image_cap_kb: float = 512.0,
        min_interval_s: float = 0.25,
        clock=time.monotonic,
    ) -> None:
        self.dir = dir
        self.budget_bytes = int(budget_mb * (1 << 20))
        self.segment_rows = max(1, int(segment_rows))
        self.segments = max(2, int(segments))
        self.image_cap = int(image_cap_kb * 1024)
        self.min_interval_s = float(min_interval_s)  # sync-ok: host config scalar
        self._clock = clock
        self._lock = threading.Lock()
        self._t_last = -float("inf")  # sync-ok: host sentinel
        self._idx = 0
        self._rows_in_seg = 0
        self.recorded = 0
        self.dropped = 0
        self._warned = False
        try:
            os.makedirs(self.dir, exist_ok=True)
            existing = sorted(
                glob.glob(os.path.join(self.dir, "seg_*.jsonl"))
            )
            if existing:
                newest = max(existing, key=os.path.getmtime)
                self._idx = int(os.path.basename(newest)[4:-6])
                with open(newest) as f:
                    self._rows_in_seg = sum(1 for _ in f)
        except (OSError, ValueError) as e:
            self._warn(f"init failed: {e}")

    # -- write side --------------------------------------------------------

    def write_meta(self, meta: Dict) -> None:
        """The replay context (config snapshot, checkpoint step, vocab
        fingerprint) written once at boot — replay refuses to guess."""
        try:
            path = os.path.join(self.dir, META_FILE)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(meta, f, sort_keys=True, indent=1)
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            self._warn(f"meta write failed: {e}")

    def record(
        self,
        *,
        reasons: List[str],
        request_id: str = "",
        tenant: str = "",
        caption: str = "",
        beams: Optional[List[Dict]] = None,
        signals: Optional[Dict[str, float]] = None,
        image_bytes: Optional[bytes] = None,
        alphas=None,
        status: int = 200,
        extra: Optional[Dict] = None,
    ) -> bool:
        """Tail-sample one outlier request; True when it landed on disk.
        Rate-limited: captures closer together than ``min_interval_s``
        are counted (``dropped``) but not written."""
        now = self._clock()
        with self._lock:
            if now - self._t_last < self.min_interval_s:
                self.dropped += 1
                return False
            self._t_last = now
            row = {
                "t_unix": round(time.time(), 3),
                "reasons": list(reasons),
                "request_id": request_id,
                "tenant": tenant,
                "status": int(status),
                "caption": caption,
                "beams": beams or [],
                "signals": {
                    k: round(float(v), 6)  # sync-ok: host scalar, already drained
                    for k, v in (signals or {}).items()
                },
                "alphas_digest": alphas_digest(alphas),
            }
            if extra:
                row.update(extra)
            try:
                row["image"], row["image_bytes"] = self._store_image(
                    image_bytes
                )
                self._append(row)
                self.recorded += 1
            except (OSError, TypeError, ValueError) as e:
                self._warn(f"record failed: {e}")
                return False
            try:
                self._enforce_budget()
            except OSError:
                pass  # budget enforcement is best-effort
            return True

    def _store_image(
        self, image_bytes: Optional[bytes]
    ) -> Tuple[Optional[str], int]:
        """(stored filename | None, original byte count).  Size-capped:
        an oversized body records its metadata but not its payload."""
        if not image_bytes:
            return None, 0
        n = len(image_bytes)
        if n > self.image_cap:
            return None, n
        name = f"img_{_crc32c_hex(image_bytes)}.bin"
        path = os.path.join(self.dir, name)
        if not os.path.exists(path):
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(image_bytes)
            os.replace(tmp, path)
        return name, n

    def _append(self, row: Dict) -> None:
        if self._rows_in_seg >= self.segment_rows:
            self._idx = (self._idx + 1) % self.segments
            self._rows_in_seg = 0
            path = self._segment_path(self._idx)
            open(path, "w").close()  # reclaim the oldest slot
        path = self._segment_path(self._idx)
        with open(path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        self._rows_in_seg += 1

    def _segment_path(self, idx: int) -> str:
        return os.path.join(self.dir, f"seg_{idx:03d}.jsonl")

    def _enforce_budget(self) -> None:
        """Keep the whole directory (segments + images) under the disk
        budget: unreferenced/oldest image payloads go first, then the
        oldest non-current segments."""
        entries = []
        total = 0
        for name in os.listdir(self.dir):
            path = os.path.join(self.dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            total += st.st_size
            entries.append((st.st_mtime, st.st_size, name, path))
        if total <= self.budget_bytes:
            return
        current = os.path.basename(self._segment_path(self._idx))
        for _mtime, size, name, path in sorted(entries):
            if name in (current, META_FILE):
                continue
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            if total <= self.budget_bytes:
                return

    def _warn(self, msg: str) -> None:
        if not self._warned:
            self._warned = True
            print(f"sat_tpu exemplar recorder: {msg}", file=sys.stderr)

    # -- read side ---------------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            return {"recorded": self.recorded, "dropped": self.dropped}


def read_meta(dir: str) -> Optional[Dict]:
    try:
        with open(os.path.join(dir, META_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_exemplars(dir: str) -> Tuple[List[Dict], int]:
    """(exemplar rows sorted by wall time, torn-line count).  Torn or
    garbage lines — a process killed mid-append — are skipped."""
    rows: List[Dict] = []
    torn = 0
    for path in sorted(glob.glob(os.path.join(dir, "seg_*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                        if not isinstance(rec, dict):
                            raise ValueError("not an object")
                        rows.append(rec)
                    except ValueError:
                        torn += 1
        except OSError:
            continue
    rows.sort(key=lambda r: r.get("t_unix", 0))
    return rows, torn


def load_image(dir: str, row: Dict) -> Optional[bytes]:
    """The stored request bytes for one exemplar row (None when the
    image was over the size cap or already evicted by the budget)."""
    name = row.get("image")
    if not name:
        return None
    try:
        with open(os.path.join(dir, name), "rb") as f:
            return f.read()
    except OSError:
        return None
