"""Measure the fused Pallas attention kernel against XLA on the real chip.

Decides the fate of ``use_pallas_attention`` (VERDICT r1 item 6): flagship
decode shapes, both implementations timed over identical inputs.  Round 5
extends the single-B=48 measurement to a batch sweep (VERDICT r4
next-round #8): default B ∈ {32, 48, 64, 128}, one correctness check and
one speedup per size, and the ENABLE verdict requires the kernel to hold
>= 1.0x at EVERY size — a knob that wins at one operating point and
loses at another must not be default-on.  Measurements run on TPU
(no platform override); ``--cpu`` exists only as a plumbing smoke.

Usage: python scripts/bench_pallas.py [--batch 48] [--iters 200] [--cpu]
  (--batch 0 = the default sweep; --cpu pins the host backend and runs
  the kernel in Pallas interpret mode — a smoke of the sweep/correctness
  plumbing, NOT a performance measurement)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(fn, args, iters: int) -> float:
    """On-device loop timing: ONE dispatched program runs ``iters``
    serially-dependent kernel invocations under lax.fori_loop (each
    iteration's query vector depends on the previous output context), and
    one device_get closes the window.  Host-side independent-dispatch
    timing is NOT trustworthy on the tunneled 'axon' platform — dispatch
    latency (~1 ms) swamps µs-scale kernels and block_until_ready has been
    observed returning before remote completion (see PERF.md)."""
    import jax

    t1, t2, w2, ctx = args

    @jax.jit
    def loop(t2c, t1, w2, ctx):
        def body(_, c):
            out_ctx, _alpha = fn(t1, c, w2, ctx)
            return c + out_ctx * 1e-6  # serializing dep, ~no perturbation
        return jax.lax.fori_loop(0, iters, body, t2c)

    jax.device_get(loop(t2, t1, w2, ctx)[0, 0])  # compile + warm
    t0 = time.perf_counter()
    out = loop(t2, t1, w2, ctx)
    jax.device_get(out[0, 0])
    return (time.perf_counter() - t0) / iters


def bench_one(B: int, iters: int, block_arg: int, interpret: bool = False):
    """Time XLA vs the kernel at one batch size; returns a result row or
    None when the kernel fails to lower at every tiling."""
    import jax
    import jax.numpy as jnp

    from sat_tpu.ops.pallas_attention import fused_attend, fused_attend_reference

    # flagship decode shapes: VGG16 grid N=196, da=D=512
    N, da, D = 196, 512, 512
    rng = np.random.default_rng(0)
    t1 = jnp.asarray(rng.normal(size=(B, N, da)).astype(np.float32))
    t2 = jnp.asarray(rng.normal(size=(B, da)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(da, 1)).astype(np.float32))
    ctx = jnp.asarray(rng.normal(size=(B, N, D)).astype(np.float32))

    xla = jax.jit(fused_attend_reference, static_argnames=("compute_dtype",))
    t_xla = timeit(xla, (t1, t2, w2, ctx), iters)
    traffic_mb = (t1.nbytes + ctx.nbytes) / 1e6
    print(
        f"[B={B:3d}] XLA fused:    {t_xla*1e6:8.1f} us   "
        f"(~{traffic_mb / t_xla / 1e3:.0f} GB/s effective)", flush=True,
    )

    # no divisibility guard: fused_attend pads the batch axis up to a
    # multiple of block_b, so every tiling is valid at every B
    blocks = [block_arg] if block_arg else [4, 8, 16]
    best = (None, float("inf"))
    for bb in blocks:
        try:
            t_pal = timeit(
                lambda *a: fused_attend(*a, block_b=bb, interpret=interpret),
                (t1, t2, w2, ctx), iters,
            )
        except Exception as e:  # mosaic lowering failure at this tiling
            print(f"[B={B:3d}] pallas bb={bb}: FAILED ({type(e).__name__}: {e})",
                  flush=True)
            continue
        print(
            f"[B={B:3d}] pallas bb={bb:2d}: {t_pal*1e6:8.1f} us   "
            f"(~{traffic_mb / t_pal / 1e3:.0f} GB/s effective)", flush=True,
        )
        if t_pal < best[1]:
            best = (bb, t_pal)

    if best[0] is None:
        return None

    # correctness BEFORE the verdict: a fast-but-wrong kernel must never
    # emit the ENABLE line.  Both impls are compared against a
    # highest-precision ground truth rather than against each other: on
    # TPU the XLA twin's fp32 einsum runs at default matmul precision
    # (bf16 MXU passes), while the kernel's weighted-sum reduction is full
    # fp32 on the VPU — the kernel is *more* accurate, so an
    # impl-vs-impl allclose at tight tolerance fails for the wrong reason.
    with jax.default_matmul_precision("highest"):
        truth = jax.jit(
            lambda *a: fused_attend_reference(*a, compute_dtype="float32")
        )(t1, t2, w2, ctx)
    want = fused_attend_reference(t1, t2, w2, ctx)
    got = fused_attend(t1, t2, w2, ctx, block_b=best[0], interpret=interpret)

    def max_err(a, b):
        return float(np.max(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64))))

    err_alpha = (max_err(got[1], truth[1]), max_err(want[1], truth[1]))
    err_ctx = (max_err(got[0], truth[0]), max_err(want[0], truth[0]))
    print(f"[B={B:3d}] max |err| vs fp32 ground truth — alpha: pallas "
          f"{err_alpha[0]:.2e} xla {err_alpha[1]:.2e}; context: pallas "
          f"{err_ctx[0]:.2e} xla {err_ctx[1]:.2e}", flush=True)
    assert err_alpha[0] <= max(err_alpha[1] * 1.5, 1e-5), (B, err_alpha)
    assert err_ctx[0] <= max(err_ctx[1] * 1.5, 1e-4), (B, err_ctx)

    speedup = t_xla / best[1]
    print(f"[B={B:3d}] best pallas: block_b={best[0]}  "
          f"speedup vs XLA: {speedup:.2f}x  correctness OK", flush=True)
    return {
        "batch": B,
        "xla_us": round(t_xla * 1e6, 1),
        "pallas_us": round(best[1] * 1e6, 1),
        "block_b": best[0],
        "speedup": round(speedup, 3),
        "err_ctx_pallas": err_ctx[0],
        "err_ctx_xla": err_ctx[1],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=0,
                    help="B (images × beams); 0 = sweep 32,48,64,128")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--block-b", type=int, default=0, help="0 = sweep tilings")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (smoke runs; the env's "
                    "sitecustomize force-registers the tunneled TPU "
                    "plugin over JAX_PLATFORMS)")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", flush=True)

    batches = [args.batch] if args.batch else [32, 48, 64, 128]
    rows = []
    for B in batches:
        row = bench_one(B, args.iters, args.block_b, interpret=args.cpu)
        if row is None:
            print(f"verdict: pallas kernel failed at B={B} — keep XLA path")
            return 1
        rows.append(row)

    min_speedup = min(r["speedup"] for r in rows)
    from sat_tpu.telemetry import bench_stamp

    print(
        json.dumps(
            {"sweep": rows, "min_speedup": min_speedup, **bench_stamp()}
        ),
        flush=True,
    )
    if args.cpu:
        # interpret-mode timings are meaningless; the smoke's value is
        # that the sweep + correctness plumbing ran — no verdict off-TPU
        print("smoke complete (interpret mode): no enable/keep verdict")
        return 0
    # default-on requires holding the win at EVERY measured operating
    # point (VERDICT r4 next-round #8); 1.0 exactly is a wash, keep it —
    # the 1.02 margin keeps run-to-run timing noise from flipping the
    # default on a result indistinguishable from a wash (ADVICE r5 #1)
    print(
        "verdict: ENABLE use_pallas_attention"
        if min_speedup >= 1.02
        else "verdict: keep XLA path (wash or loses at some batch size)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
