"""Bulk offline captioning benchmark: steady throughput + resume cost.

Times the real ``--phase bulk`` CLI end-to-end (docs/BULK.md) against a
procedurally generated corpus and a tiny blessed checkpoint:

* ``bulk_throughput_captions_s`` — steady-state captions/second of the
  decode loop, read from the run's final heartbeat (the gauge clock
  starts after AOT warmup, so compile time is excluded — that cost is
  bench_serve's ``serve_warmup_s`` territory);
* ``bulk_resume_overhead_s`` — wall seconds of a relaunch over a fully
  completed output dir: corpus walk + manifest load + per-shard crc
  verification, and NO jax boot (the resume fast path exits before the
  device runtime loads).  This is the fixed tax every ``--supervise``
  restart pays before new work starts.

The run is rejected (exit 1) if the job reports any steady-state XLA
recompile — the zero-recompile guarantee is the premise of the
throughput number.

Prints BENCH-contract JSON rows on stdout ({"metric", "value", "unit",
"vs_baseline", ...}; schema via ``telemetry.bench_stamp``) so
``scripts/check_regression.py`` gates the trajectory.

Usage: python scripts/bench_bulk.py [--images 24] [--shard-rows 6]
       [--workdir DIR] [--timeout 420]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from sat_tpu import telemetry

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_bulk +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _child_env():
    from sat_tpu.utils.compile_cache import cache_dir

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    env["JAX_COMPILATION_CACHE_DIR"] = cache_dir(".jax_cache")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    env["SAT_DEVICE_WATCHDOG_S"] = "0"
    return env


def _make_corpus(corpus_dir: str, n: int, size: int) -> None:
    """n procedural JPEGs — deterministic, no dataset download."""
    import cv2
    import numpy as np

    os.makedirs(corpus_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(n):
        img = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        cv2.imwrite(os.path.join(corpus_dir, f"corpus_{i:05d}.jpg"), img)


_SEED_CHILD = r'''
import os, sys
import jax
import numpy as np
from sat_tpu.config import Config
from sat_tpu.resilience import lineage
from sat_tpu.train.checkpoint import save_checkpoint
from sat_tpu.train.step import create_train_state

config = Config.load(sys.argv[1])
os.makedirs(config.save_dir, exist_ok=True)
state = create_train_state(jax.random.PRNGKey(0), config)
save_checkpoint(state, config)
lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
'''


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--images", type=int, default=24)
    ap.add_argument("--shard-rows", type=int, default=6)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--timeout", type=int, default=420,
                    help="per-child-run timeout, seconds")
    args = ap.parse_args()

    workdir = args.workdir or tempfile.mkdtemp(prefix="bench_bulk_")
    made_workdir = args.workdir is None
    try:
        from sat_tpu.config import Config
        from sat_tpu.data.vocabulary import Vocabulary

        corpus = os.path.join(workdir, "corpus")
        _make_corpus(corpus, args.images, 32)
        vocab_file = os.path.join(workdir, "vocabulary.csv")
        vocabulary = Vocabulary(size=30)
        vocabulary.build(["a man riding a horse.", "a cat on a table."])
        vocabulary.save(vocab_file)
        out_dir = os.path.join(workdir, "out")
        config = Config(
            phase="bulk", image_size=32, dim_embedding=16,
            num_lstm_units=16, dim_initialize_layer=16,
            dim_attend_layer=16, dim_decode_layer=32,
            compute_dtype="float32", vocabulary_size=vocabulary.size,
            vocabulary_file=vocab_file, beam_size=2,
            serve_slot_pages=2, serve_page_width=2,
            telemetry=True, heartbeat_interval=0.1,
            shard_cache="off",
            save_dir=os.path.join(workdir, "models"),
            summary_dir=os.path.join(workdir, "summary"),
            bulk_input=corpus, bulk_output=out_dir,
            bulk_shard_rows=args.shard_rows,
        )
        cfg_path = os.path.join(workdir, "bulk.json")
        config.save(cfg_path)

        log("blessing a tiny checkpoint (init-only, no train steps)")
        proc = subprocess.run(
            [sys.executable, "-c", _SEED_CHILD, cfg_path],
            capture_output=True, text=True, cwd=REPO, env=_child_env(),
            timeout=args.timeout,
        )
        if proc.returncode != 0:
            log(f"seed child failed rc {proc.returncode}:\n{proc.stderr}")
            return 1

        log(f"decode run: {args.images} images, shards of {args.shard_rows}")
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "sat_tpu.cli", "--config", cfg_path],
            capture_output=True, text=True, cwd=REPO, env=_child_env(),
            timeout=args.timeout,
        )
        decode_wall_s = time.perf_counter() - t0
        if proc.returncode != 0 or "bulk: complete" not in proc.stderr:
            log(f"bulk run failed rc {proc.returncode}:\n{proc.stderr}")
            return 1
        hb_path = os.path.join(config.summary_dir, "telemetry",
                               "heartbeat.json")
        with open(hb_path) as f:
            bulk = json.load(f).get("bulk", {})
        throughput = bulk.get("captions_per_s", 0.0)
        steady = bulk.get("steady_compiles")
        log(f"decode: {throughput:.1f} captions/s steady "
            f"({decode_wall_s:.1f}s wall incl. boot), "
            f"{steady} steady-state recompiles")
        if steady != 0:
            log(f"REJECTED: {steady} steady-state XLA recompiles "
                "(a shape leaked past the AOT warmup)")
            return 1
        if bulk.get("images_done") != args.images:
            log(f"REJECTED: {bulk.get('images_done')} of {args.images} "
                "images captioned")
            return 1

        log("resume run over the completed output dir")
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "sat_tpu.cli", "--config", cfg_path],
            capture_output=True, text=True, cwd=REPO, env=_child_env(),
            timeout=args.timeout,
        )
        resume_s = time.perf_counter() - t0
        if proc.returncode != 0 or "nothing to do" not in proc.stderr:
            log(f"resume run failed rc {proc.returncode}:\n{proc.stderr}")
            return 1
        log(f"resume: {resume_s:.2f}s (verified + skipped every shard, "
            "no jax boot)")

        rows = [
            {
                "metric": "bulk_throughput_captions_s",
                "value": round(throughput, 3),
                "unit": "captions/s",
                "vs_baseline": 1.0,
                "images": args.images,
                "shard_rows": args.shard_rows,
                "decode_wall_s": round(decode_wall_s, 2),
                **telemetry.bench_stamp(),
            },
            {
                "metric": "bulk_resume_overhead_s",
                "value": round(resume_s, 3),
                "unit": "s",
                "vs_baseline": 1.0,
                "shards_verified": (args.images + args.shard_rows - 1)
                // args.shard_rows,
                **telemetry.bench_stamp(),
            },
        ]
        print(json.dumps(rows, indent=1), flush=True)
        return 0
    finally:
        if made_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
