"""Cost attribution, tenant metering and the capacity model (ISSUE 18;
docs/OBSERVABILITY.md "Cost attribution and tenant metering").

Pins the contracts:

* the **accounting identity** — per-request attributed device time sums
  to the measured busy-span time within ±5%, under staggered, bursty
  and multi-tenant admission (unit-simulated over fused windows, and
  end-to-end over a booted CPU server);
* the **ledger** — cumulative per-tenant rows, torn-tail tolerant
  (kill -9 mid-append loses one snapshot of recency, never a
  double-count), rate-limited flush on an injectable clock;
* the **capacity model** — headroom/ceiling gauges from windowed deltas,
  ceiling held across idle windows, busy clamped to [0, 1]; and the
  encode-cache sketch: >0 would-hit under Zipf-ish repeats, 0 under
  unique traffic, exact window eviction;
* the **SLO hook** — the ``gauge_floor`` kind burns when a gauge falls
  below target (the capacity_headroom objective's comparator);
* the **exposition** — true Prometheus histograms (cumulative
  ``_bucket``/``_sum``/``_count``) on /metrics, tenant + cost stamped
  into access records and Perfetto lane args;
* **zero steady-state compiles** with metering on — attribution rides
  already-synced boundaries and adds no shapes.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from sat_tpu.telemetry import promtext
from sat_tpu.telemetry.capacity import CapacityModel, EncodeCacheSketch
from sat_tpu.telemetry.metering import (
    MeteringLedger,
    RequestCost,
    latest_totals,
    measured_busy_ms,
    read_ledger,
)
from sat_tpu.telemetry.slo import Objective, SLOEngine
from sat_tpu.telemetry.spans import Telemetry
from sat_tpu.telemetry.tracectx import RequestTracer

# ---------------------------------------------------------------------------
# RequestCost + ledger (pure, jax-free)
# ---------------------------------------------------------------------------


def test_request_cost_accumulates_and_rounds():
    c = RequestCost()
    c.add_encode(2_500_000)            # 2.5 ms lane share
    c.add_decode(1_000_000, steps=4)   # two fused windows
    c.add_decode(500_000, steps=2)
    c.set_occupancy(10_000_000)
    d = c.as_dict()
    assert d["encode_ms"] == 2.5
    assert d["decode_ms"] == 1.5
    assert d["device_ms"] == 4.0
    assert d["occupancy_ms"] == 10.0
    assert d["decode_steps"] == 6 and d["dispatches"] == 2


def test_ledger_charge_rollup_and_counters():
    tel = Telemetry(capacity=1024)
    ledger = MeteringLedger(tel=tel)
    c = RequestCost()
    c.add_encode(3_000_000)
    c.add_decode(1_000_000, steps=5)
    c.set_occupancy(8_000_000)
    ledger.charge("pro", cost=c, queue_ms=1.5, detok_ms=0.25)
    ledger.charge("pro", cost=None, error=True)  # shed: host cost only
    snap = ledger.snapshot()
    assert set(snap) == {"pro"}
    row = snap["pro"]
    assert row["requests"] == 2 and row["errors"] == 1
    assert row["device_ms"] == 4.0 and row["occupancy_ms"] == 8.0
    assert row["queue_ms"] == 1.5 and row["detok_ms"] == 0.25
    assert row["decode_steps"] == 5 and row["dispatches"] == 1
    ctr = tel.counters()
    assert ctr["metering/pro/requests"] == 2
    assert ctr["metering/pro/device_ms"] == pytest.approx(4.0)
    assert ledger.attributed_device_ms() == pytest.approx(4.0)


def test_ledger_flush_is_rate_limited_and_cumulative(tmp_path):
    now = [0.0]
    path = str(tmp_path / "metering.jsonl")
    ledger = MeteringLedger(path=path, flush_interval_s=5.0,
                            clock=lambda: now[0])
    c = RequestCost()
    c.add_encode(1_000_000)
    ledger.charge("a", cost=c)
    assert not os.path.exists(path)  # inside the interval: no append
    now[0] = 6.0
    ledger.charge("a", cost=c)
    rows = read_ledger(path)
    assert len(rows) == 1  # one cumulative row, not one per charge
    assert rows[0]["tenant"] == "a" and rows[0]["requests"] == 2
    now[0] = 12.0
    ledger.charge("b", cost=c)
    rows = read_ledger(path)
    # later rows supersede: replay needs only the last row per tenant
    totals = latest_totals(rows)
    assert totals["a"]["requests"] == 2 and totals["b"]["requests"] == 1
    assert totals["a"]["device_ms"] == pytest.approx(2.0)


def test_ledger_read_tolerates_torn_tail_and_garbage(tmp_path):
    path = str(tmp_path / "metering.jsonl")
    good1 = json.dumps({"tenant": "a", "requests": 5, "device_ms": 10.0})
    good2 = json.dumps({"tenant": "a", "requests": 9, "device_ms": 21.0})
    with open(path, "w") as f:
        f.write(good1 + "\n")
        f.write("not json at all\n")
        f.write(json.dumps(["wrong", "shape"]) + "\n")
        f.write(json.dumps({"no_tenant": 1}) + "\n")
        f.write(good2 + "\n")
        f.write('{"tenant": "a", "requests": 99, "device_')  # torn tail
    rows = read_ledger(path)
    assert [r["requests"] for r in rows] == [5, 9]
    # the torn tail costs exactly one snapshot of recency
    assert latest_totals(rows)["a"]["requests"] == 9


def test_ledger_read_spans_rollover(tmp_path):
    path = str(tmp_path / "metering.jsonl")
    with open(path + ".1", "w") as f:
        f.write(json.dumps({"tenant": "a", "requests": 1}) + "\n")
    with open(path, "w") as f:
        f.write(json.dumps({"tenant": "a", "requests": 4}) + "\n")
    rows = read_ledger(path)
    assert [r["requests"] for r in rows] == [1, 4]  # oldest first
    assert read_ledger(str(tmp_path / "missing.jsonl")) == []


# ---------------------------------------------------------------------------
# The accounting identity (unit-simulated admission patterns)
# ---------------------------------------------------------------------------


def _simulate_serving(tel, ledger, pattern):
    """Replay an admission pattern against the REAL attribution rules:
    requests submit/retire at step boundaries; each fused window charges
    every live request dur/n_live; encode chunks charge dur/chunk.
    ``pattern`` is a list of (tenant, submit_step, n_steps)."""
    rng = np.random.default_rng(7)
    costs = {}
    for i, (tenant, _s, _n) in enumerate(pattern):
        costs[i] = (tenant, RequestCost())
    # encode: power-of-two lanes over arrival order, staggered chunks
    order = sorted(range(len(pattern)), key=lambda i: pattern[i][1])
    k = 0
    while k < len(order):
        chunk = order[k : k + int(rng.choice([1, 2, 4]))]
        dur = int(rng.integers(200_000, 2_000_000))
        tel.record("serve/encode", 0, dur)
        share = dur // len(chunk)
        for i in chunk:
            costs[i][1].add_encode(share)
        k += len(chunk)
    last_step = max(s + n for _t, s, n in pattern)
    for step in range(last_step):
        live = [
            i for i, (_t, s, n) in enumerate(pattern) if s <= step < s + n
        ]
        if not live:
            continue
        dur = int(rng.integers(100_000, 1_500_000))
        tel.record("serve/step", 0, dur)
        share = dur // len(live)
        for i in live:
            costs[i][1].add_decode(share, steps=1)
    for tenant, cost in costs.values():
        ledger.charge(tenant, cost=cost)


@pytest.mark.parametrize(
    "pattern",
    [
        # staggered: arrivals trickle in, overlapping lifetimes
        [("a", s, 6) for s in range(0, 20, 2)],
        # bursty: everyone lands at once, drains at different lengths
        [("a", 0, n) for n in (2, 3, 5, 8, 13, 21)],
        # multi-tenant mix, ragged arrivals and lengths
        [("free", 0, 9), ("free", 1, 4), ("pro", 2, 11),
         ("pro", 2, 2), ("free", 7, 5), ("pro", 12, 3)],
    ],
    ids=["staggered", "bursty", "multi-tenant"],
)
def test_accounting_identity_unit(pattern):
    """Attributed device-ms ≈ measured busy-ms within ±5% — by
    construction the only slack is integer division truncation, far
    inside the bound."""
    tel = Telemetry(capacity=4096)
    ledger = MeteringLedger(tel=tel)
    _simulate_serving(tel, ledger, pattern)
    attributed = ledger.attributed_device_ms()
    measured = measured_busy_ms(tel)
    assert measured > 0
    assert abs(attributed - measured) <= 0.05 * measured


# ---------------------------------------------------------------------------
# Encode-cache sketch + capacity model
# ---------------------------------------------------------------------------


def test_sketch_window_eviction_and_refcounts():
    s = EncodeCacheSketch(window=2)
    # key 1 repeats inside the window (hit), then 2 and 3 push it out of
    # the 2-entry window, so its return is a miss — exactly what a
    # 2-entry cache would have scored
    assert [s.observe(k) for k in (1, 1, 2, 3, 1)] == [
        False, True, False, False, False,
    ]
    assert s.lookups == 5 and s.hits == 1
    assert s.ratio() == pytest.approx(0.2)


def test_sketch_zipf_hits_unique_misses():
    rng = np.random.default_rng(0)
    zipf = EncodeCacheSketch(window=256)
    ranks = np.arange(1, 65)
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    for key in rng.choice(ranks, size=500, p=p):
        zipf.observe(int(key))
    assert zipf.ratio() > 0.5  # heavy head repeats inside the window
    unique = EncodeCacheSketch(window=256)
    for key in range(500):
        unique.observe(key)
    assert unique.ratio() == 0.0


def test_capacity_model_headroom_ceiling_and_idle_hold():
    tel = Telemetry(capacity=1024)
    ledger = MeteringLedger(tel=tel)
    now = [0.0]
    model = CapacityModel(tel, ledger, slots=4, interval_s=1.0,
                          clock=lambda: now[0])
    # window 1: 4 requests, 2000 ms occupancy over 4 slots x 1 s => 50%
    for _ in range(4):
        c = RequestCost()
        c.set_occupancy(int(500e6))
        ledger.charge("a", cost=c)
    now[0] = 1.0
    model.maybe_update()
    g = tel.gauges()
    assert g["capacity/slot_busy_ratio"] == pytest.approx(0.5)
    assert g["capacity/headroom_pct"] == pytest.approx(50.0)
    # ceiling: slots * d_req / d_occ_s = 4 * 4 / 2.0 = 8 captions/s
    assert g["capacity/ceiling_captions_per_s"] == pytest.approx(8.0)
    assert g["capacity/completed_per_s"] == pytest.approx(4.0)
    # idle window: busy drops to 0, headroom to 100 — but the last known
    # ceiling holds (an idle replica still has a known capacity)
    now[0] = 2.0
    model.maybe_update()
    g = tel.gauges()
    assert g["capacity/slot_busy_ratio"] == 0.0
    assert g["capacity/headroom_pct"] == 100.0
    assert g["capacity/ceiling_captions_per_s"] == pytest.approx(8.0)
    # saturated window clamps busy at 1.0 (occupancy credits at retire)
    for _ in range(20):
        c = RequestCost()
        c.set_occupancy(int(1e9))
        ledger.charge("a", cost=c)
    now[0] = 3.0
    model.maybe_update()
    g = tel.gauges()
    assert g["capacity/slot_busy_ratio"] == 1.0
    assert g["capacity/headroom_pct"] == 0.0


def test_capacity_model_rate_limit_and_sketch_gauge():
    tel = Telemetry(capacity=1024)
    ledger = MeteringLedger(tel=tel)
    sketch = EncodeCacheSketch(window=8)
    now = [0.0]
    model = CapacityModel(tel, ledger, slots=2, interval_s=1.0,
                          sketch=sketch, clock=lambda: now[0])
    now[0] = 0.5
    # the first publish bypasses the rate limit — a scrape that lands
    # before any update must never see an empty capacity block
    model.maybe_update()
    assert tel.gauges()["capacity/headroom_pct"] == 100.0
    sketch.observe(1)
    sketch.observe(1)
    now[0] = 0.9
    model.maybe_update()  # inside the interval now: publishes nothing new
    assert "capacity/encode_cache_would_hit_ratio" not in tel.gauges()
    now[0] = 1.5
    model.maybe_update()
    g = tel.gauges()
    assert g["capacity/headroom_pct"] == 100.0
    assert g["capacity/encode_cache_would_hit_ratio"] == pytest.approx(0.5)


def test_gauge_floor_kind_burns_below_target():
    tel = Telemetry(capacity=1024)
    engine = SLOEngine(
        tel,
        [Objective(name="capacity_headroom", kind="gauge_floor",
                   target=20.0, source="capacity/headroom_pct")],
    )
    tel.gauge("capacity/headroom_pct", 80.0)
    res = engine.tick()["capacity_headroom"]
    assert res["burning"] is False
    assert res["burn_fast"] == pytest.approx(0.25)
    tel.gauge("capacity/headroom_pct", 5.0)
    res = engine.tick()["capacity_headroom"]
    assert res["burning"] is True
    assert res["measured_fast"] == 5.0
    assert tel.gauges()["slo/capacity_headroom_burning"] == 1


# ---------------------------------------------------------------------------
# Exposition: histograms + tenant/cost stamping
# ---------------------------------------------------------------------------


def test_promtext_histogram_cumulative_buckets():
    tel = Telemetry(capacity=1024)
    for ms in (1, 5, 20, 200, 2000):
        tel.record("serve/request", 0, int(ms * 1e6))
    text = promtext.render(
        tel,
        histograms={
            "sat_request_latency_seconds": (
                "serve/request", (0.01, 0.1, 1.0), 1e-9
            )
        },
    )
    lines = dict(
        line.rsplit(" ", 1)
        for line in text.splitlines()
        if line.startswith("sat_request_latency_seconds")
    )
    assert lines['sat_request_latency_seconds_bucket{le="0.01"}'] == "2"
    assert lines['sat_request_latency_seconds_bucket{le="0.1"}'] == "3"
    assert lines['sat_request_latency_seconds_bucket{le="1.0"}'] == "4"
    assert lines['sat_request_latency_seconds_bucket{le="+Inf"}'] == "5"
    assert lines["sat_request_latency_seconds_count"] == "5"
    assert float(lines["sat_request_latency_seconds_sum"]) == pytest.approx(
        2.226
    )
    assert "# TYPE sat_request_latency_seconds histogram" in text


def test_tracer_stamps_tenant_and_cost(tmp_path):
    path = str(tmp_path / "access.jsonl")
    tracer = RequestTracer(path=path)
    trace = tracer.begin()
    cost = RequestCost()
    cost.add_encode(2_000_000)
    cost.add_decode(1_000_000, steps=3)
    record = tracer.finish(
        trace, 200, int(5e6), bucket=2, tenant="pro", cost=cost
    )
    assert record["tenant"] == "pro"
    assert record["cost"]["device_ms"] == 3.0
    with open(path) as f:
        on_disk = json.loads(f.readline())
    assert on_disk["tenant"] == "pro" and on_disk["cost"]["decode_steps"] == 3
    lane = [
        e for e in tracer.trace_events(anchor_ns=0)
        if e.get("cat") == "request" and e["name"].startswith("request ")
    ][0]
    assert lane["args"]["tenant"] == "pro"
    assert lane["args"]["cost"]["device_ms"] == 3.0
    # absent tenant/cost: fields stay out of the record (schema-stable)
    bare = tracer.finish(tracer.begin(), 200, int(1e6))
    assert "tenant" not in bare and "cost" not in bare


# ---------------------------------------------------------------------------
# End-to-end on a booted CPU server (batch mode, tiny model)
# ---------------------------------------------------------------------------

TINY_MODEL = dict(
    phase="serve",
    image_size=32,
    dim_embedding=16,
    num_lstm_units=16,
    dim_initialize_layer=16,
    dim_attend_layer=16,
    dim_decode_layer=32,
    compute_dtype="float32",
    beam_size=2,
    serve_buckets=(1, 2),
    serve_max_batch=2,
    serve_max_wait_ms=10.0,
    serve_queue_depth=8,
    heartbeat_interval=0.0,
)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    import cv2
    import jax

    from sat_tpu import runtime, telemetry
    from sat_tpu.config import Config
    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.resilience import lineage
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.train.checkpoint import save_checkpoint
    from sat_tpu.train.step import create_train_state

    root = str(tmp_path_factory.mktemp("metering"))
    vocab_file = os.path.join(root, "vocabulary.csv")
    vocabulary = Vocabulary(size=30)
    vocabulary.build(["a man riding a horse.", "a cat on a table."])
    vocabulary.save(vocab_file)
    config = Config(
        **TINY_MODEL,
        vocabulary_size=vocabulary.size,
        vocabulary_file=vocab_file,
        save_dir=os.path.join(root, "models"),
        summary_dir=os.path.join(root, "summary"),
    )
    os.makedirs(config.save_dir, exist_ok=True)
    tel = telemetry.enable(capacity=16384)
    runtime._install_compile_listener()
    state = create_train_state(jax.random.PRNGKey(0), config)
    save_checkpoint(state, config)
    lineage.mark_last_good(config.save_dir, int(np.asarray(state.step)))
    state, _source = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()

    rng = np.random.default_rng(0)
    jpegs = []
    for i in range(4):
        img = rng.integers(0, 255, (32, 32, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        assert ok
        jpegs.append(bytes(buf))
    yield {"config": config, "engine": engine, "tel": tel, "jpegs": jpegs}
    telemetry.disable()


def _boot(stack, **overrides):
    from sat_tpu.serve.server import CaptionServer

    config = stack["config"].replace(**overrides)
    return CaptionServer(config, stack["engine"], port=0).start()


def _post(port, data, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/caption",
        data=data,
        method="POST",
        headers={"Content-Type": "image/jpeg", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port, path, timeout=30):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


def test_e2e_identity_stats_metrics_and_zero_compiles(stack):
    """The acceptance pin, end-to-end: bursty multi-tenant traffic on a
    booted server yields per-tenant cost rows whose device-ms sums match
    the measured busy-span delta within ±5%, shows up on /stats and
    /metrics (histograms included), stamps access records — all with
    ZERO steady-state compiles and metering on."""
    tel, jpegs = stack["tel"], stack["jpegs"]
    server = _boot(stack, tenants="alpha:2,beta:1")
    try:
        assert server.metering is not None and server.capacity is not None
        status, _payload = _post(server.port, jpegs[0])  # warm the path
        assert status == 200
        compiles0 = tel.counters().get("jax/compiles", 0)
        busy0 = measured_busy_ms(tel)
        attr0 = server.metering.attributed_device_ms()

        results = []

        def _one(i):
            tenant = "alpha" if i % 3 else "beta"
            results.append(
                _post(server.port, jpegs[i % len(jpegs)],
                      headers={"X-Tenant": tenant})[0]
            )

        threads = [
            threading.Thread(target=_one, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results.count(200) == 8

        # the accounting identity over exactly this burst
        attributed = server.metering.attributed_device_ms() - attr0
        measured = measured_busy_ms(tel) - busy0
        assert measured > 0
        assert abs(attributed - measured) <= 0.05 * measured

        # zero steady-state compiles with metering on
        assert tel.counters().get("jax/compiles", 0) == compiles0

        # /stats: tenants_cost rows + the capacity block
        _s, raw = _get(server.port, "/stats")
        stats = json.loads(raw)
        cost_block = stats["tenants_cost"]
        assert set(cost_block) >= {"alpha", "beta"}
        assert cost_block["alpha"]["requests"] >= 5
        assert cost_block["alpha"]["device_ms"] > 0
        assert cost_block["beta"]["dispatches"] >= 1
        assert stats["capacity"]["headroom_pct"] <= 100.0
        assert "ceiling_captions_per_s" in stats["capacity"]
        # scheduler admissions ride the tenants block for reconciliation
        assert stats["tenants"]["alpha"]["admitted"] >= 5

        # /metrics: metering counters + true histogram families
        _s, text = _get(server.port, "/metrics")
        assert 'sat_counter_total{name="metering/alpha/device_ms"}' in text
        assert 'sat_gauge{name="capacity/headroom_pct"}' in text
        assert "# TYPE sat_request_latency_seconds histogram" in text
        assert 'sat_request_latency_seconds_bucket{le="+Inf"}' in text
        assert "sat_request_latency_seconds_count" in text

        # access records carry tenant + cost
        recs = [
            r for r in server.tracer.finished()
            if r.get("tenant") == "beta"
        ]
        assert recs and recs[-1]["cost"]["device_ms"] > 0

        # the ledger flushed (shutdown forces the tail below)
        server.metering.maybe_flush(force=True)
        tdir = server.config.telemetry_dir or os.path.join(
            server.config.summary_dir, "telemetry"
        )
        ledger_rows = read_ledger(os.path.join(tdir, "metering.jsonl"))
        totals = latest_totals(ledger_rows)
        assert totals["alpha"]["schema"] == 1
        assert totals["alpha"]["requests"] == cost_block["alpha"]["requests"]
    finally:
        server.shutdown()


def test_e2e_metering_off_knob(stack):
    """--serve_metering off: no ledger, no capacity gauges, /stats has
    no tenants_cost block — the pre-metering schema, unchanged."""
    server = _boot(stack, serve_metering=False)
    try:
        assert server.metering is None and server.capacity is None
        status, _payload = _post(server.port, stack["jpegs"][0])
        assert status == 200
        stats = json.loads(_get(server.port, "/stats")[1])
        assert "tenants_cost" not in stats and "capacity" not in stats
    finally:
        server.shutdown()


def test_e2e_encode_cache_hits_bill_zero_encode_and_identity(stack):
    """ISSUE 20 metering satellite: with the content-addressed encode
    cache on, a cache-hit request is charged ZERO encode device-ms (only
    the miss requests split the measured encode window), and the
    attributed≈measured accounting identity still holds within ±5% under
    Zipf-style repeats.  Tenants split the traffic so the assertion is
    exact: 'cold' sends each unique image first (all misses), 'warm'
    sends only repeats (all hits)."""
    import time

    from sat_tpu.data.vocabulary import Vocabulary
    from sat_tpu.serve.engine import ServeEngine, load_serving_state
    from sat_tpu.serve.server import CaptionServer

    tel, jpegs = stack["tel"], stack["jpegs"]
    config = stack["config"].replace(
        encode_cache="on",
        encode_cache_mb=4,
        tenants="cold:1,warm:1",
    )
    vocabulary = Vocabulary(config.vocabulary_size, config.vocabulary_file)
    state, _source = load_serving_state(config)
    engine = ServeEngine(config, state, vocabulary, tel=tel)
    engine.warmup()
    server = CaptionServer(config, engine, port=0).start()
    try:
        assert engine.encode_cache is not None
        compiles0 = tel.counters().get("jax/compiles", 0)
        busy0 = measured_busy_ms(tel)
        attr0 = server.metering.attributed_device_ms()
        # cold tenant encodes each unique image once...
        for jpeg in jpegs:
            status, _payload = _post(
                server.port, jpeg, headers={"X-Tenant": "cold"}
            )
            assert status == 200
        # ...then the Zipf head repeats land as pure hits on 'warm'
        rng = np.random.default_rng(11)
        ranks = np.arange(1, len(jpegs) + 1, dtype=np.float64)
        p = (1.0 / ranks ** 1.1) / (1.0 / ranks ** 1.1).sum()
        for pick in rng.choice(len(jpegs), size=10, p=p):
            status, _payload = _post(
                server.port, jpegs[int(pick)], headers={"X-Tenant": "warm"}
            )
            assert status == 200
        # hit requests billed zero encode device-ms; misses paid it all
        snap = server.metering.snapshot()
        assert snap["warm"]["requests"] == 10
        assert snap["warm"]["encode_ms"] == 0.0
        assert snap["warm"]["decode_ms"] > 0  # hits still decode
        assert snap["cold"]["encode_ms"] > 0
        # the identity: attributed ≈ measured busy within ±5% — the
        # cache gather rides its own span OUTSIDE the busy set, so hits
        # don't dilute the ledger
        attributed = server.metering.attributed_device_ms() - attr0
        measured = measured_busy_ms(tel) - busy0
        assert measured > 0
        assert abs(attributed - measured) <= 0.05 * measured
        # zero steady-state compiles with cache + metering both on
        assert tel.counters().get("jax/compiles", 0) == compiles0
        # the ACTUAL hit ratio publishes next to the sketch's would-hit
        # prediction, plus the reconciliation delta
        assert engine.encode_cache.hit_ratio() > 0.5
        time.sleep(1.1)  # capacity tick interval
        _s, text = _get(server.port, "/metrics")
        assert 'sat_gauge{name="capacity/encode_cache_hit_ratio"}' in text
        assert 'sat_gauge{name="capacity/encode_cache_would_hit_ratio"}' in text
        assert 'sat_gauge{name="capacity/encode_cache_reconcile_delta"}' in text
        gauges = tel.gauges()
        delta = gauges["capacity/encode_cache_reconcile_delta"]
        assert abs(delta) <= 1.0  # a bounded ratio-vs-ratio difference
        assert gauges["capacity/encode_cache_hit_ratio"] == pytest.approx(
            engine.encode_cache.hit_ratio(), abs=1e-3
        )
    finally:
        server.shutdown()
