"""Poison-sample quarantine: contain bad records instead of crashing.

The data plane (docs/DATA_PIPELINE.md) used to be all-or-nothing: a
torn shard row was silently trained on and a single truncated JPEG
killed the run from a prefetch worker.  This module is the containment
half of the data-plane immune system (``data/integrity.py`` is the
detection half):

* an **append-only JSONL ledger** (``quarantine.jsonl`` next to the
  run's summaries) records every quarantined record — file, reason,
  kind (image/caption), epoch/step, content sha — one atomic line per
  record, so a watcher can tail it and a replay can preload it;
* **deterministic substitution**: a quarantined row is replaced
  in-batch by a known-good row chosen by a stable hash of the
  quarantine key, so batch geometry never changes (no recompiles) and
  a replayed run given the same ledger is bitwise-identical to the run
  that produced it;
* a **quarantine-fraction ceiling**: sporadic corruption is contained,
  but when more than ``quarantine_max_fraction`` of all rows seen have
  been quarantined (and at least ``MIN_RECORDS_FOR_CEILING`` records
  are involved), the corruption is systemic — training on mostly
  substituted data is worse than stopping — and the run aborts with
  :data:`DATA_CORRUPTION_EXIT_CODE` (87; 86 is the watchdog's).

Jax-free on purpose: the supervisor imports the exit code, and
``--repair_shards`` runs without a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry

# Exit-code vocabulary (see resilience/watchdog.py): 86 = wedged run
# aborted by the device watchdog; 87 = systemic data corruption — the
# quarantine ceiling tripped.  Distinct codes because the supervisor
# must restart 86 (state on disk is good) and must NOT restart 87
# (restarting re-reads the same rotten data).
DATA_CORRUPTION_EXIT_CODE = 87

# The ceiling never fires on fewer than this many quarantined records:
# one bad file in a ten-image smoke run is sporadic, not systemic.
MIN_RECORDS_FOR_CEILING = 8


class SystemicCorruption(RuntimeError):
    """Raised when the quarantine-fraction ceiling trips; mapped to
    exit code 87 by ``cli.main`` and treated as fatal (no restart) by
    the supervisor."""


def ledger_path_for(config) -> str:
    """Ledger location: ``config.quarantine_ledger`` when set, else
    ``quarantine.jsonl`` beside the run's summaries."""
    if getattr(config, "quarantine_ledger", ""):
        return config.quarantine_ledger
    return os.path.join(config.summary_dir, "quarantine.jsonl")


def _norm(path: str) -> str:
    return os.path.normpath(os.path.abspath(path))


def _file_sha(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()
    except OSError:
        return None


class QuarantineManager:
    """Thread-safe quarantine ledger + substitution policy.

    One instance per run, shared by every ``PrefetchLoader`` the run
    constructs (train and eval), because the ceiling is a *run-level*
    judgement.  All methods may be called from prefetch producer
    threads.
    """

    def __init__(
        self,
        ledger_path: str,
        max_fraction: float = 0.5,
        min_records: int = MIN_RECORDS_FOR_CEILING,
    ) -> None:
        self.ledger_path = ledger_path
        self.max_fraction = float(max_fraction)  # sync-ok: host scalar
        self.min_records = int(min_records)
        self._lock = threading.Lock()
        # file-kind entries keyed by normalized absolute path; caption-
        # kind entries keyed by batch position (pass, batch, row) — a
        # file appears under several captions, so a bad *caption* row
        # is identified by where it sits in the epoch stream, which is
        # deterministic (DataSet order is a pure function of seed+epoch)
        self._by_file: Dict[str, Dict[str, Any]] = {}
        self._by_pos: Dict[Tuple[int, int, int], Dict[str, Any]] = {}
        self._rows_seen = 0
        self._load()

    # -- ledger ------------------------------------------------------------

    def _load(self) -> None:
        """Preload an existing ledger (replay path): already-known
        records are substituted proactively, never re-appended."""
        try:
            with open(self.ledger_path) as f:
                lines = f.readlines()
        except OSError:
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                continue  # torn tail line: the ledger itself can be torn
            self._index(entry)
        self._gauge_locked()

    def _index(self, entry: Dict[str, Any]) -> None:
        if entry.get("kind") == "caption" and "pos" in entry:
            self._by_pos[tuple(entry["pos"])] = entry
        elif entry.get("file"):
            self._by_file[_norm(entry["file"])] = entry

    def _append(self, entry: Dict[str, Any]) -> None:
        d = os.path.dirname(self.ledger_path)
        if d:
            os.makedirs(d, exist_ok=True)
        # O_APPEND single-write: atomic enough for one-writer JSONL, and
        # a torn final line is tolerated by _load()
        with open(self.ledger_path, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
            f.flush()

    # -- queries (producer threads) ----------------------------------------

    def known_bad_file(self, image_file: str) -> bool:
        with self._lock:
            return _norm(image_file) in self._by_file

    def known_bad_pos(self, pass_idx: int, batch: int, row: int) -> bool:
        with self._lock:
            return (pass_idx, batch, row) in self._by_pos

    def files(self) -> List[str]:
        """Normalized paths of every file-kind quarantined record (the
        ``--repair_shards`` suspect list)."""
        with self._lock:
            return sorted(self._by_file)

    @property
    def total(self) -> int:
        with self._lock:
            return len(self._by_file) + len(self._by_pos)

    # -- bookkeeping -------------------------------------------------------

    def note_rows(self, n: int) -> None:
        """Count rows entering the pipeline (the ceiling's denominator)."""
        with self._lock:
            self._rows_seen += int(n)

    def _gauge_locked(self) -> None:
        total = len(self._by_file) + len(self._by_pos)
        telemetry.gauge("data/quarantined_total", total)
        telemetry.gauge(
            "data/quarantined_fraction",
            round(total / max(1, self._rows_seen), 4),
        )

    # -- the one write path ------------------------------------------------

    def quarantine(
        self,
        image_file: str,
        reason: str,
        kind: str = "image",
        pos: Optional[Tuple[int, int, int]] = None,
        exc: Optional[BaseException] = None,
    ) -> None:
        """Record one bad row.  Dedups (a bad file re-encountered every
        epoch is one ledger line), appends, updates telemetry, and
        raises :class:`SystemicCorruption` when the ceiling trips."""
        with self._lock:
            key_file = _norm(image_file) if image_file else ""
            if kind == "caption" and pos is not None:
                if tuple(pos) in self._by_pos:
                    return
            elif key_file and key_file in self._by_file:
                return
            gauges = telemetry.get().gauges()
            entry: Dict[str, Any] = {
                "file": key_file,
                "reason": str(reason),
                "kind": kind,
                "epoch": gauges.get("data/epoch"),
                "step": gauges.get("train/step"),
                "sha": _file_sha(key_file) if key_file else None,
            }
            if pos is not None:
                entry["pos"] = list(pos)
            if exc is not None:
                entry["error"] = f"{type(exc).__name__}: {exc}"
            self._index(entry)
            self._append(entry)
            telemetry.count("data/quarantined")
            self._gauge_locked()
            total = len(self._by_file) + len(self._by_pos)
            fraction = total / max(1, self._rows_seen)
            if total >= self.min_records and fraction > self.max_fraction:
                raise SystemicCorruption(
                    f"systemic data corruption: {total} of "
                    f"{self._rows_seen} rows quarantined "
                    f"({fraction:.0%} > ceiling "
                    f"{self.max_fraction:.0%}) — refusing to train on "
                    f"mostly substituted data (exit "
                    f"{DATA_CORRUPTION_EXIT_CODE}); ledger: "
                    f"{self.ledger_path}"
                )

    # -- deterministic substitution ----------------------------------------

    @staticmethod
    def substitute_index(key: str, num_healthy: int) -> int:
        """Stable healthy-row choice for a quarantined row: a hash of
        the quarantine key, so the same ledger replayed yields the same
        substitutions (bitwise-reproducible batches)."""
        return zlib.crc32(key.encode("utf-8")) % max(1, num_healthy)
