"""Checkpoint save / restore / import, npy-lineage compatible.

The reference persists a flat ``{variable_name: ndarray}`` dict via
``np.save`` plus a pickled Config carrying ``global_step``
(/root/reference/base_model.py:242-255), restores per-variable and skips
missing names (partial restore, base_model.py:257-278), imports pretrained
CNNs from a *nested* ``{op_name: {param_name: ndarray}}`` npy
(base_model.py:280-297), and ships a trim tool that strips optimizer slots
(/root/reference/data/models/trim_model.py:11-18).

This module reproduces all four capabilities on the JAX pytree state:

* ``save_checkpoint``   — flat name→array ``<step>.npz`` + ``config.json``
  sidecar holding global_step (the config.pickle equivalent);
* ``restore_checkpoint`` — by explicit file or latest-in-dir, per-leaf
  assignment tolerant of missing/mismatched entries;
* ``load_pretrained_cnn`` — reads the reference's nested npy formats
  (``vgg16_no_fc.npy`` / ``resnet50_no_fc.npy``); module names match the
  reference's TF scopes 1:1 (conv1_1…conv5_3, res2a_branch2a…), so the map
  is name-table-driven, ignore-missing like the reference;
* ``trim_checkpoint``   — drops ``optimizer/*`` entries for slim
  inference checkpoints.

Checkpoints are written atomically (tmp + rename) so a preempted host
never leaves a torn file — the failure-recovery story the reference lacks.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..resilience import faultinject, lineage
from ..resilience.lineage import CheckpointWriteError
from ..resilience.retry import retry_io
from .. import telemetry
from ..utils.dist import gather_tree_replicated
from ..utils.fileio import atomic_write

# ---------------------------------------------------------------------------
# pytree <-> flat name dict
# ---------------------------------------------------------------------------


def _key_to_str(entry: Any) -> str:
    """One path entry → a stable string segment."""
    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return entry.name
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _path_name(prefix: str, path) -> str:
    """Leaf path → checkpoint entry name (single definition shared by save
    and restore so the two can never disagree)."""
    name = "/".join(_key_to_str(e) for e in path)
    return prefix + name if name else prefix.rstrip("/")


def flatten_with_names(tree: Any, prefix: str = "") -> Dict[str, Any]:
    """Pytree → {slash/joined/path: leaf}.  Works on dicts, NamedTuples
    (optax states), and lists alike."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {_path_name(prefix, path): leaf for path, leaf in leaves}


def _assign_leaves(tree: Any, prefix: str, data: Dict[str, np.ndarray]):
    """Rebuild ``tree`` with any leaf whose name appears in ``data`` (same
    shape) replaced.  Returns (new_tree, loaded_count) — the per-variable
    tolerant assignment of the reference's load (base_model.py:272-277)."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
    new_leaves = []
    count = 0
    for path, leaf in paths:
        name = _path_name(prefix, path)
        if name in data:
            value = np.asarray(data[name])
            if hasattr(leaf, "shape") and tuple(value.shape) == tuple(leaf.shape):
                # jnp.array, not the raw numpy value: the CPU backend turns
                # an aligned numpy argument into a ZERO-COPY device buffer
                # that borrows the host memory, and train_step's
                # donate_argnums then lets XLA free/reuse a buffer it never
                # owned — a use-after-free that shows up as heap pointers in
                # restored Adam slots on resume (timing-dependent; the
                # persistent compile cache makes it reproducible).  An
                # explicit device copy gives every restored leaf an
                # XLA-owned buffer, same as fresh-init jit outputs.
                new_leaves.append(jnp.array(value.astype(leaf.dtype)))
                count += 1
                continue
        new_leaves.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), count


def state_to_flat(state: Any) -> Dict[str, np.ndarray]:
    """TrainState → flat dict.  Optimizer slots live under ``optimizer/`` so
    the trim tool (reference trim_model.py:14) can drop them by prefix.
    Works on mesh-sharded states (single- or multi-process): shards held
    by other hosts are all-gathered first so every process can materialize
    full values (the distributed save path)."""
    with telemetry.span("ckpt/snapshot"):
        flat: Dict[str, np.ndarray] = {}
        flat.update(flatten_with_names(state.params, "params/"))
        if state.batch_stats:
            flat.update(flatten_with_names(state.batch_stats, "batch_stats/"))
        flat.update(flatten_with_names(state.opt_state, "optimizer/"))
        flat["global_step"] = np.asarray(state.step)
        flat = gather_tree_replicated(flat)
        # One batched D2H transfer for the whole dict, not one per leaf.  The
        # snapshot must OWN its bytes: on the CPU backend device_get returns
        # zero-copy views of the live device buffers, and those buffers are
        # donated into the next dispatched step (train/step.py donate_argnums)
        # — an async writer serializing a view after donation would persist
        # whatever XLA wrote over it (observed as denormal garbage in Adam mu
        # slots of resumed runs).  OWNDATA is False exactly for such views, so
        # TPU-path arrays (device_get already copied) aren't copied twice.
        host = jax.device_get(flat)
        return {
            k: v if isinstance(v, np.ndarray) and v.flags["OWNDATA"] else np.array(v)
            for k, v in host.items()
        }


# ---------------------------------------------------------------------------
# save / restore
# ---------------------------------------------------------------------------


class AsyncCheckpointWriter:
    """Overlaps checkpoint disk writes with training.

    The reference stalls its hot loop every ``save_period=50`` steps while
    every variable is pulled to host AND written out
    (/root/reference/base_model.py:61-62,242-255).  On TPU the
    device→host snapshot is the only part that must synchronize with the
    step stream — the state is donated into the next dispatched step
    (train/step.py donate_argnums), so its buffers must be materialized
    on host before training proceeds — but npz serialization + disk I/O
    (hundreds of MB with Adam slots) have no such constraint.  ``save``
    therefore snapshots synchronously and hands the numpy tree to a
    single worker thread; saves serialize in submission order, worker
    failures surface on the next ``save``/``close`` (the PrefetchLoader
    error contract), and ``close`` drains the queue.

    Single-process only: the multi-host save path needs a cross-host
    barrier in line with the step stream, so ``save`` falls back to the
    synchronous writer when ``jax.process_count() > 1``.
    """

    def __init__(self) -> None:
        import queue
        import threading

        # bounded like PrefetchLoader's queue (data/images.py): each item
        # is a full host snapshot (hundreds of MB with Adam slots), so a
        # slow disk must apply backpressure on save() — degrading toward
        # sync-save speed — rather than stack snapshots until OOM
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._error: Optional[BaseException] = None
        # _error crosses the worker/caller thread boundary; the lock makes
        # that handoff explicit rather than leaning on CPython's per-ref
        # atomicity.  It does NOT close the save()-time window between
        # _check and put() — a failure landing there surfaces on the NEXT
        # call, which is what the permanent-failure contract in _check
        # guarantees (the actual ADVICE r3 fix).
        self._error_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="sat-ckpt-writer", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        import threading

        while True:
            item = self._q.get()
            if item is None:
                return
            if isinstance(item, threading.Event):  # flush() barrier
                item.set()
                continue
            flat, path, config, save_dir, healthy = item
            try:
                _write_flat(flat, path, config, save_dir, healthy=healthy)
            except BaseException as e:  # surfaced on next save/close
                with self._error_lock:
                    if self._error is None:  # keep the FIRST failure (root cause)
                        self._error = e

    def _check(self) -> None:
        # the failure is permanent: a writer that lost a snapshot cannot
        # promise anything about later ones, so every subsequent
        # save()/close() re-raises the same root cause rather than
        # silently resuming
        with self._error_lock:
            e = self._error
        if e is not None:
            # CheckpointWriteError subclasses RuntimeError, so callers
            # matching the long-standing message keep working while the
            # CLI can map the typed failure to a non-zero exit
            raise CheckpointWriteError("async checkpoint write failed") from e

    def save(
        self,
        state: Any,
        config: Config,
        save_dir: Optional[str] = None,
        healthy: bool = True,
    ) -> str:
        self._check()
        if jax.process_count() > 1:
            return save_checkpoint(state, config, save_dir, healthy=healthy)
        save_dir = save_dir or config.save_dir
        flat = state_to_flat(state)  # the synchronous part
        step = int(flat["global_step"])
        path = os.path.join(save_dir, f"{step}.npz")
        self._q.put((flat, path, config, save_dir, healthy))
        return path

    def flush(self) -> None:
        """Block until every save queued so far is on disk (with its
        lineage tail applied), then surface any worker failure.  The
        rollback path needs this: LAST_GOOD is only readable after the
        write that blesses it has drained."""
        import threading

        barrier = threading.Event()
        self._q.put(barrier)
        barrier.wait()
        self._check()

    def close(self) -> None:
        """Drain pending writes; re-raise the first worker failure."""
        self._q.put(None)
        self._thread.join()
        self._check()

    def __enter__(self) -> "AsyncCheckpointWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _topology_snapshot(config: Config) -> Dict[str, Any]:
    """Device topology the checkpoint is being written under, recorded in
    the lineage sidecar so elastic resume (docs/RESILIENCE.md) can report
    a topology change.  Informational only: the saved state is host-flat
    full arrays, so a restore onto fewer (or more) chips is a
    re-placement (``parallel.sharding.reshard_train_state``), never a
    data transform — the snapshot exists so the change is visible, not
    because it gates anything."""
    devices = jax.devices()
    return {
        "device_count": len(devices),
        "platform": devices[0].platform if devices else "unknown",
        "process_count": jax.process_count(),
        "mesh_shape": list(config.mesh_shape),
        "mesh_axes": list(config.mesh_axes),
    }


def _write_flat(
    flat: Dict[str, np.ndarray],
    path: str,
    config: Config,
    save_dir: str,
    healthy: bool = True,
) -> None:
    """The disk half of a checkpoint save (shared by the sync and async
    paths): atomic npz + config.json sidecar, then the lineage tail —
    sha256 sidecar, post-write verify, LAST_GOOD advance (only when the
    verify passed AND the run was ``healthy`` at its last metrics check),
    and keep-N retention (docs/RESILIENCE.md)."""
    step = int(flat["global_step"])
    # write through the file object: np.savez(path) appends '.npz' itself
    with telemetry.span("ckpt/write"):
        retry_io(
            lambda: atomic_write(path, "wb", lambda f: np.savez(f, **flat)),
            desc=f"write checkpoint {path}",
        )
    # hash NOW, while the file is still exactly what we serialized: a
    # sidecar computed later would faithfully fingerprint whatever rot
    # happened in between and the verify would bless corrupt bytes
    with telemetry.span("ckpt/sidecar"):
        try:
            from ..data.vocabulary import vocab_fingerprint

            vocab = vocab_fingerprint(
                config.vocabulary_file, config.vocabulary_size
            )
        except Exception:
            vocab = None  # attestation is best-effort; the save is not
        lineage.write_sidecar(
            path, topology=_topology_snapshot(config), vocab=vocab
        )
    retry_io(
        lambda: config.replace(global_step=step).save(
            os.path.join(save_dir, "config.json")
        ),
        desc=f"write checkpoint config {save_dir}",
    )
    # injection point: bit-rot between the rename and the verify — the
    # post-write verify below must catch it and refuse to bless the file
    faultinject.FaultPlan.from_env().maybe_corrupt_checkpoint(path, step)
    # verify + LAST_GOOD advance + retention, timed as one phase
    with telemetry.span("ckpt/finalize"):
        lineage.finalize_save(
            save_dir, path, step, healthy=healthy, keep=config.keep_checkpoints
        )
    telemetry.count("ckpt/saves")
    telemetry.gauge("ckpt/last_save_step", step)
    telemetry.gauge("ckpt/last_save_unix", time.time())


def save_checkpoint(
    state: Any,
    config: Config,
    save_dir: Optional[str] = None,
    healthy: bool = True,
) -> str:
    """Write ``<global_step>.npz`` + ``config.json`` under save_dir.

    Mirrors the reference's save (base_model.py:242-255): everything —
    params, BN stats, optimizer slots, global step — in one flat archive,
    with the config (embedding global_step) alongside for
    resume-from-latest.  Atomic via tmp+rename; ``healthy=False`` (the
    anomaly sentinel saw non-finite metrics) still writes the file but
    withholds the ``LAST_GOOD`` blessing.
    """
    save_dir = save_dir or config.save_dir
    flat = state_to_flat(state)
    step = int(flat["global_step"])
    path = os.path.join(save_dir, f"{step}.npz")
    if jax.process_index() == 0:
        # process 0 writes; other hosts only participated in the gather
        # (the reference's chief-writes checkpointing, main_distributed.py:64)
        _write_flat(flat, path, config, save_dir, healthy=healthy)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"sat_tpu_ckpt_{step}")
    return path


def latest_checkpoint(save_dir: str) -> Optional[str]:
    """Resolve the newest checkpoint like the reference's config.pickle
    lookup (base_model.py:262-269), falling back to a directory scan.

    The scan (``resilience.lineage.checkpoint_steps``) accepts only real,
    non-empty ``<step>.npz`` regular files — in-flight atomic-write temps,
    sidecars, ``slim.npz`` exports, zero-byte husks from a full disk, and
    lookalike directories are never mis-parsed into a candidate."""
    steps = set(lineage.checkpoint_steps(save_dir))
    cfg_path = os.path.join(save_dir, "config.json")
    # The config.json pointer can name a step the scan rejected (e.g. its
    # npz truncated to zero bytes) — intersect, don't trust.
    if os.path.exists(cfg_path):
        try:
            pointed = int(Config.load(cfg_path).global_step)
        except (ValueError, KeyError, TypeError):
            pass  # torn config.json → rely on the directory scan
        else:
            path = os.path.join(save_dir, f"{pointed}.npz")
            try:
                if os.path.isfile(path) and os.path.getsize(path) > 0:
                    steps.add(pointed)
            except OSError:
                pass
    if steps:
        return os.path.join(save_dir, f"{max(steps)}.npz")
    return None


def load_flat(path: str) -> Dict[str, np.ndarray]:
    def _read() -> Dict[str, np.ndarray]:
        with np.load(path, allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    return retry_io(_read, desc=f"read checkpoint {path}")


def _note_elastic_restore(path: str) -> None:
    """Report when a checkpoint written under one device topology is being
    restored under another (elastic resume).  Purely informational — the
    host-flat checkpoint format makes the restore itself topology-free —
    but an operator resuming an 8-chip run on 1 chip should see it said
    out loud, and ``ckpt/elastic_restores`` makes it greppable in
    heartbeat/bench artifacts."""
    recorded = lineage.read_sidecar_topology(path)
    if not recorded:
        return
    now = len(jax.devices())
    then = recorded.get("device_count")
    if then is not None and int(then) != now:
        telemetry.count("ckpt/elastic_restores")
        print(
            f"sat_tpu: elastic resume — checkpoint {os.path.basename(path)} "
            f"was written on {then} device(s) "
            f"(mesh {recorded.get('mesh_shape')}), restoring onto {now}; "
            "state will be re-placed on the current mesh",
            file=sys.stderr,
            flush=True,
        )


class VocabMismatchError(RuntimeError):
    """The checkpoint's lineage sidecar attests a different vocabulary
    than the one this run is configured with.  Without this check the
    word-embedding rows would be silently skipped by the shape-tolerant
    partial restore and the model would decode gibberish."""


def _check_vocab(path: str, expect: Optional[dict]) -> None:
    """Compare the run's vocabulary fingerprint against the sidecar's.
    Both sides optional: a legacy sidecar (no vocab record) or a run
    that could not fingerprint its vocabulary checks nothing."""
    if not expect:
        return
    recorded = lineage.read_sidecar_meta(path).get("vocab")
    if not recorded:
        return
    if (
        recorded.get("sha256") != expect.get("sha256")
        or int(recorded.get("size", 0)) != int(expect.get("size", 0))
    ):
        raise VocabMismatchError(
            f"vocab mismatch (got {expect.get('size')} words, sha "
            f"{str(expect.get('sha256'))[:12]}…; checkpoint "
            f"{os.path.basename(path)} expects {recorded.get('size')} "
            f"words, sha {str(recorded.get('sha256'))[:12]}…) — the "
            "vocabulary file changed since this checkpoint was trained; "
            "restore with the original vocabulary.csv or retrain"
        )


def restore_checkpoint(
    state: Any,
    model_file: Optional[str] = None,
    save_dir: Optional[str] = None,
    expect_vocab: Optional[dict] = None,
) -> Tuple[Any, int]:
    """Restore into an existing state skeleton.

    ``model_file`` explicit, else latest under ``save_dir`` — the
    reference's two load modes (base_model.py:258-269).  Missing /
    shape-mismatched entries are skipped (partial restore), so trimmed
    inference checkpoints load cleanly into a full train state.
    Returns (new_state, tensors_loaded).

    ``expect_vocab`` (``data.vocabulary.vocab_fingerprint`` of the
    run's configured vocabulary) is compared against the candidate's
    lineage sidecar; a mismatch raises :class:`VocabMismatchError`
    IMMEDIATELY — it is a configuration error, not file rot, so the
    save_dir mode does NOT walk back past it (every older checkpoint of
    the run was trained against the same vocabulary).

    In ``save_dir`` mode a torn / corrupt / unreadable newest checkpoint
    is not fatal: each candidate is integrity-checked
    (``resilience.lineage.verify_checkpoint`` — sha256 sidecar when
    present, zip CRC otherwise) and the restore walks back to the newest
    checkpoint that verifies AND loads.  An explicit ``model_file`` is
    the operator saying "this file" — it is loaded as-is and failures
    propagate.
    """
    if model_file:
        _check_vocab(model_file, expect_vocab)
        flat = load_flat(model_file)
        _note_elastic_restore(model_file)
    else:
        if not save_dir:
            raise FileNotFoundError(f"no checkpoint found (save_dir={save_dir!r})")
        flat = None
        rejected = []
        for step in sorted(lineage.checkpoint_steps(save_dir), reverse=True):
            path = os.path.join(save_dir, f"{step}.npz")
            ok, reason = lineage.verify_checkpoint(path)
            if ok:
                try:
                    _check_vocab(path, expect_vocab)
                    flat = load_flat(path)
                    _note_elastic_restore(path)
                    break
                except (OSError, ValueError) as e:  # verified yet unloadable
                    reason = f"load failed: {e}"
            rejected.append(f"{os.path.basename(path)} ({reason})")
            telemetry.count("ckpt/walkbacks")
            print(
                f"sat_tpu: checkpoint {path} rejected ({reason}); "
                "walking back to an older checkpoint",
                file=sys.stderr,
                flush=True,
            )
        if flat is None:
            detail = f"; rejected: {', '.join(rejected)}" if rejected else ""
            raise FileNotFoundError(
                f"no verifiable checkpoint found (save_dir={save_dir!r}{detail})"
            )

    params, n_p = _assign_leaves(state.params, "params/", flat)
    batch_stats, n_b = _assign_leaves(state.batch_stats, "batch_stats/", flat)
    opt_state, n_o = _assign_leaves(state.opt_state, "optimizer/", flat)
    step = state.step
    if "global_step" in flat:
        step = jnp.array(np.asarray(flat["global_step"], dtype=np.int32))
    new_state = state._replace(
        params=params, batch_stats=batch_stats, opt_state=opt_state, step=step
    )
    # global_step deliberately not counted: count==0 must mean "nothing
    # usable restored" so callers can treat it as a hard error.
    return new_state, n_p + n_b + n_o


def trim_checkpoint(in_path: str, out_path: str) -> int:
    """Strip optimizer slots (reference trim_model.py:11-18).  Returns the
    number of entries kept."""
    flat = load_flat(in_path)
    kept = {k: v for k, v in flat.items() if not k.startswith("optimizer/")}
    atomic_write(out_path, "wb", lambda f: np.savez(f, **kept))
    return len(kept)


# ---------------------------------------------------------------------------
# pretrained-CNN import (reference nested-npy formats)
# ---------------------------------------------------------------------------

# Param-name aliases across the caffe-converted npy files and TF scopes.
_KERNEL_NAMES = {"kernel", "weights", "W", "w"}
_BIAS_NAMES = {"bias", "biases", "b", "offset", "beta"}
_SCALE_NAMES = {"scale", "gamma"}
_MEAN_NAMES = {"mean", "moving_mean", "mu"}
_VAR_NAMES = {"variance", "moving_variance", "var"}


def _nested_npy(data_path: str) -> Dict[str, Dict[str, np.ndarray]]:
    raw = np.load(data_path, allow_pickle=True, encoding="latin1")
    d = raw.item() if hasattr(raw, "item") and raw.dtype == object else dict(raw)
    return {str(k): {str(p): np.asarray(a) for p, a in v.items()} for k, v in d.items()}


def _find_op(tree: Any, op: str) -> Optional[Dict[str, Any]]:
    """Locate the dict node named ``op`` at any depth — Flax nests block
    submodules (cnn/res2a/res2a_branch2a/...) one level deeper than the
    reference's flat TF scopes."""
    if not isinstance(tree, dict):
        return None
    if op in tree and isinstance(tree[op], dict):
        return tree[op]
    for child in tree.values():
        hit = _find_op(child, op)
        if hit is not None:
            return hit
    return None


def _set_key(dest: Dict[str, Any], key: str, value: np.ndarray) -> bool:
    """Assign ``key`` within the op's subtree; our nn.Conv wrapper nests
    an inner 'conv' module, so descend through child dicts if needed."""
    if key in dest and not isinstance(dest[key], dict):
        if tuple(dest[key].shape) != tuple(value.shape):
            return False
        dest[key] = value.astype(dest[key].dtype)
        return True
    for child in dest.values():
        if isinstance(child, dict) and _set_key(child, key, value):
            return True
    return False


def _place_nested(
    cnn_params: Dict[str, Any],
    batch_stats: Dict[str, Any],
    nested: Dict[str, Dict[str, np.ndarray]],
) -> int:
    """Place ``{op: {param: arr}}`` entries into the (numpy, mutated
    in-place) CNN param / batch-stat trees, alias-mapping param names.
    Unknown ops/params are skipped, matching the reference's
    ignore_missing=True (base_model.py:295-296).  Returns tensors placed."""
    count = 0

    def place(tree: Dict[str, Any], op: str, key: str, value: np.ndarray) -> bool:
        dest = _find_op(tree, op)
        return dest is not None and _set_key(dest, key, value)

    for op_name, entries in nested.items():
        for param_name, value in entries.items():
            if param_name in _KERNEL_NAMES:
                key, tree = "kernel", cnn_params
            elif param_name in _SCALE_NAMES:
                key, tree = "scale", cnn_params
            elif param_name in _BIAS_NAMES:
                key, tree = "bias", cnn_params
            elif param_name in _MEAN_NAMES:
                key, tree = "mean", batch_stats
            elif param_name in _VAR_NAMES:
                key, tree = "var", batch_stats
            else:
                continue
            if place(tree, op_name, key, value):
                count += 1
    return count


def load_pretrained_cnn(
    variables: Dict[str, Any], data_path: str
) -> Tuple[Dict[str, Any], int]:
    """Import a reference-format pretrained CNN npy into the variable tree.

    The file is ``{op_name: {param_name: array}}`` (base_model.py:286-289);
    op names are the TF scopes our Flax modules reuse verbatim (conv1_1 …,
    res2a_branch2a …, bn_conv1 …).  Conv kernels arrive HWIO (TF layout =
    ours).  BN stats land in ``batch_stats``; scale/offset in params.
    Returns (new_variables, tensors_loaded).
    """
    return _import_cnn_nested(variables, _nested_npy(data_path))


# ---------------------------------------------------------------------------
# full reference-checkpoint import (TF1 flat-name format)
# ---------------------------------------------------------------------------

_DECODER_SCOPES = ("word_embedding", "initialize", "attend", "decode")


def import_reference_checkpoint(
    state: Any, path: str, restore_step: bool = False
) -> Tuple[Any, int]:
    """Ingest a checkpoint written by the reference's own save():
    a flat ``{var.name: value}`` npy (base_model.py:242-249).

    Name translation, not weight surgery — the decoder was designed with
    TF1-compatible layouts so every tensor drops in unchanged:

    * ``<scope>/<fc>/kernel:0`` → ``params/decoder/<scope>/<fc>/kernel``
      for the word_embedding / initialize / attend / decode scopes
      (reference model.py:219-225,358-459);
    * ``lstm/lstm_cell/{kernel,bias}:0`` → ``params/decoder/lstm/*`` —
      the single concatenated [(D+E+H), 4H] matrix with TF1's (i, j, f, o)
      gate order, which lstm_step consumes natively (the +1.0 forget bias
      is a runtime constant on both sides, never stored);
    * CNN scopes (``conv1_1/kernel:0``, ``res2a_branch2a/...``,
      BN gamma/beta/moving_mean/moving_variance) place through the same
      alias machinery as the nested pretrained import;
    * optimizer slots (``OptimizeLoss/...``) are dropped — the reference's
      Adam state has no meaning for our optax chain.  ``global_step:0`` is
      only adopted with ``restore_step=True``: a foreign step count would
      otherwise drive the train loop's resume fast-forward (skipping
      epochs, or no-opping entirely when it exceeds the epoch budget) —
      fine-tuning an imported model starts a fresh optimization at step 0.

    Returns (new_state, tensors_loaded).
    """
    raw = np.load(path, allow_pickle=True, encoding="latin1").item()

    decoder_flat: Dict[str, np.ndarray] = {}
    cnn_nested: Dict[str, Dict[str, np.ndarray]] = {}
    step: Optional[np.ndarray] = None
    for name, value in raw.items():
        name = name.split(":")[0]
        parts = name.split("/")
        if parts[0] == "global_step":
            step = np.asarray(value, dtype=np.int32)
        elif parts[0].startswith("OptimizeLoss") or "optimizer" in parts[0].lower():
            continue
        elif parts[0] == "lstm":
            decoder_flat[f"params/decoder/lstm/{parts[-1]}"] = np.asarray(value)
        elif parts[0] in _DECODER_SCOPES:
            decoder_flat["params/decoder/" + "/".join(parts)] = np.asarray(value)
        elif len(parts) >= 2:
            cnn_nested.setdefault(parts[0], {})[parts[-1]] = np.asarray(value)

    params, n_dec = _assign_leaves(state.params, "params/", decoder_flat)
    new_state, n_cnn = apply_cnn_import(state._replace(params=params), cnn_nested)
    if restore_step and step is not None:
        new_state = new_state._replace(step=step)
    return new_state, n_dec + n_cnn


def apply_cnn_import(state: Any, nested_or_path: Any) -> Tuple[Any, int]:
    """Import a nested CNN dict (or its npy path) into a TrainState —
    the variables-wrap/unwrap shared by the reference-checkpoint import
    and runtime.setup_state's --load_cnn branch."""
    variables: Dict[str, Any] = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    if isinstance(nested_or_path, str):
        nested_or_path = _nested_npy(nested_or_path)
    variables, count = _import_cnn_nested(variables, nested_or_path)
    return (
        state._replace(
            params=variables["params"],
            batch_stats=variables.get("batch_stats", state.batch_stats),
        ),
        count,
    )


def _import_cnn_nested(
    variables: Dict[str, Any], nested: Dict[str, Dict[str, np.ndarray]]
) -> Tuple[Dict[str, Any], int]:
    """load_pretrained_cnn body for an already-loaded nested dict."""
    cnn_params = jax.tree_util.tree_map(np.asarray, variables["params"]["cnn"])
    batch_stats = jax.tree_util.tree_map(
        np.asarray, variables.get("batch_stats", {})
    )
    count = _place_nested(cnn_params, batch_stats, nested)
    new_variables = dict(variables)
    new_params = dict(variables["params"])
    new_params["cnn"] = cnn_params
    new_variables["params"] = new_params
    if batch_stats:
        new_variables["batch_stats"] = batch_stats
    return new_variables, count


# ---------------------------------------------------------------------------
# reference-checkpoint EXPORT (migration in the other direction)
# ---------------------------------------------------------------------------

_BN_EXPORT_NAMES = {
    "scale": "gamma", "bias": "beta", "mean": "moving_mean", "var": "moving_variance",
}


def _export_cnn_tree(tree: Any, out: Dict[str, np.ndarray]) -> None:
    """Walk a CNN param/batch-stat tree emitting reference TF-scope names:
    a node holding our Conv wrapper's inner 'conv' module becomes
    ``<op>/{kernel,bias}``; a node of BN leaves becomes
    ``<op>/{gamma,beta}`` (params) / ``<op>/{moving_mean,moving_variance}``
    (stats); anything else (res2a block containers) recurses."""
    if not isinstance(tree, dict):
        return
    for op, sub in tree.items():
        if not isinstance(sub, dict):
            continue
        inner = sub.get("conv")
        if isinstance(inner, dict) and "kernel" in inner:
            for leaf, arr in inner.items():
                out[f"{op}/{leaf}:0"] = np.asarray(arr)
        elif any(k in sub and not isinstance(sub[k], dict) for k in _BN_EXPORT_NAMES):
            for leaf, arr in sub.items():
                if leaf in _BN_EXPORT_NAMES and not isinstance(arr, dict):
                    out[f"{op}/{_BN_EXPORT_NAMES[leaf]}:0"] = np.asarray(arr)
        else:
            _export_cnn_tree(sub, out)


def export_reference_checkpoint(state: Any, path: str) -> int:
    """Inverse of :func:`import_reference_checkpoint`: write the
    reference's flat ``{var.name: value}`` npy (base_model.py:242-249), so
    a sat_tpu-trained model migrates BACK into the reference (its load()
    assigns by var name with missing-key tolerance, base_model.py:270-277)
    — and so the import path can be proven end-to-end offline by
    round-tripping a real trained state (RESULTS.md import-finetune run).

    Same name conventions the import consumes: decoder scopes verbatim
    (``word_embedding/weights:0``, ``attend/fc_1a/kernel:0``, …), the TF1
    LSTMCell under ``lstm/lstm_cell/`` with its concatenated (i,j,f,o)
    kernel unchanged, conv kernels HWIO as stored, BN as
    gamma/beta/moving_mean/moving_variance.  Optimizer slots are not
    exported (our optax state has no meaning to the reference's Adam).
    Returns the tensor count written."""
    # Mesh-sharded states (single- or multi-process): gather shards held
    # by other hosts first, then one batched D2H transfer — the same
    # discipline as state_to_flat; per-leaf np.asarray would crash on
    # non-addressable arrays and pay one transfer per tensor.
    gathered = jax.device_get(
        gather_tree_replicated(
            {"params": state.params, "batch_stats": state.batch_stats or {}}
        )
    )
    state = state._replace(
        params=gathered["params"], batch_stats=gathered["batch_stats"]
    )
    flat: Dict[str, np.ndarray] = {}
    dec = state.params.get("decoder", {})
    for scope, sub in dec.items():
        if scope == "lstm":
            for leaf, arr in sub.items():
                flat[f"lstm/lstm_cell/{leaf}:0"] = np.asarray(arr)
            continue
        for name, node in sub.items():
            if isinstance(node, dict):
                for leaf, arr in node.items():
                    flat[f"{scope}/{name}/{leaf}:0"] = np.asarray(arr)
            else:
                flat[f"{scope}/{name}:0"] = np.asarray(node)

    _export_cnn_tree(state.params.get("cnn", {}), flat)
    if getattr(state, "batch_stats", None):
        _export_cnn_tree(state.batch_stats, flat)

    flat["global_step:0"] = np.asarray(int(state.step), np.int64)
    atomic_write(
        path, "wb",
        lambda f: np.save(f, np.array(flat, dtype=object), allow_pickle=True),
    )
    return len(flat) - 1  # global_step is bookkeeping, not a tensor
