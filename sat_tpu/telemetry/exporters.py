"""Telemetry exporters: Chrome trace JSON, telemetry.jsonl, breakdown report.

Three output formats, one source (:class:`~sat_tpu.telemetry.spans.Telemetry`):

* :func:`export_chrome_trace` — trace-event JSON (``ph:"X"`` complete
  events, microsecond timestamps) loadable in Perfetto /
  ``chrome://tracing``, one track per recording thread;
* :func:`append_jsonl` — one JSON line per call (written at ``log_every``
  boundaries, alongside ``metrics.jsonl``) carrying the counters, gauges,
  and per-span running totals at that moment;
* :func:`step_breakdown` / :func:`format_breakdown` — the end-of-run
  per-phase step-time report (count, total, p50/p95/max) the CLI prints
  and saves as JSON.  Phases are the *disjoint* decomposition of a step;
  the residual between the step-total span and the phase sum is reported
  as the ``other`` phase, so the phase sum always reconstructs measured
  wall time (docs/OBSERVABILITY.md explains how to read it).

All writers degrade on failure (observability must never kill the run —
the SummaryWriter rule) and none of them touch jax.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

from ..utils.fileio import atomic_write
from . import process_identity, run_id


# ---------------------------------------------------------------------------
# Chrome trace-event JSON
# ---------------------------------------------------------------------------


def chrome_trace(
    tel,
    process_name: Optional[str] = None,
    extra_events: Optional[List[Dict]] = None,
    pid: Optional[int] = None,
) -> Dict:
    """The trace-event document for ``tel``'s retained span window.

    Timestamps are microseconds since the recorder's anchor; the absolute
    anchor (unix seconds) rides in ``otherData`` for post-hoc alignment
    with ``metrics.jsonl``'s wall-clock stamps.  ``extra_events`` are
    pre-built trace events appended verbatim — the request lanes from
    ``tracectx.RequestTracer.trace_events`` ride in through here.

    The trace ``pid`` defaults to the run's **process_index** (not the OS
    pid): per-host traces from one multi-host run then occupy distinct,
    stable lanes, and ``scripts/merge_traces.py`` can concatenate them
    into one Perfetto timeline with a lane per host.  The OS pid still
    rides in ``otherData``.
    """
    names, ids, t0s, durs, tids = tel.spans_snapshot()
    process_index, process_count = process_identity()
    if pid is None:
        pid = process_index
    if process_name is None:
        process_name = (
            f"sat_tpu host p{process_index}"
            if process_count > 1
            else "sat_tpu host"
        )
    events: List[Dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": process_name},
        }
    ]
    anchor = tel.anchor_ns
    for k in range(len(ids)):
        events.append(
            {
                "name": names[int(ids[k])],
                "cat": "host",
                "ph": "X",
                "pid": pid,
                "tid": int(tids[k]),
                "ts": (int(t0s[k]) - anchor) / 1e3,
                "dur": int(durs[k]) / 1e3,
            }
        )
    if extra_events:
        events.extend(extra_events)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": run_id(),
            "anchor_unix": tel.anchor_unix,
            "os_pid": os.getpid(),
            "process_index": process_index,
            "process_count": process_count,
            "counters": tel.counters(),
            "gauges": tel.gauges(),
        },
    }


def export_chrome_trace(
    tel, path: str, extra_events: Optional[List[Dict]] = None
) -> Optional[str]:
    """Write the Perfetto-loadable trace JSON atomically; returns the path
    (None when the write failed — reported, never raised)."""
    try:
        doc = chrome_trace(tel, extra_events=extra_events)
        atomic_write(path, "w", lambda f: json.dump(doc, f))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: telemetry trace export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


# ---------------------------------------------------------------------------
# periodic telemetry.jsonl
# ---------------------------------------------------------------------------


def snapshot_row(tel, step: Optional[int] = None) -> Dict:
    """One JSON-able snapshot of the recorder: counters, gauges, and
    per-span running (count, total ms, max ms) — same stamp fields as
    ``metrics.jsonl`` rows so the two join on (run_id, step/time)."""
    spans = {
        name: {
            "count": c,
            "total_ms": round(total / 1e6, 3),
            "max_ms": round(mx / 1e6, 3),
        }
        for name, (c, total, mx) in tel.aggregates().items()
    }
    row: Dict = {
        "run_id": run_id(),
        "wall_time": round(time.time(), 6),
        "mono_ns": time.perf_counter_ns(),
        "counters": tel.counters(),
        "gauges": tel.gauges(),
        "spans": spans,
    }
    if step is not None:
        row["step"] = int(step)
    return row


def rotating_append(
    path: str, line: str, cap_bytes: int = 0, tel=None
) -> bool:
    """Append one line to a size-capped JSONL file.

    When the file would grow past ``cap_bytes`` the current file rolls to
    ``<path>.1`` (single rollover — at most ``2 * cap_bytes`` on disk, the
    previous ``.1`` is dropped) and the append lands in a fresh file.
    ``cap_bytes <= 0`` disables rotation.  Failures degrade to a one-line
    warning (and the ``telemetry/export_errors`` counter when ``tel`` is
    given) — the shared sink for ``telemetry.jsonl`` / ``access.jsonl`` /
    ``slo.jsonl``, so none of them can fill a disk or kill a run.
    Returns True when the line landed."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        data = line if line.endswith("\n") else line + "\n"
        if cap_bytes > 0:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            if size and size + len(data.encode("utf-8")) > cap_bytes:
                os.replace(path, path + ".1")
        with open(path, "a") as f:
            f.write(data)
        return True
    except (OSError, ValueError) as e:
        if tel is not None:
            tel.count("telemetry/export_errors")
        print(
            f"sat_tpu: telemetry append failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return False


def append_jsonl(
    tel, path: str, step: Optional[int] = None, cap_bytes: int = 0
) -> None:
    """Append one snapshot row through the rotating sink; failures degrade
    to a one-line warning (tracked by ``telemetry/export_errors``)."""
    try:
        line = json.dumps(snapshot_row(tel, step))
    except (TypeError, ValueError) as e:
        tel.count("telemetry/export_errors")
        print(
            f"sat_tpu: telemetry.jsonl append failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return
    rotating_append(path, line, cap_bytes, tel=tel)


# ---------------------------------------------------------------------------
# step-time breakdown
# ---------------------------------------------------------------------------


def _stats(count: int, total_ns: int, max_ns: int, samples_ns: np.ndarray) -> Dict:
    out = {
        "count": int(count),
        "total_s": round(total_ns / 1e9, 6),
        "mean_ms": round(total_ns / count / 1e6, 4) if count else 0.0,
        "max_ms": round(max_ns / 1e6, 4),
    }
    if samples_ns.size:
        p50, p95 = np.percentile(samples_ns, [50, 95])
        out["p50_ms"] = round(float(p50) / 1e6, 4)
        out["p95_ms"] = round(float(p95) / 1e6, 4)
    else:
        out["p50_ms"] = out["p95_ms"] = None
    return out


def step_breakdown(
    tel,
    step_span: str,
    phases: Iterable[str],
    nested: Iterable[str] = (),
) -> Optional[Dict]:
    """Per-phase step-time report.

    ``step_span`` is the whole-iteration span; ``phases`` are its disjoint
    sub-intervals (their durations never overlap, so their sum plus the
    computed ``other`` residual equals the step total).  ``nested`` names
    spans that occur INSIDE a phase (e.g. ``feed/device_put`` inside the
    data wait) — reported for visibility but excluded from the sum.
    Returns None when no steps were recorded.
    """
    agg = tel.aggregates()
    if step_span not in agg:
        return None
    steps, wall_ns, max_ns = agg[step_span]
    report: Dict = {
        "run_id": run_id(),
        "step_span": step_span,
        "steps": steps,
        "wall_s": round(wall_ns / 1e9, 6),
        "steps_per_s": round(steps / (wall_ns / 1e9), 3) if wall_ns else 0.0,
        "step": _stats(steps, wall_ns, max_ns, tel.durations_ns(step_span)),
    }
    accounted = 0
    out_phases: Dict[str, Dict] = {}
    for name in phases:
        if name not in agg:
            continue
        c, total, mx = agg[name]
        accounted += total
        out_phases[name] = _stats(c, total, mx, tel.durations_ns(name))
    other_ns = max(0, wall_ns - accounted)
    out_phases["other"] = {
        "count": steps,
        "total_s": round(other_ns / 1e9, 6),
        "mean_ms": round(other_ns / steps / 1e6, 4) if steps else 0.0,
        "max_ms": None,
        "p50_ms": None,
        "p95_ms": None,
    }
    report["phases"] = out_phases
    report["phase_total_s"] = round((accounted + other_ns) / 1e9, 6)
    report["nested"] = {
        name: _stats(*agg[name], tel.durations_ns(name))
        for name in nested
        if name in agg
    }
    report["counters"] = tel.counters()
    return report


def format_breakdown(report: Dict) -> str:
    """The human-readable report the CLI prints at end of run."""
    lines = [
        f"step-time breakdown ({report['step_span']}): "
        f"{report['steps']} steps in {report['wall_s']:.3f} s wall "
        f"({report['steps_per_s']:.2f} steps/s)",
        f"  {'phase':<24} {'total_s':>9} {'share':>7} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'max_ms':>9}",
    ]
    wall = report["wall_s"] or 1.0

    def fmt(v):
        return f"{v:9.3f}" if isinstance(v, (int, float)) else f"{'-':>9}"

    for name, st in report["phases"].items():
        share = 100.0 * st["total_s"] / wall
        lines.append(
            f"  {name:<24} {st['total_s']:9.3f} {share:6.1f}% "
            f"{fmt(st['p50_ms'])} {fmt(st['p95_ms'])} {fmt(st['max_ms'])}"
        )
    for name, st in report.get("nested", {}).items():
        lines.append(
            f"  ({name}: nested)        {st['total_s']:9.3f}         "
            f"{fmt(st['p50_ms'])} {fmt(st['p95_ms'])} {fmt(st['max_ms'])}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# attention introspection: attn.jsonl + HTML contact sheet
# ---------------------------------------------------------------------------


def attention_record(row: Dict) -> Optional[Dict]:
    """One machine-readable attention record for a decoded caption.

    ``row`` is a decode_dataset result carrying ``words`` and beam-0
    ``alphas`` [len(words), N] (present when ``save_attention_maps`` is
    on).  Returns None for rows without alphas (mesh paths that dropped
    them, rows past the dedup).  Per-word entropy H_t = -Σ_i α_ti ln α_ti
    and the coverage deviation mean_i (1 - Σ_t α_ti)² are the decode-time
    twins of the ``diag/attn_entropy`` / ``diag/alpha_coverage_dev``
    train taps (telemetry/device.py), so train and eval attention health
    read on one scale."""
    if "alphas" not in row or row.get("alphas") is None:
        return None
    alphas = np.asarray(row["alphas"], dtype=np.float32)   # [L, N]
    if alphas.ndim != 2 or alphas.shape[0] == 0:
        return None
    L, N = alphas.shape
    g = int(round(np.sqrt(N)))
    clipped = np.clip(alphas, 1e-10, 1.0)
    entropy = -np.sum(alphas * np.log(clipped), axis=-1)   # [L]
    coverage = alphas.sum(axis=0)                          # [N]
    dev = 1.0 - coverage
    return {
        "run_id": run_id(),
        "image_id": row.get("image_id"),
        "image_file": row.get("image_file"),
        "caption": row.get("caption"),
        "words": list(row.get("words", [])),
        "grid": g,
        "num_ctx": int(N),
        "entropy": [round(float(h), 4) for h in entropy],
        "entropy_mean": round(float(entropy.mean()), 4),
        "entropy_frac_mean": round(float(entropy.mean() / np.log(N)), 4),
        "coverage_dev": round(float(np.mean(dev * dev)), 5),
        "alpha_max": round(float(alphas.max()), 4),
        "alphas": [[round(float(a), 4) for a in word_row] for word_row in alphas],
    }


def export_attention_jsonl(results: List[Dict], path: str) -> int:
    """Write one attention record per captioned image; returns the count
    written (0 when no row carried alphas).  Failures degrade to a
    warning — artifact export never kills eval."""
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        n = 0
        with open(path, "w") as f:
            for row in results:
                rec = attention_record(row)
                if rec is None:
                    continue
                f.write(json.dumps(rec) + "\n")
                n += 1
        return n
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: attn.jsonl export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return 0


def render_attention_sheet(
    results: List[Dict], path: str, max_images: int = 16, cell_px: int = 5
) -> Optional[str]:
    """Self-contained HTML contact sheet of per-word alpha grids.

    One row per caption: each generated word gets a g×g heat grid (pure
    CSS cells, no image deps — renders anywhere, ships in one file) with
    its entropy underneath; a caption-level summary leads the row.  Cell
    intensity shares one scale per caption (alpha_max), the same
    no-per-tile-autoscaling rule as the cv2 panels — a near-uniform map
    must not fake the contrast of a peaked one.  Reading guide:
    docs/OBSERVABILITY.md "Reading an attention contact sheet"."""
    recs = [r for r in map(attention_record, results) if r is not None]
    if not recs:
        return None
    shown = recs[:max_images]
    parts: List[str] = [
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>sat_tpu attention contact sheet</title><style>"
        "body{font-family:sans-serif;background:#fafafa;margin:16px}"
        ".cap{background:#fff;border:1px solid #ddd;border-radius:6px;"
        "padding:10px;margin-bottom:14px}"
        ".meta{font-size:13px;color:#333;margin-bottom:6px}"
        ".tiles{display:flex;flex-wrap:wrap;gap:8px}"
        ".tile{text-align:center}"
        ".word{font-size:11px;max-width:90px;overflow:hidden;"
        "text-overflow:ellipsis;white-space:nowrap}"
        ".ent{font-size:10px;color:#777}"
        "table.g{border-collapse:collapse}"
        f"table.g td{{width:{cell_px}px;height:{cell_px}px;padding:0}}"
        "</style></head><body>",
        f"<h2>attention contact sheet — {len(recs)} captions"
        f"{' (showing ' + str(len(shown)) + ')' if len(shown) < len(recs) else ''}"
        f"</h2><div class='meta'>run {run_id()} — cell intensity is "
        "α scaled by the caption's max; H is per-word entropy "
        "(ln N = uniform)</div>",
    ]
    for rec in shown:
        g = rec["grid"]
        vmax = rec["alpha_max"] or 1.0
        parts.append(
            "<div class='cap'><div class='meta'>"
            f"<b>{rec.get('image_id')}</b> — “{rec.get('caption')}” "
            f"(H̄={rec['entropy_mean']:.2f}, "
            f"uniformity={rec['entropy_frac_mean']:.2f}, "
            f"coverage_dev={rec['coverage_dev']:.4f})</div><div class='tiles'>"
        )
        for word, ent, word_alphas in zip(
            rec["words"], rec["entropy"], rec["alphas"]
        ):
            rows_html = []
            for r in range(g):
                cells = "".join(
                    f"<td style='background:rgba(185,28,28,"
                    f"{min(1.0, word_alphas[r * g + c] / vmax):.2f})'></td>"
                    for c in range(g)
                )
                rows_html.append(f"<tr>{cells}</tr>")
            parts.append(
                f"<div class='tile'><table class='g'>{''.join(rows_html)}"
                f"</table><div class='word'>{word}</div>"
                f"<div class='ent'>H={ent:.2f}</div></div>"
            )
        parts.append("</div></div>")
    parts.append("</body></html>")
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        atomic_write(path, "w", lambda f: f.write("".join(parts)))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: attention sheet export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None


def save_breakdown(report: Dict, path: str) -> Optional[str]:
    try:
        atomic_write(path, "w", lambda f: json.dump(report, f, indent=2))
        return path
    except (OSError, ValueError) as e:
        print(
            f"sat_tpu: breakdown export failed ({path}): {e}",
            file=sys.stderr,
            flush=True,
        )
        return None
