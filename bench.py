"""Benchmark: training throughput + MFU of the flagship caption model.

Measures steady-state captions/sec of the jitted train step — VGG16
encoder forward (frozen CNN, the reference's published configuration,
/root/reference/config.py:8-43 + README.md:85-89), 20-step scan decoder,
backward, global-norm clip 5.0, Adam — on whatever single device JAX
provides (the driver runs this on one real TPU chip).

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
and emits it IMMEDIATELY after the first timed window completes (the
round-1 run was killed at rc=124 with zero output; every stage now logs
progress to stderr so a timeout still leaves a diagnosable tail).

The reference publishes no throughput numbers (SURVEY.md §6), so
``vs_baseline`` is computed against ``published.train_captions_per_sec``
in BASELINE.json when present (recorded from a prior round), else 1.0.

Env knobs: BENCH_BATCH (default 32), BENCH_STEPS (default 10),
BENCH_WARMUP (default 2), BENCH_PEAK_TFLOPS (override chip bf16 peak for
MFU when the device kind is unknown), BENCH_TRAIN_CNN=1 (joint CNN+RNN
training instead of the default frozen-CNN reference configuration;
vs_baseline is pinned to 1.0 there since the recorded baseline is the
frozen config), BENCH_RNG_IMPL (override config.rng_impl, e.g.
threefry2x32 to reproduce the PERF.md dropout-PRNG A/B),
BENCH_WATCHDOG_S (hard deadline, default 540),
BENCH_CPU=1 (pin the CPU backend for dev/smoke runs),
BENCH_CNN=resnet50 (bench the second encoder family; vs_baseline pins
to 1.0 off the recorded vgg16 config), BENCH_REMAT=1 / BENCH_REMAT_CNN=1
(decoder / encoder rematerialization A/Bs),
BENCH_EVAL=0 (skip the additive eval-decode metric; BENCH_EVAL_ITERS
sizes its window).  When the eval-decode extras are measured, a second,
richer JSON line is printed after the contract line.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


# bf16 peak FLOP/s per chip by accelerator generation (public spec sheets;
# used only to report MFU next to raw throughput).
_PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,   # JAX reports v5e as device_kind "TPU v5 lite"
    "v5p": 459.0,
    "v6e": 918.0,
    "v6lite": 918.0,
}


def _peak_flops(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, tf in _PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return None


def _program_flops(compiled) -> float | None:
    """FLOPs/step from XLA's cost analysis of the compiled program."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:  # cost analysis is best-effort on some backends
        log(f"cost_analysis unavailable: {e!r}")
        return None


def _arm_watchdog() -> "callable":
    """Hard deadline for the whole bench (BENCH_WATCHDOG_S, default 540s).

    The tunneled TPU backend can wedge with jax.devices() blocking
    uninterruptibly (observed this round: >2h); without a watchdog the
    driver sees rc=124 and nothing else.  Failing fast with a clear stderr
    tail is strictly more informative.  Returns a disarm callback."""
    import threading

    deadline = float(os.environ.get("BENCH_WATCHDOG_S", "540"))
    done = threading.Event()

    def monitor():
        if not done.wait(deadline):
            log(
                f"WATCHDOG: bench did not finish within {deadline:.0f}s — "
                "device backend unreachable or compile stuck; aborting"
            )
            os._exit(3)

    threading.Thread(target=monitor, daemon=True).start()
    return done.set


def main() -> None:
    disarm = _arm_watchdog()
    log("importing jax")
    import jax

    if os.environ.get("BENCH_CPU") == "1":
        # dev/smoke runs off-TPU; config pin needed because the axon
        # sitecustomize re-registers the TPU plugin over JAX_PLATFORMS
        jax.config.update("jax_platforms", "cpu")

    # Persistent compilation cache: a re-run (or a driver retry) skips the
    # 20-40s XLA compile entirely.
    cache_dir = os.path.join(os.path.dirname(__file__) or ".", ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:
        log(f"compilation cache not enabled: {e!r}")

    import jax.numpy as jnp

    from sat_tpu.config import Config
    from sat_tpu.train.step import create_train_state, make_jit_train_step

    device = jax.devices()[0]
    log(f"platform={device.platform} device_kind={getattr(device, 'device_kind', '?')}")

    B = int(os.environ.get("BENCH_BATCH", "32"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    train_cnn = os.environ.get("BENCH_TRAIN_CNN", "0") == "1"
    cnn = os.environ.get("BENCH_CNN", "vgg16")  # or resnet50
    config = Config(batch_size=B, train_cnn=train_cnn, cnn=cnn)
    if "BENCH_RNG_IMPL" in os.environ:  # e.g. threefry2x32, to rerun the
        config = config.replace(rng_impl=os.environ["BENCH_RNG_IMPL"])  # PERF.md A/B
    if os.environ.get("BENCH_REMAT") == "1":  # decoder-remat A/B
        config = config.replace(remat_decoder=True)
    if os.environ.get("BENCH_REMAT_CNN") == "1":  # encoder-remat A/B (joint)
        config = config.replace(remat_cnn=True)

    T = config.max_caption_length

    rng = np.random.default_rng(0)
    log(f"building host batch B={B} T={T}")
    host_batch = {
        "images": rng.normal(size=(B, 224, 224, 3)).astype(np.float32),
        "word_idxs": rng.integers(0, config.vocabulary_size, size=(B, T)).astype(
            np.int32
        ),
        "masks": (np.arange(T)[None, :] < rng.integers(8, T + 1, size=(B, 1))).astype(
            np.float32
        ),
    }

    log("initializing model state")
    state = create_train_state(jax.random.PRNGKey(0), config)
    step_rng = jax.random.key(1, impl=config.rng_impl)
    log("transferring batch + state to device")
    batch = jax.device_put(host_batch, device)
    state = jax.device_put(state, device)
    jax.block_until_ready((batch, state))

    train_step = make_jit_train_step(config)
    log("lowering + compiling train step (first compile ~20-40s uncached)")
    t_c = time.perf_counter()
    compiled = train_step.lower(state, batch, step_rng).compile()
    compile_s = time.perf_counter() - t_c
    log(f"compiled in {compile_s:.1f}s")
    flops_per_step = _program_flops(compiled)

    log(f"warmup x{warmup}")
    for _ in range(warmup):
        state, metrics = compiled(state, batch, step_rng)
        loss = float(metrics["total_loss"])  # hard host sync barrier
        log(f"warmup step done, loss={loss:.4f}")

    log(f"timing window x{n_steps}")
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = compiled(state, batch, step_rng)
    float(metrics["total_loss"])  # sync
    elapsed = time.perf_counter() - t0

    captions_per_sec = n_steps * B / elapsed
    step_ms = 1e3 * elapsed / n_steps
    log(f"{captions_per_sec:.2f} captions/sec ({step_ms:.1f} ms/step)")

    baseline = None
    if not train_cnn and cnn == "vgg16":
        # the recorded baseline is the frozen-CNN configuration; a joint
        # CNN+RNN run is a different workload, not a regression against it
        try:
            with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
                baseline = json.load(f).get("published", {}).get("train_captions_per_sec")
        except (OSError, json.JSONDecodeError):
            pass
    vs_baseline = captions_per_sec / baseline if baseline else 1.0

    result = {
        "metric": "train_captions_per_sec",
        "value": round(captions_per_sec, 2),
        "unit": "captions/sec/chip",
        "vs_baseline": round(vs_baseline, 3),
        "step_time_ms": round(step_ms, 2),
        "batch_size": B,
        "train_cnn": train_cnn,
        "cnn": cnn,
        "compile_s": round(compile_s, 1),
        "device_kind": getattr(device, "device_kind", device.platform),
    }
    peak = _peak_flops(device)
    if flops_per_step is not None:
        achieved = flops_per_step * n_steps / elapsed
        result["tflops_per_sec"] = round(achieved / 1e12, 2)
        if peak:
            result["mfu"] = round(achieved / peak, 4)
    # THE contract line — flushed the moment the first window completes
    # (the round-1 artifact died at rc=124 with zero output; nothing may
    # delay this print).
    print(json.dumps(result), flush=True)

    # Eval-decode throughput (encode + on-device batched beam search) in
    # the same artifact.  Strictly additive AFTER the contract line: a
    # fuller JSON line is re-emitted once the extras exist, so a driver
    # reading either the first or the last JSON line gets valid metrics.
    # (BENCH_EVAL=0 disables.)
    if os.environ.get("BENCH_EVAL", "1") == "1":
        try:
            from sat_tpu.ops.beam_search import beam_search_jit

            log("eval decode: compiling encoder+beam program (beam=3)")
            eval_iters = int(os.environ.get("BENCH_EVAL_ITERS", "5"))

            @jax.jit
            def decode(params, images):
                from sat_tpu.models.captioner import encode

                contexts, _ = encode(
                    {"params": params}, config, images, train=False
                )
                out = beam_search_jit(
                    params["decoder"], config, contexts, 1, beam_size=3
                )
                # serializing dependency for chained timing (PERF.md)
                return out, images + 1e-30 * out.log_scores.sum()

            t_c = time.perf_counter()
            out, images_c = decode(state.params, batch["images"])
            jax.device_get(out.log_scores[0, 0])
            log(f"eval decode compiled+first in {time.perf_counter() - t_c:.1f}s")
            t0 = time.perf_counter()
            for _ in range(eval_iters):
                out, images_c = decode(state.params, images_c)
            jax.device_get(out.log_scores[0, 0])
            eval_elapsed = time.perf_counter() - t0
            result["eval_images_per_sec"] = round(eval_iters * B / eval_elapsed, 2)
            result["eval_batch_ms"] = round(1e3 * eval_elapsed / eval_iters, 1)
            log(f"eval decode: {result['eval_images_per_sec']} images/sec @ beam=3")
            print(json.dumps(result), flush=True)
        except Exception as e:  # pragma: no cover - additive metric only
            log(f"eval decode bench skipped: {e!r}")

    disarm()


if __name__ == "__main__":
    main()
