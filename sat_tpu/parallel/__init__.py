"""Distributed layer: device mesh, sharding rules, parallel train/eval steps.

TPU-native replacement for the reference's TF1 ParameterServer strategy
(/root/reference/clusterone_config.py:87-125, main_distributed.py:39-101):
synchronous SPMD over a `jax.sharding.Mesh` instead of asynchronous gRPC
parameter-server pulls.  Gradients all-reduce over ICI via XLA-inserted
collectives; multi-host bootstrap wraps `jax.distributed.initialize`
(the equivalent of the reference's TF_CONFIG/PS_HOSTS env plumbing).
"""

from .mesh import make_mesh, initialize_distributed, mesh_from_devices, sync_processes
from .sharding import (
    batch_sharding,
    param_partition_specs,
    replicated,
    shard_batch,
    shard_train_state,
    train_state_shardings,
)
from .context import (
    make_context_parallel_loss,
    make_context_parallel_train_step,
)
from .train import (
    create_parallel_train_state,
    make_parallel_beam_search,
    make_parallel_train_step,
)

__all__ = [
    "make_mesh",
    "sync_processes",
    "mesh_from_devices",
    "initialize_distributed",
    "batch_sharding",
    "replicated",
    "param_partition_specs",
    "train_state_shardings",
    "shard_batch",
    "shard_train_state",
    "make_parallel_train_step",
    "create_parallel_train_state",
    "make_parallel_beam_search",
    "make_context_parallel_loss",
    "make_context_parallel_train_step",
]
