"""Fault tolerance for preemptible-TPU training (docs/RESILIENCE.md).

Four recovery paths, each provable under deterministic fault injection
(``tests/test_resilience.py``):

* **preemption** → periodic checkpoints + bitwise mid-epoch resume, with
  graceful SIGTERM draining (:mod:`.preempt`);
* **corrupt/torn checkpoints** → sha256 sidecars, post-write verify, the
  ``LAST_GOOD`` pointer, restore walk-back (:mod:`.lineage`);
* **NaN/diverging steps** → the log-boundary anomaly sentinel with
  ``warn | skip | rollback`` policies (:mod:`.sentinel`);
* **flaky storage** → classified, jittered-backoff IO retries
  (:mod:`.retry`);
* **silent wedges** → the zero-sync progress watchdog's escalation
  ladder (gauges → stack dump → abort, :mod:`.watchdog`) under the
  crash-only ``--supervise`` restart loop (:mod:`.supervisor`), resuming
  from ``LAST_GOOD`` on whatever device topology is available now
  (the lineage sidecar records the topology the checkpoint was written
  under);
* **poisoned input data** → the append-only quarantine ledger with
  deterministic substitution and the systemic-corruption ceiling
  (exit 87, never restarted — :mod:`.quarantine`), fed by the
  per-record integrity checks in :mod:`sat_tpu.data.integrity`.

Nothing here imports jax at module level; the injection harness
(:mod:`.faultinject`) is inert unless ``SAT_FI_*`` env vars arm it.
"""

from .faultinject import (
    FaultPlan,
    InjectedIOError,
    SimulatedPreemption,
    corrupt_byte,
    reset_io_faults,
)
from .lineage import (
    CheckpointWriteError,
    apply_retention,
    checkpoint_steps,
    file_sha256,
    finalize_save,
    last_good_checkpoint,
    last_good_step,
    mark_last_good,
    read_sidecar_topology,
    sidecar_path,
    verify_checkpoint,
    write_sidecar,
)
from .preempt import GracefulShutdown
from .quarantine import (
    DATA_CORRUPTION_EXIT_CODE,
    QuarantineManager,
    SystemicCorruption,
)
from .retry import backoff_delay, configure, is_retryable, retry_io
from .sentinel import AnomalySentinel
from .supervisor import supervise
from .watchdog import WATCHDOG_EXIT_CODE, Watchdog

__all__ = [
    "AnomalySentinel",
    "CheckpointWriteError",
    "DATA_CORRUPTION_EXIT_CODE",
    "FaultPlan",
    "GracefulShutdown",
    "InjectedIOError",
    "QuarantineManager",
    "SimulatedPreemption",
    "SystemicCorruption",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "apply_retention",
    "backoff_delay",
    "checkpoint_steps",
    "configure",
    "corrupt_byte",
    "file_sha256",
    "finalize_save",
    "is_retryable",
    "last_good_checkpoint",
    "last_good_step",
    "mark_last_good",
    "read_sidecar_topology",
    "reset_io_faults",
    "retry_io",
    "sidecar_path",
    "supervise",
    "verify_checkpoint",
    "write_sidecar",
]
