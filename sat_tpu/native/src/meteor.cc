// METEOR 1.5 segment scorer — native replacement for the reference's
// persistent meteor-1.5.jar subprocess (/root/reference/utils/coco/
// pycocoevalcap/meteor/meteor.py:15-58).
//
// Mirror of the Python implementation in sat_tpu/evalcap/meteor.py
// (golden-tested against it): stage-wise greedy alignment — exact (1.0),
// Porter-stem (0.6), synonym (0.8) with nearest-occurrence pairing,
// paraphrase phrase spans (0.6, longest-hyp-span-first) — and METEOR 1.5
// scoring with the English rank-tuned parameters α=0.85, β=0.2, γ=0.6,
// δ=0.75 (Denkowski & Lavie 2014): content/function-word discounted P
// and R (per-side coverage, so paraphrase spans of unequal length score
// correctly), fragmentation penalty only when the alignment has more
// than one chunk.  The function-word, synonym, and paraphrase tables are
// pushed in from Python (meteor_data.py) via sat_meteor_set_data so both
// backends share one source of truth.

#include <algorithm>
#include <cmath>
#include <cstdlib>
#include <map>
#include <sstream>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace sat_native {

std::string porter_stem(const std::string& input);

namespace {

constexpr double kAlpha = 0.85;
constexpr double kBeta = 0.2;
constexpr double kGamma = 0.6;
constexpr double kDelta = 0.75;
constexpr double kExactWeight = 1.0;
constexpr double kStemWeight = 0.6;
constexpr double kSynonymWeight = 0.8;
constexpr double kParaphraseWeight = 0.6;

std::unordered_set<std::string> g_function_words;
// word -> group ids (two words are synonyms iff their id sets intersect)
std::unordered_map<std::string, std::vector<int>> g_synonyms;
// phrase (space-joined) -> group ids; same intersection semantics
std::unordered_map<std::string, std::vector<int>> g_paraphrases;
int g_max_paraphrase_len = 0;

std::vector<std::string> split_ws(const std::string& s) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < s.size()) {
    while (i < s.size() && s[i] == ' ') i++;
    size_t start = i;
    while (i < s.size() && s[i] != ' ') i++;
    if (i > start) out.push_back(s.substr(start, i - start));
  }
  return out;
}

struct Match {
  int hyp_idx;
  int ref_idx;
  double weight;
};

void run_key_stage(const std::vector<std::string>& hyp_keys,
                   const std::vector<std::string>& ref_keys,
                   std::vector<bool>* hyp_used, std::vector<bool>* ref_used,
                   double weight, std::vector<Match>* matches,
                   std::vector<double>* hyp_w, std::vector<double>* ref_w) {
  std::map<std::string, std::vector<int>> ref_slots;
  for (int j = 0; j < static_cast<int>(ref_keys.size()); j++) {
    if (!(*ref_used)[j]) ref_slots[ref_keys[j]].push_back(j);
  }
  for (int i = 0; i < static_cast<int>(hyp_keys.size()); i++) {
    if ((*hyp_used)[i]) continue;
    auto it = ref_slots.find(hyp_keys[i]);
    if (it == ref_slots.end() || it->second.empty()) continue;
    // nearest remaining reference occurrence to position i
    auto& slots = it->second;
    auto best = std::min_element(
        slots.begin(), slots.end(),
        [i](int a, int b) { return std::abs(a - i) < std::abs(b - i); });
    int j = *best;
    slots.erase(best);
    (*hyp_used)[i] = true;
    (*ref_used)[j] = true;
    matches->push_back({i, j, weight});
    (*hyp_w)[i] = weight;
    (*ref_w)[j] = weight;
  }
}

bool share_group(const std::vector<int>& a, const std::vector<int>& b) {
  for (int ga : a)
    for (int gb : b)
      if (ga == gb) return true;
  return false;
}

void run_synonym_stage(const std::vector<std::string>& hyp,
                       const std::vector<std::string>& ref,
                       std::vector<bool>* hyp_used,
                       std::vector<bool>* ref_used,
                       std::vector<Match>* matches,
                       std::vector<double>* hyp_w,
                       std::vector<double>* ref_w) {
  for (int i = 0; i < static_cast<int>(hyp.size()); i++) {
    if ((*hyp_used)[i]) continue;
    auto hit = g_synonyms.find(hyp[i]);
    if (hit == g_synonyms.end()) continue;
    int best_j = -1;
    for (int j = 0; j < static_cast<int>(ref.size()); j++) {
      if ((*ref_used)[j]) continue;
      auto rit = g_synonyms.find(ref[j]);
      if (rit == g_synonyms.end()) continue;
      if (share_group(hit->second, rit->second)) {
        if (best_j < 0 || std::abs(j - i) < std::abs(best_j - i)) best_j = j;
      }
    }
    if (best_j >= 0) {
      (*hyp_used)[i] = true;
      (*ref_used)[best_j] = true;
      matches->push_back({i, best_j, kSynonymWeight});
      (*hyp_w)[i] = kSynonymWeight;
      (*ref_w)[best_j] = kSynonymWeight;
    }
  }
}

std::string join_span(const std::vector<std::string>& words, int start,
                      int len) {
  std::string out;
  for (int k = 0; k < len; k++) {
    if (k) out += ' ';
    out += words[start + k];
  }
  return out;
}

// Paraphrase stage: longest unmatched hypothesis span first (leftmost
// within a length); reference candidate = nearest unmatched span sharing
// a group id, longer spans preferred on distance ties (mirrors the
// Python iteration order exactly).  Covered words get per-side weight;
// zipped word pairs feed the chunk count.
void run_paraphrase_stage(const std::vector<std::string>& hyp,
                          const std::vector<std::string>& ref,
                          std::vector<bool>* hyp_used,
                          std::vector<bool>* ref_used,
                          std::vector<Match>* matches,
                          std::vector<double>* hyp_w,
                          std::vector<double>* ref_w) {
  auto span_free = [](const std::vector<bool>& used, int start, int len) {
    for (int k = 0; k < len; k++)
      if (used[start + k]) return false;
    return true;
  };
  for (int L = g_max_paraphrase_len; L >= 1; L--) {
    for (int i = 0; i + L <= static_cast<int>(hyp.size()); i++) {
      if (!span_free(*hyp_used, i, L)) continue;
      auto hit = g_paraphrases.find(join_span(hyp, i, L));
      if (hit == g_paraphrases.end()) continue;
      int best_j = -1, best_m = 0, best_d = 0;
      for (int M = g_max_paraphrase_len; M >= 1; M--) {
        for (int j = 0; j + M <= static_cast<int>(ref.size()); j++) {
          if (!span_free(*ref_used, j, M)) continue;
          auto rit = g_paraphrases.find(join_span(ref, j, M));
          if (rit == g_paraphrases.end()) continue;
          if (!share_group(hit->second, rit->second)) continue;
          int d = std::abs(j - i);
          if (best_j < 0 || d < best_d) {
            best_j = j;
            best_m = M;
            best_d = d;
          }
        }
      }
      if (best_j < 0) continue;
      for (int k = 0; k < L; k++) {
        (*hyp_used)[i + k] = true;
        (*hyp_w)[i + k] = kParaphraseWeight;
      }
      for (int k = 0; k < best_m; k++) {
        (*ref_used)[best_j + k] = true;
        (*ref_w)[best_j + k] = kParaphraseWeight;
      }
      for (int k = 0; k < std::min(L, best_m); k++) {
        matches->push_back({i + k, best_j + k, kParaphraseWeight});
      }
    }
  }
}

// δ-discounted weighted match fraction for one side (P or R) from the
// per-side coverage weights (-1 = unmatched).
double side_score(const std::vector<std::string>& words,
                  const std::vector<double>& weights) {
  int n_f = 0;
  for (const auto& w : words)
    if (g_function_words.count(w)) n_f++;
  int n_c = static_cast<int>(words.size()) - n_f;
  double denom = kDelta * n_c + (1.0 - kDelta) * n_f;
  if (denom == 0.0) return 0.0;
  double wc = 0.0, wf = 0.0;
  for (size_t idx = 0; idx < words.size(); idx++) {
    if (weights[idx] < 0.0) continue;
    if (g_function_words.count(words[idx]))
      wf += weights[idx];
    else
      wc += weights[idx];
  }
  return (kDelta * wc + (1.0 - kDelta) * wf) / denom;
}

}  // namespace

void meteor_set_data(const std::string& function_words,
                     const std::string& synset_lines,
                     const std::string& paraphrase_lines) {
  g_function_words.clear();
  for (const auto& w : split_ws(function_words)) g_function_words.insert(w);
  g_synonyms.clear();
  std::istringstream in(synset_lines);
  std::string line;
  int gid = 0;
  while (std::getline(in, line)) {
    auto words = split_ws(line);
    if (words.empty()) continue;
    for (const auto& w : words) g_synonyms[w].push_back(gid);
    gid++;
  }
  // paraphrase groups: one group per line, phrases separated by '|'
  g_paraphrases.clear();
  g_max_paraphrase_len = 0;
  std::istringstream pin(paraphrase_lines);
  int pgid = 0;
  while (std::getline(pin, line)) {
    bool any = false;
    size_t pos = 0;
    while (pos <= line.size()) {
      size_t bar = line.find('|', pos);
      if (bar == std::string::npos) bar = line.size();
      std::string phrase = line.substr(pos, bar - pos);
      auto words = split_ws(phrase);
      if (!words.empty()) {
        g_paraphrases[join_span(words, 0, static_cast<int>(words.size()))]
            .push_back(pgid);
        g_max_paraphrase_len =
            std::max(g_max_paraphrase_len, static_cast<int>(words.size()));
        any = true;
      }
      pos = bar + 1;
    }
    if (any) pgid++;
  }
}

double meteor_segment(const std::string& hypothesis,
                      const std::string& reference) {
  std::vector<std::string> hyp = split_ws(hypothesis);
  std::vector<std::string> ref = split_ws(reference);
  if (hyp.empty() || ref.empty()) return 0.0;

  std::vector<bool> hyp_used(hyp.size(), false), ref_used(ref.size(), false);
  std::vector<double> hyp_w(hyp.size(), -1.0), ref_w(ref.size(), -1.0);
  std::vector<Match> matches;
  run_key_stage(hyp, ref, &hyp_used, &ref_used, kExactWeight, &matches,
                &hyp_w, &ref_w);

  std::vector<std::string> hyp_stems(hyp.size()), ref_stems(ref.size());
  // corpus scoring re-stems the same caption vocabulary across thousands
  // of segments; cache stems (safe: the ctypes layer serializes scoring)
  // bounded (the Python twin uses lru_cache(65536)): an open-ended
  // vocabulary in a long-lived process must not grow it without limit
  static std::unordered_map<std::string, std::string> stem_cache;
  auto cached_stem = [](const std::string& w) -> const std::string& {
    auto it = stem_cache.find(w);
    if (it == stem_cache.end()) {
      if (stem_cache.size() >= 65536) stem_cache.clear();
      it = stem_cache.emplace(w, porter_stem(w)).first;
    }
    return it->second;
  };
  for (size_t i = 0; i < hyp.size(); i++) hyp_stems[i] = cached_stem(hyp[i]);
  for (size_t j = 0; j < ref.size(); j++) ref_stems[j] = cached_stem(ref[j]);
  run_key_stage(hyp_stems, ref_stems, &hyp_used, &ref_used, kStemWeight,
                &matches, &hyp_w, &ref_w);

  run_synonym_stage(hyp, ref, &hyp_used, &ref_used, &matches, &hyp_w, &ref_w);
  run_paraphrase_stage(hyp, ref, &hyp_used, &ref_used, &matches, &hyp_w,
                       &ref_w);

  if (matches.empty()) return 0.0;
  std::sort(matches.begin(), matches.end(),
            [](const Match& a, const Match& b) {
              return a.hyp_idx != b.hyp_idx ? a.hyp_idx < b.hyp_idx
                                            : a.ref_idx < b.ref_idx;
            });

  int chunks = 1;
  for (size_t k = 1; k < matches.size(); k++) {
    if (!(matches[k].hyp_idx == matches[k - 1].hyp_idx + 1 &&
          matches[k].ref_idx == matches[k - 1].ref_idx + 1)) {
      chunks++;
    }
  }

  // m for the fragmentation penalty: average matched-word count over the
  // two sides (equals the pair count for word-level stages; generalizes
  // to paraphrase spans of unequal length)
  int hyp_covered = 0, ref_covered = 0;
  for (double w : hyp_w) hyp_covered += (w >= 0.0);
  for (double w : ref_w) ref_covered += (w >= 0.0);
  double m_avg = 0.5 * (hyp_covered + ref_covered);

  double p = side_score(hyp, hyp_w);
  double r = side_score(ref, ref_w);
  if (p == 0.0 || r == 0.0) return 0.0;
  double fmean = (p * r) / (kAlpha * p + (1.0 - kAlpha) * r);
  // single-chunk alignments carry no fragmentation penalty (jar
  // behavior: identical sentences score exactly 1.0)
  if (chunks <= 1) return fmean;
  double frag = static_cast<double>(chunks) / m_avg;
  double penalty = kGamma * std::pow(frag, kBeta);
  return fmean * (1.0 - penalty);
}

}  // namespace sat_native
