"""Diag-tap overhead accounting: train-step cost with --diag_level off/basic/full.

docs/OBSERVABILITY.md claims the in-graph model-health taps
(telemetry/device.py) are cheap enough to leave on: a handful of scalar
reductions fused into the step program, fetched on the existing log sync.
This bench puts a number on it — the measured wall-clock delta between a
``diag_level=off`` and a ``diag_level=basic`` (and ``full``) train step
on a small synthetic model, expressed as percent of a ``--step-ms``
(default 30 ms) production device step.  The acceptance bar is
``basic < 1%`` (ISSUE 4).

Methodology: the three step variants are compiled up front, then timed in
INTERLEAVED rounds (off/basic/full, off/basic/full, ...) with a device
sync per timed block, taking the per-round minimum block time —
interleaving cancels drift (thermal, CI noisy neighbors) that
back-to-back arms would alias into the delta.

Prints a BENCH-contract JSON row ({"metric","value","unit",
"vs_baseline",...}) stamped with the shared provenance header
(``sat_tpu.telemetry.bench_stamp``), so ``scripts/check_regression.py``
can gate it across sessions.

Usage: python scripts/bench_diag.py [--batch 8] [--iters 30] [--rounds 5]
       [--step-ms 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[bench_diag +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--iters", type=int, default=30,
                    help="steps per timed block")
    ap.add_argument("--rounds", type=int, default=5,
                    help="interleaved measurement rounds per arm")
    ap.add_argument("--step-ms", type=float, default=30.0,
                    help="production device step the overhead is scored "
                         "against (BASELINE.json: ~30 ms)")
    args = ap.parse_args()

    log("importing jax")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sat_tpu import telemetry
    from sat_tpu.config import Config
    from sat_tpu.train.step import create_train_state, make_jit_train_step

    base = Config(
        phase="train",
        batch_size=args.batch,
        image_size=32,
        dim_embedding=16,
        num_lstm_units=16,
        dim_initialize_layer=16,
        dim_attend_layer=16,
        dim_decode_layer=32,
        vocabulary_size=200,
        compute_dtype="float32",
    )
    rng = jax.random.PRNGKey(0)
    B, T = args.batch, base.max_caption_length
    batch = {
        "images": jnp.asarray(
            np.random.default_rng(0).integers(
                0, 255, (B, base.image_size, base.image_size, 3), np.uint8
            )
        ),
        "word_idxs": jnp.asarray(
            np.random.default_rng(1).integers(0, 200, (B, T), np.int32)
        ),
        "masks": jnp.ones((B, T), jnp.float32),
    }
    step_rng = jax.random.key(1, impl=base.rng_impl)

    arms = {}
    for level in ("off", "basic", "full"):
        config = base.replace(diag_level=level)
        step_fn = make_jit_train_step(config)
        state = create_train_state(rng, config)
        # steady state: compile + a couple of dispatches outside the timer
        for _ in range(3):
            state, metrics = step_fn(state, batch, step_rng)
        jax.block_until_ready(metrics)
        arms[level] = (step_fn, state)
        log(f"{level}: compiled, {len(metrics)} metric outputs")

    times = {level: [] for level in arms}
    for r in range(args.rounds):
        for level, (step_fn, state) in arms.items():
            t0 = time.perf_counter()
            metrics = None
            for _ in range(args.iters):
                state, metrics = step_fn(state, batch, step_rng)
            jax.block_until_ready(metrics)
            times[level].append((time.perf_counter() - t0) / args.iters)
            arms[level] = (step_fn, state)
    ms = {level: 1e3 * min(samples) for level, samples in times.items()}
    log(f"per-step: off {ms['off']:.4f} ms, basic {ms['basic']:.4f} ms, "
        f"full {ms['full']:.4f} ms")

    # the gated quantity: what basic taps add to a production step budget
    basic_delta_ms = max(0.0, ms["basic"] - ms["off"])
    full_delta_ms = max(0.0, ms["full"] - ms["off"])
    overhead_pct = 100.0 * basic_delta_ms / args.step_ms
    log(f"basic taps: +{basic_delta_ms:.4f} ms/step = {overhead_pct:.4f}% "
        f"of a {args.step_ms:.0f} ms step (bar: 1%)")

    result = {
        "metric": "diag_tap_overhead",
        "value": round(overhead_pct, 4),
        "unit": "%_of_step",
        "vs_baseline": 1.0,  # the acceptance bar (ISSUE 4: < 1%)
        "off_ms_per_step": round(ms["off"], 4),
        "basic_ms_per_step": round(ms["basic"], 4),
        "full_ms_per_step": round(ms["full"], 4),
        "basic_delta_ms": round(basic_delta_ms, 4),
        "full_delta_ms": round(full_delta_ms, 4),
        "step_ms_assumed": args.step_ms,
        "iters": args.iters,
        "rounds": args.rounds,
        "batch_size": args.batch,
        **telemetry.bench_stamp(),
    }
    print(json.dumps(result), flush=True)
    return 0 if overhead_pct < 1.0 else 1


if __name__ == "__main__":
    sys.exit(main())
