"""Host-side image decoding and the async device-feed pipeline.

The reference loads images synchronously inside the train loop
(/root/reference/utils/misc.py:6-36 and base_model.py:53), stalling the
device every step.  Here the same preprocessing (decode → BGR→RGB → resize
224×224 → subtract ILSVRC-2012 per-channel mean) runs in a thread pool that
stays ``prefetch_depth`` batches ahead and hands ready numpy batches to the
device while the previous step is still running.

Preprocessing parity notes (utils/misc.py:13-28):
* cv2 decodes BGR; the reference flips channels to RGB via an axis-swap;
* the per-channel mean is the spatial mean of the Caffe ILSVRC-2012 mean
  image, [104.00698793, 116.66876762, 122.67891434] in (B,G,R) npy order —
  the reference subtracts this vector *as-is* from the RGB image
  (utils/misc.py:27), and we reproduce that exactly since pretrained
  weights were trained against it;
* "center crop" is 224→224, a no-op kept only for shape clarity.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

# Spatial mean of the Caffe ILSVRC-2012 mean image (BGR npy channel order);
# matches np.load('ilsvrc_2012_mean.npy').mean(1).mean(1) in the reference.
ILSVRC_2012_MEAN = np.array([104.00698793, 116.66876762, 122.67891434], np.float32)


class ImageLoader:
    """raw=True defers the astype(float32)−mean step to the accelerator
    (models.captioner.encode mean-subtracts uint8 inputs on device):
    numerically IDENTICAL — the resize already happens on the uint8 image,
    mean-sub is the final op either way — but the host skips a float32
    allocation per image and the host→device feed shrinks 4×.  The config
    knob is ``device_preprocess`` (on by default)."""

    def __init__(
        self, mean: Optional[np.ndarray] = None, size: int = 224,
        raw: bool = False,
    ):
        if raw and mean is not None:
            raise ValueError(
                "raw=True defers mean subtraction to the device, which "
                "hardcodes ILSVRC_2012_MEAN (captioner.encode) — a custom "
                "mean would be silently ignored; use raw=False with it"
            )
        self.mean = ILSVRC_2012_MEAN if mean is None else np.asarray(mean, np.float32)
        self.size = size
        self.raw = raw

    def load_image(self, image_file: str) -> np.ndarray:
        import cv2

        image = cv2.imread(image_file)
        if image is None:
            raise FileNotFoundError(f"cannot decode image: {image_file}")
        image = image[:, :, ::-1]  # BGR → RGB
        image = cv2.resize(image, (self.size, self.size))
        if self.raw:
            return np.ascontiguousarray(image)  # uint8 RGB, device finishes
        return image.astype(np.float32) - self.mean

    def load_images(self, image_files: Sequence[str]) -> np.ndarray:
        return np.stack([self.load_image(f) for f in image_files])


class PrefetchLoader:
    """Wraps a batch iterator; decodes images in a thread pool and keeps a
    bounded queue of ready batches so the accelerator never waits on cv2.

    Yields dicts with 'images' [B,224,224,3] — float32 mean-subtracted, or
    uint8 RGB when the loader runs raw=True (device finishes the
    preprocessing; see ImageLoader) — plus any extra arrays the source
    iterator produced ('word_idxs', 'masks', 'files')."""

    def __init__(
        self,
        dataset,
        image_loader: Optional[ImageLoader] = None,
        num_workers: int = 8,
        prefetch_depth: int = 2,
    ):
        self.dataset = dataset
        self.loader = image_loader or ImageLoader()
        self.num_workers = num_workers
        self.prefetch_depth = max(1, prefetch_depth)

    def _decode_batch(self, batch, pool: ThreadPoolExecutor):
        if isinstance(batch, tuple):
            files, word_idxs, masks = batch
            out = {
                "word_idxs": np.asarray(word_idxs, np.int32),
                "masks": np.asarray(masks, np.float32),
            }
        else:
            files, out = batch, {}
        out["images"] = np.stack(list(pool.map(self.loader.load_image, files)))
        out["files"] = list(files)
        return out

    def __iter__(self) -> Iterator[dict]:
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_depth)
        sentinel = object()
        stop = threading.Event()
        error: List[BaseException] = []

        def producer():
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for batch in self.dataset:
                        item = self._decode_batch(batch, pool)
                        # Bounded put that aborts if the consumer went away,
                        # so an abandoned iterator can't pin a thread.
                        while not stop.is_set():
                            try:
                                q.put(item, timeout=0.1)
                                break
                            except queue.Full:
                                continue
                        if stop.is_set():
                            return
            except BaseException as e:  # surfaced on the consumer side
                error.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
