"""Data tables for the METEOR 1.5 scorer (sat_tpu/evalcap/meteor.py).

METEOR 1.5 ships two English resources this environment cannot fetch
(zero egress, and the reference never bundled them either — its
meteor-1.5.jar is a missing large blob,
/root/reference/utils/coco/.MISSING_LARGE_BLOBS):

* ``function.words`` — words with relative corpus frequency > 1e-3,
  used for the δ content/function discount.  Reproduced here as a
  curated list of English closed-class words (articles, pronouns,
  prepositions, conjunctions, auxiliaries, particles, high-frequency
  adverbs) — the same population the frequency criterion selects.
* WordNet synsets for the synonym match stage.  Reproduced as a compact
  exact-match synonym table: groups of words treated as synonymous.
  Curated for general English with extra coverage of the COCO caption
  domain (scene/object/action vocabulary).  This is a subset of WordNet;
  divergence is documented in meteor.py.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Set

# ---------------------------------------------------------------------------
# function words (METEOR 1.5 function.words equivalent)
# ---------------------------------------------------------------------------

FUNCTION_WORDS: FrozenSet[str] = frozenset(
    """
    a an the this that these those some any each every either neither
    no such own same other another both all few many much more most
    several certain various
    i me my mine myself we us our ours ourselves you your yours yourself
    yourselves he him his himself she her hers herself it its itself
    they them their theirs themselves one ones who whom whose which what
    whatever whoever whichever something anything nothing everything
    someone anyone everyone somebody anybody nobody everybody
    am is are was were be been being do does did doing done have has had
    having will would shall should may might must can could ought need
    dare used
    and or but nor so yet for because although though while whereas if
    unless until since when whenever where wherever why how than whether
    as that lest once
    of in on at by to from with without within into onto upon about
    above below under over between among through during before after
    behind beside besides beyond against along across around down up
    off out near toward towards past via per amid amidst atop
    not never also just only very too quite rather almost nearly then
    there here now again ever still already perhaps maybe however
    therefore thus hence meanwhile moreover furthermore anyway instead
    else
     's 't n't 'll 're 've 'd 'm
    """.split()
)

# ---------------------------------------------------------------------------
# synonym groups (compact WordNet-synset stand-in, exact-match lookup)
# ---------------------------------------------------------------------------

_SYNONYM_GROUPS = [
    # --- general English ---
    "big large huge enormous giant gigantic massive",
    "small little tiny miniature petite",
    "quick fast rapid speedy swift",
    "slow sluggish unhurried",
    "happy glad joyful cheerful pleased delighted",
    "sad unhappy sorrowful gloomy",
    "angry mad furious irate",
    "pretty beautiful lovely gorgeous attractive handsome",
    "ugly hideous unattractive unsightly",
    "smart intelligent clever bright brainy",
    "stupid dumb foolish silly",
    "begin start commence initiate",
    "end finish conclude terminate stop cease",
    "buy purchase acquire",
    "sell vend",
    "speak talk converse chat",
    "say tell state utter",
    "look watch view observe see gaze stare",
    "hear listen",
    "walk stroll saunter amble wander",
    "run sprint dash jog race",
    "jump leap hop bound vault",
    "throw toss hurl fling pitch",
    "catch grab seize snatch capture",
    "hold grasp grip clutch",
    "carry tote haul lug transport",
    "pull tug drag yank tow",
    "push shove press",
    "eat consume devour dine munch",
    "drink sip gulp",
    "cook prepare bake",
    "cut slice chop carve dice",
    "make create build construct produce fabricate",
    "break shatter smash crack fracture",
    "fix repair mend restore",
    "clean wash scrub rinse",
    "close shut",
    "open unlock",
    "give provide supply furnish grant",
    "get obtain receive gain",
    "keep retain preserve maintain",
    "leave depart exit",
    "arrive come reach",
    "show display exhibit present demonstrate",
    "hide conceal cover",
    "find discover locate spot",
    "lose misplace",
    "help assist aid support",
    "like enjoy love adore fancy",
    "hate dislike despise loathe",
    "want desire wish crave",
    "need require",
    "think ponder contemplate consider reflect",
    "know understand comprehend realize",
    "remember recall recollect",
    "forget overlook",
    "choose select pick elect",
    "answer reply respond",
    "ask inquire question query",
    "shout yell scream holler",
    "whisper murmur mutter",
    "laugh giggle chuckle",
    "cry weep sob",
    "smile grin beam",
    "sleep doze nap slumber snooze rest",
    "wake awaken rouse",
    "sit perch",
    "stand rise",
    "fall tumble drop plunge",
    "climb ascend scale mount",
    "descend dismount",
    "fly soar glide hover",
    "swim wade paddle",
    "travel journey trek voyage",
    "drive steer pilot operate",
    "ride mount",
    "play frolic romp",
    "work labor toil",
    "study learn",
    "teach instruct educate train coach",
    "write compose pen scribble jot",
    "read peruse",
    "draw sketch illustrate doodle",
    "paint color",
    "sing chant croon",
    "dance twirl",
    "move shift relocate",
    "turn rotate spin twist revolve pivot",
    "shake tremble shiver quiver wobble",
    "touch feel",
    "smell sniff scent",
    "taste sample savor",
    "wear don sport",
    "begin beginning",
    "nice pleasant agreeable enjoyable",
    "bad terrible awful horrible dreadful poor lousy",
    "good great excellent fine wonderful superb fantastic terrific",
    "cold chilly frigid freezing frosty cool",
    "hot warm heated scorching sweltering",
    "wet damp moist soggy soaked drenched",
    "dry arid parched",
    "new fresh novel recent modern",
    "old ancient aged elderly antique vintage",
    "young youthful juvenile",
    "tall high lofty towering",
    "short low",
    "wide broad spacious vast expansive",
    "narrow slim thin slender skinny",
    "thick dense",
    "heavy weighty hefty",
    "light lightweight",
    "hard difficult tough challenging",
    "easy simple effortless",
    "loud noisy deafening",
    "quiet silent hushed still",
    "bright brilliant radiant luminous vivid shiny gleaming",
    "dark dim shadowy gloomy murky",
    "clean spotless tidy neat",
    "dirty filthy grimy muddy soiled messy",
    "full crowded packed stuffed",
    "empty vacant bare hollow",
    "strange odd weird peculiar unusual curious bizarre",
    "normal ordinary usual typical common regular",
    "important significant crucial vital essential",
    "funny amusing humorous comical hilarious",
    "scary frightening terrifying fearsome creepy spooky",
    "dangerous hazardous risky perilous unsafe",
    "safe secure protected",
    "rich wealthy affluent",
    "poor impoverished needy",
    "famous renowned celebrated noted",
    "tired exhausted weary fatigued sleepy drowsy",
    "hungry starving famished",
    "real genuine authentic actual true",
    "fake false counterfeit phony artificial",
    "whole entire complete total full",
    "part portion piece segment section fragment slice",
    "group bunch cluster crowd gathering collection herd flock pack",
    "pair couple duo twosome",
    "lots many numerous plenty several",
    "top summit peak crest",
    "bottom base foot",
    "middle center midst",
    "edge border rim margin brink verge",
    "side flank",
    "front fore",
    "back rear behind",
    "place location spot site position area region zone",
    "road street avenue boulevard lane highway roadway",
    "path trail track walkway footpath sidewalk pavement",
    "house home residence dwelling abode",
    "building structure edifice",
    "store shop market boutique",
    "restaurant diner cafe eatery bistro",
    "kitchen galley",
    "bathroom restroom washroom lavatory toilet",
    "bedroom chamber",
    "car automobile auto vehicle sedan",
    "truck lorry pickup",
    "bicycle bike cycle",
    "motorcycle motorbike moped scooter",
    "bus coach minibus",
    "train locomotive railcar",
    "airplane plane aircraft jet airliner",
    "boat ship vessel sailboat yacht ferry canoe kayak",
    "child kid youngster toddler tot",
    "children kids youngsters toddlers",
    "baby infant newborn",
    "boy lad",
    "girl lass",
    "man gentleman guy fellow male dude",
    "men gentlemen guys males fellows dudes",
    "woman lady female gal",
    "women ladies females gals",
    "person individual human",
    "people persons individuals humans folks",
    "friend pal buddy companion mate",
    "doctor physician surgeon",
    "police officer cop policeman constable",
    "photo photograph picture image snapshot",
    "television tv telly",
    "phone telephone cellphone smartphone mobile",
    "computer laptop pc",
    "couch sofa settee loveseat",
    "chair seat stool",
    "table desk counter countertop",
    "bag sack purse handbag satchel backpack knapsack",
    "cup mug glass tumbler",
    "plate dish platter",
    "bowl basin",
    "bottle flask jug",
    "box container carton crate bin",
    "garbage trash rubbish waste refuse litter",
    "gift present",
    "money cash currency",
    "clothes clothing garments apparel attire outfit",
    "shirt blouse tee tshirt",
    "pants trousers slacks jeans",
    "coat jacket blazer parka overcoat",
    "hat cap beanie bonnet helmet",
    "shoe boot sneaker sandal slipper",
    "rock stone boulder pebble",
    "hill mound knoll slope",
    "mountain peak mount",
    "forest woods woodland grove",
    "tree sapling",
    "grass lawn turf",
    "flower blossom bloom",
    "river stream creek brook",
    "lake pond lagoon reservoir",
    "ocean sea",
    "beach shore coast seashore seaside",
    "rain shower drizzle downpour",
    "snow sleet slush",
    "wind breeze gust gale",
    "storm tempest thunderstorm",
    "fire blaze flame inferno",
    "smoke fumes",
    "sun sunshine sunlight",
    "sky heavens",
    "cloud clouds",
    "night nighttime evening dusk",
    "morning dawn daybreak sunrise",
    "day daytime",
    "dog puppy pup canine hound pooch",
    "cat kitten feline kitty",
    "horse pony stallion mare steed equine",
    "cow cattle bull ox bovine calf",
    "sheep lamb ewe ram",
    "goat kid billy",
    "pig hog swine boar piglet",
    "bird fowl",
    "chicken hen rooster",
    "duck duckling",
    "fish trout salmon",
    "bear cub",
    "elephant pachyderm",
    "monkey ape primate chimp chimpanzee",
    "lion lioness",
    "tiger tigress",
    "rabbit bunny hare",
    "mouse rodent rat",
    "snake serpent",
    "insect bug",
    "butterfly moth",
    "bee wasp hornet",
    "meal dinner supper feast lunch breakfast brunch",
    "food cuisine fare grub",
    "bread loaf baguette toast",
    "cake pastry dessert",
    "candy sweets confection",
    "meat beef pork steak",
    "vegetable veggie produce",
    "fruit produce",
    "juice beverage drink",
    "coffee espresso latte cappuccino",
    "laptop notebook",
    "ball sphere orb",
    "toy plaything",
    "game match contest competition",
    "sport athletics",
    "team squad crew",
    "player athlete competitor",
    "field pitch meadow pasture paddock",
    "park playground",
    "garden yard backyard",
    "fence railing barrier",
    "wall partition",
    "door doorway entrance entry gateway gate",
    "window pane",
    "roof rooftop",
    "floor ground",
    "stairs staircase stairway steps",
    "bridge overpass viaduct",
    "tower spire",
    "church chapel cathedral",
    "school academy",
    "hospital clinic infirmary",
    "airport airfield",
    "station depot terminal",
    "city town metropolis municipality",
    "village hamlet",
    "country nation land",
    "world earth globe",
]

SYNONYM_GROUPS = tuple(tuple(g.split()) for g in _SYNONYM_GROUPS)


def build_synonym_index() -> Dict[str, Set[int]]:
    """word → set of group ids.  Two words are synonyms iff their id sets
    intersect (exact-match synset semantics)."""
    index: Dict[str, Set[int]] = {}
    for gid, group in enumerate(SYNONYM_GROUPS):
        for w in group:
            index.setdefault(w, set()).add(gid)
    return index


# ---------------------------------------------------------------------------
# paraphrase groups (compact stand-in for METEOR 1.5's en paraphrase table)
# ---------------------------------------------------------------------------
# The jar's paraphrase stage (weight 0.6) matches multi-word phrase spans
# via an ~80MB table extracted from bilingual pivoting; neither the table
# nor egress to fetch it exists here.  This compact curated table keeps
# the STAGE faithful (span-level alignment mechanics, weight, chunk
# accounting) with coverage focused on English caption phrasing; phrases
# within a group are mutually substitutable.  Divergence (table size) is
# documented in meteor.py.

_PARAPHRASE_GROUPS = [
    ("next to", "beside", "alongside", "adjacent to", "close to", "near"),
    ("in front of", "before", "ahead of"),
    ("on top of", "atop", "upon", "on"),
    ("a number of", "a group of", "a bunch of", "several", "many", "a lot of", "lots of"),
    ("a couple of", "a pair of", "two"),
    ("is sitting", "sits", "is seated"),
    ("is standing", "stands"),
    ("is riding", "rides"),
    ("is holding", "holds", "is carrying", "carries"),
    ("is wearing", "wears", "is dressed in", "dressed in"),
    ("is eating", "eats", "is consuming"),
    ("is walking", "walks", "is strolling"),
    ("is running", "runs"),
    ("is playing", "plays"),
    ("is looking at", "looks at", "is watching", "watches", "is viewing"),
    ("is flying", "flies", "is soaring"),
    ("is jumping", "jumps", "is leaping"),
    ("is lying", "lies", "is laying", "lays"),
    ("gets ready to", "prepares to", "is about to", "is preparing to"),
    ("in the middle of", "in the center of", "amid", "amidst"),
    ("at the top of", "atop"),
    ("at the bottom of", "below", "beneath", "under", "underneath"),
    ("on the side of", "beside"),
    ("a man", "a person", "a guy", "a gentleman", "someone"),
    ("a woman", "a person", "a lady", "someone"),
    ("a child", "a kid", "a youngster", "a little one"),
    ("a large", "a big", "a huge"),
    ("a small", "a little", "a tiny"),
    ("a lot", "plenty", "a great deal"),
    ("each other", "one another"),
    ("in order to", "to", "so as to"),
    ("because of", "due to", "owing to", "on account of"),
    ("a few", "some", "a couple"),
    ("right now", "currently", "at the moment", "presently"),
    ("as well", "also", "too", "in addition"),
    ("kind of", "sort of", "type of"),
    ("is filled with", "is full of", "contains"),
    ("is covered in", "is covered with"),
    ("made of", "made from", "composed of", "constructed of"),
    ("a photo of", "a picture of", "an image of", "a photograph of"),
    ("black and white", "monochrome"),
    ("fire hydrant", "hydrant"),
    ("stop sign", "traffic sign"),
    ("traffic light", "stoplight", "traffic signal"),
    ("cell phone", "cellphone", "mobile phone", "phone"),
    ("hot dog", "hotdog", "frankfurter"),
    ("teddy bear", "stuffed bear", "stuffed animal"),
    ("parking lot", "car park"),
    ("train station", "railway station", "depot"),
    ("living room", "lounge", "sitting room"),
    ("dining table", "dinner table", "table"),
    ("front of", "ahead of"),
    ("group of people", "crowd", "crowd of people", "people"),
    ("body of water", "water", "lake", "pond"),
    ("up close", "close up", "closeup"),
    ("gets on", "boards", "mounts"),
    ("gets off", "dismounts", "exits"),
    ("takes off", "departs", "lifts off"),
    ("comes in", "enters", "arrives"),
    ("goes out", "exits", "leaves"),
]

PARAPHRASE_GROUPS = tuple(_PARAPHRASE_GROUPS)

MAX_PARAPHRASE_LEN = max(
    len(p.split()) for g in PARAPHRASE_GROUPS for p in g
)


def build_paraphrase_index() -> Dict[str, Set[int]]:
    """phrase (space-joined words) → set of group ids.  Two spans are
    paraphrases iff their id sets intersect, mirroring the synonym
    semantics at phrase granularity."""
    index: Dict[str, Set[int]] = {}
    for gid, group in enumerate(PARAPHRASE_GROUPS):
        for p in group:
            index.setdefault(p, set()).add(gid)
    return index
