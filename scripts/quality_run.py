"""End-to-end quality-evidence run: train -> checkpoint -> beam-search eval
-> BLEU/METEOR/ROUGE/CIDEr, on a self-contained fixture corpus.

The reference's north-star is BLEU-4 = 29.5 on COCO val2014
(/root/reference/README.md:85-89).  This environment has no network access,
so COCO itself cannot be fetched; this script instead runs the *entire*
pipeline (data prep -> vocab build -> prefetch-fed jitted training ->
checkpoint save/restore -> on-device beam search -> PTB tokenize -> four
scorers) on a procedurally generated caption corpus where each image has a
distinct, learnable caption.  A model that actually learns drives BLEU-4
from ~0 to near-saturation; a broken pipeline stays at 0.  Results land in
RESULTS.md at the repo root.

Usage:  python scripts/quality_run.py  [--steps N] [--out DIR]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

COLORS = ["red", "blue", "green", "yellow", "black", "white", "brown", "orange"]
ANIMALS = ["cat", "dog", "horse", "bird", "rabbit", "sheep"]
PLACES = ["park", "beach", "kitchen", "street", "garden", "field", "harbor", "station"]

# rich-corpus pools (VERDICT r2 §next-round #4: 1000+ word vocabulary,
# full caption-length distribution)
ADJS = [
    "big", "small", "tiny", "huge", "fluffy", "sleepy", "playful", "spotted",
    "striped", "muddy", "shiny", "elderly", "young", "swift", "sluggish",
    "quiet", "noisy", "gentle", "curious", "clever", "lazy", "hungry",
    "cheerful", "grumpy",
]
VERBS = [
    "sitting", "standing", "sprinting", "sleeping", "playing", "eating",
    "drinking", "jumping", "strolling", "resting", "hiding", "waiting",
    "watching", "climbing", "digging", "paddling",
]
WEATHER = ["sunny", "rainy", "cloudy", "windy", "foggy", "snowy", "stormy", "hazy"]
TIMES = ["morning", "afternoon", "evening", "midday"]

# pronounceable fake words, deterministic and collision-free: base-70
# syllable triples.  Each rich-corpus image carries THREE unique tokens
# (a name, a toy, a landmark) so vocabulary grows 3/image past the
# ~60-word common pools — 336 images -> 1000+ distinct words.
_SYLLABLES = [c + v for c in "bdfgklmnprstvz" for v in "aeiou"]


def _fake_word(i: int) -> str:
    a, rest = i % 70, i // 70
    b, c = rest % 70, rest // 70
    return _SYLLABLES[c % 70] + _SYLLABLES[b] + _SYLLABLES[a]


def make_rich_corpus(root: str, num_images: int = 336, image_edge: int = 64):
    """Few-hundred-image corpus with a 1000+ word vocabulary and the full
    caption-length distribution up to the 20-token cap.

    Per image: a unique (color, animal, place) scene like make_corpus plus
    three unique fake-word tokens, and TWO reference captions whose length
    band cycles short (7 tokens) / medium (12) / long (19) / max (20)
    so masking, the scan decoder, and scoring see every length.  Every
    41st image carries a third, 29-token caption that filter_by_cap_len
    must drop (reference coco.py:323-339).  Images get a distinctive
    color block + a unique per-image texture so the mapping is learnable
    by memorization."""
    import cv2

    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    rng = np.random.default_rng(1)

    images, annotations = [], []
    lengths = []
    ann_id = itertools.count(1000)
    for i in range(num_images):
        fname = f"rich_{i:06d}.jpg"
        img = rng.integers(0, 90, (image_edge, image_edge, 3), dtype=np.uint8)
        hue = np.zeros(3, dtype=np.uint8)
        hue[i % 3] = 120 + (i * 7) % 130
        img[: image_edge // 2, :, :] = hue
        img[image_edge // 2:, : image_edge // 2, (i // 3) % 3] = 210
        cv2.imwrite(os.path.join(img_dir, fname), img)
        images.append({"id": i + 1, "file_name": fname})

        color = COLORS[i % len(COLORS)]
        animal = ANIMALS[(i // 3) % len(ANIMALS)]
        place = PLACES[(i // 7) % len(PLACES)]
        adj = ADJS[(i // 2) % len(ADJS)]
        verb = VERBS[(i // 5) % len(VERBS)]
        weather = WEATHER[(i // 11) % len(WEATHER)]
        daytime = TIMES[(i // 13) % len(TIMES)]
        name, toy, mark = _fake_word(3 * i), _fake_word(3 * i + 1), _fake_word(3 * i + 2)

        # Every image's caption pair must surface all three unique tokens
        # (name + toy + mark) or the vocabulary undershoots 1000 words.
        band = i % 4
        if band == 0:      # short: 7 tokens incl. '.'
            caps = [
                f"{name} the {color} {animal} is {verb}.",
                f"{name} has the {toy} and {mark}.",
            ]
        elif band == 1:    # medium: 12 tokens
            caps = [
                f"the {adj} {color} {animal} named {name} is {verb} in the {place}.",
                f"a {adj} {color} {animal} named {name} guards the {toy} and {mark}.",
            ]
        elif band == 2:    # long: 19 tokens
            caps = [
                f"on a {weather} {daytime} the {adj} {color} {animal} named "
                f"{name} is {verb} near the {place} with a {toy}.",
                f"on one {weather} {daytime} a {adj} {color} {animal} named "
                f"{name} was {verb} near the {place} with the {mark}.",
            ]
        else:              # max: exactly 20 tokens
            caps = [
                f"on a {weather} {daytime} the {adj} {color} {animal} named "
                f"{name} is {verb} by the old {mark} near the {place}.",
                f"on a {weather} {daytime} a {adj} {color} {animal} named "
                f"{name} was {verb} by the old {toy} near the {place}.",
            ]
        if i % 41 == 0:    # over-cap caption: filter_by_cap_len must drop it
            caps.append(
                f"this is a deliberately very long extra caption about the {adj} "
                f"{color} {animal} named {name} that keeps {verb} near the "
                f"{place} with a {toy} by the {mark} today."
            )
        for cap in caps:
            lengths.append(len(cap.replace(".", " .").split()))
            annotations.append(
                {"id": next(ann_id), "image_id": i + 1, "caption": cap}
            )

    caption_file = os.path.join(root, "captions.json")
    with open(caption_file, "w") as f:
        json.dump({"images": images, "annotations": annotations}, f)
    return img_dir, caption_file, lengths


def make_corpus(root: str, num_images: int = 48, image_edge: int = 96):
    """Procedural COCO-format corpus: image i shows a color-coded pattern and
    carries two reference captions with identical content words (the learnable
    target) and one function-word variation (so scoring vs 2 refs is
    non-degenerate, like real COCO)."""
    import cv2

    img_dir = os.path.join(root, "images")
    os.makedirs(img_dir, exist_ok=True)
    combos = list(itertools.product(range(len(COLORS)), range(len(ANIMALS)), range(len(PLACES))))
    if num_images > len(combos):
        raise SystemExit(
            f"--num-images must be <= {len(combos)} (distinct caption combos)"
        )
    rng = np.random.default_rng(0)
    rng.shuffle(combos)

    images, annotations = [], []
    for i in range(num_images):
        ci, ai, pi = combos[i]
        fname = f"fixture_{i:06d}.jpg"
        # visually distinctive image: color block keyed to the caption's
        # color word + unique per-image texture, so the mapping is learnable
        img = rng.integers(0, 80, (image_edge, image_edge, 3), dtype=np.uint8)
        hue = np.zeros(3, dtype=np.uint8)
        hue[ci % 3] = 250 - 20 * (ci // 3)
        img[: image_edge // 2, :, :] = hue
        img[image_edge // 2 :, : image_edge // 2, (ai % 3)] = 200
        img[image_edge // 2 :, image_edge // 2 :, (pi % 3)] = 120
        cv2.imwrite(os.path.join(img_dir, fname), img)
        images.append({"id": i + 1, "file_name": fname})
        color, animal, place = COLORS[ci], ANIMALS[ai], PLACES[pi]
        caps = [
            f"a {color} {animal} in the {place}.",
            f"the {color} {animal} is in the {place}.",
        ]
        for j, cap in enumerate(caps):
            annotations.append({"id": 1000 + 2 * i + j, "image_id": i + 1, "caption": cap})

    caption_file = os.path.join(root, "captions.json")
    with open(caption_file, "w") as f:
        json.dump({"images": images, "annotations": annotations}, f)
    return img_dir, caption_file


def read_loss_curve(metrics_path: str, samples: int = 12):
    """(step, total_loss) rows of the FINAL run in a metrics.jsonl,
    downsampled to ~``samples`` rows (last row always kept).  A step that
    does not increase marks the start of a newer run appended to the same
    --out dir; earlier segments are discarded."""
    curve = []
    with open(metrics_path) as f:
        for line in f:
            rec = json.loads(line)
            if "total_loss" in rec:
                if curve and rec["step"] <= curve[-1][0]:
                    curve = []
                curve.append((rec["step"], rec["total_loss"]))
    sampled = curve[:: max(1, len(curve) // samples)]
    if curve and sampled[-1][0] != curve[-1][0]:
        sampled.append(curve[-1])
    return sampled


def update_results_sections(md_path: str, main_text: str = None,
                            section: str = None, section_text: str = None) -> None:
    """RESULTS.md is assembled from a main body plus marker-delimited
    sections (``<!-- section:NAME -->…<!-- /section:NAME -->``) owned by
    other evidence scripts (import-finetune).  Rewriting the main body
    preserves existing sections; a section writer replaces just its own."""
    import re

    old = ""
    if os.path.exists(md_path):
        with open(md_path) as f:
            old = f.read()
    pat = re.compile(r"<!-- section:(\S+) -->\n.*?<!-- /section:\1 -->", re.S)
    sections = {m.group(1): m.group(0) for m in pat.finditer(old)}
    body = main_text if main_text is not None else pat.sub("", old).rstrip() + "\n"
    if section is not None:
        sections[section] = (
            f"<!-- section:{section} -->\n{section_text.rstrip()}\n"
            f"<!-- /section:{section} -->"
        )
    parts = [body.rstrip()] + [sections[k] for k in sorted(sections)]
    with open(md_path, "w") as f:
        f.write("\n\n".join(parts) + "\n")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600, help="target train steps")
    ap.add_argument("--out", default="runs/quality")
    ap.add_argument(
        "--corpus", default="basic", choices=["basic", "rich"],
        help="rich = few-hundred images, 1000+ word vocab, caption lengths "
        "7-20 plus over-cap captions the length filter must drop",
    )
    ap.add_argument("--num-images", type=int, default=None,
                    help="default 48 (basic) / 336 (rich)")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument(
        "--frozen-cnn", action="store_true",
        help="reference-published configuration (RNN-only training); "
        "default trains the CNN jointly",
    )
    ap.add_argument(
        "--beam-compare", action="store_true",
        help="also decode greedily (beam=1) and record the beam-3 deltas",
    )
    ap.add_argument(
        "--corpus-only", action="store_true",
        help="generate the fixture corpus under --out and exit (for runs "
        "that only need the inputs, e.g. the profiler stage)",
    )
    ap.add_argument(
        "--image-size", type=int, default=224,
        help="input edge; 224 = flagship, smaller for CPU runs",
    )
    ap.add_argument(
        "--cpu", action="store_true",
        help="pin the CPU backend (the env force-registers the TPU plugin)",
    )
    ap.add_argument(
        "--cnn", default="vgg16", choices=["vgg16", "resnet50"],
        help="encoder family (resnet50 exercises the BN/bottleneck path)",
    )
    ap.add_argument(
        "--no-results-md", action="store_true",
        help="write scores.json only; leave RESULTS.md untouched (for "
        "secondary-evidence runs, e.g. the resnet50 variant)",
    )
    ap.add_argument(
        "--extra-set", action="append", default=[], metavar="KEY=VALUE",
        help="extra Config overrides appended AFTER the protocol defaults "
        "(e.g. fc_drop_rate=0.0 for a saturation run — memorization-"
        "protocol dropout caps teacher-forced accuracy)",
    )
    args = ap.parse_args()

    if args.cpu:
        # both mechanisms deliberately: this environment's sitecustomize
        # imports jax itself and re-pins the platform, so the env var
        # alone does not stick (tests/conftest.py documents the same)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")

    t0 = time.time()
    root = os.path.abspath(args.out)
    os.makedirs(root, exist_ok=True)
    if args.num_images is None:
        args.num_images = 336 if args.corpus == "rich" else 48
    cap_lengths = None
    if args.corpus == "rich":
        img_dir, caption_file, cap_lengths = make_rich_corpus(
            root, num_images=args.num_images
        )
    else:
        img_dir, caption_file = make_corpus(root, num_images=args.num_images)
    print(f"[quality +{time.time()-t0:5.1f}s] corpus: {args.num_images} images at {img_dir}")
    if args.corpus_only:
        return 0

    from sat_tpu.cli import build_config

    steps_per_epoch = -(-2 * args.num_images // args.batch_size)  # 2 captions/image
    num_epochs = -(-args.steps // steps_per_epoch)
    overrides = [
        f"train_image_dir={img_dir}",
        f"train_caption_file={caption_file}",
        f"eval_image_dir={img_dir}",
        f"eval_caption_file={caption_file}",
        # corpus-keyed cache/vocab names: a rerun with a different
        # --corpus into the same --out must not silently train on the
        # previous corpus's cached anns/data/vocab
        f"vocabulary_file={root}/vocabulary_{args.corpus}.csv",
        f"temp_annotation_file={root}/anns_{args.corpus}.csv",
        f"temp_data_file={root}/data_{args.corpus}.npy",
        f"save_dir={root}/models",
        f"summary_dir={root}/summary",
        f"eval_result_dir={root}/results",
        f"eval_result_file={root}/results.json",
        "max_train_ann_num=none",
        "max_eval_ann_num=none",
        f"batch_size={args.batch_size}",
        f"num_epochs={num_epochs}",
        # rich corpus: top-5000 cap like the reference's published config;
        # the corpus itself supplies 1000+ distinct words
        "vocabulary_size=5000" if args.corpus == "rich" else "vocabulary_size=200",
        # overfit protocol: mild dropout + slightly hotter Adam so ~600
        # steps saturate; documented in RESULTS.md
        "fc_drop_rate=0.1",
        "lstm_drop_rate=0.1",
        "initial_learning_rate=0.0003",
        "save_period=0",
        "log_every=10",
        f"image_size={args.image_size}",
        f"cnn={args.cnn}",
    ]
    overrides += args.extra_set    # caller overrides win (later --set)
    set_args = [x for o in overrides for x in ("--set", o)]

    train_flags = [] if args.frozen_cnn else ["--train_cnn"]
    config, _ = build_config(["--phase=train"] + train_flags + set_args)

    import jax

    from sat_tpu import runtime

    # Persistent compilation cache (same dir as bench.py): the resnet50
    # CPU-XLA compile in particular runs tens of minutes cold on this
    # 1-core host; a rerun must not pay it twice.
    from sat_tpu.utils.compile_cache import enable as _enable_cache

    _enable_cache(jax)

    device = jax.devices()[0]
    print(f"[quality +{time.time()-t0:5.1f}s] device: {device.device_kind} ({device.platform})")
    print(f"[quality +{time.time()-t0:5.1f}s] training {num_epochs} epochs x {steps_per_epoch} steps")
    state = runtime.train(config)
    train_s = time.time() - t0
    print(f"[quality +{train_s:5.1f}s] training done at step {int(state.step)}")

    eval_config, _ = build_config(["--phase=eval", "--beam_size=3"] + set_args)
    scores = runtime.evaluate(eval_config, state=state)

    greedy_scores = None
    if args.beam_compare:
        greedy_config, _ = build_config(
            ["--phase=eval", "--beam_size=1"] + set_args
        )
        greedy_config = greedy_config.replace(
            eval_result_file=f"{root}/results_greedy.json"
        )
        greedy_scores = runtime.evaluate(greedy_config, state=state)
    total_s = time.time() - t0

    sampled = read_loss_curve(os.path.join(root, "summary", "metrics.jsonl"))

    vocab_words = None
    try:
        with open(f"{root}/vocabulary_{args.corpus}.csv") as f:
            vocab_words = sum(1 for _ in f) - 1      # header row
    except OSError:
        pass

    payload = {
        "scores": scores,
        "steps": int(state.step),
        "device": device.device_kind,
        "train_seconds": round(train_s, 1),
        "total_seconds": round(total_s, 1),
        "num_images": args.num_images,
        "corpus": args.corpus,
        "train_cnn": not args.frozen_cnn,
        "vocab_words": vocab_words,
        "protocol": "overfit-fixture",
    }
    if greedy_scores is not None:
        payload["greedy_scores"] = greedy_scores
    if cap_lengths is not None:
        hist = {}
        for n in cap_lengths:
            hist[n] = hist.get(n, 0) + 1
        payload["caption_token_length_histogram"] = {
            str(k): hist[k] for k in sorted(hist)
        }
    with open(os.path.join(root, "scores.json"), "w") as f:
        json.dump(payload, f, indent=2)

    argv = " ".join(sys.argv[1:])
    lines = [
        "# RESULTS — quality evidence (fixture-scale end-to-end run)",
        "",
        f"Produced by `python scripts/quality_run.py {argv}`".rstrip() + " "
        f"on **{device.device_kind}** ({device.platform}); total wall-clock "
        f"{total_s:.0f}s (train {train_s:.0f}s for {int(state.step)} steps "
        "including compiles, the rest is eval-side beam search + scoring).",
        "",
    ]
    if device.platform != "tpu":
        lines += [
            "*Backend note:* this run used a non-TPU backend (typically "
            "because the tunneled TPU was unreachable — see `bench.py`'s "
            "watchdog). The pipeline under test is identical on every "
            "backend: same jitted programs, same on-device beam search.",
            "",
        ]
    cnn_mode = (
        "frozen randomly-initialized CNN — RNN-only training like the "
        "reference's published mode, though without its pretrained VGG16 "
        "weights (unavailable offline)"
        if args.frozen_cnn else "`--train_cnn`"
    )
    corpus_desc = (
        f"self-contained {args.num_images}-image corpus with a "
        f"**{vocab_words}-word built vocabulary**, caption lengths spanning "
        "7-20 tokens (plus over-cap captions the length filter drops)"
        if args.corpus == "rich"
        else f"self-contained {args.num_images}-image corpus"
    )
    lines += [
        "**Protocol.** This environment has no network egress, so COCO val2014 "
        "(the reference's BLEU-4 = 29.5 benchmark, `/root/reference/README.md:85-89`) "
        "cannot be fetched. Instead this run drives the complete pipeline — COCO-format "
        "ingestion, vocabulary build, prefetch-fed jitted training of the full "
        f"{args.cnn}+attention-LSTM model ({cnn_mode}), checkpointing, on-device batched "
        "beam search (beam=3), PTB tokenization, and all four scorers — on a "
        f"{corpus_desc} where every image carries a "
        "distinct learnable caption (content words correlated with image pixels). "
        "The memorization protocol turns caption quality into a pipeline-integrity "
        "test: a model that learns saturates BLEU; any break in the chain "
        "(preprocessing, attention, decoding, tokenization, scoring) keeps it near 0.",
        "",
        "## Scores (beam_size=3, eval over all corpus images)",
        "",
        "| Metric | Score |" if greedy_scores is None
        else "| Metric | beam=3 | greedy (beam=1) | Δ |",
        "|---|---|" if greedy_scores is None else "|---|---|---|---|",
    ]
    for k, v in scores.items():
        if greedy_scores is None:
            lines.append(f"| {k} | {v:.4f} |")
        else:
            g = greedy_scores.get(k, float("nan"))
            lines.append(f"| {k} | {v:.4f} | {g:.4f} | {v - g:+.4f} |")
    lines += [
        "",
        f"Raw artifacts: `{args.out}/scores.json`, `{args.out}/results.json` "
        "(per-image captions).",
        "",
    ]
    if cap_lengths is not None:
        bands = {"7 (short)": 0, "12 (medium)": 0, "19 (long)": 0,
                 "20 (max)": 0, ">20 (filtered)": 0}
        for n in cap_lengths:
            if n > 20: bands[">20 (filtered)"] += 1
            elif n >= 20: bands["20 (max)"] += 1
            elif n >= 15: bands["19 (long)"] += 1
            elif n >= 10: bands["12 (medium)"] += 1
            else: bands["7 (short)"] += 1
        lines += [
            "## Caption length distribution (tokens incl. terminator)",
            "",
            "| Band | Captions |",
            "|---|---|",
        ] + [f"| {k} | {v} |" for k, v in bands.items()] + [""]
    lines += [
        "## Training loss curve (total_loss from metrics.jsonl)",
        "",
        "| Step | Total loss |",
        "|---|---|",
    ]
    for step, loss in sampled:
        lines.append(f"| {step} | {loss:.3f} |")
    vocab_note = "vocabulary_size=5000 (top-5000 cap)" if args.corpus == "rich" \
        else "`vocabulary_size=200`"
    lines += [
        "",
        "## Config deltas vs flagship defaults",
        "",
        f"{'frozen randomly-initialized CNN (RNN-only training)' if args.frozen_cnn else '`--train_cnn`'}, "
        f"`batch_size={args.batch_size}`, {vocab_note}, "
        "`fc_drop_rate=0.1`, `lstm_drop_rate=0.1`, `initial_learning_rate=3e-4` "
        f"(overfit protocol), `num_epochs={num_epochs}`, "
        f"`image_size={args.image_size}`. Everything else — {args.cnn} "
        "encoder, 512-unit attention LSTM, Adam, global-norm clip 5.0, "
        "doubly-stochastic attention penalty — is the reference-published "
        "configuration (`/root/reference/config.py:8-43`).",
        "",
    ]
    if args.no_results_md:
        print(f"[quality +{time.time()-t0:5.1f}s] scores.json written "
              "(--no-results-md)")
    else:
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        md = os.path.join(repo_root, "RESULTS.md")
        if args.corpus == "rich":
            # the rich run is supplementary evidence: it owns its marked
            # section and must not replace the flagship main body
            update_results_sections(
                md, section="rich-corpus",
                section_text="\n".join(lines[1:]),  # drop the H1
            )
        else:
            update_results_sections(md, main_text="\n".join(lines))
        print(f"[quality +{time.time()-t0:5.1f}s] RESULTS.md written")
    for k, v in scores.items():
        print(f"  {k}: {v:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
