"""Training observability: TensorBoard-compatible event files + JSONL.

The reference writes TF summaries every step — scalar losses/accuracy,
per-trainable-variable mean/std/min/max/histogram, and attention-map stats
(/root/reference/model.py:515-543, written at base_model.py:46-47,63).

This module reproduces that capability with zero TensorFlow: a
``SummaryWriter`` that emits the TFRecord/Event wire format directly
(varint-encoded protobuf + masked CRC32C framing), so standard TensorBoard
reads our logs, and mirrors every scalar into a ``metrics.jsonl`` for
dependency-free analysis.  Per-variable summaries carry both the
mean/std/min/max scalar family and a true HistogramProto (TensorBoard's
histogram tab), bucketed with TF's exponential bucket scheme.
"""

from __future__ import annotations

import json
import os
import struct
import sys
import time
from typing import Any, Dict, Mapping, Optional

import numpy as np

from .. import telemetry

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — TFRecord framing requires it; stdlib zlib.crc32 is
# the wrong polynomial.  Table-driven, reflected, poly 0x82F63B78.
#
# Two implementations, bitwise-identical: the per-byte scalar loop (the
# oracle, and the fast path for short frames — every Event's 8-byte length
# header goes through here) and a numpy lane-parallel path for large
# payloads (per-variable HistogramProto frames reach hundreds of KB;
# the Python loop costs ~300 ms/MB, the vector path ~3 ms/MB).
# ---------------------------------------------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)

_CRC_TABLE_NP = np.array(_CRC_TABLE, dtype=np.uint32)

# CRC state transition is GF(2)-affine in the state: processing k zero
# bytes maps state s -> M_k @ s for a 32×32 bit-matrix M_k.  A matrix is
# stored as 32 uint32 columns (column b = image of basis bit 1<<b); the
# one-zero-byte matrix follows directly from the table recurrence
# s' = T[s & 0xFF] ^ (s >> 8) applied to each basis vector.
_ADV1 = np.array(
    [_CRC_TABLE[(1 << b) & 0xFF] ^ ((1 << b) >> 8) for b in range(32)],
    dtype=np.uint32,
)


def _gf2_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Compose two 32-column GF(2) matrices: out = a @ b (b applied first)."""
    out = np.zeros(32, np.uint32)
    for col in range(32):
        v = int(b[col])
        acc = 0
        while v:
            low = v & -v
            acc ^= int(a[low.bit_length() - 1])
            v ^= low
        out[col] = acc
    return out


def _matvec_vec(m: np.ndarray, states: np.ndarray) -> np.ndarray:
    """Apply one GF(2) matrix to many uint32 states at once (32 numpy ops)."""
    out = np.zeros_like(states)
    for b in range(32):
        out ^= np.where((states >> np.uint32(b)) & np.uint32(1), m[b], np.uint32(0))
    return out


def _crc32c_scalar(data: bytes, crc: int = 0xFFFFFFFF) -> int:
    """The reference per-byte loop (no final xor; callers apply it)."""
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc


_CRC_VECTOR_MIN = 4096    # below this the scalar loop wins (setup cost)


def crc32c(data: bytes) -> int:
    if len(data) < _CRC_VECTOR_MIN:
        return _crc32c_scalar(data) ^ 0xFFFFFFFF

    # Split into K equal byte-columns processed as K independent CRC
    # lanes in lockstep (the classic interleaved/chunked scheme): lane 0
    # starts from the real init state, others from 0, so by table
    # linearity (T[a^b] = T[a]^T[b]) the concatenation identity
    #   crc(A||B) = advance(crc(A), len(B)) ^ crc_zero_init(B)
    # lets a log2(K) tree of zero-advance matrices stitch the lanes back
    # into the exact serial result.  K scales with the payload (bounded
    # Python-level row loop, ~256 iterations) — the stitch is only
    # log2(K) rounds, so wide is cheap.
    K = 1 << max(8, min(16, (len(data) // 256).bit_length() - 1))
    rows = len(data) // K
    chunk = rows * K
    cols = np.frombuffer(data[:chunk], np.uint8).reshape(K, rows)
    states = np.zeros(K, np.uint32)
    states[0] = 0xFFFFFFFF
    for j in range(rows):
        states = _CRC_TABLE_NP[(states ^ cols[:, j]) & np.uint32(0xFF)] ^ (
            states >> np.uint32(8)
        )
    # stitch: at each level pair adjacent lanes, advancing the left lane
    # over the right lane's span (doubling each round)
    adv = _ADV1
    span = rows
    # advance-by-`rows` matrix = _ADV1 composed rows times (square-and-
    # multiply over the bits of `rows`)
    adv_span = None
    bit_m = _ADV1
    r = rows
    while r:
        if r & 1:
            adv_span = bit_m if adv_span is None else _gf2_matmul(bit_m, adv_span)
        r >>= 1
        if r:
            bit_m = _gf2_matmul(bit_m, bit_m)
    while states.size > 1:
        left, right = states[0::2], states[1::2]
        states = _matvec_vec(adv_span, left) ^ right
        if states.size > 1:
            adv_span = _gf2_matmul(adv_span, adv_span)
        span *= 2
    crc = int(states[0])
    # serial tail for the remainder bytes
    return _crc32c_scalar(data[chunk:], crc) ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire encoding for tensorboard Event/Summary messages.
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field_len(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _encode_value(tag: str, value: float) -> bytes:
    # Summary.Value { string tag = 1; float simple_value = 2; }
    return _field_len(1, tag.encode("utf-8")) + b"\x15" + struct.pack(
        "<f", float(value)
    )


def _encode_event(
    wall_time: float,
    step: int,
    scalars: Optional[Mapping[str, float]] = None,
    file_version: Optional[str] = None,
    summary_bytes: Optional[bytes] = None,
) -> bytes:
    # Event { double wall_time = 1; int64 step = 2;
    #         string file_version = 3; Summary summary = 5; }
    out = b"\x09" + struct.pack("<d", wall_time) + b"\x10" + _varint(int(step))
    if file_version is not None:
        out += _field_len(3, file_version.encode("utf-8"))
    summary = summary_bytes or b""
    if scalars:
        summary += b"".join(
            _field_len(1, _encode_value(tag, v)) for tag, v in scalars.items()
        )
    if summary:
        out += _field_len(5, summary)
    return out


# ---------------------------------------------------------------------------
# HistogramProto — TensorBoard's histogram tab (the reference logs one per
# trainable variable, /root/reference/model.py:527).  Buckets follow TF's
# exponential scheme: ±1e-12·1.1^k up to ±1e20, plus 0 and ±float-max, so
# standard TensorBoard renders our histograms identically.
# ---------------------------------------------------------------------------


def _make_bucket_limits():
    pos = []
    v = 1e-12
    while v < 1e20:
        pos.append(v)
        v *= 1.1
    fmax = float(np.finfo(np.float64).max)
    return [-fmax] + [-x for x in reversed(pos)] + [0.0] + pos + [fmax]


BUCKET_LIMITS = np.asarray(_make_bucket_limits())


def _packed_doubles(field: int, values) -> bytes:
    payload = struct.pack(f"<{len(values)}d", *[float(v) for v in values])
    return _field_len(field, payload)


def _encode_histo(
    lo: float, hi: float, num: float, total: float, sumsq: float, counts
) -> bytes:
    """HistogramProto{min=1,max=2,num=3,sum=4,sum_squares=5,
    bucket_limit=6,bucket=7} with zero-run trimming (empty leading/trailing
    buckets dropped, like TF's proto compression)."""
    counts = np.asarray(counts)
    nz = np.flatnonzero(counts)
    if len(nz):
        s, e = int(nz[0]), int(nz[-1]) + 1
    else:
        s, e = 0, 1
    out = (
        b"\x09" + struct.pack("<d", float(lo))
        + b"\x11" + struct.pack("<d", float(hi))
        + b"\x19" + struct.pack("<d", float(num))
        + b"\x21" + struct.pack("<d", float(total))
        + b"\x29" + struct.pack("<d", float(sumsq))
    )
    out += _packed_doubles(6, BUCKET_LIMITS[s:e])
    out += _packed_doubles(7, counts[s:e])
    return out


def _histo_from_array(values) -> bytes:
    x = np.asarray(values, dtype=np.float64).ravel()
    # ±inf land in the outermost buckets; NaNs are dropped entirely (from
    # num/sum/min/max too) so the proto stays internally consistent even
    # for a diverged run — the case this summary exists to debug.
    x = x[~np.isnan(x)]
    x = np.clip(x, BUCKET_LIMITS[0], BUCKET_LIMITS[-1])
    counts = np.bincount(
        np.searchsorted(BUCKET_LIMITS, x, side="left"),
        minlength=len(BUCKET_LIMITS),
    )
    return _encode_histo(
        x.min() if x.size else 0.0,
        x.max() if x.size else 0.0,
        x.size,
        x.sum(),
        (x * x).sum(),
        counts,
    )


def _encode_histo_value(tag: str, histo: bytes) -> bytes:
    # Summary.Value { string tag = 1; HistogramProto histo = 5; }
    return _field_len(1, tag.encode("utf-8")) + _field_len(5, histo)


def _frame_record(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


def _reduce_stats(leaf_list):
    """On-device (mean, std, min, max, sum, sum_sq, bucket_counts) per
    array; jitted once at module level so periodic variable_stats calls hit
    the compile cache.  Histogram bucketing happens on device too, so only
    ~1.5k counts per variable cross to the host — never the full tensor."""
    import jax

    global _reduce_stats_jit
    if _reduce_stats_jit is None:
        import jax.numpy as jnp

        # float32 view of TF's float64 bucket edges (x64 is disabled on
        # TPU); the 1.1 growth factor dwarfs float32 eps so bucket
        # boundaries stay distinct.  The ±float64-max sentinels exceed the
        # float32 range, so pin them to ±float32-max — no float32 tensor
        # value can exceed them, preserving the catch-all semantics.
        f32max = float(np.finfo(np.float32).max)
        limits = jnp.asarray(
            np.clip(BUCKET_LIMITS, -f32max, f32max), dtype=jnp.float32
        )

        @jax.jit
        def reduce_all(leaves):
            out = []
            for x in leaves:
                x = x.astype(jnp.float32)
                flat = x.ravel()
                # diverged-run safety, mirroring _histo_from_array: ±inf
                # clip into the outermost buckets, NaNs drop from counts
                # AND histo moments (nan*-reductions) so sum(bucket)==num
                finite = ~jnp.isnan(flat)
                clipped = jnp.clip(flat, limits[0], limits[-1])  # ±inf → edges
                idx = jnp.searchsorted(limits, clipped, side="left")
                counts = jnp.bincount(
                    jnp.minimum(idx, limits.shape[0] - 1),
                    weights=finite.astype(jnp.float32),
                    length=limits.shape[0],
                )
                clean = jnp.where(finite, clipped, 0.0)
                any_f = finite.any()
                out.append(
                    (
                        jnp.mean(x), jnp.std(x), jnp.min(x), jnp.max(x),
                        jnp.where(any_f, jnp.nanmin(clipped), 0.0),
                        jnp.where(any_f, jnp.nanmax(clipped), 0.0),
                        jnp.sum(clean), jnp.sum(clean * clean),
                        jnp.sum(finite), counts,
                    )
                )
            return out

        _reduce_stats_jit = reduce_all
    return _reduce_stats_jit(leaf_list)


_reduce_stats_jit = None


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class SummaryWriter:
    """Writes ``events.out.tfevents.<ts>.<host>`` + ``metrics.jsonl`` under
    ``log_dir``.  Usage: ``writer.scalars(step, {...})`` per step, plus
    ``writer.variable_stats(step, params)`` for the per-variable summaries
    the reference logs (model.py:527-535)."""

    def __init__(self, log_dir: str, filename_suffix: str = ""):
        os.makedirs(log_dir, exist_ok=True)
        ts = int(time.time())
        host = os.uname().nodename if hasattr(os, "uname") else "host"
        self._event_path = os.path.join(
            log_dir, f"events.out.tfevents.{ts}.{host}{filename_suffix}"
        )
        self._jsonl_path = os.path.join(log_dir, "metrics.jsonl")
        self._events = open(self._event_path, "ab")
        self._jsonl = open(self._jsonl_path, "a")
        self._closed = False
        self._degraded = False
        self._write_events(
            _frame_record(
                _encode_event(time.time(), 0, file_version="brain.Event:2")
            )
        )

    # Observability must degrade, never kill training: once the loop is
    # unwinding (exception, SIGTERM teardown, disk full) a failing event
    # write would mask the real outcome with a logging traceback.  The
    # first failure warns and the writer goes quiet; close() is idempotent
    # because both the with-block AND an outer ExitStack may reach it.
    def _write_events(self, data: bytes) -> None:
        if self._closed or self._degraded:
            return
        try:
            self._events.write(data)
        except (OSError, ValueError) as e:
            self._degrade("event file", e)

    def _write_jsonl(self, line: str) -> None:
        if self._closed or self._degraded:
            return
        try:
            self._jsonl.write(line)
        except (OSError, ValueError) as e:
            self._degrade("metrics.jsonl", e)

    def _degrade(self, what: str, exc: BaseException) -> None:
        if self._degraded:  # warn once; later failures are the same story
            return
        self._degraded = True
        print(
            f"sat_tpu: summary writer disabled — {what} write failed: {exc}",
            file=sys.stderr,
            flush=True,
        )

    def scalars(self, step: int, values: Mapping[str, float]) -> None:
        clean: Dict[str, float] = {}
        # tfevents can only carry finite floats, but a diverged run must
        # still leave a trace: non-finite values go to metrics.jsonl as
        # strings ("nan"/"inf") so the failure is visible post-hoc.
        record: Dict[str, Any] = {}
        for tag, v in values.items():
            v = float(np.asarray(v))
            if np.isfinite(v):
                clean[tag] = v
                record[tag] = v
            else:
                record[tag] = repr(v)
        if not record:
            return
        if clean:
            self._write_events(
                _frame_record(_encode_event(time.time(), step, clean))
            )
        # Every row carries wall-clock + monotonic stamps and the process
        # run id so post-hoc joins against telemetry.jsonl/heartbeat.json
        # key on (run_id, time), never on file mtimes.
        self._write_jsonl(
            json.dumps(
                {
                    "step": int(step),
                    "wall_time": round(time.time(), 6),
                    "mono_ns": time.perf_counter_ns(),
                    "run_id": telemetry.run_id(),
                    **record,
                }
            )
            + "\n"
        )

    def histograms(self, step: int, values: Mapping[str, Any]) -> None:
        """True HistogramProto summaries (reference model.py:527) for
        host-side arrays; one event carrying every tag."""
        summary = b"".join(
            _field_len(1, _encode_histo_value(tag, _histo_from_array(v)))
            for tag, v in values.items()
        )
        self._write_events(
            _frame_record(
                _encode_event(time.time(), step, summary_bytes=summary)
            )
        )

    def variable_stats(
        self, step: int, tree, prefix: str = "params", max_vars: int = 0
    ) -> None:
        """Per-variable mean/std/min/max scalars + full histograms — the
        reference's variable_summary for every trainable
        (model.py:516-527).  Arrays are reduced and bucketed on device
        before the host transfer (only scalars + bucket counts move)."""
        import jax

        stats = {}
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        if max_vars:
            leaves = leaves[:max_vars]

        arrays = [leaf for _, leaf in leaves]
        reduced = jax.device_get(_reduce_stats(arrays))
        histo_summary = b""
        for (path, _), (
            mean, std, lo, hi, hlo, hhi, total, sumsq, num, counts
        ) in zip(leaves, reduced):
            name = prefix + "/" + "/".join(str(getattr(e, "key", getattr(e, "name", e))) for e in path)
            stats[f"{name}/mean"] = mean
            stats[f"{name}/std"] = std
            stats[f"{name}/min"] = lo
            stats[f"{name}/max"] = hi
            histo = _encode_histo(hlo, hhi, num, total, sumsq, counts)
            histo_summary += _field_len(1, _encode_histo_value(name, histo))
        self.scalars(step, stats)
        self._write_events(
            _frame_record(
                _encode_event(time.time(), step, summary_bytes=histo_summary)
            )
        )

    def flush(self) -> None:
        if self._closed:
            return
        try:
            self._events.flush()
            self._jsonl.flush()
        except (OSError, ValueError) as e:
            self._degrade("flush", e)

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        self._closed = True
        try:
            self._events.close()
            self._jsonl.close()
        except (OSError, ValueError) as e:
            self._degrade("close", e)

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
