"""Admission control + dispatch discipline for the captioning service.

Two batchers share one bounded-queue admission contract (429 shed on a
full queue, 503 while draining, 504 deadline triage before device time):

* :class:`MicroBatcher` — whole-batch dispatch (``serve_mode="batch"``):
  requests accumulate in the queue, the dispatch thread gathers up to
  ``max_batch`` of them (holding an underfull batch open at most
  ``max_wait_ms``), pads to the engine's bucket ladder, and dispatches
  one monolithic beam search per batch.  The dispatch chain is
  double-buffered exactly like ``runtime.device_prefetch``: batch n+1 is
  dispatched before batch n's results are drained, so host-side
  detokenization overlaps device beam search.

* :class:`ContinuousBatcher` — step-level continuous batching
  (``serve_mode="continuous"``): queued requests are admitted into free
  slots of a :class:`~sat_tpu.serve.slot_pool.PagedSlotPool` *between
  decode steps* — no hold-open window, no whole-batch barrier — and each
  slot retires the step its early-exit condition fires, freeing capacity
  for the next arrival mid-decode.  A request that arrives 1 ms after a
  step starts waits one ~step, not one ~full decode; short captions stop
  paying max-length cost.  Detokenization runs on its own worker thread
  so the step loop never blocks on host string work.

Both bound the per-dispatch device drain with the wedge watchdog
(``serve_wedge_timeout_ms``): a drain the device never answers fails the
in-flight requests with 500 and fires ``on_wedge`` (the server's
degrade + re-warm hook) instead of stranding them.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..resilience import faultinject
from ..telemetry.metering import RequestCost
from .engine import BucketOverflow
from .scheduler import DeficitRoundRobin


class _WedgeTimeout(Exception):
    """An in-flight batch's result drain exceeded serve_wedge_timeout_ms."""


def choose_decode_depth(
    depths: Tuple[int, ...], queue_depth: int, pending: int
) -> int:
    """Adaptive fused-window policy (docs/SERVING.md "Fused decode
    window"): with requests waiting to be seeded — anything queued or
    held pending a free slot — run the shallow K=1 lane so admission
    happens at the very next tick and submit→seeded latency is
    preserved; with nothing waiting, run the deepest warmed lane so each
    in-flight caption amortizes one host dispatch over K device steps.
    Pure host arithmetic so the policy is unit-testable without a pool."""
    if queue_depth > 0 or pending > 0:
        return depths[0]
    return depths[-1]


class Rejected(Exception):
    """Admission refused; ``status`` is the HTTP code the frontend maps.

    ``scope`` distinguishes a *tenant-scoped* shed (that tenant's queue
    lane or token bucket is full — other tenants are unaffected) from a
    *global* one (drain, fleet saturation); the frontend surfaces it as
    the ``X-Shed-Scope`` response header and computes the Retry-After
    hint from the matching signal (tenant bucket refill vs. service
    p50)."""

    def __init__(self, status: int, reason: str, scope: str = "global"):
        super().__init__(reason)
        self.status = status
        self.reason = reason
        self.scope = scope


@dataclass
class Request:
    """One admitted caption request; ``done`` fires with either ``result``
    (the engine's per-image dict) or ``error`` (http status, message)."""

    # the preprocessed image row; None for a decode-tier request that
    # arrived as a pre-encoded context grid (``context`` set instead)
    image: Optional[np.ndarray]
    t_submit_ns: int
    deadline_unix: Optional[float] = None
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, Any]] = None
    error: Optional[Tuple[int, str]] = None
    bucket: Optional[int] = None
    # which engine param slot serves this request ("incumbent", "canary"
    # or a resident-model alias); stamped at admission and honored by
    # both dispatch disciplines
    slot: str = "incumbent"
    # which tenant submitted this request — the DRR scheduler drains its
    # lane in deficit order; "default" is the bare-request tenant
    tenant: str = "default"
    # request-scoped tracing (telemetry.tracectx): stamped when the
    # gather loop pops this request; the trace rides along so the batcher
    # can attribute each phase to the originating X-Request-Id
    t_gather_ns: Optional[int] = None
    # continuous mode: when this request's slot was seeded (decode-phase
    # attribution runs from here to harvest)
    t_admit_ns: Optional[int] = None
    trace: Optional[Any] = None
    # per-request device-cost accumulator (telemetry/metering.py):
    # created at submit when telemetry is on; attribution sites charge
    # it on already-synced boundaries and the server's terminal funnel
    # folds it into the tenant ledger.  None with telemetry off.
    cost: Optional[RequestCost] = None
    # raw POSTed image bytes, kept ONLY when the quality plane is on so
    # the exemplar flight recorder can store a replayable copy of an
    # outlier request; None otherwise (no per-request body retention)
    raw: Optional[bytes] = None
    # content address of the preprocessed image (crc32c of its bytes),
    # stamped by the server when --encode_cache is on; the dispatch
    # paths route keyed requests through the encode cache
    key: Optional[int] = None
    # pre-encoded [N, D] context grid (encode/decode tier handoff,
    # serve/handoff.py): when set, dispatch seeds the slot from it and
    # skips the encode lane — and the cache — entirely
    context: Optional[np.ndarray] = None

    def mark(self, phase: str, t0_ns: int, dur_ns: int) -> None:
        if self.trace is not None:
            self.trace.mark(phase, t0_ns, dur_ns)

    def fail(self, status: int, reason: str) -> None:
        self.error = (status, reason)
        self.done.set()


class _BatcherBase:
    """Bounded-queue admission + lifecycle shared by both dispatch
    disciplines; subclasses implement ``_loop``."""

    def __init__(
        self,
        engine,
        queue_depth: Optional[int] = None,
        tel=None,
        on_wedge: Optional[Callable[[], None]] = None,
        wedge_timeout_ms: Optional[float] = None,
        weights: Optional[Dict[str, float]] = None,
        quality=None,
        exemplars=None,
    ) -> None:
        config = engine.config
        self.engine = engine
        depth = int(
            queue_depth if queue_depth is not None else config.serve_queue_depth
        )
        # admission queue: per-tenant sub-queues drained in weighted
        # deficit order (serve/scheduler.py).  Without a weights table
        # this is a single default lane popping in exact FIFO order —
        # the pre-tenant behavior, bit for bit.
        self._q = DeficitRoundRobin(maxsize=depth, weights=weights)
        self._tel = tel if tel is not None else telemetry.get()
        # wedge containment (docs/SERVING.md degraded health): when > 0,
        # the result drain of each in-flight dispatch is bounded — a
        # result the device never returns fails its requests with 500
        # instead of stranding them, and ``on_wedge`` (the server's
        # degrade+re-warm hook) fires.  0 keeps the drain unbounded.
        wedge_ms = (
            wedge_timeout_ms
            if wedge_timeout_ms is not None
            else config.serve_wedge_timeout_ms
        )
        self.wedge_timeout_s = float(wedge_ms) / 1e3  # sync-ok: host config scalar
        self.on_wedge = on_wedge
        # armed only via SAT_FI_WEDGE_SERVE_BATCH (inert in production);
        # captured once so the fire-once bookkeeping persists across
        # batches
        self._plan = faultinject.FaultPlan.from_env()
        # quality plane (telemetry/quality.py): a QualityMonitor and an
        # ExemplarRecorder, both None with --serve_quality off — every
        # quality hook below is then a single attribute compare
        self._quality = quality
        self._exemplars = exemplars
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # lifecycle control commands (arm_canary / swap / disarm_canary)
        # execute ON the loop thread at the admission boundary — the same
        # single-owner discipline as the continuous re-warm queue — so no
        # batch ever straddles a param-slot flip
        self._control_q: "queue.Queue[Tuple[str, Dict[str, Any], threading.Event]]" = (
            queue.Queue()
        )

    # -- admission (called from HTTP worker threads) -----------------------

    def submit(
        self,
        image: Optional[np.ndarray],
        deadline_unix: Optional[float] = None,
        trace: Optional[Any] = None,
        slot: str = "incumbent",
        tenant: str = "default",
        raw: Optional[bytes] = None,
        key: Optional[int] = None,
        context: Optional[np.ndarray] = None,
    ) -> Request:
        """Admit one preprocessed image — or, on a decode-tier replica, a
        pre-encoded ``context`` grid; raises Rejected(503) while
        draining and Rejected(429) when the tenant's queue lane is full
        (a tenant-scoped shed under a multi-tenant scheduler — one
        tenant's backlog never consumes another's queue space)."""
        if self._draining.is_set():
            self._tel.count("serve/rejected_draining")
            raise Rejected(503, "server is draining; not accepting work")
        req = Request(
            image=image,
            t_submit_ns=time.perf_counter_ns(),
            deadline_unix=deadline_unix,
            trace=trace,
            slot=slot,
            tenant=tenant,
            cost=RequestCost() if self._tel.enabled else None,
            # body bytes are retained only while this request is in
            # flight AND the quality plane wants exemplars
            raw=raw if self._exemplars is not None else None,
            key=key,
            context=context,
        )
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._tel.count("serve/shed")
            if self._q.multi:
                self._tel.count(f"serve/tenant_{tenant}_shed")
                raise Rejected(
                    429,
                    f"tenant {tenant!r} queue full "
                    f"({self._q.maxsize} waiting); shed",
                    scope="tenant",
                ) from None
            raise Rejected(
                429, f"queue full ({self._q.maxsize} waiting); shed"
            ) from None
        self._tel.count("serve/submitted")
        self._tel.gauge("serve/queue_depth", self._q.qsize())
        return req

    def queue_depth(self) -> int:
        return self._q.qsize()

    def tenant_depths(self) -> Dict[str, int]:
        """Per-tenant queued depth (the /stats tenants block)."""
        return self._q.depths()

    def tenant_admitted(self) -> Dict[str, int]:
        """Cumulative per-tenant scheduler admissions (the /stats
        tenants block's reconciliation count against the cost ledger)."""
        return self._q.admitted()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "_BatcherBase":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sat-serve-batcher", daemon=True
            )
            self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = 60.0) -> None:
        """Graceful stop: new submits reject (503), everything already
        admitted is dispatched, completed and signalled, then the
        dispatch thread exits."""
        self._draining.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    def _loop(self) -> None:  # pragma: no cover - subclasses implement
        raise NotImplementedError

    # -- lifecycle control (sat_tpu/lifecycle) -----------------------------

    def lifecycle_control(self, action: str, timeout: float = 120.0) -> Dict[str, Any]:
        """Run a lifecycle action (``arm_canary`` / ``swap`` /
        ``disarm_canary``) on the loop thread between dispatches; inline
        when the loop isn't running (tests, pre-start).  Returns the
        action's result dict; raises on an action-level failure."""
        box: Dict[str, Any] = {}
        if self._thread is None or not self._thread.is_alive():
            self._apply_control(action, box)
        else:
            ev = threading.Event()
            self._control_q.put((action, box, ev))
            if not ev.wait(timeout=timeout):
                raise RuntimeError(f"lifecycle {action!r} timed out")
        if "error" in box:
            raise RuntimeError(box["error"])
        return box

    def _maybe_control(self) -> None:
        while True:
            try:
                action, box, ev = self._control_q.get_nowait()
            except queue.Empty:
                return
            try:
                self._apply_control(action, box)
            except Exception as e:  # report to the caller, keep serving
                box["error"] = f"lifecycle {action!r} failed: {e}"
            finally:
                ev.set()

    def _apply_control(self, action: str, box: Dict[str, Any]) -> None:
        """Batch-mode semantics: dispatched batches captured their param
        tree at dispatch time, so the swap is a pointer flip with no
        drain to wait out; arm/disarm need no device state at all (the
        canary slot is resolved per dispatch)."""
        if action == "arm_canary":
            box["ok"] = True
        elif action == "swap":
            t0 = time.monotonic()
            box["step"] = self.engine.promote_candidate()
            box["blackout_ms"] = (time.monotonic() - t0) * 1e3
        elif action == "disarm_canary":
            box["ok"] = True
        else:
            raise ValueError(f"unknown lifecycle action {action!r}")

    # -- wedge watchdog ----------------------------------------------------

    def _bounded_decode(self, decode: Callable[[], Any]):
        """Run ``decode`` in a helper thread bounded by
        ``wedge_timeout_s``; raises :class:`_WedgeTimeout` when the device
        never returns.  The helper is a daemon — a truly wedged drain
        parks it forever, which is exactly the state the timeout reports
        instead of sharing."""
        box: Dict[str, Any] = {}
        done = threading.Event()

        def _run():
            try:
                box["results"] = decode()
            except BaseException as e:
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=_run, name="sat-serve-drain", daemon=True)
        t.start()
        if not done.wait(timeout=self.wedge_timeout_s):
            raise _WedgeTimeout()
        if "error" in box:
            raise box["error"]
        return box["results"]

    # -- quality plane (telemetry/quality.py) ------------------------------

    def _apply_quality_skew(self, scores: np.ndarray) -> np.ndarray:
        """SAT_FI_QUALITY_SKEW: depress the drained top-beam log scores
        by the armed amount — margins and normalized log-probs shift like
        a quietly degraded checkpoint while caption TOKENS stay bitwise
        identical (so exemplar replay still reproduces).  Env-read per
        drain (not via the construction-time FaultPlan) so the chaos
        campaign can arm it against a live server; inert path is one env
        get."""
        skew = faultinject.consume_quality_skew()
        if skew and scores.size:
            scores = scores.copy()
            scores[:, 0] -= skew
        return scores

    def _observe_quality(
        self, payloads, words, lengths, scores, alphas, results
    ) -> None:
        """Per-request quality signals at the detok boundary — pure host
        arithmetic on arrays the drain already synced (zero new device
        syncs).  Outliers flagged by the monitor are handed to the
        exemplar flight recorder; any failure here is counted and
        swallowed (observability must never fail a request)."""
        if self._quality is None:
            return
        from ..telemetry.quality import extract_signals

        vocab_size = len(self.engine.vocabulary.words)
        eos_id = self.engine.eos_id
        try:
            for i, r in enumerate(payloads):
                sig = extract_signals(
                    words[i], lengths[i], scores[i],
                    vocab_size=vocab_size, eos_id=eos_id,
                    alphas=None if alphas is None else alphas[i],
                )
                reasons = self._quality.observe(sig, tenant=r.tenant)
                if reasons and self._exemplars is not None:
                    captions = results[i]["captions"] if results else []
                    self._exemplars.record(
                        reasons=reasons,
                        request_id=getattr(r.trace, "trace_id", ""),
                        tenant=r.tenant,
                        caption=captions[0]["caption"] if captions else "",
                        beams=captions,
                        signals=sig,
                        image_bytes=r.raw,
                        alphas=None if alphas is None else alphas[i],
                        extra={"slot": r.slot, "bucket": r.bucket},
                    )
        except Exception:
            self._tel.count("serve/quality_errors")


class MicroBatcher(_BatcherBase):
    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        tel=None,
        pipeline_depth: int = 1,
        on_wedge: Optional[Callable[[], None]] = None,
        wedge_timeout_ms: Optional[float] = None,
        weights: Optional[Dict[str, float]] = None,
        quality=None,
        exemplars=None,
    ) -> None:
        super().__init__(
            engine,
            queue_depth=queue_depth,
            tel=tel,
            on_wedge=on_wedge,
            wedge_timeout_ms=wedge_timeout_ms,
            weights=weights,
            quality=quality,
            exemplars=exemplars,
        )
        config = engine.config
        self.max_batch = int(
            max_batch if max_batch is not None else config.serve_max_batch
        )
        wait_ms = (
            max_wait_ms if max_wait_ms is not None else config.serve_max_wait_ms
        )
        self.max_wait_s = wait_ms / 1e3
        # in-flight dispatches held before draining (device_prefetch's
        # ``ahead``); 0 degrades to fully synchronous dispatch→drain
        self.pipeline_depth = max(0, int(pipeline_depth))
        self._batch_index = 0  # 1-based, counted at dispatch

    # -- dispatch loop -----------------------------------------------------

    def _gather(self) -> Optional[List[Request]]:
        """Block for the first request (polling the drain flag), then hold
        the batch open up to ``max_wait_s`` or until ``max_batch``.
        Returns None when draining and the queue is empty."""
        while True:
            try:
                first = self._q.get(timeout=0.05)
                break
            except queue.Empty:
                if self._draining.is_set():
                    return None
                if not self._control_q.empty():
                    # wake the loop for a lifecycle command; [] is the
                    # "nothing gathered, not draining" sentinel
                    return []
        first.t_gather_ns = time.perf_counter_ns()
        batch = [first]
        flush_at = time.monotonic() + self.max_wait_s
        while len(batch) < self.max_batch:
            wait = flush_at - time.monotonic()
            if wait <= 0:
                break
            try:
                rider = self._q.get(timeout=wait)
            except queue.Empty:
                break
            rider.t_gather_ns = time.perf_counter_ns()
            batch.append(rider)
        return batch

    def _admit(self, batch: List[Request]) -> List[Request]:
        """Queue-wait accounting + deadline triage at the dispatch
        boundary: expired requests fail fast (504) without device time."""
        now_ns = time.perf_counter_ns()
        now_unix = time.time()
        live = []
        for r in batch:
            self._tel.record(
                "serve/queue_wait", r.t_submit_ns, now_ns - r.t_submit_ns
            )
            # per-request phase attribution: queue_wait ends when the
            # gather loop popped the request; batch_form is the hold-open
            # window between that pop and this dispatch boundary
            t_gather = r.t_gather_ns if r.t_gather_ns is not None else now_ns
            r.mark("queue_wait", r.t_submit_ns, t_gather - r.t_submit_ns)
            r.mark("batch_form", t_gather, now_ns - t_gather)
            if r.deadline_unix is not None and now_unix > r.deadline_unix:
                self._tel.count("serve/expired")
                r.fail(504, "deadline expired while queued")
            else:
                live.append(r)
        return live

    def _dispatch(self, live: List[Request], slot: str = "incumbent"):
        t0 = time.perf_counter_ns()
        if live[0].context is not None:
            # decode-tier group (pre-encoded handoff grids): the loop
            # groups by kind, so the whole group carries contexts
            bucket = self.engine.pick_bucket(len(live))
            out = self.engine.dispatch_contexts(
                [r.context for r in live], slot=slot,
                costs=[r.cost for r in live],
            )
        else:
            batch, bucket = self.engine.pad_batch([r.image for r in live])
            keys = [r.key for r in live]
            if getattr(self.engine, "encode_cache", None) is None or any(
                k is None for k in keys
            ):
                # unkeyed requests (cache off, or direct submit()s that
                # never saw the server's crc stamp) take the plain path
                keys = None
            out = self.engine.dispatch(
                batch, slot=slot, costs=[r.cost for r in live], keys=keys
            )
        t1 = time.perf_counter_ns()
        self._tel.record("serve/dispatch", t0, t1 - t0)
        self._tel.count("serve/batches")
        self._tel.count(f"serve/bucket_{bucket}")
        self._tel.count("serve/padded_rows", bucket - len(live))
        for r in live:
            r.bucket = bucket
            r.mark("dispatch", t0, t1 - t0)
            # batch-mode occupancy runs dispatch→drain: the window this
            # request's bucket row held device-resident beam state
            r.t_admit_ns = t1
        return out

    def _finish(self, entry) -> None:
        out, live, index, slot = entry

        def _drain():
            if self._plan.maybe_wedge_serve(index):
                # injected stuck batch: park exactly like a drain whose
                # device never answers (interruptible only by process exit)
                time.sleep(3600.0)
            self._plan.maybe_slow_serve()
            self._plan.maybe_slow_canary(slot)
            return self.engine.drain_output(out, len(live))

        try:
            t0 = time.perf_counter_ns()
            # only the device drain is wedge-bounded — detok is pure host
            # work that cannot hang on the device
            if self.wedge_timeout_s > 0:
                arrays = self._bounded_decode(_drain)
            else:
                arrays = _drain()
            t1 = time.perf_counter_ns()
            words, lengths, scores, alphas = arrays
            scores = self._apply_quality_skew(scores)
            results = self.engine.detok_rows(
                (words, lengths, scores, alphas), len(live)
            )
            t2 = time.perf_counter_ns()
            # the aggregate span keeps its pre-split meaning (drain+detok)
            # so /stats latency percentiles stay comparable across runs
            self._tel.record("serve/detok", t0, t2 - t0)
            if self._tel.enabled:
                # decode attribution (telemetry/metering.py): the drained
                # window is the batch's decode device time — each live
                # request is charged an equal share, and the window span
                # doubles as the measured-busy feed for the accounting
                # identity (BUSY_SPANS)
                self._tel.record("serve/decode_window", t0, t1 - t0)
                share = (t1 - t0) // len(live)
                for r in live:
                    if r.cost is not None:
                        r.cost.add_decode(share)
                        if r.t_admit_ns is not None:
                            r.cost.set_occupancy(t1 - r.t_admit_ns)
            for r in live:
                r.mark("drain", t0, t1 - t0)
                r.mark("detok", t1, t2 - t1)
        except _WedgeTimeout:
            # the batch is gone; its requesters get a fast 500 and the
            # server's hook degrades health + re-warms the engine
            self._tel.count("serve/wedged_batches")
            for r in live:
                if not r.done.is_set():
                    r.fail(
                        500,
                        "in-flight batch wedged past "
                        f"{self.wedge_timeout_s * 1e3:g}ms; results discarded",
                    )
            if self.on_wedge is not None:
                try:
                    self.on_wedge()
                except Exception:
                    pass  # degrading health must never kill the batcher
            return
        except Exception as e:  # keep serving; fail only this batch
            self._tel.count("serve/detok_errors")
            for r in live:
                if not r.done.is_set():
                    r.fail(500, f"decode failed: {e}")
            return
        for r, result in zip(live, results):
            r.result = result
            r.done.set()
            self._tel.count("serve/completed")
        # quality observation AFTER completion: requesters never wait on
        # signal extraction or exemplar I/O
        self._observe_quality(live, words, lengths, scores, alphas, results)

    def _dispatch_group(self, group: List[Request], slot: str, inflight) -> None:
        try:
            out = self._dispatch(group, slot)
        except BucketOverflow as e:
            # a burst past the largest warmed bucket is backpressure,
            # not a server fault: shed with 429 + a Retry-After hint
            # (the frontend adds the header)
            self._tel.count("serve/shed_bucket_overflow")
            for r in group:
                r.fail(
                    429,
                    f"{e}; retry after the current batch drains",
                )
            return
        except Exception as e:  # device/shape failure: fail the batch
            self._tel.count("serve/dispatch_errors")
            for r in group:
                r.fail(500, f"dispatch failed: {e}")
            return
        self._batch_index += 1
        inflight.append((out, group, self._batch_index, slot))

    def _loop(self) -> None:
        inflight: "deque" = deque()
        while True:
            self._maybe_control()
            if inflight and self._q.qsize() == 0:
                # Nothing to gather right now: flush the oldest in-flight
                # batch instead of parking in _gather while its requesters
                # wait on a device that may already be done.  Overlap
                # still happens under load — the queue is non-empty then,
                # so dispatch n+1 precedes this drain of n.
                self._finish(inflight.popleft())
                continue
            batch = self._gather()
            self._tel.gauge("serve/queue_depth", self._q.qsize())
            if batch is None:
                break
            if not batch:  # woken for a lifecycle command
                continue
            live = self._admit(batch)
            if not live:
                continue
            # one dispatch per (param slot, payload kind): a gathered
            # batch mixing canary and incumbent requests splits so each
            # dispatch runs against exactly one param tree, and image vs
            # pre-encoded-context requests split because they enter the
            # device through different programs
            groups: Dict[Tuple[str, bool], List[Request]] = {}
            for r in live:
                groups.setdefault(
                    (r.slot, r.context is not None), []
                ).append(r)
            for gkey in sorted(groups):
                self._dispatch_group(groups[gkey], gkey[0], inflight)
            while len(inflight) > self.pipeline_depth:
                self._finish(inflight.popleft())
        while inflight:  # drain: complete what the device still owes
            self._finish(inflight.popleft())


class ContinuousBatcher(_BatcherBase):
    """Step-level continuous batching over a paged slot pool.

    The loop interleaves three phases with no whole-batch barrier:

    1. **admit** — pop whatever is queued (up to the pool's free slots),
       triage deadlines, seed a page per block of new requests;
    2. **step** — one fused ``decode_multi_step`` dispatch over the pool
       (up to K decode steps per dispatch, K chosen per tick from queue
       pressure — :func:`choose_decode_depth`); draining the [S] done
       flags is the loop's only host↔device sync, bounded by the wedge
       watchdog;
    3. **harvest** — merge + drain finished slots, free them, and hand
       the host arrays to the detok worker thread (string work never
       blocks the step loop).

    All device programs are AOT executables owned by the pool, so steady
    state never recompiles (asserted by tests/test_continuous.py)."""

    def __init__(
        self,
        engine,
        pool=None,
        queue_depth: Optional[int] = None,
        tel=None,
        on_wedge: Optional[Callable[[], None]] = None,
        wedge_timeout_ms: Optional[float] = None,
        weights: Optional[Dict[str, float]] = None,
        quality=None,
        exemplars=None,
    ) -> None:
        super().__init__(
            engine,
            queue_depth=queue_depth,
            tel=tel,
            on_wedge=on_wedge,
            wedge_timeout_ms=wedge_timeout_ms,
            weights=weights,
            quality=quality,
            exemplars=exemplars,
        )
        if pool is None:
            from .slot_pool import PagedSlotPool

            pool = PagedSlotPool(engine, tel=self._tel)
        self.pool = pool
        self._step_index = 0  # 1-based; SAT_FI_WEDGE_SERVE_BATCH=n wedges step n
        self._detok_q: "queue.Queue" = queue.Queue()
        self._detok_thread: Optional[threading.Thread] = None
        # re-warm requests are executed ON the loop thread (the pool is
        # single-owner; a concurrent warmup would race admission)
        self._rewarm_q: "queue.Queue[threading.Event]" = queue.Queue()
        # lifecycle canary: a clone_warmed pool stepping the candidate
        # params (zero extra compiles), present only during a canary
        # window; requests that can't be seeded because their slot's pool
        # is full wait here — held, never dropped
        self._canary_pool = None
        # multi-tenant resident models: one clone_warmed pool per
        # resident param slot, created lazily ON the loop thread the
        # first time a request routes to that slot (same single-owner
        # discipline as the canary pool; shares every AOT executable, so
        # a resident's first request costs zero compiles)
        self._model_pools: Dict[str, Any] = {}
        self._pending: List[Request] = []

    def _pools(self) -> List[Any]:
        pools = [self.pool]
        if self._canary_pool is not None:
            pools.append(self._canary_pool)
        pools.extend(self._model_pools.values())
        return pools

    def _occupancy_total(self) -> int:
        return sum(p.occupancy() for p in self._pools())

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ContinuousBatcher":
        if self.pool._carry is None:
            self.pool.warmup()
        if self._detok_thread is None:
            self._detok_thread = threading.Thread(
                target=self._detok_loop, name="sat-serve-detok", daemon=True
            )
            self._detok_thread.start()
        super().start()
        return self

    # -- admission into slots ----------------------------------------------

    def _pop_queued(self, cap: int) -> List[Request]:
        out: List[Request] = []
        while len(out) < cap:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        return out

    def _admit(self, reqs: List[Request], pool=None) -> None:
        """Deadline triage + seed into free slots of ``pool`` (the main
        pool by default), marking per-request admission phases.  Callers
        never pass more than the pool's free_count()."""
        pool = pool if pool is not None else self.pool
        now_ns = time.perf_counter_ns()
        now_unix = time.time()
        items = []
        for r in reqs:
            r.t_gather_ns = now_ns
            self._tel.record(
                "serve/queue_wait", r.t_submit_ns, now_ns - r.t_submit_ns
            )
            r.mark("queue_wait", r.t_submit_ns, now_ns - r.t_submit_ns)
            if r.deadline_unix is not None and now_unix > r.deadline_unix:
                self._tel.count("serve/expired")
                r.fail(504, "deadline expired while queued")
            else:
                items.append((r.image, r))
        if not items:
            return
        t0 = time.perf_counter_ns()
        n = pool.admit(items)
        t1 = time.perf_counter_ns()
        self._tel.count("serve/admitted", n)
        for _, r in items[:n]:
            # the page width is the continuous path's dispatch "bucket"
            r.bucket = pool.width
            r.t_admit_ns = t1
            r.mark("admit", t0, t1 - t0)
            # submit → seeded: the continuous path's admission latency
            # (what max_wait_ms + whole-batch gathering used to cost)
            self._tel.record(
                "serve/admission_wait", r.t_submit_ns, t1 - r.t_submit_ns
            )
        for _, r in items[n:]:  # unreachable by construction; never strand
            r.fail(500, "slot pool admission overflow")
        self._tel.gauge("serve/queue_depth", self._q.qsize())

    def _route_admissions(self) -> None:
        """Route held + queued requests to their slot's pool, admitting
        up to each pool's free capacity.  A request whose pool is full
        stays in ``_pending`` (consumed first next iteration) — the
        lifecycle plane must never drop or fail work just because the
        canary pool is briefly saturated.  Requests arrive here in the
        scheduler's deficit order, so slot seats are granted in deficit
        order too."""
        pools = {"incumbent": self.pool}
        if self._canary_pool is not None:
            pools["canary"] = self._canary_pool
        pools.update(self._model_pools)
        free = {k: p.free_count() for k, p in pools.items()}
        headroom = sum(free.values()) - len(self._pending)
        reqs = self._pending
        if headroom > 0:
            reqs = reqs + self._pop_queued(headroom)
        self._pending = []
        groups: Dict[str, List[Request]] = {k: [] for k in pools}
        for r in reqs:
            slot = r.slot
            if slot not in pools:
                if self.engine.has_resident(slot):
                    # first request for this resident model: clone the
                    # warmed pool on this (the loop) thread — zero
                    # compiles, fresh carry — and hold the request one
                    # tick so it seeds into the new pool next iteration
                    pool = self.pool.clone_warmed(slot)
                    self._model_pools[slot] = pool
                    pools[slot] = pool
                    free[slot] = 0
                    groups[slot] = []
                else:
                    slot = "incumbent"
            if len(groups[slot]) < free[slot]:
                groups[slot].append(r)
            else:
                self._pending.append(r)
        for slot, group in groups.items():
            if group:
                self._admit(group, pools[slot])

    # -- the step loop -----------------------------------------------------

    def _choose_k(self) -> int:
        return choose_decode_depth(
            self.pool.decode_depths, self._q.qsize(), len(self._pending)
        )

    def _step_pools(self, index: int) -> List[Tuple[Any, np.ndarray]]:
        """One fused ``decode_multi_step`` dispatch over every occupied
        pool (the canary pool steps right after the incumbent when
        armed); returns ``[(pool, done_flags)]``.  The window depth K is
        chosen per tick from queue pressure (:func:`choose_decode_depth`)
        and runs as one device dispatch; the on-device early exit means a
        pool that seals mid-window reports ``steps_run < K``."""
        if self._plan.maybe_wedge_serve(index):
            # injected stuck step: park exactly like a drain whose device
            # never answers (interruptible only by process exit)
            time.sleep(3600.0)
        self._plan.maybe_slow_serve()
        k = self._choose_k()
        out = []
        for pool in self._pools():
            if pool.occupancy() == 0:
                continue
            self._plan.maybe_slow_canary(pool.param_slot)
            live = pool.inflight_payloads() if self._tel.enabled else None
            t0 = time.perf_counter_ns()
            done_dev, steps_dev = pool.multi_step(k)
            done = np.asarray(done_dev)  # sync-ok: step boundary — the continuous loop's one bounded sync
            steps_run = int(np.asarray(steps_dev))  # sync-ok: same dispatch as the done drain above
            t1 = time.perf_counter_ns()
            self._tel.record("serve/step", t0, t1 - t0)
            if live:
                # decode attribution (telemetry/metering.py): every live
                # slot riding this fused window is charged an equal share
                # — the marginal cost of keeping its slot hot for these
                # steps_run steps, weighted by pool fill per dispatch
                share = (t1 - t0) // len(live)
                for r in live:
                    cost = getattr(r, "cost", None)
                    if cost is not None:
                        cost.add_decode(share)
            # the chosen-K lane as its own named span: in Perfetto the
            # serve/dispatch_k* tracks show dispatch amortization live
            self._tel.record(f"serve/dispatch_k{k}", t0, t1 - t0)
            # raw loop-iteration count (not ns) — < k when the pool
            # sealed mid-window and the on-device early exit fired
            self._tel.record("serve/steps_per_dispatch", 0, steps_run)
            self._tel.count("serve/steps", steps_run)
            self._tel.count("serve/dispatches")
            out.append((pool, done))
        return out

    def _fail_inflight(self, status: int, reason: str) -> None:
        for pool in self._pools():
            for r in pool.inflight_payloads():
                if not r.done.is_set():
                    r.fail(status, reason)
        for r in self._pending:
            if not r.done.is_set():
                r.fail(status, reason)
        self._pending = []

    def _handle_wedge(self) -> None:
        # same counter the batch path trips, so /healthz consumers and
        # the chaos campaign see one wedge signal across modes
        self._tel.count("serve/wedged_batches")
        self._fail_inflight(
            500,
            "in-flight decode step wedged past "
            f"{self.wedge_timeout_s * 1e3:g}ms; slots discarded",
        )
        for pool in self._pools():
            try:
                pool.reset()
            except Exception:
                pass  # a reset the device won't answer is the wedge itself
        if self.on_wedge is not None:
            try:
                self.on_wedge()
            except Exception:
                pass  # degrading health must never kill the batcher

    def _harvest(self, done: np.ndarray, pool=None) -> None:
        pool = pool if pool is not None else self.pool
        t0 = time.perf_counter_ns()
        payloads, words, lengths, scores, steps, alphas = pool.harvest(done)
        t1 = time.perf_counter_ns()
        for i, r in enumerate(payloads):
            r.mark("drain", t0, t1 - t0)
            if r.t_admit_ns is not None:
                r.mark("decode", r.t_admit_ns, t1 - r.t_admit_ns)
                if r.cost is not None:
                    # occupancy: seeded → harvested, the HBM-seconds this
                    # request's slot (KV pages, beam state) was held
                    r.cost.set_occupancy(t1 - r.t_admit_ns)
            if r.cost is not None:
                r.cost.decode_steps += int(steps[i])
            # raw per-request loop-iteration count (not ns): short
            # captions SHOW their early retirement here
            self._tel.record("serve/decode_steps", 0, int(steps[i]))
        self._detok_q.put((payloads, words, lengths, scores, alphas, t1))

    def _detok_loop(self) -> None:
        while True:
            item = self._detok_q.get()
            if item is None:
                return
            payloads, words, lengths, scores, alphas, t1 = item
            scores = self._apply_quality_skew(scores)
            # harvest → dequeue is detok-THREAD queueing, not string work:
            # attribute it to its own span so serve/detok (and the
            # per-request detok phase) measures pure detokenize cost — a
            # deep fused window harvests in bursts, and folding the burst
            # queueing into detok misattributes loop-side wins as
            # host-side detok regressions
            td = time.perf_counter_ns()
            self._tel.record("serve/detok_queue", t1, td - t1)
            try:
                results = self.engine.detok_rows(
                    (words, lengths, scores), len(payloads)
                )
            except Exception as e:
                self._tel.count("serve/detok_errors")
                for r in payloads:
                    if not r.done.is_set():
                        r.fail(500, f"detokenize failed: {e}")
                continue
            t2 = time.perf_counter_ns()
            self._tel.record("serve/detok", td, t2 - td)
            for r, result in zip(payloads, results):
                r.mark("detok_queue", t1, td - t1)
                r.mark("detok", td, t2 - td)
                r.result = result
                r.done.set()
                self._tel.count("serve/completed")
            # after completion, on the detok thread — the step loop never
            # pays for signal extraction or exemplar I/O
            self._observe_quality(
                payloads, words, lengths, scores, alphas, results
            )

    def _maybe_rewarm(self) -> None:
        try:
            ev = self._rewarm_q.get_nowait()
        except queue.Empty:
            return
        # anything still bound was admitted during the degraded window;
        # warmup rebuilds an empty carry, so hand them a retryable 503
        # rather than silently dropping their slots
        self._fail_inflight(503, "server re-warming after wedge; retry")
        try:
            self.pool.warmup()
            if self._canary_pool is not None:
                # re-clone so the canary pool shares the freshly proven
                # executables and starts from an empty carry too
                self._canary_pool = self.pool.clone_warmed("canary")
            for slot in list(self._model_pools):
                self._model_pools[slot] = self.pool.clone_warmed(slot)
        finally:
            ev.set()

    def _loop(self) -> None:
        while True:
            self._maybe_rewarm()
            self._maybe_control()
            if self._occupancy_total() == 0 and not self._pending:
                # idle: park for the first arrival, polling the drain flag
                try:
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    if self._draining.is_set():
                        break
                    continue
                self._pending.append(first)
            # admit whatever is queued RIGHT NOW into each slot's free
            # slots — between steps, with no hold-open window
            self._route_admissions()
            if self._occupancy_total() == 0:
                continue  # everything admitted expired at the deadline gate
            self._step_index += 1
            index = self._step_index
            try:
                if self.wedge_timeout_s > 0:
                    dones = self._bounded_decode(
                        lambda: self._step_pools(index)
                    )
                else:
                    dones = self._step_pools(index)
            except _WedgeTimeout:
                self._handle_wedge()
                continue
            except Exception as e:  # keep serving; fail only in-flight work
                self._tel.count("serve/dispatch_errors")
                self._fail_inflight(500, f"decode step failed: {e}")
                for pool in self._pools():
                    try:
                        pool.reset()
                    except Exception:
                        pass
                continue
            for pool, done in dones:
                if done.any():
                    self._harvest(done, pool)
        # drain: queue empty and pool empty — flush the detok worker
        self._detok_q.put(None)
        if self._detok_thread is not None:
            self._detok_thread.join(timeout=30.0)
            self._detok_thread = None

    # -- lifecycle control (executed on the loop thread) -------------------

    def _drain_step_bound(self, stop) -> bool:
        """Step + harvest until ``stop()`` is satisfied, bounded by the
        caption-length step budget so a done flag that never fires can't
        wedge the loop forever.  Returns whether the drain completed."""
        limit = 2 * self.pool.max_len + 8
        for _ in range(limit):
            if stop():
                return True
            self._step_index += 1
            for pool, done in self._step_pools(self._step_index):
                if done.any():
                    self._harvest(done, pool)
        return stop()

    def _apply_control(self, action: str, box: Dict[str, Any]) -> None:
        """Continuous-mode semantics: the decode carry is re-fed to every
        step with whatever params the pool resolves NOW, so a swap must
        wait out in-flight captions (they finish under the params they
        started with).  That wait — during which nothing new is admitted
        — IS the swap blackout window, bounded by the caption-length
        step budget."""
        if action == "arm_canary":
            if self._canary_pool is None:
                self._canary_pool = self.pool.clone_warmed("canary")
            box["ok"] = True
        elif action == "swap":
            t0 = time.monotonic()
            if not self._drain_step_bound(
                lambda: self._occupancy_total() == 0
            ):
                self._fail_inflight(
                    500, "lifecycle swap drain exceeded its step bound"
                )
            box["step"] = self.engine.promote_candidate()
            self._canary_pool = None
            box["blackout_ms"] = (time.monotonic() - t0) * 1e3
        elif action == "disarm_canary":
            pool = self._canary_pool
            if pool is not None:
                # rollback: in-flight canary captions complete normally
                # (still against the candidate params — it is slow or
                # diverging, not gone), then the pool is dropped
                if not self._drain_step_bound(
                    lambda: pool.occupancy() == 0
                ):
                    for r in pool.inflight_payloads():
                        if not r.done.is_set():
                            r.fail(503, "canary retired mid-decode; retry")
                    pool.reset()
                self._canary_pool = None
            box["ok"] = True
        else:
            raise ValueError(f"unknown lifecycle action {action!r}")

    def rewarm(self) -> None:
        """The server's wedge-recovery hook: re-run the pool warmup
        (cached compiles — cheap) and rebuild an empty carry, proving the
        device answers before health recovers.  Executed on the loop
        thread when it's alive — the pool is single-owner, and a warmup
        racing admission would clobber freshly seeded slots."""
        if self._thread is None or not self._thread.is_alive():
            self.pool.warmup()
            return
        ev = threading.Event()
        self._rewarm_q.put(ev)
        if not ev.wait(timeout=120.0):
            raise RuntimeError("slot-pool re-warm timed out")
