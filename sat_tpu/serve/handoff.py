"""Feature-grid handoff format for encode/decode tier disaggregation.

An encode-tier replica answers ``POST /encode`` with one preprocessed
image's encoder context grid; a decode-tier replica accepts that grid on
``POST /caption`` (content type below) and seeds a decode slot from it,
skipping its own encode lane.  The wire format keeps the router jax-free
and the decode side paranoid:

    {"magic": "sat-grid1", "dtype": "float32", "shape": [196, 512],
     "crc32c": <int>, "step": <int>}\\n<raw row-major grid bytes>

* the JSON header line pins dtype + shape so the decode replica can
  validate the aval against its own warmed executables BEFORE touching
  device memory (shape drift = different params geometry = reject);
* ``crc32c`` covers the payload bytes with the same Castagnoli digest
  the integrity plane uses — a flipped bit in transit is a 400, not a
  silently wrong caption;
* ``step`` carries the encoder's model step so a decode replica serving
  a different promote generation can refuse a stale grid.

Deliberately jax-free (numpy only): the router forwards these blobs and
the chaos/bench harnesses craft corrupt ones without importing jax.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils.summary import crc32c

# content type for a grid-carrying request/response body
GRID_CONTENT_TYPE = "application/x-sat-grid"

_MAGIC = "sat-grid1"
# grids are small (a few hundred KB); a multi-MB header line means a
# corrupt or hostile frame, not a bigger model
_MAX_HEADER_BYTES = 4096


class HandoffError(ValueError):
    """Malformed/corrupt grid frame — maps to HTTP 400 at the server."""


def encode_grid(grid: np.ndarray, step: Optional[int] = None) -> bytes:
    """Serialize one context grid ``[N, D]`` (any rank works) into a
    self-describing frame: header line + raw bytes."""
    arr = np.ascontiguousarray(grid)
    payload = arr.tobytes()
    header = {
        "magic": _MAGIC,
        "dtype": arr.dtype.name,
        "shape": list(arr.shape),
        "crc32c": crc32c(payload),
    }
    if step is not None:
        header["step"] = int(step)
    return json.dumps(header, sort_keys=True).encode("utf-8") + b"\n" + payload


def decode_grid(data: bytes) -> Tuple[np.ndarray, Dict[str, Any]]:
    """Parse and verify a grid frame; returns ``(grid, header)``.

    Raises :class:`HandoffError` on any malformation: missing/oversized
    header, wrong magic, bad dtype, byte-count/shape mismatch, or crc32c
    mismatch.  The returned array is read-only (it aliases ``data``)."""
    nl = data.find(b"\n", 0, _MAX_HEADER_BYTES)
    if nl < 0:
        raise HandoffError("grid frame: no header line within bound")
    try:
        header = json.loads(data[:nl].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise HandoffError(f"grid frame: unparseable header ({exc})") from exc
    if not isinstance(header, dict) or header.get("magic") != _MAGIC:
        raise HandoffError("grid frame: bad magic")
    try:
        dtype = np.dtype(str(header["dtype"]))
        shape = tuple(int(d) for d in header["shape"])
        want_crc = int(header["crc32c"])
    except (KeyError, TypeError, ValueError) as exc:
        raise HandoffError(f"grid frame: bad header field ({exc})") from exc
    if any(d <= 0 for d in shape):
        raise HandoffError(f"grid frame: non-positive dim in shape {shape}")
    payload = data[nl + 1:]
    want_bytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(payload) != want_bytes:
        raise HandoffError(
            f"grid frame: payload is {len(payload)} bytes, "
            f"shape {shape}/{dtype.name} needs {want_bytes}"
        )
    got_crc = crc32c(payload)
    if got_crc != want_crc:
        raise HandoffError(
            f"grid frame: crc32c mismatch (header {want_crc:#010x}, "
            f"payload {got_crc:#010x})"
        )
    return np.frombuffer(payload, dtype=dtype).reshape(shape), header


def check_aval(
    grid: np.ndarray, shape: Sequence[int], dtype
) -> None:
    """Reject a grid whose aval doesn't match the decode side's warmed
    context row (``HandoffError`` → HTTP 400): seeding a slot from a
    mis-shaped grid would either recompile or silently misdecode."""
    want = tuple(int(d) for d in shape)
    if tuple(grid.shape) != want or grid.dtype != np.dtype(dtype):
        raise HandoffError(
            f"grid aval mismatch: got {tuple(grid.shape)}/{grid.dtype.name}, "
            f"this replica decodes {want}/{np.dtype(dtype).name}"
        )
