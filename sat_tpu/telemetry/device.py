"""In-graph model-health taps (``--diag_level``, docs/OBSERVABILITY.md).

The host-side telemetry layer (spans/heartbeat) times the run but the
device stays a black box: when loss drifts nobody can say whether the
gradient exploded, which layer group it exploded in, or whether the
doubly-stochastic attention regularizer is actually flattening the
alphas the paper is built around.  These taps answer that *from inside
the compiled step*: a small dict of scalar reductions computed next to
the gradients and merged into the metrics pytree ``train_step`` already
returns, so they ride the existing ``log_every`` ``device_get`` boundary
in ``runtime.train`` — **zero additional device syncs**, just a few more
scalars on the one fetch the loop already pays for.

Unlike the rest of ``sat_tpu.telemetry`` this module imports jax (the
taps are traced code); it is therefore NOT imported by the package
``__init__`` — ``train/step.py`` and ``models/captioner.py`` import it
directly, and only when ``config.diag_level != "off"``, so the off
path's XLA program is bit-for-bit the pre-diagnostics program
(tests/test_device_diag.py pins this).

Tap catalogue (all fp32 scalars, keys prefixed ``diag/``):

==================================  =====  ==================================
key                                 level  meaning
==================================  =====  ==================================
``diag/param_norm``                 basic  global L2 of the trainable tree
``diag/update_norm``                basic  global L2 of the optimizer update
``diag/update_ratio``               basic  update_norm / param_norm — the
                                           classic LR-sanity signal (~1e-3)
``diag/attn_entropy``               basic  mean masked per-word attention
                                           entropy H_t = -Σ_i α_ti ln α_ti
``diag/attn_entropy_frac``          basic  attn_entropy / ln N (1 = uniform,
                                           0 = one-hot)
``diag/alpha_coverage_dev``         basic  mean_{b,i} (1 - Σ_t α_ti)² — the
                                           paper's doubly-stochastic term,
                                           unscaled (= 2·attention_loss /
                                           attention_loss_factor)
``diag/logit_max``                  basic  max |pre-softmax logit| — drift
                                           here precedes softmax saturation
``diag/grad_nonfinite``             full   count of non-finite grad leaves'
                                           elements
``diag/grad_norm/<group>``          full   per-layer-group grad L2
``diag/update_norm/<group>``        full   per-layer-group update L2
``diag/param_norm/<group>``         full   per-layer-group param L2
==================================  =====  ==================================

Per-group keys localize a blow-up: a NaN in ``lstm/kernel`` makes
``diag/grad_norm/decoder.lstm`` (and everything downstream) NaN while
``.../decoder.word_embedding`` stays finite, and the anomaly sentinel
(resilience/sentinel.py) names every non-finite metric key in its
report — the taps are how it learns *which* tensor went bad.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

DIAG_LEVELS = ("off", "basic", "full")


def _l2(tree) -> jnp.ndarray:
    """Global L2 norm of a pytree, accumulated in fp32 (optax.global_norm
    without the optax import — this module must stay importable from
    model code)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


def _nonfinite_count(tree) -> jnp.ndarray:
    """Total count of non-finite elements across the pytree's leaves."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0)
    return sum(
        jnp.sum(~jnp.isfinite(x.astype(jnp.float32))) for x in leaves
    ).astype(jnp.float32)


def _layer_groups(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Flatten a params-like dict one level: {"decoder": {"lstm": ...}} →
    {"decoder.lstm": ...} — the per-layer-group granularity of the full
    taps.  Non-dict values keep their top-level name."""
    groups: Dict[str, Any] = {}
    for top, sub in tree.items():
        if isinstance(sub, dict) and sub:
            for name, leaf_tree in sub.items():
                groups[f"{top}.{name}"] = leaf_tree
        else:
            groups[top] = sub
    return groups


def attention_entropy(
    alphas: jnp.ndarray, masks: jnp.ndarray
) -> jnp.ndarray:
    """Mean per-word attention entropy over real (masked-in) words.

    alphas: [B,T,N] softmax rows; masks: [B,T].  H_t = -Σ_i α_ti ln α_ti,
    averaged over the mask.  ln N for a uniform map (≈5.28 for N=196),
    0 for a one-hot map."""
    a = alphas.astype(jnp.float32)
    h = -jnp.sum(a * jnp.log(jnp.clip(a, 1e-10, 1.0)), axis=-1)  # [B,T]
    m = masks.astype(jnp.float32)
    return jnp.sum(h * m) / jnp.maximum(jnp.sum(m), 1.0)


def alpha_coverage_deviation(
    alphas: jnp.ndarray, masks: jnp.ndarray
) -> jnp.ndarray:
    """mean_{b,i} (1 - Σ_t α_ti)² over masked alphas — the unscaled
    doubly-stochastic attention penalty (Xu et al. eq. 14; captioner
    scales it by ``attention_loss_factor * 0.5``)."""
    a = alphas.astype(jnp.float32) * masks.astype(jnp.float32)[..., None]
    coverage = a.sum(axis=1)                       # [B,N]
    d = 1.0 - coverage
    return jnp.mean(d * d)


def loss_taps(
    level: str,
    *,
    alphas: jnp.ndarray,
    masks: jnp.ndarray,
    logits: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Forward-pass taps, computed where the loss already holds the
    alphas/logits (models/captioner.compute_loss) so nothing extra rides
    through aux."""
    if level == "off":
        return {}
    ent = attention_entropy(alphas, masks)
    n = alphas.shape[-1]
    return {
        "diag/attn_entropy": ent,
        "diag/attn_entropy_frac": ent / jnp.float32(jnp.log(float(n))),
        "diag/alpha_coverage_dev": alpha_coverage_deviation(alphas, masks),
        "diag/logit_max": jnp.max(jnp.abs(logits.astype(jnp.float32))),
    }


def grad_taps(
    level: str,
    *,
    grads: Dict[str, Any],
    updates: Dict[str, Any],
    params: Dict[str, Any],
) -> Dict[str, jnp.ndarray]:
    """Backward/update-side taps, computed in train_step where the
    gradient and optimizer update trees are live.  ``params`` is the
    post-update trainable tree."""
    if level == "off":
        return {}
    param_norm = _l2(params)
    update_norm = _l2(updates)
    taps: Dict[str, jnp.ndarray] = {
        "diag/param_norm": param_norm,
        "diag/update_norm": update_norm,
        "diag/update_ratio": update_norm / jnp.maximum(param_norm, 1e-12),
    }
    if level == "full":
        taps["diag/grad_nonfinite"] = _nonfinite_count(grads)
        for kind, tree in (
            ("grad_norm", grads),
            ("update_norm", updates),
            ("param_norm", params),
        ):
            for group, sub in _layer_groups(tree).items():
                taps[f"diag/{kind}/{group}"] = _l2(sub)
    return taps
