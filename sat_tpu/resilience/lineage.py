"""Checkpoint lineage: integrity sidecars, LAST_GOOD pointer, retention.

The atomic tmp+rename write (``utils.fileio.atomic_write``) guarantees a
checkpoint file is never *torn by us* — but it cannot protect against
bit-rot, a truncating copy, a misbehaving network filesystem, or a
checkpoint written from an already-diverged state.  This module adds the
lineage layer on top:

* every ``<step>.npz`` gets a ``<step>.npz.sha256`` **integrity sidecar**
  written right after the rename;
* a ``LAST_GOOD`` pointer file names the newest checkpoint that passed a
  **post-write verify** (bytes re-read and hashed against the sidecar)
  while the run was **healthy** (finite metrics at the anomaly sentinel's
  last check) — the rollback target that is safe by construction;
* a **retention policy** keeps the newest N checkpoints plus whatever
  ``LAST_GOOD`` names, so bounded disk can't silently delete the one
  checkpoint that still verifies;
* :func:`verify_checkpoint` is the shared detector for torn / corrupt /
  unreadable files, used by the post-write verify, the restore walk-back
  (``train.checkpoint.restore_checkpoint``), and ``train()``'s final-save
  confirmation.

Directory layout::

    save_dir/
      1500.npz  1500.npz.sha256
      1550.npz  1550.npz.sha256
      LAST_GOOD          # text: "1550\n"
      config.json        # step-stamped Config sidecar (train.checkpoint)

No jax at module level: lineage is pure host IO, shared with the jax-free
``scripts/bench_ckpt.py``.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import zipfile
from typing import List, Optional, Tuple

from ..utils.fileio import atomic_write
from .retry import retry_io

LAST_GOOD_NAME = "LAST_GOOD"
REJECTED_NAME = "REJECTED"
SIDECAR_SUFFIX = ".sha256"

_STEP_RE = re.compile(r"(\d+)\.npz")


class CheckpointWriteError(RuntimeError):
    """A checkpoint the training loop depended on did not land (queued
    async write failed, or the final save failed verification)."""


def checkpoint_steps(save_dir: str) -> List[int]:
    """Sorted steps of the real ``<step>.npz`` checkpoints under
    ``save_dir`` — regular files with non-zero size only.  Temp files from
    in-flight atomic writes (``*.tmp``), sidecars, trimmed exports
    (``slim.npz``), zero-byte husks left by a full disk, and directories
    that merely look like checkpoints are all skipped rather than
    mis-parsed (the ``latest_checkpoint`` hardening)."""
    steps = []
    if not os.path.isdir(save_dir):
        return steps
    for fn in os.listdir(save_dir):
        m = _STEP_RE.fullmatch(fn)
        if not m:
            continue
        path = os.path.join(save_dir, fn)
        try:
            if not os.path.isfile(path) or os.path.getsize(path) == 0:
                continue
        except OSError:
            continue
        steps.append(int(m.group(1)))
    return sorted(set(steps))


# ---------------------------------------------------------------------------
# integrity sidecars + verification
# ---------------------------------------------------------------------------


def sidecar_path(ckpt_path: str) -> str:
    return ckpt_path + SIDECAR_SUFFIX


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_sidecar(
    ckpt_path: str,
    topology: Optional[dict] = None,
    vocab: Optional[dict] = None,
) -> str:
    """Hash the landed checkpoint and record it; the sidecar is what makes
    later verification a byte-for-byte statement instead of a guess.

    ``topology`` (optional) is the device topology the checkpoint was
    written under — ``{"device_count", "mesh_shape", "mesh_axes",
    "platform"}``; ``vocab`` (optional) is the content identity of the
    vocabulary the model was trained against — ``{"sha256", "size"}``
    (data.vocabulary.vocab_fingerprint).  Both ride one JSON line
    appended AFTER the digest line.  :func:`verify_checkpoint` reads
    only the first whitespace-delimited token, so the extension is
    invisible to every existing sidecar consumer;
    :func:`read_sidecar_meta` is the reader.  Elastic resume
    (docs/RESILIENCE.md) uses the topology to report changes — the
    saved state itself is always host-flat full arrays, so restoring
    onto a different mesh is a re-placement, not a data transform.  The
    vocab record lets restore fail fast on a vocabulary swap instead of
    silently skipping the mismatched embedding."""
    digest = retry_io(
        lambda: file_sha256(ckpt_path), desc=f"hash checkpoint {ckpt_path}"
    )
    lines = f"{digest}  {os.path.basename(ckpt_path)}\n"
    meta = {}
    if topology:
        meta["topology"] = topology
    if vocab:
        meta["vocab"] = vocab
    if meta:
        lines += json.dumps(meta, sort_keys=True) + "\n"
    atomic_write(sidecar_path(ckpt_path), "w", lambda f: f.write(lines))
    return digest


def read_sidecar_meta(ckpt_path: str) -> dict:
    """The JSON metadata record from ``ckpt_path``'s sidecar (topology,
    vocab, ...), or {} when the sidecar is missing or predates the
    extension."""
    sc = sidecar_path(ckpt_path)
    try:
        with open(sc) as f:
            for line in f.read().splitlines()[1:]:
                line = line.strip()
                if line.startswith("{"):
                    return json.loads(line)
    except (OSError, ValueError):
        return {}
    return {}


def read_sidecar_topology(ckpt_path: str) -> Optional[dict]:
    """Topology record from ``ckpt_path``'s sidecar, or None when the
    sidecar is missing or predates the topology extension."""
    return read_sidecar_meta(ckpt_path).get("topology")


def verify_checkpoint(ckpt_path: str) -> Tuple[bool, str]:
    """Is ``ckpt_path`` a restorable checkpoint?  Returns (ok, reason).

    With a sidecar: re-read and compare the sha256 — catches truncation
    and bit-rot exactly.  Without one (legacy / foreign checkpoints):
    fall back to structural verification — the zip central directory must
    parse and every member's CRC must check out (``testzip`` decompresses
    everything), which catches torn and corrupt files, just without the
    byte-exactness of the hash.
    """
    if not os.path.isfile(ckpt_path):
        return False, "missing"
    try:
        if os.path.getsize(ckpt_path) == 0:
            return False, "empty file"
        sc = sidecar_path(ckpt_path)
        if os.path.isfile(sc):
            with open(sc) as f:
                want = f.read().split()[0] if f else ""
            got = retry_io(
                lambda: file_sha256(ckpt_path), desc=f"hash checkpoint {ckpt_path}"
            )
            if got != want:
                return False, f"sha256 mismatch (sidecar {want[:12]}…, file {got[:12]}…)"
            return True, "sha256 ok"
        with zipfile.ZipFile(ckpt_path) as z:
            bad = z.testzip()
            if bad is not None:
                return False, f"corrupt member {bad}"
        return True, "zip crc ok (no sidecar)"
    except (OSError, zipfile.BadZipFile, ValueError) as e:
        return False, f"unreadable: {e}"


# ---------------------------------------------------------------------------
# LAST_GOOD pointer
# ---------------------------------------------------------------------------


def mark_last_good(save_dir: str, step: int) -> None:
    """Advance the pointer — callers do this ONLY after the post-write
    verify passed and the run was healthy at its last metrics check."""
    atomic_write(
        os.path.join(save_dir, LAST_GOOD_NAME), "w", lambda f: f.write(f"{int(step)}\n")
    )


def last_good_step(save_dir: str) -> Optional[int]:
    path = os.path.join(save_dir, LAST_GOOD_NAME)
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def last_good_checkpoint(save_dir: str) -> Optional[str]:
    """Path of the newest VERIFIABLE known-good checkpoint: the pointer
    target if it still verifies, else the walk-back from the pointer
    through older checkpoints (the pointer file itself may be stale or its
    target rotted since it was written)."""
    pointed = last_good_step(save_dir)
    candidates = checkpoint_steps(save_dir)
    if pointed is not None:
        # older-or-equal to the pointer: checkpoints past it were never
        # blessed (unverified, or written while the sentinel was unhealthy)
        candidates = [s for s in candidates if s <= pointed]
    for step in sorted(candidates, reverse=True):
        path = os.path.join(save_dir, f"{step}.npz")
        ok, reason = verify_checkpoint(path)
        if ok:
            return path
        print(
            f"sat_tpu: last-good candidate {path} rejected ({reason}); walking back",
            file=sys.stderr,
            flush=True,
        )
    return None


# ---------------------------------------------------------------------------
# rejection ledger
# ---------------------------------------------------------------------------


def _rejected_path(save_dir: str) -> str:
    return os.path.join(save_dir, REJECTED_NAME)


def rejected_steps(save_dir: str) -> set:
    """Steps the lifecycle controller has permanently rejected (failed
    canary, vocab mismatch, shape drift).  A rejected step is never
    re-canaried even if LAST_GOOD still points at it."""
    steps = set()
    try:
        with open(_rejected_path(save_dir)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    steps.add(int(json.loads(line)["step"]))
                except (ValueError, KeyError, TypeError):
                    continue
    except OSError:
        pass
    return steps


def is_rejected(save_dir: str, step: int) -> bool:
    return int(step) in rejected_steps(save_dir)


def mark_rejected(save_dir: str, step: int, reason: str) -> bool:
    """Append ``step`` to the rejection ledger (one JSON line per entry).
    Exactly-once: returns False without writing when the step is already
    in the ledger, so a rollback raced with a re-poll records a single
    rejection.  Append (not atomic rewrite) keeps earlier entries intact
    even if this write is torn — a torn tail line is skipped by the
    reader."""
    step = int(step)
    if is_rejected(save_dir, step):
        return False
    record = json.dumps({"step": step, "reason": str(reason)}, sort_keys=True)
    path = _rejected_path(save_dir)
    # a torn tail from a crashed append has no newline: start fresh so
    # this record parses instead of gluing onto the garbage
    prefix = ""
    try:
        with open(path, "rb") as f:
            f.seek(-1, os.SEEK_END)
            if f.read(1) != b"\n":
                prefix = "\n"
    except (OSError, ValueError):
        pass
    with open(path, "a") as f:
        f.write(prefix + record + "\n")
        f.flush()
        os.fsync(f.fileno())
    return True


# ---------------------------------------------------------------------------
# retention
# ---------------------------------------------------------------------------


def apply_retention(save_dir: str, keep: int) -> List[str]:
    """Keep the newest ``keep`` checkpoints plus the ``LAST_GOOD`` target;
    delete the rest (files + sidecars).  ``keep<=0`` keeps everything.
    Returns the deleted paths."""
    if keep <= 0:
        return []
    steps = checkpoint_steps(save_dir)
    protect = set(steps[-keep:])
    pointed = last_good_step(save_dir)
    if pointed is not None:
        protect.add(pointed)
    deleted = []
    for step in steps:
        if step in protect:
            continue
        path = os.path.join(save_dir, f"{step}.npz")
        for victim in (path, sidecar_path(path)):
            try:
                os.unlink(victim)
                deleted.append(victim)
            except FileNotFoundError:
                pass
            except OSError as e:  # retention must never kill training
                print(f"sat_tpu: retention could not delete {victim}: {e}",
                      file=sys.stderr, flush=True)
    return deleted


def finalize_save(save_dir: str, path: str, step: int, healthy: bool, keep: int) -> bool:
    """The lineage tail of every checkpoint save: sidecar → post-write
    verify → (healthy?) LAST_GOOD advance → retention.  Returns whether
    the file verified; a failed verify is reported, never raised — the
    previous LAST_GOOD remains the recovery point, which is exactly the
    degradation this layer exists to provide.

    An existing sidecar is trusted, not rewritten: the npz save hashes
    the file immediately after the rename (train.checkpoint._write_flat),
    and re-hashing here would faithfully fingerprint any rot that
    happened since — blessing exactly the corruption the verify exists
    to catch.  The fallback write covers standalone callers only."""
    if not os.path.isfile(sidecar_path(path)):
        write_sidecar(path)
    # verify AFTER any injected corruption so the injection proves the
    # detector (the env knob flips a byte between write and verify)
    ok, reason = verify_checkpoint(path)
    if not ok:
        print(
            f"sat_tpu: checkpoint {path} FAILED post-write verification "
            f"({reason}); LAST_GOOD not advanced",
            file=sys.stderr,
            flush=True,
        )
    elif not healthy:
        print(
            f"sat_tpu: checkpoint {path} written while metrics were "
            "anomalous; LAST_GOOD not advanced",
            file=sys.stderr,
            flush=True,
        )
    else:
        mark_last_good(save_dir, step)
    apply_retention(save_dir, keep)
    return ok
