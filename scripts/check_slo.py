#!/usr/bin/env python
"""CI gate over SLO alert logs (``slo.jsonl`` from telemetry/slo.py).

The SLO engine appends one record per ok↔burning transition.  This
script turns that log into exit codes the same way ``check_regression.py``
gates BENCH rows: point it at one or more ``slo.jsonl`` files (a chaos
campaign's, a serve soak's, a training run's) and it fails CI when an
objective is burning.

Usage::

    python scripts/check_slo.py <slo.jsonl> [more.jsonl ...] [--strict]

Default policy: an objective whose LAST transition is ``burning`` (it
never recovered before the run ended) fails the gate.  ``--strict``
fails on ANY burning transition, recovered or not — for runs that are
supposed to stay inside objective the whole time.

Schema compatibility: records stamped with a ``schema_version`` other
than this repo's ``sat_tpu.telemetry.SCHEMA_VERSION`` are refused — a
changed contract must bump the version, not silently reinterpret logs.
Torn trailing lines (a run killed mid-append) are tolerated and skipped,
matching every other JSONL reader in the repo.

Exit codes: 0 = all objectives ended (and under ``--strict`` stayed)
ok, 2 = burning objective, 3 = incompatible schema, 1 = usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sat_tpu.telemetry import SCHEMA_VERSION  # noqa: E402


def load_records(path: str) -> List[Dict]:
    """Parse one slo.jsonl tolerantly: torn/garbage lines are skipped
    (counted to stderr), schema mismatches raise to the exit-3 path."""
    records: List[Dict] = []
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if not isinstance(rec, dict) or "name" not in rec:
                torn += 1
                continue
            v = rec.get("schema_version")
            if v is not None and v != SCHEMA_VERSION:
                raise SystemExit3(
                    f"{path}: schema_version={v} is incompatible with this "
                    f"repo's {SCHEMA_VERSION}; regenerate the log"
                )
            records.append(rec)
    if torn:
        print(
            f"check_slo: {path}: skipped {torn} unparsable line(s)",
            file=sys.stderr,
        )
    return records


class SystemExit3(Exception):
    """Schema refusal (exit 3), distinct from usage/IO errors (exit 1)."""


def evaluate(records: List[Dict], strict: bool) -> List[str]:
    """Names of objectives that fail the gate under the chosen policy."""
    last: Dict[str, Dict] = {}
    ever_burned: Dict[str, Dict] = {}
    for rec in records:
        last[rec["name"]] = rec
        if rec.get("event") == "burning":
            ever_burned[rec["name"]] = rec
    if strict:
        return sorted(ever_burned)
    return sorted(
        name for name, rec in last.items() if rec.get("event") == "burning"
    )


def _describe(rec: Dict) -> str:
    t = rec.get("target")
    m = rec.get("measured_fast")
    return (
        f"{rec.get('name')} [{rec.get('kind')}]: event={rec.get('event')} "
        f"measured={m} target={t} burn_fast={rec.get('burn_fast')} "
        f"burn_slow={rec.get('burn_slow')}"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("logs", nargs="+", help="slo.jsonl file(s) to gate")
    ap.add_argument(
        "--strict",
        action="store_true",
        help="fail on any burning transition, even if it later recovered",
    )
    args = ap.parse_args(argv)

    failed: List[str] = []
    total = 0
    try:
        for path in args.logs:
            records = load_records(path)
            total += len(records)
            for rec in records:
                print(f"check_slo: {path}: {_describe(rec)}")
            bad = evaluate(records, args.strict)
            failed.extend(f"{path}:{name}" for name in bad)
    except SystemExit3 as e:
        print(f"check_slo: REFUSED — {e}", file=sys.stderr)
        return 3
    except OSError as e:
        print(f"check_slo: cannot read log: {e}", file=sys.stderr)
        return 1

    if not total:
        # no transitions at all = nothing ever burned: a clean run's
        # slo.jsonl is empty or absent-but-named, and that passes
        print("check_slo: no transitions recorded — all objectives ok")
        return 0
    if failed:
        mode = "burned at least once" if args.strict else "ended burning"
        print(
            f"check_slo: FAIL — {len(failed)} objective(s) {mode}: "
            + ", ".join(failed),
            file=sys.stderr,
        )
        return 2
    print("check_slo: PASS — every objective ended ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
