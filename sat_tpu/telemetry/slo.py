"""SLO engine: declared objectives, sliding windows, fast/slow burn rates.

An objective turns a signal the telemetry layer already records into a
yes/no question — "is the serve p99 under 250 ms?", "are we training at
least 400 captions/s?", "is the newest checkpoint younger than 15
minutes?" — and the engine answers it continuously with the standard
multiwindow trick: an objective is **burning** only when BOTH a fast
window (default 60 s, catches pages-worth incidents quickly) and a slow
window (default 300 s, suppresses blips) violate the target.  Early in a
run both windows see the same short history, so a sustained violation
still alarms before the slow window has fully filled — by design: a run
that starts bad should page, not grandfather itself in.

Six objective kinds cover the fleet contract (docs/OBSERVABILITY.md):

``latency_p99``
    pXX of a span's durations inside the window (source: a span name,
    e.g. ``serve/request``); burn = measured / target.
``error_ratio``
    Δ(source counter) / Δ(denom counter) over the window (e.g.
    ``serve/http_5xx`` over ``serve/http_requests``); burn = ratio/target.
``rate_floor``
    Δ(source counter-or-gauge) / Δt × scale over the window (e.g. the
    ``train/step`` gauge × batch_size = captions/s); burn =
    target / measured — a *floor*, burning means too slow.
``age_ceiling``
    now_unix − gauge value (e.g. ``ckpt/last_save_unix``); burn =
    measured / target.  Instantaneous — both windows read the same age.
``gauge_ceiling``
    the gauge value itself vs target (e.g. ``fleet/step_p95_skew`` vs
    the straggler factor); burn = measured / target.  Instantaneous,
    like ``age_ceiling`` but without the now−stamp subtraction — for
    signals that are already a ratio or level, not a timestamp.
``gauge_floor``
    the gauge value vs a MINIMUM (e.g. ``capacity/headroom_pct`` vs the
    headroom the fleet must keep free); burn = target / measured —
    burning means the gauge fell below target.  Instantaneous, the
    floor twin of ``gauge_ceiling``.

Outputs, all riding existing carriers: ``slo/*`` gauges (picked up by
heartbeat and /metrics), ok↔burning transition records appended to
``slo.jsonl`` through the shared rotating sink (``scripts/check_slo.py``
turns that log into CI exit codes), and :meth:`SLOEngine.burning` which
/healthz consults to report "degraded" with the objective named.

jax-free, sync-free, degrade-don't-raise throughout; evaluation is a
pure read of the recorder plus a small snapshot deque.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from . import SCHEMA_VERSION, run_id

KINDS = (
    "latency_p99",
    "error_ratio",
    "rate_floor",
    "age_ceiling",
    "gauge_ceiling",
    "gauge_floor",
)

# an objective only evaluates once its window holds this many events
# (latency/error kinds) — one outlier must not page
MIN_EVENTS = 3


@dataclass(frozen=True)
class Objective:
    """One declared objective: a signal, a target, and how to compare."""

    name: str          # short id, appears in gauges / healthz / slo.jsonl
    kind: str          # one of KINDS
    target: float      # the objective value (ms, ratio, rate, seconds)
    source: str        # span name (latency), counter (error/rate), gauge
    denom: str = ""    # error_ratio only: the denominator counter
    scale: float = 1.0  # rate_floor only: units per source increment

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r} (one of {KINDS})")
        if self.target <= 0:
            raise ValueError(f"SLO {self.name}: target must be > 0")


def _quantile(sorted_vals: List[int], q: float) -> int:
    idx = min(len(sorted_vals) - 1, int(q / 100.0 * len(sorted_vals)))
    return sorted_vals[idx]


class SLOEngine:
    """Evaluates objectives over the recorder; owns the slo.jsonl log.

    ``clock_ns`` is injectable for deterministic transition tests — it
    must be the same timebase the recorder's span t0s use
    (``perf_counter_ns`` in production)."""

    def __init__(
        self,
        tel,
        objectives: List[Objective],
        jsonl_path: str = "",
        cap_bytes: int = 0,
        fast_s: float = 60.0,
        slow_s: float = 300.0,
        clock_ns: Callable[[], int] = time.perf_counter_ns,
        wall_clock: Callable[[], float] = time.time,
    ) -> None:
        self._tel = tel
        self.objectives = list(objectives)
        self.jsonl_path = jsonl_path
        self.cap_bytes = int(cap_bytes)
        self.fast_s = fast_s
        self.slow_s = max(slow_s, fast_s)
        self._clock_ns = clock_ns
        self._wall = wall_clock
        # (t_ns, counters, gauges) snapshots for windowed deltas; sized to
        # comfortably cover the slow window at the default tick cadence
        self._snaps: "deque" = deque(maxlen=4096)
        self._burning: Dict[str, bool] = {o.name: False for o in objectives}
        self._last_eval: Dict[str, Dict] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- measurement -------------------------------------------------------

    def _span_p99_ms(self, source: str, window_s: float, now_ns: int):
        names, ids, t0s, durs, _ = self._tel.spans_snapshot()
        try:
            want = names.index(source)
        except ValueError:
            return None
        cutoff = now_ns - int(window_s * 1e9)
        samples = [
            int(durs[k])
            for k in range(len(ids))
            if int(ids[k]) == want and int(t0s[k]) >= cutoff
        ]
        if len(samples) < MIN_EVENTS:
            return None
        samples.sort()
        return _quantile(samples, 99) / 1e6

    def _window_snap(self, window_s: float, now_ns: int):
        """The oldest retained snapshot inside the window (None when the
        window isn't half-covered yet — too early to judge a rate)."""
        cutoff = now_ns - int(window_s * 1e9)
        oldest = None
        for snap in self._snaps:
            if snap[0] >= cutoff:
                oldest = snap
                break
        if oldest is None or now_ns - oldest[0] < int(window_s * 0.5e9):
            return None
        return oldest

    def _measure(self, obj: Objective, window_s: float, now_ns: int):
        """(measured, burn) for one objective over one window; (None,
        None) when there isn't enough data to judge."""
        if obj.kind == "latency_p99":
            p99 = self._span_p99_ms(obj.source, window_s, now_ns)
            if p99 is None:
                return None, None
            return p99, p99 / obj.target
        if obj.kind == "error_ratio":
            old = self._window_snap(window_s, now_ns)
            if old is None:
                return None, None
            counters = self._tel.counters()
            d_err = counters.get(obj.source, 0) - old[1].get(obj.source, 0)
            d_all = counters.get(obj.denom, 0) - old[1].get(obj.denom, 0)
            if d_all < MIN_EVENTS:
                return None, None
            ratio = d_err / max(1, d_all)
            return ratio, ratio / obj.target
        if obj.kind == "rate_floor":
            old = self._window_snap(window_s, now_ns)
            if old is None:
                return None, None
            counters = self._tel.counters()
            gauges = self._tel.gauges()
            now_v = counters.get(obj.source, gauges.get(obj.source))
            old_v = old[1].get(obj.source, old[2].get(obj.source))
            if now_v is None or old_v is None:
                return None, None
            dt_s = (now_ns - old[0]) / 1e9
            if dt_s <= 0:
                return None, None
            rate = (now_v - old_v) / dt_s * obj.scale
            return rate, obj.target / max(rate, 1e-9)
        if obj.kind == "age_ceiling":
            stamp = self._tel.gauges().get(obj.source)
            if stamp is None:
                return None, None
            age = max(0.0, self._wall() - stamp)
            return age, age / obj.target
        if obj.kind == "gauge_ceiling":
            value = self._tel.gauges().get(obj.source)
            if value is None:
                return None, None
            return float(value), float(value) / obj.target  # sync-ok: host gauge scalar
        if obj.kind == "gauge_floor":
            value = self._tel.gauges().get(obj.source)
            if value is None:
                return None, None
            return float(value), obj.target / max(float(value), 1e-9)  # sync-ok: host gauge scalar
        return None, None

    # -- evaluation --------------------------------------------------------

    def tick(self) -> Dict[str, Dict]:
        """One evaluation pass: snapshot, measure both windows per
        objective, flip burning states, emit gauges + transitions."""
        now_ns = self._clock_ns()
        with self._lock:
            self._snaps.append(
                (now_ns, dict(self._tel.counters()), dict(self._tel.gauges()))
            )
            results: Dict[str, Dict] = {}
            burning_total = 0
            for obj in self.objectives:
                fast_v, fast_b = self._measure(obj, self.fast_s, now_ns)
                slow_v, slow_b = self._measure(obj, self.slow_s, now_ns)
                burning = bool(
                    fast_b is not None
                    and slow_b is not None
                    and fast_b >= 1.0
                    and slow_b >= 1.0
                )
                entry = {
                    "name": obj.name,
                    "kind": obj.kind,
                    "target": obj.target,
                    "measured_fast": fast_v,
                    "measured_slow": slow_v,
                    "burn_fast": fast_b,
                    "burn_slow": slow_b,
                    "burning": burning,
                }
                results[obj.name] = entry
                burning_total += int(burning)
                self._tel.gauge(
                    f"slo/{obj.name}_burn",
                    round(fast_b, 4) if fast_b is not None else 0.0,
                )
                self._tel.gauge(f"slo/{obj.name}_burning", int(burning))
                if burning != self._burning[obj.name]:
                    self._burning[obj.name] = burning
                    self._emit_transition(entry)
            self._tel.gauge("slo/burning_total", burning_total)
            self._last_eval = results
            return results

    def _emit_transition(self, entry: Dict) -> None:
        if not self.jsonl_path:
            return
        from .exporters import rotating_append

        record = {
            "schema_version": SCHEMA_VERSION,
            "run_id": run_id(),
            "t_unix": round(self._wall(), 3),
            "event": "burning" if entry["burning"] else "ok",
            **entry,
        }
        try:
            line = json.dumps(record)
        except (TypeError, ValueError) as e:
            print(
                f"sat_tpu: slo.jsonl record unserializable: {e}",
                file=sys.stderr,
                flush=True,
            )
            return
        rotating_append(self.jsonl_path, line, self.cap_bytes, tel=self._tel)

    # -- read side ---------------------------------------------------------

    def burning(self) -> List[str]:
        """Names of currently-burning objectives (healthz degrades on
        any)."""
        with self._lock:
            return sorted(n for n, b in self._burning.items() if b)

    def snapshot(self) -> Dict[str, Dict]:
        """The most recent evaluation per objective."""
        with self._lock:
            return dict(self._last_eval)

    # -- background thread (Heartbeat-style) -------------------------------

    def start(self, interval_s: float = 5.0) -> "SLOEngine":
        if self._thread is not None or not self.objectives:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception as e:  # observability never kills the run
                    print(
                        f"sat_tpu: SLO tick failed: {e}",
                        file=sys.stderr,
                        flush=True,
                    )

        self._thread = threading.Thread(target=_loop, name="sat-slo", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None


def objectives_from_config(config, phase: str, tenants=()) -> List[Objective]:
    """The declared objectives for a phase; a target of 0 disables that
    objective (the config default), so a run with no ``slo_*`` settings
    gets an empty list and the engine never starts.

    ``tenants`` (serve phase only) grows the tenant dimension: a
    sequence of ``(name, p99_ms, error_ratio)`` lane targets — one
    burn-rate lane pair per tenant over that tenant's own latency span
    and error-ratio counters (``serve/tenant_<name>_request`` /
    ``_5xx`` / ``_requests``, the per-tenant twins of the serve-wide
    signals, fed by the server's ``_finish_request``).  The multiwindow
    burn math is unchanged; a flooding tenant burns its own lanes while
    everyone else's stay green (the chaos campaign's isolation
    assertion).  Empty for single-tenant serving — no extra lanes."""
    out: List[Objective] = []
    if phase == "serve":
        if config.slo_serve_p99_ms > 0:
            out.append(
                Objective(
                    name="serve_p99_ms",
                    kind="latency_p99",
                    target=config.slo_serve_p99_ms,
                    source="serve/request",
                )
            )
        if config.slo_error_ratio > 0:
            out.append(
                Objective(
                    name="error_ratio",
                    kind="error_ratio",
                    target=config.slo_error_ratio,
                    source="serve/http_5xx",
                    denom="serve/http_requests",
                )
            )
        if config.slo_capacity_headroom_pct > 0:
            # capacity plane (telemetry/capacity.py): burn when the
            # replica's published headroom-% falls below the floor —
            # paging on approach to the ceiling, before latency melts
            out.append(
                Objective(
                    name="capacity_headroom",
                    kind="gauge_floor",
                    target=config.slo_capacity_headroom_pct,
                    source="capacity/headroom_pct",
                )
            )
        if config.slo_quality_psi > 0:
            # quality plane (telemetry/quality.py): burn when the worst
            # per-signal PSI vs the frozen reference stays at/above the
            # ceiling — diagnostic like the tenant lanes (healthz stays
            # "ok"; drift is a model problem, routing away fixes nothing)
            out.append(
                Objective(
                    name="quality_drift",
                    kind="gauge_ceiling",
                    target=config.slo_quality_psi,
                    source="quality/psi_max",
                )
            )
        if config.slo_quality_unk > 0:
            out.append(
                Objective(
                    name="quality_unk",
                    kind="gauge_ceiling",
                    target=config.slo_quality_unk,
                    source="quality/unk_rate",
                )
            )
        for name, p99_ms, error_ratio in tenants:
            if p99_ms > 0:
                out.append(
                    Objective(
                        name=f"tenant_{name}_p99_ms",
                        kind="latency_p99",
                        target=p99_ms,
                        source=f"serve/tenant_{name}_request",
                    )
                )
            if error_ratio > 0:
                out.append(
                    Objective(
                        name=f"tenant_{name}_error_ratio",
                        kind="error_ratio",
                        target=error_ratio,
                        source=f"serve/tenant_{name}_5xx",
                        denom=f"serve/tenant_{name}_requests",
                    )
                )
    elif phase == "canary":
        # the lifecycle controller's qualification objectives: the same
        # targets the serve plane declares, measured over CANARY-slot
        # traffic only (the server records canary requests under their
        # own span/counters), plus the caption-divergence ceiling that
        # p99/error-rate cannot see.  Evaluated by a per-cycle engine
        # whose windows are clipped to the canary window.
        if config.slo_serve_p99_ms > 0:
            out.append(
                Objective(
                    name="canary_p99_ms",
                    kind="latency_p99",
                    target=config.slo_serve_p99_ms,
                    source="serve/canary_request",
                )
            )
        if config.slo_error_ratio > 0:
            out.append(
                Objective(
                    name="canary_error_ratio",
                    kind="error_ratio",
                    target=config.slo_error_ratio,
                    source="serve/canary_5xx",
                    denom="serve/canary_requests",
                )
            )
        if config.canary_divergence_max > 0:
            out.append(
                Objective(
                    name="canary_divergence",
                    kind="gauge_ceiling",
                    target=config.canary_divergence_max,
                    source="lifecycle/caption_divergence",
                )
            )
    elif phase == "train":
        if config.slo_captions_per_s > 0:
            out.append(
                Objective(
                    name="captions_per_s",
                    kind="rate_floor",
                    target=config.slo_captions_per_s,
                    source="train/step",
                    scale=config.batch_size,
                )
            )
        if config.slo_ckpt_age_s > 0:
            out.append(
                Objective(
                    name="ckpt_age_s",
                    kind="age_ceiling",
                    target=config.slo_ckpt_age_s,
                    source="ckpt/last_save_unix",
                )
            )
        if config.fleet_telemetry:
            # the fleet plane publishes worst-host-p95 / fleet-median as
            # fleet/step_p95_skew; sustained skew at/above the straggler
            # factor is exactly the verdict condition, so it pages too
            out.append(
                Objective(
                    name="fleet_step_skew",
                    kind="gauge_ceiling",
                    target=config.straggler_factor,
                    source="fleet/step_p95_skew",
                )
            )
    return out
